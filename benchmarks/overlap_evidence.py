"""Collective/compute overlap evidence from a TPU-targeted AOT compile.

VERDICT r4 ask #4: every config in out/scaling_table.json records
``async_pairs: 0`` because the CPU backend lowers collectives
synchronously — while the design docstrings (schedules.py, tensor
parallel layers) claim XLA's latency-hiding scheduler overlaps the
pipeline ring's ppermute with stage compute, the way the reference's
side-stream DDP machinery overlaps bucketed NCCL allreduce with backward
(apex/parallel/distributed.py:425-475). That claim was untestable on one
chip — but it IS checkable without hardware: ``jax.experimental.
topologies.get_topology_desc`` gives an 8-device v5e topology through
the same PJRT plugin, and AOT-compiling the REAL hybrid train step
against it yields post-scheduling TPU HLO, where asynchronous
collectives appear as ``collective-permute-start``/``-done`` (etc.)
pairs and the instructions BETWEEN a start and its done in schedule
order are the compute the transfer is hidden behind.

The program lowered here is the multi-chip gate's dense hybrid config
(__graft_entry__._dryrun_config: tp=2 x pp=2 x dp=2 — Megatron TP +
SPMD pipeline ring + data-parallel gradient reduction), built
abstractly via ``jax.eval_shape`` (topology devices cannot hold real
buffers) at a width where latency hiding has compute to hide behind.

Sequence parallelism (r6): ``--sequence-parallel`` AOT-compiles the
``GPTConfig.sequence_parallel=True`` hybrid step — the per-layer forward
TP all-reduces decomposed into reduce-scatter/all-gather conjugates. The
record ALWAYS carries (host-side, no TPU needed) a ``collective_census``
block — per-layer and full-forward collective counts on the TP axis for
plain vs sequence-parallel, from ``lint.trace.sequence_parallel_hazards``
(the "all-reduce count per layer 2 -> 0" number) — and an
``activation_bytes`` block (``monitor.hbm.
sequence_parallel_activation_report``: the tp-x sequence-region memory
claim as bytes). When the TPU compile client is unavailable the census
still gates: ``ok_basis: "census_only"``.

ZeRO (r8): ``--zero`` switches to the optimizer-sharding evidence mode
(host-side trace only, no TPU): the SAME dp-only train step is traced
replicated and ZeRO-sharded (``amp.MixedPrecisionOptimizer(
zero_axis="data")``), and the record shows the data-axis grad all-reduce
replaced by the psum_scatter + bf16 all_gather pair — collective counts
from ``lint.trace.zero_redundancy_hazards`` (the plain step IS the
hazard; the zero step must be clean) and payload bytes per verb from
``monitor.comms.CommAccount``, including the bf16-vs-fp32 gather-byte
halving measured by tracing both gather dtypes. An ``optimizer_state``
block (``monitor.hbm.optimizer_state_report`` at the 345M flagship
shape, via ``eval_shape`` — no buffers) carries the bytes/rank ÷ dp
claim. Default output: ``out/zero_evidence.json``.

Quantized collectives (r10): ``--qcomm`` is the quantized-grad-reduce
evidence mode (host-side; the error-feedback microbenchmark EXECUTES on
CPU, everything else is trace-only): the SAME dp-only O2 ZeRO train step
is traced at the fp32 wire (``reduce_dtype=None``) and the int8 wire
(``reduce_dtype="int8"``), and the record shows the compiled
reduce-scatter's wire bytes dropping to exactly 1/4 — payload bytes per
(verb, wire dtype) from ``monitor.comms.CommAccount.by_verb_dtype``
(the int8 all_to_all row vs the fp32 psum_scatter row, with the fp32
per-chunk scale side-channel booked separately) — plus the
``lint.trace.quantized_comm_hazards`` census (the fp32-wire step IS the
fat-wire hazard under a quantized-reduce request; the int8 step must
trace clean with a residual leaf in its state). An ``error_feedback``
block runs the repeated-step microbenchmark for real: the cumulative
quantization error of the reduce DIVERGES without the residual and
stays bounded with it. Default output: ``out/qcomm_evidence.json``.

ZeRO-3 (r9): ``--zero3`` is the fully-sharded-param evidence mode
(host-side trace only, no TPU): the SAME dp-only loss+grad is traced
through the fully-sharded drive (``zero3_shard`` chunks + per-layer
just-in-time gathers via ``run_layers`` ``chunk_meta``) and through a
bulk whole-stack-gather control, and the record shows per-layer gathers
replacing the model-sized bulk gather — census from
``lint.trace.zero3_gather_hazards`` (the bulk control IS the hazard;
the ZeRO-3 step must trace clean) plus the conservation law from
``monitor.comms.CommAccount`` (L per-layer gathers move exactly the
bulk gather's bytes). A ``param_state_report`` block prices the 345M
flagship's per-rank param+master+moment bytes per ZeRO stage, and a
``placement_rung`` block (``benchmarks.gpt_scaling.placement_rung``)
carries the 2.7B-class shape whose per-rank bytes place under ZeRO-3
but not replicated. Default output: ``out/zero3_evidence.json``.

MoE expert parallelism (ISSUE 15): ``--moe`` is the expert-dispatch
evidence mode (EXECUTES on the 8-device CPU virtual mesh): the
expert-parallel ``MoEMLP.apply_expert_parallel`` is traced at the exact
wire and the int8 dispatch wire (``dispatch_dtype="int8"`` —
``parallel/quantize.quantized_all_to_all``), and the record shows the
booked dispatch bytes equal to the analytic (experts x capacity x
hidden) bucket arithmetic with the int8 payload at EXACTLY 1/4 the fp32
bytes (fp32 per-block scales booked separately); the
``lint.trace.moe_dispatch_hazards`` census (the serial layer under an
expert-parallel reading IS the replicated-expert hazard, the EP trace is
clean at both wires, no bulk expert all_gather anywhere); an EXECUTED
serial-vs-expert-parallel forward equivalence (exact at the fp32 wire,
scale-bounded at int8); and a serve smoke — the expert-parallel MoE
engine's greedy streams == the serial engine's, page-leak-free, decode
signature shape-stable. Default output: ``out/moe_evidence.json``.

Run (needs the axon PJRT plugin for the TPU compile client; no chip
time is used — this is compile-only):
    PYTHONPATH=/root/repo:/root/.axon_site python \
        benchmarks/overlap_evidence.py --sequence-parallel \
        --output out/overlap_evidence_sp.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# async pair opcodes in post-scheduling TPU HLO
_ASYNC_STARTS = ("collective-permute-start", "all-reduce-start",
                 "all-gather-start", "reduce-scatter-start", "async-start")
# schedule-order instructions that count as "compute hidden behind the
# transfer" when they sit between a start and its done
_COMPUTE_OPS = ("fusion", "convolution", "dot", "custom-call")


def build_abstract_step(tp, pp, dp, *, hidden, layers, heads, seq, vocab,
                        n_micro, mesh, sequence_parallel=False):
    """The gate's hybrid train-step gradient function + fully-abstract
    sharded args (mirrors __graft_entry__._dryrun_config, but via
    eval_shape: topology devices cannot hold buffers)."""
    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import collectives, mesh as mesh_lib
    from apex_tpu.parallel.distributed import allreduce_gradients_by_spec
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_specs,
        pipelined_loss_fn,
    )

    cfg = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
        axis=mesh_lib.AXIS_MODEL if tp > 1 else None,
        sequence_parallel=sequence_parallel and tp > 1,
        compute_dtype=jnp.bfloat16, remat=True)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")

    all_specs = model.specs()
    specs = dict(
        {k: v for k, v in all_specs.items() if k != "layers"},
        layers=pipeline_specs(all_specs["layers"]),
    )
    pipe_loss = pipelined_loss_fn(
        embed=model.embed,
        run_layers=lambda lp, h: model.run_layers(lp, h),
        head_loss=lambda p, h, t: model.head(p, h, t),
        num_microbatches=n_micro,
        virtual_pipeline_size=1,
    )
    rest_specs = {k: v for k, v in specs.items() if k != "layers"}
    layer_specs = specs["layers"]
    grad_axes = mesh_lib.get_gradient_reduction_axes()

    def sharded_grads(p, toks, tgts, scale):
        rest = {k: v for k, v in p.items() if k != "layers"}

        def scaled_loss(rest, layers):
            return pipe_loss(rest, layers, toks, tgts) * scale

        loss, (rest_g, layer_g) = jax.value_and_grad(
            scaled_loss, argnums=(0, 1))(rest, p["layers"])
        rest_g = allreduce_gradients_by_spec(rest_g, rest_specs)
        layer_g = allreduce_gradients_by_spec(layer_g, layer_specs)
        loss = collectives.pmean(loss, grad_axes)
        return loss, dict(rest_g, layers=layer_g)

    data_spec = P(mesh_lib.AXIS_DATA)
    shard_fn = jax.shard_map(
        sharded_grads, mesh=mesh,
        in_specs=(specs, data_spec, data_spec, P()),
        out_specs=(P(), specs), check_vma=False)

    # abstract param tree (cast to the O2 policy like the real path)
    abstract_params = jax.eval_shape(
        lambda k: amp.cast_params(model.init(k), policy),
        jax.random.PRNGKey(0))

    def with_sharding(avals, spec_tree):
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=NamedSharding(mesh, s)),
            avals, spec_tree,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

    batch = 2 * dp * n_micro
    params_in = with_sharding(abstract_params, specs)
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                               sharding=NamedSharding(mesh, data_spec))
    scale = jax.ShapeDtypeStruct((), jnp.float32,
                                 sharding=NamedSharding(mesh, P()))
    return shard_fn, (params_in, tok, tok, scale)


def analyse(hlo_text):
    """Count async collective pairs and, for each, the compute
    instructions scheduled between start and done (post-scheduling HLO
    text order IS the schedule on TPU)."""
    lines = hlo_text.splitlines()
    pairs = []
    open_starts = {}  # instr name -> (opcode, line idx)
    for i, line in enumerate(lines):
        m = re.search(r"%(\S+?)\s*=.*?\b([a-z][a-z-]*-start)\(", line)
        if m and m.group(2) in _ASYNC_STARTS:
            open_starts[m.group(1)] = (m.group(2), i)
            continue
        m = re.search(r"[a-z-]*-done\(%?([\w.-]+)\)", line)
        if m and m.group(1) in open_starts:
            op, i0 = open_starts.pop(m.group(1))
            compute = sum(
                1 for j in range(i0 + 1, i)
                if any(f" {c}(" in lines[j] or f"{c}(" in lines[j].split("=")[-1][:30]
                       for c in _COMPUTE_OPS))
            pairs.append({"op": op, "sched_span": i - i0,
                          "compute_between": compute})
    counts = {}
    for p in pairs:
        counts[p["op"]] = counts.get(p["op"], 0) + 1
    overlapped = sum(1 for p in pairs if p["compute_between"] > 0)
    return {
        "async_pairs": len(pairs),
        "async_pairs_by_op": counts,
        "pairs_with_compute_between": overlapped,
        "max_compute_between": max(
            (p["compute_between"] for p in pairs), default=0),
        "sync_all_reduce": sum(
            1 for l in lines
            if re.search(r"=\s*\S+\s+all-reduce\(", l)),
    }


def collective_census(tp, *, hidden, layers, heads, seq, vocab):
    """Per-layer and full-forward collective counts on the TP axis, plain
    vs sequence-parallel — host-side trace only (no compile, no TPU). The
    per-layer numbers come from tracing ONE layer body directly (a scanned
    stack would count call sites once regardless of depth:
    lint.trace.sequence_parallel_hazards docstring)."""
    from apex_tpu.lint.trace import sequence_parallel_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel.mesh import AXIS_MODEL

    out = {}
    toks = jnp.zeros((2, seq), jnp.int32)
    for label, sp in (("plain", False), ("sequence_parallel", True)):
        cfg = GPTConfig(
            vocab_size=vocab, hidden_size=hidden, num_layers=layers,
            num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
            axis=AXIS_MODEL, sequence_parallel=sp,
            compute_dtype=jnp.bfloat16, remat=False)
        model = GPTModel(cfg)
        # full (unsharded) shapes under an axis_env binding are fine for
        # COUNTING: the collectives appear either way, values are unused
        params = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype),
            jax.eval_shape(model.init, jax.random.PRNGKey(0)))
        layer0 = jax.tree.map(lambda x: x[0], params["layers"])
        h = jnp.zeros((2, seq, hidden), jnp.bfloat16)
        per_layer = sequence_parallel_hazards(
            lambda p, hh: model._layer(p, hh, None), layer0, h,
            tp_axis=AXIS_MODEL, axes={AXIS_MODEL: tp})
        full = sequence_parallel_hazards(
            lambda p, t: model.apply(p, t, jnp.roll(t, -1, -1)),
            params, toks, tp_axis=AXIS_MODEL, axes={AXIS_MODEL: tp})
        out[label] = {
            "per_layer_forward": per_layer["census"]["activation"],
            "per_layer_all_reduce": per_layer["activation_psums"],
            "full_forward": full["census"]["activation"],
            "full_forward_all_reduce": full["activation_psums"],
            "hazard": full["hazard"],
        }
    return out


def zero_evidence_census(dp, *, hidden, layers, heads, seq, vocab):
    """The ZeRO decomposition claim as numbers — host-side trace only.

    Traces the same dp-only O2 train step three ways (replicated; ZeRO
    with bf16 gather; ZeRO with fp32 gather) under an axis_env binding and
    reports, for the data axis: collective counts split bulk/scalar
    (``lint.trace.zero_redundancy_hazards`` — the replicated step's
    full-size grad psum IS the flagged hazard, the ZeRO step must trace
    clean) and payload bytes per verb (``monitor.comms.CommAccount``; the
    all_gather rows tally at the actual wire dtype, so the bf16 row must
    be exactly half the fp32 row)."""
    from apex_tpu import amp
    from apex_tpu.lint.trace import zero_redundancy_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel.distributed import allreduce_gradients

    cfg = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, remat=False)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    # zero-valued params at full shape: values are unused for COUNTING
    # (collective_census idiom above), and nothing touches a device mesh
    params = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(lambda k: amp.cast_params(model.init(k), policy),
                       jax.random.PRNGKey(0)))
    toks = jnp.zeros((2, seq), jnp.int32)
    tgts = jnp.zeros((2, seq), jnp.int32)

    modes = {
        "plain": amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-4), policy),
        "zero": amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data",
            gather_dtype="bf16"),
        # control for the compression ratio: force the wire dtype UP to
        # fp32 (under O2 the default gather already rides the bf16 param
        # dtype, so "no gather_dtype" is not the uncompressed baseline)
        "zero_fp32_gather": amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data",
            gather_dtype=jnp.float32),
    }
    out = {}
    for label, mp_opt in modes.items():
        def step(p, toks, tgts, mp_opt=mp_opt, plain=(label == "plain")):
            s = mp_opt.init(p)

            def scaled(p):
                return model.loss(p, toks, tgts) * s.scaler.loss_scale

            loss, g = jax.value_and_grad(scaled)(p)
            if plain:
                g = allreduce_gradients(g, ("data",))
            new_p, _new_s, _m = mp_opt.apply_gradients(s, p, g)
            return new_p, loss

        with comm_accounting() as acct:
            jx = jax.make_jaxpr(step, axis_env=[("data", dp)])(
                params, toks, tgts)
        hz = zero_redundancy_hazards(jx, zero_axis="data")
        by_verb = {}
        for r in acct.records:
            if r["axis"] != "data":
                continue
            row = by_verb.setdefault(r["verb"], {"bytes": 0, "calls": 0})
            row["bytes"] += r["bytes"]
            row["calls"] += 1
        out[label] = {
            "comm_bytes_by_verb": by_verb,
            "hazard": hz["hazard"],
            "bulk_psums": hz["bulk_psums"],
            "census": hz["census"],
        }
    return out


def zero3_gather_census(dp, *, hidden, layers, heads, seq, vocab):
    """The ZeRO-3 per-layer-gather claim as numbers — host-side trace only.

    Traces the SAME dp-only O2 loss+grad two ways under an axis_env
    binding: the fully-sharded drive (``zero3_shard`` chunks; each layer's
    weights all-gather just-in-time inside the unrolled layer loop via
    ``run_layers`` ``chunk_meta``) and a bulk control that gathers every
    stacked layer leaf whole before the loss (the O(model)
    rematerialization ZeRO-3 removes). Reports, per mode: the
    ``lint.trace.zero3_gather_hazards`` census (the control must flag, the
    ZeRO-3 step must trace clean with >= num_layers layer gathers) and the
    data-axis ``all_gather`` payload bytes from ``monitor.comms.
    CommAccount`` — the conservation law: L per-layer gathers move exactly
    the bytes of the one whole-stack gather they replace (every leaf row
    here divides by dp, so no padding slack)."""
    from apex_tpu import amp
    from apex_tpu.lint.trace import zero3_gather_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.distributed import (
        gather_chunked_tree,
        gather_stacked_leaf,
    )

    cfg = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, unroll_layers=True)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    # zero-valued params at full shape: values are unused for COUNTING
    params = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(lambda k: amp.cast_params(model.init(k), policy),
                       jax.random.PRNGKey(0)))
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-4), policy, zero_axis="data", zero_level=3,
        gather_dtype="bf16")
    meta = mp_opt.zero3_meta(params)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jnp.zeros((2, seq), jnp.int32)

    def jit_gather_loss(p):
        chunks = mp_opt.zero3_shard(p)
        rest = gather_chunked_tree(
            {k: v for k, v in chunks.items() if k != "layers"}, rest_meta)
        return model.loss(dict(rest, layers=chunks["layers"]), toks, toks,
                          layer_chunk_meta=layer_meta)

    def bulk_gather_loss(p):
        chunks = mp_opt.zero3_shard(p)
        layers_full = jax.tree.map(
            lambda c, s: gather_stacked_leaf(c, s.shape, s.dtype, "data",
                                             gather_dtype=jnp.bfloat16),
            chunks["layers"], layer_meta.shapes,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
        rest = gather_chunked_tree(
            {k: v for k, v in chunks.items() if k != "layers"}, rest_meta)
        return model.loss(dict(rest, layers=layers_full), toks, toks)

    out = {}
    for label, fn in (("zero3_per_layer", jit_gather_loss),
                      ("bulk_control", bulk_gather_loss)):
        with comm_accounting() as acct:
            jx = jax.make_jaxpr(jax.value_and_grad(fn),
                                axis_env=[("data", dp)])(params)
        hz = zero3_gather_hazards(jx, zero_axis="data",
                                  model_elems=n_params)
        gathers = [r for r in acct.records
                   if r["axis"] == "data" and r["verb"] == "all_gather"]
        out[label] = {
            "hazard": hz["hazard"],
            "layer_gathers": hz["layer_gathers"],
            "bulk_gathers": hz["bulk_gathers"],
            "min_model_elems": hz["min_model_elems"],
            "gather_bytes": sum(r["bytes"] for r in gathers),
            "gather_calls": len(gathers),
        }

    # conservation components, each traced alone: ONE layer's JIT gather
    # and the once-per-step rest gather. (In the full step trace above the
    # remat trace cache books the identically-shaped layer body once, so
    # its tally is rest + 1 layer — the components let the record state
    # rest + L x layer == bulk exactly.)
    from apex_tpu.optimizers.distributed import chunk_size

    def chunk_of(s):
        size = 1
        for d in s.shape:
            size *= int(d)
        return jnp.zeros((chunk_size(size, dp),), s.dtype)

    is_sds = lambda x: isinstance(x, jax.ShapeDtypeStruct)  # noqa: E731
    layer0 = jax.tree.map(chunk_of, layer_meta.shapes, is_leaf=is_sds)
    rest0 = jax.tree.map(chunk_of, rest_meta.shapes, is_leaf=is_sds)
    with comm_accounting() as acct_layer:
        jax.make_jaxpr(lambda c: gather_chunked_tree(c, layer_meta),
                       axis_env=[("data", dp)])(layer0)
    with comm_accounting() as acct_rest:
        jax.make_jaxpr(lambda c: gather_chunked_tree(c, rest_meta),
                       axis_env=[("data", dp)])(rest0)
    out["components"] = {
        "one_layer_gather_bytes": sum(
            r["bytes"] for r in acct_layer.records
            if r["axis"] == "data" and r["verb"] == "all_gather"),
        "rest_gather_bytes": sum(
            r["bytes"] for r in acct_rest.records
            if r["axis"] == "data" and r["verb"] == "all_gather"),
        "num_layers": int(layers),
    }
    return out, n_params


def qcomm_evidence_census(dp, *, hidden, layers, heads, seq, vocab):
    """The quantized-grad-reduce claim as numbers — host-side trace only.

    Traces the same dp-only O2 ZeRO train step at the fp32 wire
    (``reduce_dtype=None``) and the int8 wire (``reduce_dtype="int8"``)
    under an axis_env binding and reports, for the data axis: payload
    bytes per (verb, wire dtype) (``monitor.comms.CommAccount.
    by_verb_dtype`` — the int8 all_to_all row must be exactly 1/4 of the
    fp32 psum_scatter row, the fp32 per-chunk scale side-channel booked
    separately) and the ``lint.trace.quantized_comm_hazards`` census (the
    fp32-wire step is the fat-wire hazard when read as a quantized-reduce
    request; the int8 step must trace clean, with a residual 'err' leaf
    in its abstract state)."""
    from apex_tpu import amp
    from apex_tpu.lint.trace import quantized_comm_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, remat=False)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    # zero-valued params at full shape: values are unused for COUNTING
    # (zero_evidence_census idiom), nothing touches a device mesh
    params = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(lambda k: amp.cast_params(model.init(k), policy),
                       jax.random.PRNGKey(0)))
    toks = jnp.zeros((2, seq), jnp.int32)

    modes = {
        "fp32_wire": amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data",
            gather_dtype="bf16"),
        "int8_wire": amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data",
            gather_dtype="bf16", reduce_dtype="int8"),
        "e5m2_wire": amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data",
            gather_dtype="bf16", reduce_dtype="e5m2"),
    }
    out = {}
    for label, mp_opt in modes.items():
        def step(p, toks, tgts, mp_opt=mp_opt):
            s = mp_opt.init(p)

            def scaled(p):
                return model.loss(p, toks, tgts) * s.scaler.loss_scale

            loss, g = jax.value_and_grad(scaled)(p)
            new_p, _new_s, _m = mp_opt.apply_gradients(s, p, g)
            return new_p, loss

        with comm_accounting() as acct:
            jx = jax.make_jaxpr(step, axis_env=[("data", dp)])(
                params, toks, toks)
        if mp_opt.reduce_dtype is not None:
            # the abstract state (host-side, no axis binding needed —
            # only the axis SIZE enters the chunk shapes) carries the
            # residual tree the hazard check wants to see
            import types

            residual = mp_opt.zero_abstract_state(
                params, types.SimpleNamespace(shape={"data": dp})).residual
        else:
            residual = "unchecked"
        hz = quantized_comm_hazards(jx, zero_axis="data", residual=residual)
        out[label] = {
            "comm_bytes_by_verb_dtype": acct.by_verb_dtype(axis="data"),
            "hazard": hz["hazard"],
            "fat_reduces": hz["fat_reduces"],
            "quantized_reduces": hz["quantized_reduces"],
            "census": hz["census"],
            "residual_in_state": (mp_opt.reduce_dtype is not None
                                  and isinstance(residual, dict)
                                  and "err" in residual),
        }
    return out


def error_feedback_microbench(dp=8, elems=4099, steps=24, seed=0):
    """The repeated-step error-feedback claim, EXECUTED (CPU, vmap binds
    the axis): reduce the SAME per-rank gradients ``steps`` times through
    the int8 wire and track ``|cumulative_decoded - t * exact|``. Without
    the residual the per-step rounding bias is constant-signed and the
    cumulative error grows ~linearly; with error feedback each step's
    payload carries the previous step's error, so the partial sums
    telescope and the error stays bounded by one quantization step."""
    from apex_tpu.optimizers.distributed import scatter_chunk
    from apex_tpu.parallel.quantize import quantized_reduce_scatter

    grads = jax.random.normal(jax.random.PRNGKey(seed), (dp, elems),
                              jnp.float32)
    exact = jax.vmap(lambda g: scatter_chunk(g, dp, "data"),
                     axis_name="data")(grads)
    pad = (elems + dp - 1) // dp * dp

    def run(with_ef):
        residual = jnp.zeros((dp, pad), jnp.float32)
        cum = jnp.zeros_like(exact)
        curve = []
        for t in range(1, steps + 1):
            def one(g, r):
                c, nr = quantized_reduce_scatter(
                    g, dp, "data", "int8",
                    residual=(r if with_ef else None))
                return c, (nr if nr is not None else r)
            chunk, residual = jax.vmap(one, axis_name="data")(grads, residual)
            cum = cum + chunk
            curve.append(round(float(jnp.max(jnp.abs(cum - t * exact))), 6))
        return curve

    ef, no_ef = run(True), run(False)
    return {
        "steps": steps, "elems": elems, "dp": dp,
        "max_abs_error_with_ef": ef,
        "max_abs_error_without_ef": no_ef,
        # bounded: the EF curve's tail is no worse than its early window
        # (x2 slack for the dither of which chunk the error lands in);
        # diverging: the unassisted curve keeps growing past the EF bound
        "ef_bounded": ef[-1] <= 2.0 * max(ef[:4]),
        "no_ef_diverges": no_ef[-1] > 3.0 * ef[-1],
    }


def moe_dispatch_evidence(dp, *, hidden, experts, tokens):
    """The expert-dispatch wire claims as numbers — host-side trace only.

    Traces the expert-parallel MoE forward at the exact fp32 wire and the
    int8 dispatch wire under an ``axes={"data": dp}`` binding and
    reports: booked dispatch bytes per (verb, wire dtype) against the
    analytic ``experts x capacity x hidden`` bucket arithmetic, the
    exactly-1/4 int8 payload, the ``moe_dispatch_hazards`` census both
    ways (the serial layer read as an expert-parallel step IS the
    replicated-expert hazard; the EP traces are clean, the exact-wire EP
    trace is the fat-wire hazard under an int8 request), and a
    no-bulk-expert-gather census (zero ``all_gather`` call sites on the
    expert axis — the EP path never rematerializes the full expert
    stack)."""
    import math

    from apex_tpu.lint import ir as ir_mod
    from apex_tpu.lint.trace import iter_eqns, moe_dispatch_hazards
    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.transformer.moe import MoEMLP

    top_k, cf = 2, 2.0
    serial = MoEMLP(hidden, 4 * hidden, num_experts=experts, top_k=top_k,
                    capacity_factor=cf)
    params = serial.init(jax.random.PRNGKey(0))
    e_local = experts // dp
    local = {"router": params["router"],
             "fc1": jax.tree.map(lambda v: v[:e_local], params["fc1"]),
             "fc2": jax.tree.map(lambda v: v[:e_local], params["fc2"])}
    x = jnp.zeros((tokens, hidden), jnp.float32)
    cap = max(1, math.ceil(top_k * tokens * cf / experts))
    bucket_elems = experts * cap * hidden  # the (E, C, d) dispatch payload

    out = {"experts": experts, "top_k": top_k, "capacity_factor": cf,
           "tokens_per_shard": tokens, "capacity_per_shard": cap,
           "analytic_bucket_elems": bucket_elems}
    for label, wire in (("fp32_wire", None), ("int8_wire", "int8")):
        layer = MoEMLP(hidden, 4 * hidden, num_experts=experts,
                       top_k=top_k, capacity_factor=cf,
                       expert_axis="data", dispatch_dtype=wire)
        with comm_accounting() as acct:
            ir = ir_mod.trace_ir(layer.apply_expert_parallel, local, x,
                                 axes={"data": dp})
        hz = moe_dispatch_hazards(ir, expert_axis="data", wire_dtype=wire)
        gathers = sum(1 for eqn in iter_eqns(ir)
                      if eqn.primitive.name == "all_gather")
        out[label] = {
            "comm_bytes_by_verb_dtype": acct.by_verb_dtype(axis="data"),
            "hazard": hz["hazard"],
            "dispatch_all_to_alls": hz["dispatch_all_to_alls"],
            "fat_dispatches": hz["fat_dispatches"],
            "census": hz["census"],
            "all_gather_call_sites": gathers,
        }
    # the controls: a serial (replicated-expert) run under an EP reading
    # is the missing-dispatch hazard; the exact-wire EP trace read under
    # an int8 request is the fat-wire hazard
    out["replicated_control"] = {
        "hazard": moe_dispatch_hazards(
            serial.apply, params, x, axes={"data": dp})["hazard"]}
    exact = MoEMLP(hidden, 4 * hidden, num_experts=experts, top_k=top_k,
                   capacity_factor=cf, expert_axis="data")
    out["fat_wire_control"] = {
        "hazard": moe_dispatch_hazards(
            exact.apply_expert_parallel, local, x, axes={"data": dp},
            wire_dtype="int8")["hazard"]}
    return out


def moe_executed_equivalence(dp, *, hidden, experts, tokens, seed=0):
    """Serial vs expert-parallel forward, EXECUTED (CPU, vmap binds the
    axis): the fp32 dispatch wire reproduces the serial layer exactly
    (ample capacity, no drops), the int8 wire within the per-block scale
    bound. Forward-only under vmap (the quantized conjugates' custom-VJP
    backward composes with shard_map, not vmap-of-grad — quantize.py
    gotcha; gradient equivalence is tier-1's job via shard_map)."""
    from apex_tpu.transformer.moe import MoEMLP

    top_k, cf = 2, 16.0
    serial = MoEMLP(hidden, 4 * hidden, num_experts=experts, top_k=top_k,
                    capacity_factor=cf)
    params = serial.init(jax.random.PRNGKey(seed))
    x = jax.random.normal(jax.random.PRNGKey(seed + 1),
                          (dp * tokens, hidden), jnp.float32)
    ref, _ = serial.apply(params, x)

    e_local = experts // dp
    stacked = {
        "router": params["router"],
        "fc1": jax.tree.map(
            lambda v: v.reshape((dp, e_local) + v.shape[1:]),
            params["fc1"]),
        "fc2": jax.tree.map(
            lambda v: v.reshape((dp, e_local) + v.shape[1:]),
            params["fc2"]),
    }
    in_axes = ({"router": None,
                "fc1": jax.tree.map(lambda _: 0, stacked["fc1"]),
                "fc2": jax.tree.map(lambda _: 0, stacked["fc2"])}, 0)
    xs = x.reshape(dp, tokens, hidden)
    out = {}
    for label, wire in (("fp32_wire", None), ("int8_wire", "int8")):
        layer = MoEMLP(hidden, 4 * hidden, num_experts=experts,
                       top_k=top_k, capacity_factor=cf,
                       expert_axis="data", dispatch_dtype=wire)
        got, _aux = jax.vmap(layer.apply_expert_parallel,
                             in_axes=in_axes, axis_name="data")(stacked, xs)
        err = float(jnp.max(jnp.abs(got.reshape(ref.shape) - ref)))
        out[label] = {"max_abs_error": round(err, 8)}
    out["ref_scale"] = round(float(jnp.max(jnp.abs(ref))), 6)
    return out


def _moe_serve_smoke():
    """The expert-parallel MoE engine's greedy streams == the serial MoE
    engine's on the same weights (executed on the CPU virtual mesh), with
    zero page leaks and a shape-stable decode signature."""
    from apex_tpu.lint.trace import decode_recompile_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.serve import Engine, Request, ServeConfig

    base = dict(vocab_size=128, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_seq_len=64, hidden_dropout=0.0,
                compute_dtype=jnp.float32, remat=False,
                moe_num_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
    model_s = GPTModel(GPTConfig(axis=None, **base))
    params = model_s.init(jax.random.PRNGKey(0))
    scfg = ServeConfig(max_batch=2, max_seq=48, block_size=8)

    def mk():
        rng = np.random.default_rng(3)
        return [Request(prompt=list(rng.integers(0, 128, n)),
                        max_new_tokens=m, request_id=i)
                for i, (n, m) in enumerate(((6, 5), (11, 4), (4, 6)))]

    res_s = Engine(model_s, params, scfg).run(mk())
    mesh = mesh_lib.make_virtual_mesh(4)
    try:
        model_ep = GPTModel(GPTConfig(
            axis=None, moe_expert_axis=mesh_lib.AXIS_DATA, **base))
        eng = Engine(model_ep, params, scfg, mesh=mesh)
        res_ep = eng.run(mk())
        streams_equal = all(res_s[r].tokens == res_ep[r].tokens
                            for r in res_s)
        tw = decode_recompile_hazards(eng.decode_args, ticks=3)
        return {
            "requests": len(res_s),
            "streams_equal": bool(streams_equal),
            "pages_leaked": int(eng.allocator.used),
            "decode_signature_stable": not tw["hazard"],
            "tokens": {str(r): res_ep[r].tokens for r in sorted(res_ep)},
        }
    finally:
        mesh_lib.destroy_model_parallel()


def _moe_main(args) -> int:
    """``--moe``: the expert-parallelism evidence record
    (out/moe_evidence.json)."""
    # executed mode: force the 8-device virtual CPU mesh BEFORE first
    # backend use (the serve smoke and the vmap equivalence run for real)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass

    dp = args.dp
    record = {"metric": "moe_expert_parallel_evidence", "dp": dp,
              "hidden": args.hidden}
    ok_bytes = ok_census = ok_exec = ok_serve = False
    try:
        census = moe_dispatch_evidence(
            dp, hidden=args.hidden, experts=2 * dp, tokens=4 * args.seq)
        record["dispatch_census"] = census
        fp32 = census["fp32_wire"]["comm_bytes_by_verb_dtype"]
        int8 = census["int8_wire"]["comm_bytes_by_verb_dtype"]
        fp32_row = fp32.get("all_to_all[float32]", {})
        int8_row = int8.get("all_to_all[int8]", {})
        scales = int8.get("all_to_all[float32]", {}).get("bytes", 0)
        analytic = census["analytic_bucket_elems"]
        record["wire_compression"] = {
            "fp32_dispatch_bytes": fp32_row.get("bytes", 0),
            "int8_dispatch_bytes": int8_row.get("bytes", 0),
            "scale_sidechannel_bytes": scales,
            "analytic_bytes_per_exchange_fp32": analytic * 4,
            "ratio_int8": round(fp32_row.get("bytes", 0)
                                / max(int8_row.get("bytes", 1), 1), 3),
        }
        # booked == analytic (bytes per call site = one (E, C, d) bucket
        # at the wire itemsize) and the int8 payload is EXACTLY 1/4
        fp32_per_call = (fp32_row.get("bytes", 0)
                         // max(fp32_row.get("calls", 1), 1))
        int8_per_call = (int8_row.get("bytes", 0)
                         // max(int8_row.get("calls", 1), 1))
        ok_bytes = (fp32_per_call == analytic * 4
                    and int8_per_call == analytic
                    and int8_per_call * 4 == fp32_per_call
                    and 0 < scales < int8_row.get("bytes", 0))
        ok_census = (not census["fp32_wire"]["hazard"]
                     and not census["int8_wire"]["hazard"]
                     and census["int8_wire"]["dispatch_all_to_alls"] == 2
                     and census["fp32_wire"]["all_gather_call_sites"] == 0
                     and census["int8_wire"]["all_gather_call_sites"] == 0
                     and census["replicated_control"]["hazard"]
                     and census["fat_wire_control"]["hazard"])
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["census_error"] = str(e)[:400]
    try:
        ex = moe_executed_equivalence(dp, hidden=args.hidden,
                                      experts=2 * dp, tokens=32)
        record["executed_equivalence"] = ex
        scale = max(ex["ref_scale"], 1e-3)
        ok_exec = (ex["fp32_wire"]["max_abs_error"] < 1e-5 * max(scale, 1)
                   and ex["int8_wire"]["max_abs_error"] < 0.05 * scale)
    except Exception as e:  # noqa: BLE001
        record["executed_equivalence"] = {"error": str(e)[:300]}
    try:
        sv = _moe_serve_smoke()
        record["serve_smoke"] = sv
        ok_serve = (sv["streams_equal"] and sv["pages_leaked"] == 0
                    and sv["decode_signature_stable"])
    except Exception as e:  # noqa: BLE001
        record["serve_smoke"] = {"error": str(e)[:300]}
    record["checks"] = {"wire_bytes": ok_bytes, "census": ok_census,
                        "executed_equivalence": ok_exec,
                        "serve": ok_serve}
    record["ok"] = bool(ok_bytes and ok_census and ok_exec and ok_serve)
    print(json.dumps(record))
    output = args.output or os.path.join("out", "moe_evidence.json")
    atomic_write_json(output, record)  # atomic: no torn artifacts
    return 0 if record["ok"] else 1


def _qcomm_main(args) -> int:
    """``--qcomm``: the quantized-collectives evidence record
    (out/qcomm_evidence.json)."""
    record = {"metric": "quantized_collectives_evidence", "dp": args.dp,
              "hidden": args.hidden, "layers": args.layers,
              "seq": args.seq, "vocab": args.vocab}
    ok_census = ok_bytes = ok_ef = False
    try:
        census = qcomm_evidence_census(
            args.dp, hidden=args.hidden, layers=args.layers,
            heads=args.heads, seq=args.seq, vocab=args.vocab)
        record["collective_census"] = census
        fp32 = census["fp32_wire"]["comm_bytes_by_verb_dtype"]
        int8 = census["int8_wire"]["comm_bytes_by_verb_dtype"]
        e5m2 = census["e5m2_wire"]["comm_bytes_by_verb_dtype"]
        fp32_scatter = fp32.get("psum_scatter[float32]", {}).get("bytes", 0)
        int8_payload = int8.get("all_to_all[int8]", {}).get("bytes", 0)
        e5m2_payload = e5m2.get("all_to_all[float8_e5m2]", {}).get("bytes", 0)
        int8_scales = int8.get("all_to_all[float32]", {}).get("bytes", 0)
        record["wire_compression"] = {
            "fp32_scatter_bytes": fp32_scatter,
            "int8_payload_bytes": int8_payload,
            "e5m2_payload_bytes": e5m2_payload,
            "scale_sidechannel_bytes": int8_scales,
            "ratio_int8": round(fp32_scatter / max(int8_payload, 1), 3),
            "ratio_e5m2": round(fp32_scatter / max(e5m2_payload, 1), 3),
        }
        # the compiled reduce moves EXACTLY 1/4 the fp32 bytes at both
        # 1-byte wires; the scale side-channel is booked but tiny
        ok_bytes = (int8_payload > 0
                    and int8_payload * 4 == fp32_scatter
                    and e5m2_payload * 4 == fp32_scatter
                    and 0 < int8_scales < int8_payload // 16)
        # the fp32-wire step IS the fat-wire hazard under a quantized-
        # reduce reading; both quantized steps trace clean with residuals
        ok_census = (census["fp32_wire"]["fat_reduces"] > 0
                     and not census["int8_wire"]["hazard"]
                     and census["int8_wire"]["quantized_reduces"] > 0
                     and census["int8_wire"]["residual_in_state"]
                     and not census["e5m2_wire"]["hazard"])
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["census_error"] = str(e)[:400]
    try:
        ef = error_feedback_microbench(dp=args.dp)
        record["error_feedback"] = ef
        ok_ef = bool(ef["ef_bounded"] and ef["no_ef_diverges"])
    except Exception as e:  # noqa: BLE001
        record["error_feedback"] = {"error": str(e)[:300]}
    record["checks"] = {"census": ok_census, "wire_bytes": ok_bytes,
                        "error_feedback": ok_ef}
    record["ok"] = bool(ok_census and ok_bytes and ok_ef)
    print(json.dumps(record))
    output = args.output or os.path.join("out", "qcomm_evidence.json")
    atomic_write_json(output, record)  # atomic: no torn artifacts
    return 0 if record["ok"] else 1


def _zero3_main(args) -> int:
    """``--zero3``: the fully-sharded-param evidence record
    (out/zero3_evidence.json)."""
    record = {"metric": "zero3_fully_sharded_evidence", "dp": args.dp,
              "hidden": args.hidden, "layers": args.layers,
              "seq": args.seq, "vocab": args.vocab}
    ok_census = ok_bytes = ok_report = ok_rung = False
    try:
        census, n_params = zero3_gather_census(
            args.dp, hidden=args.hidden, layers=args.layers,
            heads=args.heads, seq=args.seq, vocab=args.vocab)
        record["gather_census"] = census
        record["model_elems"] = int(n_params)
        z3, bulk = census["zero3_per_layer"], census["bulk_control"]
        ok_census = (not z3["hazard"]                   # per-layer only...
                     and z3["bulk_gathers"] == 0
                     and z3["layer_gathers"] >= args.layers
                     and bulk["hazard"])                # ...and the control flags
        # conservation law: rest + L x one-layer == the bulk gather's
        # bytes exactly (every leaf row divides by dp here, no padding;
        # the full-step tally is rest + ONE layer because the remat trace
        # cache books the identically-shaped layer body once)
        comp = census["components"]
        per_layer_total = (comp["rest_gather_bytes"]
                           + comp["num_layers"] * comp["one_layer_gather_bytes"])
        ok_bytes = (per_layer_total == bulk["gather_bytes"]
                    and per_layer_total > 0
                    and z3["gather_bytes"] == (comp["rest_gather_bytes"]
                                               + comp["one_layer_gather_bytes"]))
        record["gather_byte_conservation"] = {
            "rest_bytes": comp["rest_gather_bytes"],
            "one_layer_bytes": comp["one_layer_gather_bytes"],
            "num_layers": comp["num_layers"],
            "per_layer_total_bytes": per_layer_total,
            "bulk_bytes": bulk["gather_bytes"],
            "step_trace_bytes": z3["gather_bytes"],
            "step_trace_note": ("the remat trace cache books the "
                                "identically-shaped layer body once: the "
                                "step tally is rest + 1 layer"),
            "equal": bool(per_layer_total == bulk["gather_bytes"]),
        }
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["census_error"] = str(e)[:400]
    try:
        # the 345M flagship shape, cast to O2 so the working copy prices
        # bf16 (bench.py: hidden 1024 x 24 layers, vocab 50304) — the
        # >=4x per-rank param-bytes claim at dp=8
        from apex_tpu import amp
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.monitor.hbm import param_state_report

        flagship = GPTModel(GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_attention_heads=16, max_seq_len=1024, hidden_dropout=0.0,
            axis=None, compute_dtype=jnp.bfloat16))
        policy = amp.get_policy("O2")
        abstract = jax.eval_shape(
            lambda k: amp.cast_params(flagship.init(k), policy),
            jax.random.PRNGKey(0))
        report = param_state_report(abstract, args.dp)
        record["param_state"] = dict(
            report, shape="345M flagship (bench.py: hidden 1024 x 24 "
                          "layers, vocab 50304; O2 bf16 working params)")
        ok_report = report["param_ratio"] >= 4.0
    except Exception as e:  # noqa: BLE001
        record["param_state"] = {"error": str(e)[:200]}
    try:
        # the 2.7B-class placement rung (gpt_scaling.placement_rung):
        # per-rank persistent bytes place under ZeRO-3, NOT replicated
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from gpt_scaling import placement_rung

        rung = placement_rung(dp=args.dp)
        record["placement_rung"] = rung
        ok_rung = (bool(rung["placed"]["zero3"])
                   and not rung["placed"]["replicated"]
                   and not rung["gather_census"]["hazard"])
    except Exception as e:  # noqa: BLE001
        record["placement_rung"] = {"error": str(e)[:300]}
    record["checks"] = {"census": ok_census, "byte_conservation": ok_bytes,
                        "param_state_ratio": ok_report,
                        "placement_rung": ok_rung}
    record["ok"] = bool(ok_census and ok_bytes and ok_report and ok_rung)
    print(json.dumps(record))
    output = args.output or os.path.join("out", "zero3_evidence.json")
    atomic_write_json(output, record)  # atomic: no torn artifacts
    return 0 if record["ok"] else 1


def _zero_main(args) -> int:
    """``--zero``: write the ZeRO evidence record (out/zero_evidence.json)."""
    record = {"metric": "zero_optimizer_evidence", "dp": args.dp,
              "hidden": args.hidden, "layers": args.layers,
              "seq": args.seq, "vocab": args.vocab}
    ok = False
    try:
        census = zero_evidence_census(
            args.dp, hidden=args.hidden, layers=args.layers,
            heads=args.heads, seq=args.seq, vocab=args.vocab)
        record["collective_census"] = census
        bf16 = census["zero"]["comm_bytes_by_verb"].get("all_gather", {})
        fp32 = census["zero_fp32_gather"]["comm_bytes_by_verb"].get(
            "all_gather", {})
        record["gather_compression"] = {
            "bf16_gather_bytes": bf16.get("bytes", 0),
            "fp32_gather_bytes": fp32.get("bytes", 0),
            "ratio": round(fp32.get("bytes", 0)
                           / max(bf16.get("bytes", 0), 1), 3),
        }
        ok = (census["plain"]["hazard"]                 # the psum IS there
              and not census["zero"]["hazard"]          # ...and decomposed
              and census["zero"]["census"]["bulk"].get("reduce_scatter", 0) > 0
              and census["zero"]["census"]["bulk"].get("all_gather", 0) > 0
              and bf16.get("bytes", 0) * 2 == fp32.get("bytes", 0))
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["census_error"] = str(e)[:400]
    try:
        # the 345M flagship shape (bench.py defaults: hidden 1024, 24
        # layers, vocab 50304), via eval_shape — no HBM is touched
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.monitor.hbm import optimizer_state_report

        flagship = GPTModel(GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_attention_heads=16, max_seq_len=1024, hidden_dropout=0.0,
            axis=None, compute_dtype=jnp.bfloat16))
        abstract = jax.eval_shape(flagship.init, jax.random.PRNGKey(0))
        record["optimizer_state"] = dict(
            optimizer_state_report(abstract, args.dp),
            shape="345M flagship (bench.py: hidden 1024 x 24 layers, "
                  "vocab 50304)")
    except Exception as e:  # noqa: BLE001
        record["optimizer_state"] = {"error": str(e)[:200]}
    record["ok"] = bool(ok)
    print(json.dumps(record))
    output = args.output or os.path.join("out", "zero_evidence.json")
    atomic_write_json(output, record)  # atomic: no torn artifacts
    return 0 if record["ok"] else 1


def _timeline_main(args) -> int:
    """``--timeline``: the EXECUTED step-anatomy evidence record
    (out/timeline_evidence.json) — unlike the trace-only modes this one
    runs on the CPU virtual mesh: a vpp-pipelined tick drive
    (``schedules.traced_pipeline_timeline``) measures per-rank bubble
    fraction against the analytic ``expected_bubble_fraction`` floor
    (loss pinned against the serial model), the untimed-schedule
    tripwire flags the compiled ring while the traced drive passes,
    traced ZeRO/ZeRO-3 steps decompose into grads/apply phase spans
    whose anatomy fractions sum to 1.0 per window, and the whole span
    file exports to a loadable Chrome trace."""
    # executed mode: force the 8-device virtual CPU mesh BEFORE first
    # backend use (XLA_FLAGS is read at backend init)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass

    from apex_tpu import amp
    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor import tracing
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer import tensor_parallel as tp_mod
    from apex_tpu.transformer.amp import build_zero_train_step
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_specs,
        pipelined_loss_fn,
        prepare_pipelined_model,
        traced_pipeline_timeline,
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        interleave_stack,
    )

    S, vpp, M = 4, 2, 4
    tiny = dict(vocab_size=128, hidden_size=32, num_layers=8,
                num_attention_heads=4, max_seq_len=16, hidden_dropout=0.0,
                compute_dtype=jnp.float32, remat=False)
    record = {"metric": "timeline_evidence", "stages": S, "vpp": vpp,
              "num_microbatches": M,
              "model": {k: (v if isinstance(v, (int, float)) else str(v))
                        for k, v in tiny.items()}}
    checks = {}

    output = args.output or os.path.join("out", "timeline_evidence.json")
    out_dir = os.path.dirname(output) or "."
    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, "timeline_trace.jsonl")
    if os.path.exists(trace_path):
        os.unlink(trace_path)  # span files append; one run = one file
    tracer = tracing.Tracer(trace_path, keep=True,
                            meta={"run": "timeline_evidence"})

    # -- measured vpp bubble fraction vs the analytic floor ----------------
    try:
        mesh = mesh_lib.make_virtual_mesh(
            S, pipeline_model_parallel_size=S)
        model = GPTModel(GPTConfig(axis=None, **tiny))
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                  tiny["vocab_size"])
        tgt = jnp.roll(toks, -1, axis=-1)
        specs = model.specs()
        layer_specs = pipeline_specs(specs["layers"])
        layers_sh = tp_mod.shard_params(
            interleave_stack(params["layers"], S, vpp), layer_specs, mesh)
        rest = {k: v for k, v in params.items() if k != "layers"}

        loss, _, anatomy = traced_pipeline_timeline(
            mesh, embed=model.embed,
            run_layers=lambda lp, h: model.run_layers(lp, h),
            head_loss=lambda p, h, t: model.head(p, h, t),
            rest_params=rest, layers=layers_sh, layer_specs=layer_specs,
            batch=toks, targets=tgt, num_microbatches=M,
            virtual_pipeline_size=vpp, tracer=tracer, step=0)
        record["pipeline"] = anatomy
        expected = anatomy["expected_bubble_fraction"]
        measured = anatomy["bubble_fraction"]["mean"]
        # contended-container tolerance: half the floor, 0.04 abs min
        checks["bubble_within_tolerance"] = bool(
            abs(measured - expected) <= max(0.04, 0.5 * expected))
        serial_loss = float(model.loss(params, toks, tgt))
        record["loss"] = {"traced_drive": round(float(loss), 6),
                          "serial": round(serial_loss, 6)}
        checks["loss_matches_serial"] = bool(
            abs(float(loss) - serial_loss) < 1e-4)

        # the tripwire this PR exists to prevent: the compiled ring under
        # an armed tracer emits NO spans (hazard); the traced tick drive
        # emits its slots (clean)
        pipe_loss = pipelined_loss_fn(
            embed=model.embed,
            run_layers=lambda lp, h: model.run_layers(lp, h),
            head_loss=lambda p, h, t: model.head(p, h, t),
            num_microbatches=M, virtual_pipeline_size=vpp)
        rest_specs_p = jax.tree.map(lambda _: P(), rest)
        compiled_drive = jax.shard_map(
            pipe_loss, mesh=mesh,
            in_specs=(rest_specs_p, layer_specs, P(), P()),
            out_specs=P(), check_vma=False)
        hz_bad = lint_trace.untimed_schedule_hazards(
            lambda: jax.make_jaxpr(compiled_drive)(
                rest, layers_sh, toks, tgt))
        hz_ok = lint_trace.untimed_schedule_hazards(
            lambda: traced_pipeline_timeline(
                mesh, embed=model.embed,
                run_layers=lambda lp, h: model.run_layers(lp, h),
                head_loss=lambda p, h, t: model.head(p, h, t),
                rest_params=rest, layers=layers_sh,
                layer_specs=layer_specs, batch=toks, targets=tgt,
                num_microbatches=M, virtual_pipeline_size=vpp, step=1))
        record["untimed_schedule"] = {
            "compiled_drive": {k: hz_bad[k]
                               for k in ("hazard", "drives", "pipe_spans")},
            "traced_drive": {k: hz_ok[k]
                             for k in ("hazard", "drives", "pipe_spans")},
        }
        checks["untimed_tripwire"] = bool(
            hz_bad["hazard"] and not hz_ok["hazard"]
            and hz_ok["pipe_spans"] > 0)
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["pipeline_error"] = str(e)[:400]
    finally:
        mesh_lib.destroy_model_parallel()

    # -- schedule engine: measured zero-bubble vs 1F1B at the same (S, M) --
    try:
        from apex_tpu.transformer.pipeline_parallel import (
            plan_schedule,
            traced_schedule_timeline,
        )

        mesh = mesh_lib.make_virtual_mesh(
            S, pipeline_model_parallel_size=S)
        model = GPTModel(GPTConfig(axis=None, **tiny))
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                  tiny["vocab_size"])
        tgt = jnp.roll(toks, -1, axis=-1)
        layer_specs = pipeline_specs(model.specs()["layers"])
        layers_plain = tp_mod.shard_params(params["layers"], layer_specs,
                                           mesh)
        rest = {k: v for k, v in params.items() if k != "layers"}
        serial_loss = float(model.loss(params, toks, tgt))
        sched_block = {}
        for sched in ("1f1b", "zero-bubble"):
            plan = plan_schedule(sched, M, S)
            zloss, _, an = traced_schedule_timeline(
                plan, mesh, embed=model.embed,
                run_layers=lambda lp, h: model.run_layers(lp, h),
                head_loss=lambda p, h, t: model.head(p, h, t),
                rest_params=rest, layers=layers_plain,
                layer_specs=layer_specs, batch=toks, targets=tgt,
                tracer=tracer, step=10 if sched == "1f1b" else 11)
            sched_block[sched] = {
                "ticks": an["ticks"],
                "measured_bubble": an["bubble_fraction"]["mean"],
                "expected_bubble_fraction": an["expected_bubble_fraction"],
                "plan_bubble_fraction": an["plan_bubble_fraction"],
                "loss": round(float(zloss), 6),
                "loss_matches_serial": bool(
                    abs(float(zloss) - serial_loss) < 1e-4),
            }
        record["schedules"] = sched_block
        zb = sched_block["zero-bubble"]
        f1b = sched_block["1f1b"]
        # the engine claim: the W/B-split planner's MEASURED bubble lands
        # strictly below 1F1B's at the same (S, M) and approaches its own
        # analytic floor (contended-container tolerance as above)
        checks["zb_bubble_below_1f1b"] = bool(
            zb["measured_bubble"] < f1b["measured_bubble"]
            and zb["loss_matches_serial"] and f1b["loss_matches_serial"])
        checks["zb_bubble_near_floor"] = bool(
            abs(zb["measured_bubble"] - zb["expected_bubble_fraction"])
            <= max(0.05, 0.5 * zb["expected_bubble_fraction"]))
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["schedules_error"] = str(e)[:400]
    finally:
        mesh_lib.destroy_model_parallel()

    # -- ZeRO-3 gather prefetch: tripwire + wire-model overlap estimate ----
    try:
        from apex_tpu.lint.trace import unprefetched_gather_hazards
        from apex_tpu.monitor import mfu as mfu_lib
        from apex_tpu.monitor.comms import comm_accounting
        from apex_tpu.optimizers.distributed import gather_chunked_tree

        dp, L = 8, 4
        pcfg = dict(vocab_size=128, hidden_size=32, num_layers=L,
                    num_attention_heads=4, max_seq_len=16,
                    hidden_dropout=0.0, axis=None,
                    compute_dtype=jnp.bfloat16, unroll_layers=True)
        policy = amp.get_policy("O2")
        mp3 = amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy, zero_axis="data", zero_level=3,
            gather_dtype="bf16")
        pparams = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype),
            jax.eval_shape(
                lambda k: amp.cast_params(
                    GPTModel(GPTConfig(**pcfg)).init(k), policy),
                jax.random.PRNGKey(0)))
        meta = mp3.zero3_meta(pparams)
        layer_meta = meta.subtree("layers")
        rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
        ptoks = jnp.zeros((2, 16), jnp.int32)

        def z3_loss(prefetch):
            pmodel = GPTModel(GPTConfig(zero3_prefetch=prefetch, **pcfg))

            def fn(p):
                chunks = mp3.zero3_shard(p)
                rest = gather_chunked_tree(
                    {k: v for k, v in chunks.items() if k != "layers"},
                    rest_meta)
                return pmodel.loss(
                    dict(rest, layers=chunks["layers"]), ptoks, ptoks,
                    layer_chunk_meta=layer_meta)
            return fn

        # compute seconds come from the SERIAL twin's grad flops (the
        # gathers add no FLOPs and tracing it needs no axis binding)
        serial_model = GPTModel(GPTConfig(**pcfg))
        flops = mfu_lib.traced_step_costs(
            jax.value_and_grad(
                lambda p: serial_model.loss(p, ptoks, ptoks)),
            pparams)["flops"]
        pref_block = {}
        for label, pf in (("serialized", 0), ("prefetched", 1)):
            grad_fn = jax.value_and_grad(z3_loss(pf))
            with comm_accounting() as acct:
                jx = jax.make_jaxpr(grad_fn, axis_env=[("data", dp)])(
                    pparams)
            hz = unprefetched_gather_hazards(jx, zero_axis="data")
            gather_bytes = sum(
                r["bytes"] for r in acct.records
                if r["axis"] == "data" and r["verb"] == "all_gather")
            # wire-model structural estimate (the labelled-emulation
            # caveat of the scaling table applies: CPU lowers collectives
            # synchronously, so the OVERLAP win is argued from structure
            # + the wire model, not a CPU wall measurement): per-layer
            # gathers that stand free ahead of the compute hide under it
            # (double-buffer pipeline: wall = first gather + L*max(c, g));
            # remat-fused gathers serialize (wall = compute + comm)
            ici_bw = tracing.ici_spec("tpu v5e")["ici_bytes_per_sec"]
            peak = mfu_lib.PEAK_SPECS["v5e"][0]  # v5e bf16 peak
            comm_s = gather_bytes / ici_bw
            compute_s = flops / peak
            c_l, g_l = compute_s / L, comm_s / L
            if hz["hazard"]:
                wall = compute_s + comm_s
            else:
                wall = g_l + L * max(c_l, g_l)
            an = tracing.step_anatomy(
                wall_s=wall, compute_s=compute_s, comm_s=comm_s)
            pref_block[label] = {
                "hazard": hz["hazard"],
                "fused_gathers": hz["fused_gathers"],
                "free_gathers": hz["free_gathers"],
                "gather_bytes": int(gather_bytes),
                "overlap_fraction": an.get("overlap_fraction", 0.0),
                "anatomy": an,
            }
        pref_block["basis"] = (
            "structural census (unprefetched_gather_hazards) x wire model "
            "(ICI table / v5e peak): the overlap fraction is a modeled "
            "number — the structure is the measured fact")
        record["zero3_prefetch"] = pref_block
        checks["prefetch_tripwire"] = bool(
            pref_block["serialized"]["hazard"]
            and not pref_block["prefetched"]["hazard"]
            and pref_block["prefetched"]["free_gathers"] >= L)
        checks["zero3_prefetch_overlap_rises"] = bool(
            pref_block["prefetched"]["overlap_fraction"]
            > pref_block["serialized"]["overlap_fraction"])
    except Exception as e:  # noqa: BLE001
        record["zero3_prefetch_error"] = str(e)[:400]

    # -- ZeRO / ZeRO-3 phase anatomy (traced two-program steps) ------------
    for lvl in (2, 3):
        key = f"zero{lvl}"
        try:
            mesh = mesh_lib.make_virtual_mesh(8)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_attention_heads=4, max_seq_len=16,
                            hidden_dropout=0.0,
                            compute_dtype=jnp.bfloat16, remat=False)
            zmodel = GPTModel(cfg)
            policy = amp.get_policy("O2")
            mp_opt = amp.MixedPrecisionOptimizer(
                FusedAdam(lr=1e-3), policy,
                zero_axis=mesh_lib.AXIS_DATA, zero_level=lvl)
            full = amp.cast_params(
                zmodel.init(jax.random.PRNGKey(0)), policy)
            zspecs, zparams, zpipe_loss = prepare_pipelined_model(
                zmodel, full, mesh, num_microbatches=2)
            zrest_specs = {k: v for k, v in zspecs.items()
                           if k != "layers"}
            grad_axes = mesh_lib.get_gradient_reduction_axes()
            data_spec = P(mesh_lib.AXIS_DATA)
            if lvl >= 3:
                z3 = mp_opt.zero3_init(zparams, mesh, zspecs)
                zparams, opt_state = z3.params, z3.opt_state
                step = build_zero_train_step(
                    mp_opt, mesh, None, None, None,
                    rest_specs=zrest_specs,
                    layer_specs=zspecs["layers"], grad_axes=grad_axes,
                    data_spec=data_spec, zero_axis=mesh_lib.AXIS_DATA,
                    zero3=z3, model=zmodel, num_microbatches=2,
                    traced=True, tracer=tracer)
            else:
                opt_state, state_specs = mp_opt.zero_init(
                    zparams, mesh, zspecs)
                step = build_zero_train_step(
                    mp_opt, mesh, zspecs, state_specs, zpipe_loss,
                    rest_specs=zrest_specs, grad_axes=grad_axes,
                    data_spec=data_spec, zero_axis=mesh_lib.AXIS_DATA,
                    traced=True, tracer=tracer)
            ztoks = jax.random.randint(jax.random.PRNGKey(2), (16, 16),
                                       0, 128)
            shard = lambda a: jax.device_put(  # noqa: E731
                a, NamedSharding(mesh, data_spec))
            ztoks = shard(ztoks)
            ztgts = shard(jnp.roll(ztoks, -1, axis=-1))
            n0 = len(tracer.records)
            for i in range(3):  # window 0 pays compile; 1-2 measure
                tracer.step = 100 * lvl + i
                with tracer.span("step", step=100 * lvl + i) as sp:
                    zparams, opt_state, zloss, _ = step(
                        zparams, opt_state, ztoks, ztgts)
                    sp.barrier(zloss)
            spans = [r for r in tracer.records[n0:]
                     if r.get("kind") == "span"]
            windows = []
            for i in (1, 2):
                st = 100 * lvl + i
                wall = next(r["dur_s"] for r in spans
                            if r["name"] == "step" and r.get("step") == st)
                grads = next(r for r in spans
                             if r["name"] == "zero.grads"
                             and r.get("step") == st)
                apply_ = next(r for r in spans
                              if r["name"] == "zero.apply"
                              and r.get("step") == st)
                an = tracing.step_anatomy(
                    wall_s=wall, compute_s=grads["dur_s"],
                    comm_s=apply_["dur_s"])
                an["comm_bytes"] = {"grads": grads.get("comm_bytes"),
                                    "apply": apply_.get("comm_bytes")}
                windows.append(an)
            record[key] = {"windows": windows,
                           "loss": round(float(zloss), 6)}
            checks[f"{key}_fracs_sum_1"] = all(
                abs(w["compute_frac"] + w["comm_frac"]
                    + w["stall_frac"] - 1.0) < 2e-3 for w in windows)
            # the phase spans must actually cover the step: anything
            # else means the split lost a phase
            checks[f"{key}_phases_cover_step"] = all(
                w["stall_frac"] < 0.3 for w in windows)
        except Exception as e:  # noqa: BLE001
            record[f"{key}_error"] = str(e)[:400]
        finally:
            mesh_lib.destroy_model_parallel()

    # -- Chrome export round-trip ------------------------------------------
    try:
        tracer.close()
        chrome_path = trace_path + ".chrome.json"
        tracing.write_chrome_trace(trace_path, chrome_path)
        with open(chrome_path) as f:
            trace = json.load(f)
        ev = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        record["chrome"] = {"path": chrome_path, "events": len(ev)}
        checks["chrome_export_loadable"] = bool(
            ev and all(
                isinstance(e.get("ts"), (int, float))
                and isinstance(e.get("dur"), (int, float))
                and e.get("dur") >= 0 and "name" in e and "pid" in e
                for e in ev))
    except Exception as e:  # noqa: BLE001
        record["chrome"] = {"error": str(e)[:300]}

    record["checks"] = {k: bool(v) for k, v in checks.items()}
    required = ("bubble_within_tolerance", "loss_matches_serial",
                "untimed_tripwire", "zb_bubble_below_1f1b",
                "zb_bubble_near_floor", "prefetch_tripwire",
                "zero3_prefetch_overlap_rises", "zero2_fracs_sum_1",
                "zero3_fracs_sum_1", "chrome_export_loadable")
    record["ok"] = all(record["checks"].get(k) for k in required)
    print(json.dumps(record))
    atomic_write_json(output, record)  # atomic: no torn artifacts
    return 0 if record["ok"] else 1


def main():
    # jax<0.5 API renames (shard_map/axis_size): installed only when the
    # harness RUNS as a program, same as gpt_scaling.py
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x4")
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--micro", type=int, default=2)
    ap.add_argument("--sequence-parallel", action="store_true",
                    help="AOT-compile the sequence_parallel=True hybrid "
                         "step (the census block always covers both modes)")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO evidence mode (host-side, no TPU): "
                         "replicated vs sharded-optimizer collective "
                         "census + bytes per verb + the optimizer-state "
                         "bytes/rank table; writes out/zero_evidence.json")
    ap.add_argument("--zero3", action="store_true",
                    help="ZeRO-3 evidence mode (host-side, no TPU): "
                         "per-layer JIT gather census vs the bulk-gather "
                         "control, gather-byte conservation, the 345M "
                         "param_state_report table, and the 2.7B-class "
                         "placement rung; writes out/zero3_evidence.json")
    ap.add_argument("--qcomm", action="store_true",
                    help="quantized-collectives evidence mode (host-side, "
                         "no TPU): fp32-wire vs int8/e5m2-wire ZeRO step "
                         "traces — bytes per (verb, wire dtype), the "
                         "quantized_comm_hazards census, and the executed "
                         "error-feedback microbenchmark; writes "
                         "out/qcomm_evidence.json")
    ap.add_argument("--timeline", action="store_true",
                    help="step-anatomy evidence mode (EXECUTES on the "
                         "8-device CPU virtual mesh): traced vpp tick "
                         "drive measuring per-rank bubble fraction vs "
                         "the analytic floor, traced ZeRO/ZeRO-3 phase "
                         "anatomy, untimed-schedule tripwire, Chrome "
                         "trace export; writes out/timeline_evidence.json")
    ap.add_argument("--moe", action="store_true",
                    help="expert-parallelism evidence mode (EXECUTES on "
                         "the CPU virtual mesh): dispatch bytes booked == "
                         "analytic with the int8 wire at exactly 1/4, the "
                         "moe_dispatch_hazards census both ways, executed "
                         "serial-vs-EP equivalence, and the serve MoE "
                         "smoke; writes out/moe_evidence.json")
    ap.add_argument("--dp", type=int, default=8,
                    help="data-axis size for the --zero census/state table")
    ap.add_argument("--output", default=None)
    args = ap.parse_args()

    if args.moe:
        sys.exit(_moe_main(args))
    if args.timeline:
        sys.exit(_timeline_main(args))
    if args.qcomm:
        sys.exit(_qcomm_main(args))
    if args.zero3:
        sys.exit(_zero3_main(args))
    if args.zero:
        sys.exit(_zero_main(args))

    from apex_tpu.parallel import mesh as mesh_lib

    record = {"metric": "tpu_aot_overlap_evidence",
              "topology": args.topology,
              "tp": args.tp, "pp": args.pp,
              "hidden": args.hidden, "layers": args.layers,
              "seq": args.seq,
              "sequence_parallel": bool(args.sequence_parallel)}

    # host-side evidence first: it must survive a missing TPU compile client
    census_ok = False
    try:
        census = collective_census(
            args.tp, hidden=args.hidden, layers=args.layers,
            heads=args.heads, seq=args.seq, vocab=args.vocab)
        record["collective_census"] = census
        census_ok = (census["sequence_parallel"]["per_layer_all_reduce"] == 0
                     and census["sequence_parallel"]["full_forward_all_reduce"] == 0
                     and census["plain"]["per_layer_all_reduce"] >= 2)
        record["census_ok"] = census_ok
    except Exception as e:  # noqa: BLE001 - census failure is a result too
        record["census_error"] = str(e)[:300]
    try:
        from apex_tpu.monitor.hbm import sequence_parallel_activation_report

        # per-rank batch mirrors build_abstract_step's 2*dp*n_micro with
        # dp derived from the requested topology ("v5e:2x4" -> 8 devices),
        # clamped to >= 1 so an over-subscribed tp*pp still reports real
        # (per-rank) bytes instead of silent zeros
        m = re.search(r"(\d+)x(\d+)", args.topology)
        n_top = int(m.group(1)) * int(m.group(2)) if m else args.tp * args.pp
        dp_guess = max(1, n_top // (args.tp * args.pp))
        record["activation_bytes"] = sequence_parallel_activation_report(
            batch=2 * dp_guess * args.micro,
            seq=args.seq, hidden=args.hidden, num_layers=args.layers,
            tp=args.tp)
    except Exception as e:  # noqa: BLE001
        record["activation_bytes"] = {"error": str(e)[:200]}

    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            topology_name=args.topology, platform="tpu")
        devs = list(topo.devices)
        dp = len(devs) // (args.tp * args.pp)
        record["dp"] = dp
        mesh = mesh_lib.initialize_model_parallel(
            tensor_model_parallel_size=args.tp,
            pipeline_model_parallel_size=args.pp,
            devices=devs)
        try:
            shard_fn, abstract_args = build_abstract_step(
                args.tp, args.pp, dp, hidden=args.hidden,
                layers=args.layers, heads=args.heads, seq=args.seq,
                vocab=args.vocab, n_micro=args.micro, mesh=mesh,
                sequence_parallel=args.sequence_parallel)
            print("lowering against topology...", file=sys.stderr)
            compiled = jax.jit(shard_fn).lower(*abstract_args).compile()
            txt = compiled.as_text()
            record.update(analyse(txt))
            # aot_async_ok is the r5 latency-hiding claim. A
            # --sequence-parallel run's configured claim is the r6
            # decomposition, which the census gates (async-pair detection
            # depends on the compile client's scheduling flags: the r5
            # tunnel run showed 2 ppermute pairs, this container's libtpu
            # shows 0 for the same program — but the all-reduce COUNT
            # comparison holds in matched conditions: 9 plain vs 4
            # sequence-parallel). A PLAIN run keeps the original meaning:
            # ok iff the async demonstration itself succeeded.
            aot_ok = bool(record["async_pairs"] > 0
                          and record["pairs_with_compute_between"] > 0)
            record["aot_async_ok"] = aot_ok
            record["ok"] = bool(aot_ok or
                                (args.sequence_parallel and census_ok))
            record["ok_basis"] = "aot" if aot_ok else "census"
        finally:
            mesh_lib.destroy_model_parallel()
    except Exception as e:  # noqa: BLE001 - a negative result is a result
        record["error"] = str(e)[:500]
        # no TPU compile client: a sequence-parallel run's decomposition
        # claim (the thing a refactor can silently regress) still gates on
        # the host-side census; a plain run has nothing left to show
        record["ok"] = bool(args.sequence_parallel and census_ok)
        record["ok_basis"] = "census_only"

    print(json.dumps(record))
    if args.output:
        atomic_write_json(args.output, record)  # atomic: no torn artifacts
    sys.exit(0 if record.get("ok") else 1)


if __name__ == "__main__":
    main()
