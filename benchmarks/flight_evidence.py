"""Flight-recorder / hang-attribution / health-rule evidence (ISSUE 14).

Executable off-TPU proof that the black-box layer does what it claims,
as one JSON artifact (``out/flight_evidence.json``, ok:true):

(a) **hang attribution** — a child process stalls INSIDE a breadcrumbed
    ``comm:`` scope; the watchdog's stall kill fires and its kill report
    names that scope (the structured-heartbeat protocol,
    ``monitor/watchdog.py`` + ``monitor/flight.py``), and the
    parent-side kill dump lands at the advertised flight path;
(b) **crash dump** — a child that journals a few real train-ish steps
    and then dies of an unhandled exception leaves a loadable
    strict-JSON flight dump holding the recent step records, the
    exception, an HBM/live-array snapshot, and the loss-scale state;
(c) **health rules** — a seeded loss-spike journal raises exactly the
    ``loss-spike`` alert (online wiring AND offline ``health.scan``
    agree); a clean journal raises zero alerts;
(d) **the gate** — ``report compare --max-alerts 0`` fails the spiked
    candidate against the clean baseline and passes a self-compare.

    JAX_PLATFORMS=cpu python benchmarks/flight_evidence.py

Artifacts write atomically (``utils/io.py``) — the evidence about torn
artifacts must not itself be tearable.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import textwrap

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# (a) watchdog kill names the breadcrumbed comm scope
# ---------------------------------------------------------------------------

_STALL_CHILD = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, os.environ["FLIGHT_EVIDENCE_REPO"])
    from apex_tpu.monitor.watchdog import Heartbeat

    hb = Heartbeat.from_env()
    hb.beat("warmup")  # stall clock now runs from real beats
    import jax  # the slow import happens with a live heartbeat behind it
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from apex_tpu.monitor.comms import collective_scope

    hb.beat("train")
    # enter a REAL comm scope: collective_scope stamps the breadcrumb
    # (and refreshes the structured heartbeat with it) on entry — then
    # wedge inside, exactly the regime the kill report must attribute
    with collective_scope("psum", "data", jnp.ones((8, 8))):
        time.sleep(600)
""")


def check_hang_attribution(stall_timeout: float) -> dict:
    from apex_tpu.monitor.watchdog import run_under_watchdog

    d = tempfile.mkdtemp(prefix="flight_ev_a_")
    flight_path = os.path.join(d, "stall.flight.json")
    env = dict(os.environ, FLIGHT_EVIDENCE_REPO=REPO,
               JAX_PLATFORMS="cpu")
    env.pop("APEX_TPU_FLIGHT", None)
    res = run_under_watchdog(
        [sys.executable, "-c", _STALL_CHILD],
        deadline=max(20 * stall_timeout, 300.0),
        stall_timeout=stall_timeout, poll_s=0.25,
        env=env, flight_path=flight_path)
    from apex_tpu.monitor import flight as flight_mod

    dump = flight_mod.load(flight_path)
    hb = res.heartbeat or {}
    last_op = (hb.get("last_op") or {}).get("op")
    out = {
        "status": res.status,
        "reason": res.reason,
        "heartbeat_stage": hb.get("stage"),
        "heartbeat_last_op": last_op,
        "kill_dump_written": dump is not None,
        "kill_dump_last_op": ((dump or {}).get("last_op") or {}).get("op")
        if isinstance((dump or {}).get("last_op"), dict) else None,
    }
    out["ok"] = bool(
        res.status == "stalled"
        and "comm:psum[data]" in (res.reason or "")
        and last_op == "comm:psum[data]"
        and out["kill_dump_last_op"] == "comm:psum[data]")
    return out


# ---------------------------------------------------------------------------
# (b) unhandled exception leaves a loadable flight dump
# ---------------------------------------------------------------------------

_CRASH_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.environ["FLIGHT_EVIDENCE_REPO"])
    import jax, jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from apex_tpu.monitor import flight
    from apex_tpu.monitor.journal import MetricsJournal

    path = os.environ["FLIGHT_EVIDENCE_JOURNAL"]
    flight.arm(path + ".flight.json", meta={"run": "crash-evidence"})
    resident = jnp.ones((128, 128), jnp.float32)  # something for the HBM snapshot
    with MetricsJournal(path) as j:
        for step in range(6):
            j.step_start()
            loss = jnp.asarray(2.0 - 0.1 * step, jnp.float32) * resident[0, 0]
            j.step_end(step=step, loss=loss, tokens=1024,
                       metrics={"loss_scale": 2.0 ** 16, "found_inf": False})
        raise RuntimeError("simulated co-tenant crash")
""")


def check_crash_dump() -> dict:
    d = tempfile.mkdtemp(prefix="flight_ev_b_")
    journal = os.path.join(d, "run.jsonl")
    env = dict(os.environ, FLIGHT_EVIDENCE_REPO=REPO,
               FLIGHT_EVIDENCE_JOURNAL=journal, JAX_PLATFORMS="cpu")
    env.pop("APEX_TPU_FLIGHT", None)
    proc = subprocess.run([sys.executable, "-c", _CRASH_CHILD], env=env,
                          capture_output=True, text=True, timeout=300)
    from apex_tpu.monitor import flight as flight_mod

    dump = flight_mod.load(journal + ".flight.json")
    out = {"child_rc": proc.returncode,
           "dump_loaded": dump is not None}
    if dump is None:
        out["stderr_tail"] = (proc.stderr or "")[-500:]
        out["ok"] = False
        return out
    ring_steps = [r for r in dump.get("ring", [])
                  if isinstance(r, dict) and r.get("kind") == "step"]
    out.update({
        "reason": dump.get("reason"),
        "exception_type": (dump.get("exception") or {}).get("type"),
        "ring_records": len(dump.get("ring", [])),
        "ring_step_records": len(ring_steps),
        "last_ring_step": ring_steps[-1].get("step") if ring_steps else None,
        "hbm_snapshot": isinstance(dump.get("hbm"), dict)
        and dump["hbm"].get("count", 0) > 0,
        "scaler_state": (dump.get("scaler") or {}).get("loss_scale"),
        "last_op": (dump.get("last_op") or {}).get("op")
        if isinstance(dump.get("last_op"), dict) else None,
        "strict_json": True,  # flight_mod.load parsed it with json.loads
    })
    out["ok"] = bool(
        proc.returncode != 0
        and dump.get("reason") == "unhandled_exception"
        and out["exception_type"] == "RuntimeError"
        and out["ring_step_records"] >= 5
        and out["last_ring_step"] == 5
        and out["hbm_snapshot"]
        and out["scaler_state"] == 2.0 ** 16
        and isinstance(out["last_op"], str)
        and out["last_op"].startswith("fetch:loss"))
    return out


# ---------------------------------------------------------------------------
# (c) seeded journals: exactly the loss-spike rule / zero alerts
# ---------------------------------------------------------------------------


def _write_run(path: str, *, spike_at=None, steps=16) -> None:
    from apex_tpu.monitor.health import HealthMonitor
    from apex_tpu.monitor.journal import MetricsJournal

    with MetricsJournal(path, health=HealthMonitor()) as j:
        for step in range(steps):
            loss = 2.0 - 0.01 * step
            if spike_at is not None and step == spike_at:
                loss = 40.0
            j.log({"kind": "step", "step": step, "wall_s": 0.1,
                   "loss": loss, "tokens": 1024, "tokens_per_sec": 1000.0,
                   "overflows": 0, "grad_norm": 1.0,
                   "loss_scale": 2.0 ** 16})


def check_health_rules(clean_path: str, spiked_path: str) -> dict:
    from apex_tpu.monitor import health as health_mod
    from apex_tpu.monitor.journal import MetricsJournal

    _write_run(clean_path)
    _write_run(spiked_path, spike_at=12)
    clean = MetricsJournal.read(clean_path)
    spiked = MetricsJournal.read(spiked_path)
    clean_alerts = health_mod.scan(clean)
    spiked_alerts = health_mod.scan(spiked)
    journaled = [r for r in spiked if r.get("kind") == "alert"]
    out = {
        "clean_alerts": len(clean_alerts),
        "spiked_alert_rules": sorted({a["rule"] for a in spiked_alerts}),
        "spiked_alerts": len(spiked_alerts),
        "online_journaled_alerts": len(journaled),
        "online_rule": journaled[0]["rule"] if journaled else None,
    }
    out["ok"] = bool(
        not clean_alerts
        and out["spiked_alert_rules"] == ["loss-spike"]
        and len(spiked_alerts) == 1
        # the ONLINE wiring (MetricsJournal(health=...)) fired the same
        # single rule as the offline scan — one predicate, two surfaces
        and len(journaled) == 1 and out["online_rule"] == "loss-spike")
    return out


# ---------------------------------------------------------------------------
# (d) the --max-alerts gate
# ---------------------------------------------------------------------------


def check_gate(clean_path: str, spiked_path: str) -> dict:
    import contextlib
    import io

    from apex_tpu.monitor import report

    with contextlib.redirect_stdout(io.StringIO()):
        gated = report.main(["compare", clean_path, spiked_path,
                             "--max-alerts", "0"])
        self_ok = report.main(["compare", spiked_path, spiked_path,
                               "--max-alerts", "0"])
        ungated = report.main(["compare", clean_path, spiked_path])
    out = {"spiked_vs_clean_rc": gated, "self_compare_rc": self_ok,
           "without_flag_rc": ungated}
    out["ok"] = bool(gated == 1 and self_ok == 0 and ungated == 0)
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=os.path.join("out",
                                                    "flight_evidence.json"))
    p.add_argument("--stall-timeout", type=float, default=20.0,
                   help="stall kill for the hang child (must exceed the "
                        "child's jax import time on this host)")
    args = p.parse_args()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass

    d = tempfile.mkdtemp(prefix="flight_ev_cd_")
    clean_path = os.path.join(d, "clean.jsonl")
    spiked_path = os.path.join(d, "spiked.jsonl")
    record = {"evidence": "flight recorder / hang attribution / health "
                          "rules / --max-alerts gate (ISSUE 14)"}
    record["hang_attribution"] = check_hang_attribution(args.stall_timeout)
    record["crash_dump"] = check_crash_dump()
    record["health_rules"] = check_health_rules(clean_path, spiked_path)
    record["max_alerts_gate"] = check_gate(clean_path, spiked_path)
    record["ok"] = all(record[k]["ok"] for k in
                       ("hang_attribution", "crash_dump", "health_rules",
                        "max_alerts_gate"))
    print(json.dumps(record))
    atomic_write_json(args.output, record)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
