"""Run-ledger + cost-model calibration evidence (ISSUE 16).

Executable off-TPU proof that the longitudinal layer does what it
claims, as one JSON artifact (``out/ledger_evidence.json``, ok:true):

(a) **110M predicted-vs-measured record** — a ledger record for the
    pinned 110M-class dense config (``lint.audit.HBM_CHECK_CONFIG``)
    joins the static-hbm pass's peak-bytes estimate against
    ``monitor.hbm``'s analytic figure (the audit ``--hbm-check``
    comparison, persisted) and a counted 1F1B plan's bubble fraction
    against the analytic floor: ``calibrate.join`` must land the hbm
    ratio within the audit gate's own band and the bubble ratio within
    3% of the floor;
(b) **regress gate** — a seeded fingerprint history passes its own
    trajectory (rc 0) and a 30% throughput drop exits non-zero naming
    ``tokens_per_sec_p50``, through ``report``'s shared predicates;
(c) **calibration loop** — ``calibrate.fit`` recovers hand-planted
    effective peak constants exactly, the file round-trips, and ARMED
    (``APEX_TPU_CALIBRATION``) it outranks a hand-typed
    ``APEX_TPU_PEAK_FLOPS`` lie in ``mfu.peak_spec``/``tracing.ici_spec``
    with ``source="calibrated"``; disarmed, nothing changes;
(d) **harness round-trip** — a real (tiny) ``pretrain_gpt --ledger
    --journal`` run in a fresh process appends one ``kind="run"`` record
    whose fingerprint matches the journal's own ``kind="meta"`` header,
    carrying both the measured rollup and the predicted block.

    JAX_PLATFORMS=cpu python benchmarks/ledger_evidence.py

Artifacts write atomically (``utils/io.py``).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# (a) the 110M-class predicted-vs-measured record
# ---------------------------------------------------------------------------


def check_110m_record() -> dict:
    from apex_tpu.lint import audit
    from apex_tpu.monitor import calibrate, ledger, tracing
    from apex_tpu.transformer.pipeline_parallel import plan_schedule

    # the static-vs-analytic HBM comparison the audit gate already pins,
    # here persisted as one ledger record's predicted/measured pair
    cross = audit.hbm_crosscheck(materialize=False)
    # counted-plan bubble (schedule-as-data: the plan IS the measurement)
    # against the analytic floor
    M, S = 8, 4
    counted = plan_schedule("1f1b", M, S).bubble_fraction()
    floor = tracing.expected_bubble_fraction("1f1b", M, S)

    d = tempfile.mkdtemp(prefix="ledger_ev_a_")
    path = os.path.join(d, "ledger.jsonl")
    rec = ledger.append_run(
        path, run="evidence-110m",
        config=dict(audit.HBM_CHECK_CONFIG, run="evidence-110m"),
        measured={"step_records": 1,
                  "hbm": {"peak_bytes": cross["reference_bytes"]},
                  "timeline": {"bubble_fraction": {"p50": counted}}},
        predicted={"hbm_peak_bytes": cross["estimated_peak_bytes"],
                   "bubble_floor": floor})
    j = calibrate.join(ledger.read(path)[0])
    out = {
        "config": audit.HBM_CHECK_CONFIG,
        "static_hbm_estimate_bytes": cross["estimated_peak_bytes"],
        "analytic_hbm_bytes": cross["reference_bytes"],
        "hbm_ratio": j.get("hbm_ratio"),
        "hbm_band": [round(1.0 / cross["bound"], 3), cross["bound"]],
        "counted_bubble": counted,
        "bubble_floor": floor,
        "bubble_ratio": j.get("bubble_ratio"),
        "fingerprint": rec["fingerprint"],
    }
    out["ok"] = bool(
        isinstance(j.get("hbm_ratio"), float)
        and 1.0 / cross["bound"] <= j["hbm_ratio"] <= cross["bound"]
        and isinstance(j.get("bubble_ratio"), float)
        and abs(j["bubble_ratio"] - 1.0) <= 0.03
        and rec["env"].get("python"))
    return out


# ---------------------------------------------------------------------------
# (b) the N-run regress gate
# ---------------------------------------------------------------------------


def check_regress_gate() -> dict:
    from apex_tpu.monitor import ledger

    d = tempfile.mkdtemp(prefix="ledger_ev_b_")
    path = os.path.join(d, "ledger.jsonl")

    def rec(rate):
        return {"kind": "run", "run": "evidence", "config": {"tp": 2},
                "fingerprint": ledger.config_fingerprint({"tp": 2}),
                "measured": {"step_records": 8,
                             "tokens_per_sec": {"p50": rate},
                             "wall_s": {"p50": 0.1}}}

    for _ in range(4):
        ledger.append(path, rec(1000.0))
    with contextlib.redirect_stdout(io.StringIO()):
        self_rc = ledger.main(["regress", path])
    ledger.append(path, rec(700.0))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        drop_rc = ledger.main(["regress", path, "--format", "json"])
    verdict = json.loads(buf.getvalue())
    out = {"self_history_rc": self_rc, "seeded_drop_rc": drop_rc,
           "regressed": verdict["regressed"],
           "history_runs": verdict["a"]["runs"]}
    out["ok"] = bool(self_rc == 0 and drop_rc == 1
                     and verdict["regressed"] == ["tokens_per_sec_p50"])
    return out


# ---------------------------------------------------------------------------
# (c) fit → save → armed precedence over the env knobs
# ---------------------------------------------------------------------------


def check_calibration_loop() -> dict:
    from apex_tpu.monitor import calibrate, ledger, mfu, tracing

    d = tempfile.mkdtemp(prefix="ledger_ev_c_")
    path = os.path.join(d, "ledger.jsonl")
    # hand-planted signal: 2e11 flops / 0.1 s wall → 2e12 FLOP/s exactly
    for _ in range(3):
        ledger.append(path, {
            "kind": "run", "run": "evidence", "config": {"tp": 2},
            "measured": {"step_records": 8,
                         "tokens_per_sec": {"p50": 1000.0},
                         "wall_s": {"p50": 0.1}},
            "predicted": {"flops_per_step": 2e11, "bytes_per_step": 1e10}})
    fit = calibrate.fit(ledger.read(path))
    cal_path = calibrate.save(os.path.join(d, "cal.json"), fit)
    out = {"fitted_peak_flops": fit.get("peak_flops"),
           "fitted_peak_hbm": fit.get("peak_hbm_bytes_per_sec"),
           "n_records": fit.get("n_records")}
    saved = {k: os.environ.pop(k, None)
             for k in ("APEX_TPU_PEAK_FLOPS", "APEX_TPU_PEAK_ICI_GBPS",
                       calibrate.ENV_CALIBRATION)}
    try:
        os.environ["APEX_TPU_PEAK_FLOPS"] = "9e99"  # the hand-typed lie
        os.environ[calibrate.ENV_CALIBRATION] = cal_path
        spec = mfu.peak_spec("tpu v4")
        ici = tracing.ici_spec()
        out["armed_peak_flops"] = spec["peak_flops"]
        out["armed_source"] = spec["source"]
        out["armed_ici_source"] = ici["source"]
        armed_ok = (spec["peak_flops"] == fit["peak_flops"]
                    and "calibrated" in spec["source"])
        del os.environ[calibrate.ENV_CALIBRATION]
        spec2 = mfu.peak_spec("tpu v4")
        out["disarmed_peak_flops"] = spec2["peak_flops"]
        out["disarmed_source"] = spec2["source"]
        disarmed_ok = (spec2["peak_flops"] == 9e99
                       and "calibrated" not in spec2["source"])
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    out["ok"] = bool(fit.get("peak_flops") == 2e12
                     and fit.get("peak_hbm_bytes_per_sec") == 1e11
                     and armed_ok and disarmed_ok)
    return out


# ---------------------------------------------------------------------------
# (d) the real harness appends a matching record
# ---------------------------------------------------------------------------


def check_harness_round_trip() -> dict:
    from apex_tpu.monitor import ledger
    from apex_tpu.monitor.journal import MetricsJournal

    d = tempfile.mkdtemp(prefix="ledger_ev_d_")
    jpath = os.path.join(d, "run.jsonl")
    lpath = os.path.join(d, "ledger.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip(),
               PYTHONPATH=os.pathsep.join(
                   [REPO] + os.environ.get("PYTHONPATH", "").split(
                       os.pathsep)))
    env.pop("APEX_TPU_LEDGER", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "gpt",
                                      "pretrain_gpt.py"),
         "--hidden", "32", "--layers", "2", "--heads", "4",
         "--vocab", "128", "--seq", "32", "--steps", "3",
         "--journal", jpath, "--ledger", lpath],
        env=env, capture_output=True, text=True, timeout=600)
    out = {"harness_rc": proc.returncode}
    if proc.returncode != 0:
        out["stderr_tail"] = (proc.stderr or "")[-500:]
        out["ok"] = False
        return out
    rows = ledger.read(lpath)
    runs = [r for r in rows if r.get("kind") == "run"]
    meta = next((r for r in MetricsJournal.read(jpath)
                 if r.get("kind") == "meta"), {})
    rec = runs[-1] if runs else {}
    out.update({
        "run_records": len(runs),
        "fingerprint": rec.get("fingerprint"),
        "journal_meta_fingerprint": meta.get("fingerprint"),
        "measured_steps": (rec.get("measured") or {}).get("step_records"),
        "predicted_keys": sorted((rec.get("predicted") or {})),
    })
    out["ok"] = bool(
        len(runs) == 1
        and rec.get("fingerprint")
        and rec["fingerprint"] == meta.get("fingerprint")
        and (rec.get("measured") or {}).get("step_records") == 3
        and "modeled_step_s" in (rec.get("predicted") or {}))
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=os.path.join("out",
                                                    "ledger_evidence.json"))
    args = p.parse_args()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass

    record = {"evidence": "run ledger + cost-model calibration "
                          "(ISSUE 16)"}
    record["record_110m"] = check_110m_record()
    record["regress_gate"] = check_regress_gate()
    record["calibration_loop"] = check_calibration_loop()
    record["harness_round_trip"] = check_harness_round_trip()
    record["ok"] = all(record[k]["ok"] for k in
                       ("record_110m", "regress_gate", "calibration_loop",
                        "harness_round_trip"))
    print(json.dumps(record))
    atomic_write_json(args.output, record)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
