"""Auto-parallelism planner evidence (ISSUE 18).

Executable off-TPU proof that the static placement search picks right
and that its cost model closes against a measured run, as one JSON
artifact (``out/plan_evidence.json``, ok:true):

(a) **three blind picks** — the planner, given only shape + mesh + HBM
    budget, reproduces decisions this repo earned empirically:

    - 2.7B on 8 ranks under 16 GiB → ZeRO-3 (replicated AND ZeRO-1/2
      carry ``static-hbm`` rejection provenance — the gpt_scaling
      placement-rung verdict, now searched not hand-checked);
    - 345M pinned at pp=4 → the zero-bubble schedule outranks
      interleaved and 1F1B on modeled step seconds via its lower
      analytic bubble floor;
    - 345M at dp=8/ZeRO-2 → fp32 wire on the default ICI table (int8
      rejected ``wire-not-binding``, the EQuARX deployment rule), int8
      wire once ``APEX_TPU_PEAK_ICI_GBPS`` narrows the modeled wire to
      where comm binds;

(b) **110M analytic join** — the planner's ZeRO-3 residency columns for
    the pinned 110M-class shape equal ``monitor.hbm.param_state_report``
    (same bytes, two independent code paths — the no-drift claim);

(c) **calibration closure** — a real (tiny) ``pretrain_gpt --plan auto
    --ledger --journal`` run in a fresh process adopts the planner's
    winner and appends a ledger record carrying the planner's predicted
    block; ``ledger calibrate`` fits effective peak constants from that
    record; ARMED (``APEX_TPU_CALIBRATION``), re-scoring the SAME winner
    resolves ``source="calibrated"`` specs and lands modeled step
    seconds within [0.25, 4]x of the measured wall p50 AND strictly
    tighter than the uncalibrated model (~100x off on this backend: the
    CPU table's peak is not this container's) — the planner's clock
    closes the loop against its own run. The band is loose because the
    8-rank mesh is virtual (every "rank" shares 2 host cores, so the
    per-rank flop division is fictional); on hardware the same closure
    rides ``ledger regress``.

    JAX_PLATFORMS=cpu python benchmarks/plan_evidence.py

Artifacts write atomically (``utils/io.py``).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

#: env knobs that would skew the blind picks if a shell left them set
_PEAK_ENV = ("APEX_TPU_PEAK_FLOPS", "APEX_TPU_PEAK_HBM_GBPS",
             "APEX_TPU_PEAK_ICI_GBPS", "APEX_TPU_CALIBRATION")


@contextlib.contextmanager
def _clean_peak_env(**overrides):
    saved = {k: os.environ.pop(k, None) for k in _PEAK_ENV}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k in overrides:
            os.environ.pop(k, None)
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# (a) three blind picks
# ---------------------------------------------------------------------------


def check_blind_picks() -> dict:
    from apex_tpu import plan as plan_mod

    out: dict = {}
    with _clean_peak_env():
        # pick 1: the placement-rung verdict, searched
        r = plan_mod.search("gpt-2.7b", mesh=8, hbm_gb=16.0)
        w = r["winner"]["candidate"]
        hbm_rej = [x for x in r["rejected"]
                   if x.get("rejected_by") == "static-hbm"
                   and x["candidate"].get("dp") == 8]
        rej_levels = sorted({x["candidate"]["zero_level"] for x in hbm_rej})
        out["pick_27b"] = {
            "winner": {k: w[k] for k in ("dp", "tp", "pp", "zero_level",
                                         "zero3_prefetch", "unroll")},
            "dp8_static_hbm_rejected_zero_levels": rej_levels,
            "ok": bool(w["zero_level"] == 3 and 0 in rej_levels
                       and 2 in rej_levels),
        }

        # pick 2: the schedule ladder at a pinned pp
        r2 = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                             num_microbatches=4, constraints={"pp": 4})
        best: dict = {}
        for rec in r2["ranked"]:
            s = rec["candidate"]["schedule"]
            best.setdefault(s, rec["predicted"]["step_seconds"])
        ws = r2["winner"]["candidate"]["schedule"]
        out["pick_zerobubble"] = {
            "winner_schedule": ws,
            "best_step_seconds_by_schedule":
                {k: round(v, 4) for k, v in best.items()},
            "winner_bubble_floor":
                r2["winner"]["predicted"]["bubble_floor"],
            "ok": bool(ws == "zerobubble"
                       and best["zerobubble"] < best["interleaved"]
                       and best["zerobubble"] < best["1f1b"]),
        }

        # pick 3, default wire: int8 rejected wire-not-binding
        r3 = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                             constraints={"dp": 8, "zero_level": 2})
        wnb = [x for x in r3["rejected"]
               if x.get("rejected_by") == "wire-not-binding"]
        default_rd = r3["winner"]["candidate"]["reduce_dtype"]

    # pick 3, narrowed wire: the SAME search flips to int8
    with _clean_peak_env(APEX_TPU_PEAK_ICI_GBPS="0.001"):
        r4 = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                             constraints={"dp": 8, "zero_level": 2})
        narrow_rd = r4["winner"]["candidate"]["reduce_dtype"]
    out["pick_int8_wire"] = {
        "default_winner_reduce_dtype": default_rd,
        "default_wire_not_binding_rejections": len(wnb),
        "narrowed_winner_reduce_dtype": narrow_rd,
        "ok": bool(default_rd is None and len(wnb) >= 1
                   and narrow_rd == "int8"),
    }
    out["ok"] = all(out[k]["ok"] for k in
                    ("pick_27b", "pick_zerobubble", "pick_int8_wire"))
    return out


# ---------------------------------------------------------------------------
# (b) the 110M analytic join: planner residency == monitor.hbm
# ---------------------------------------------------------------------------


def check_110m_join() -> dict:
    from apex_tpu import plan as plan_mod
    from apex_tpu.monitor.hbm import param_state_report

    spec = plan_mod.MODEL_PRESETS["gpt-110m"]
    report = param_state_report(plan_mod.abstract_params(spec), 8)
    with _clean_peak_env():
        rec = plan_mod.score_candidate(
            spec, plan_mod.Candidate(dp=8, zero_level=3,
                                     gather_dtype="bf16", unroll=True))
    res = rec["predicted"]["hbm"]["residency"]
    z3 = report["per_rank"]["zero3"]
    out = {
        "planner_param_bytes": res["param_bytes"],
        "planner_opt_bytes": res["opt_bytes"],
        "report_param_bytes": z3["param_bytes"],
        "report_opt_bytes": z3["opt_bytes"],
        "planner_total_with_activations": rec["predicted"]["hbm_bytes"],
    }
    out["ok"] = bool(res["param_bytes"] == z3["param_bytes"]
                     and res["opt_bytes"] == z3["opt_bytes"])
    return out


# ---------------------------------------------------------------------------
# (c) calibration closure through a real --plan auto run
# ---------------------------------------------------------------------------

#: the tiny shape the closure executes (CPU-feasible in seconds; big
#: enough that the matmul-dominated flop model is not pure noise)
_CLOSURE_SHAPE = dict(vocab=512, hidden=64, layers=4, heads=4, seq=64)
_CLOSURE_STEPS = 5
_WALL_RATIO_BAND = (0.25, 4.0)


def check_calibration_closure() -> dict:
    from apex_tpu import plan as plan_mod
    from apex_tpu.monitor import ledger

    d = tempfile.mkdtemp(prefix="plan_ev_c_")
    jpath = os.path.join(d, "run.jsonl")
    lpath = os.path.join(d, "ledger.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip(),
               PYTHONPATH=os.pathsep.join(
                   [REPO] + os.environ.get("PYTHONPATH", "").split(
                       os.pathsep)))
    for k in _PEAK_ENV + ("APEX_TPU_LEDGER",):
        env.pop(k, None)
    sh = _CLOSURE_SHAPE
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "gpt",
                                      "pretrain_gpt.py"),
         "--plan", "auto",
         "--hidden", str(sh["hidden"]), "--layers", str(sh["layers"]),
         "--heads", str(sh["heads"]), "--vocab", str(sh["vocab"]),
         "--seq", str(sh["seq"]), "--steps", str(_CLOSURE_STEPS),
         "--journal", jpath, "--ledger", lpath],
        env=env, capture_output=True, text=True, timeout=600)
    out: dict = {"harness_rc": proc.returncode}
    if proc.returncode != 0:
        out["stderr_tail"] = (proc.stderr or "")[-500:]
        out["ok"] = False
        return out
    plan_line = next((json.loads(ln) for ln in proc.stdout.splitlines()
                      if ln.startswith('{"plan"')), {})
    rec = [r for r in ledger.read(lpath) if r.get("kind") == "run"][-1]
    wall = ((rec.get("measured") or {}).get("wall_s") or {}).get("p50")
    out["adopted_winner"] = (plan_line.get("plan") or {}).get("winner")
    out["uncalibrated_modeled_s"] = \
        (rec.get("predicted") or {}).get("modeled_step_s")
    out["measured_wall_p50_s"] = wall

    cal_path = os.path.join(d, "cal.json")
    with contextlib.redirect_stdout(io.StringIO()):
        cal_rc = ledger.main(["calibrate", lpath, "--output", cal_path])
    out["calibrate_rc"] = cal_rc
    if cal_rc != 0 or not out["adopted_winner"] or not wall:
        out["ok"] = False
        return out

    spec = plan_mod.ModelSpec("pretrain_gpt", sh["vocab"], sh["hidden"],
                              sh["layers"], sh["heads"], sh["seq"])
    cand = plan_mod.Candidate(**out["adopted_winner"])
    with _clean_peak_env(APEX_TPU_CALIBRATION=cal_path):
        from apex_tpu.monitor import mfu, tracing

        peak = mfu.peak_spec()
        ici = tracing.ici_spec()
        scored = plan_mod.score_candidate(spec, cand, peak=peak, ici=ici)
    import math

    cal_s = scored["predicted"]["step_seconds"]
    ratio = cal_s / wall
    uncal_ratio = out["uncalibrated_modeled_s"] / wall
    out.update({
        "calibrated_peak_source": peak.get("source"),
        "calibrated_ici_source": ici.get("source"),
        "calibrated_modeled_s": cal_s,
        "uncalibrated_wall_ratio": round(uncal_ratio, 6),
        "wall_ratio": round(ratio, 4),
        "wall_ratio_band": list(_WALL_RATIO_BAND),
    })
    out["ok"] = bool("calibrated" in str(peak.get("source"))
                     and _WALL_RATIO_BAND[0] <= ratio
                     <= _WALL_RATIO_BAND[1]
                     and abs(math.log(ratio))
                     < abs(math.log(uncal_ratio)))
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=os.path.join("out",
                                                    "plan_evidence.json"))
    args = p.parse_args()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()

    record = {"evidence": "auto-parallelism planner: blind picks + "
                          "calibration closure (ISSUE 18)"}
    record["blind_picks"] = check_blind_picks()
    record["join_110m"] = check_110m_join()
    record["calibration_closure"] = check_calibration_closure()
    record["ok"] = all(record[k]["ok"] for k in
                       ("blind_picks", "join_110m",
                        "calibration_closure"))
    print(json.dumps(record))
    atomic_write_json(args.output, record)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
