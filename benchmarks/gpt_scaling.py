"""GPT scaling harness (reference: tests/L0/run_transformer/gpt_scaling_test.py:49-70).

The reference sweeps (dp, tp, pp) in {(8,1,1), (4,2,1), (2,1,4), (1,2,4)} over
8 GPUs, growing layer counts, parsing "Average Iteration Time" from each
subprocess — a throughput regression harness. Here each configuration runs
in-process on the mesh (virtual CPU devices in CI, real chips on a pod) and
the harness prints one JSON line per config:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/gpt_scaling.py --steps 3 --hidden 128 --layers 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives, mesh as mesh_lib
from apex_tpu.parallel.distributed import (
    allreduce_gradients,
    allreduce_gradients_by_spec,
)
from apex_tpu.transformer import tensor_parallel as tp_mod
from apex_tpu.transformer.pipeline_parallel import pipeline_specs, pipelined_loss_fn

# the reference grid, gpt_scaling_test.py:52
GRID = [(8, 1, 1), (4, 2, 1), (2, 1, 4), (1, 2, 4)]


def run_config(dp, tp, pp, *, hidden, layers, heads, vocab, seq,
               micro_batch, n_micro, steps):
    n_dev = dp * tp * pp
    if len(jax.devices()) < n_dev:
        return None
    mesh = mesh_lib.make_virtual_mesh(
        n_dev, tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)
    try:
        cfg = GPTConfig(
            vocab_size=vocab, hidden_size=hidden,
            num_layers=max(layers, pp) // pp * pp,
            num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
            axis=mesh_lib.AXIS_MODEL if tp > 1 else None,
            compute_dtype=jnp.bfloat16, remat=True,
        )
        model = GPTModel(cfg)
        policy = amp.get_policy("O2")
        mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-4), policy)
        full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        all_specs = model.specs()
        specs = dict(
            {k: v for k, v in all_specs.items() if k != "layers"},
            layers=pipeline_specs(all_specs["layers"]),
        )
        params = tp_mod.shard_params(full, specs, mesh)
        opt_state = mp_opt.init(params)
        rest_specs = {k: v for k, v in all_specs.items() if k != "layers"}
        grad_axes = mesh_lib.get_gradient_reduction_axes()
        pipe_loss = pipelined_loss_fn(
            embed=model.embed,
            run_layers=lambda lp, h: model.run_layers(lp, h),
            head_loss=lambda p, h, t: model.head(p, h, t),
            num_microbatches=n_micro,
        )
        data_spec = P(mesh_lib.AXIS_DATA)

        def sharded_grads(p, toks, tgts, scale):
            rest = {k: v for k, v in p.items() if k != "layers"}

            def scaled_loss(rest, layers):
                return pipe_loss(rest, layers, toks, tgts) * scale

            loss, (rg, lg) = jax.value_and_grad(scaled_loss, argnums=(0, 1))(
                rest, p["layers"])
            rg = allreduce_gradients_by_spec(rg, rest_specs)
            lg = allreduce_gradients(lg, grad_axes)
            return collectives.pmean(loss, grad_axes), dict(rg, layers=lg)

        shard_fn = jax.shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(specs, data_spec, data_spec, P()),
            out_specs=(P(), specs), check_vma=False)

        @jax.jit
        def train_step(params, opt_state, tokens, targets):
            sl, sg = shard_fn(params, tokens, targets, opt_state.scaler.loss_scale)
            np_, ns, m = mp_opt.apply_gradients(opt_state, params, sg)
            return np_, ns, sl / opt_state.scaler.loss_scale, m

        batch = micro_batch * dp * n_micro
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, vocab, (batch, seq)))
        tgts = jnp.roll(toks, -1, axis=-1)
        shard = lambda a: jax.device_put(a, NamedSharding(mesh, data_spec))
        toks, tgts = shard(toks), shard(tgts)

        params, opt_state, loss, _ = train_step(params, opt_state, toks, tgts)
        float(loss)  # compile + execute barrier
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss, _ = train_step(params, opt_state, toks, tgts)
        loss_val = float(loss)  # host fetch forces the whole chain
        dt = (time.perf_counter() - t0) / steps
        return {
            "config": {"dp": dp, "tp": tp, "pp": pp},
            "avg_iteration_time_s": round(dt, 4),
            "tokens_per_sec": round(batch * seq / dt, 1),
            "loss": round(loss_val, 4),
        }
    finally:
        mesh_lib.destroy_model_parallel()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--micro-batch", type=int, default=1)
    p.add_argument("--num-microbatches", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    args = p.parse_args()
    for dp, tp, pp in GRID:
        res = run_config(
            dp, tp, pp, hidden=args.hidden, layers=args.layers,
            heads=args.heads, vocab=args.vocab, seq=args.seq,
            micro_batch=args.micro_batch, n_micro=args.num_microbatches,
            steps=args.steps)
        if res is None:
            print(json.dumps({"config": {"dp": dp, "tp": tp, "pp": pp},
                              "skipped": "not enough devices"}))
        else:
            print(json.dumps(res))


if __name__ == "__main__":
    main()
