"""GPT scaling harness (reference: tests/L0/run_transformer/gpt_scaling_test.py:49-70).

The reference sweeps (dp, tp, pp) in {(8,1,1), (4,2,1), (2,1,4), (1,2,4)} over
8 GPUs, growing layer counts, parsing "Average Iteration Time" from each
subprocess — a throughput regression harness. Here each configuration runs
in-process on the mesh (virtual CPU devices in CI, real chips on a pod) and
the harness prints one JSON line per config:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/gpt_scaling.py --steps 3 --hidden 128 --layers 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives, mesh as mesh_lib
from apex_tpu.parallel.distributed import (
    allreduce_gradients,
    allreduce_gradients_by_spec,
)
from apex_tpu.transformer.pipeline_parallel import prepare_pipelined_model

# the reference grid, gpt_scaling_test.py:52 — extended with one
# context-parallel config (dp, tp, pp, cp): ring-attention sequence
# sharding is this framework's beyond-reference axis and belongs in the
# round-over-round scaling record. Trailing string markers: "sp" =
# Megatron-style sequence parallelism on the TP axis
# (GPTConfig.sequence_parallel), "zero" = ZeRO-sharded optimizer over the
# data axis (amp.MixedPrecisionOptimizer(zero_axis="data") with a bf16-
# compressed param gather), "zero3" = fully-sharded params on top
# (zero_level=3: the bf16 model persists as 1/dp chunk trees with
# per-layer just-in-time weight gathers in the layer loop), "zero-q8" =
# the ZeRO row with the grad reduce-scatter quantized to an int8 wire
# (reduce_dtype="int8": encoded all_to_all + per-chunk fp32 scales +
# error-feedback residual, parallel/quantize.py — the row's
# comm_bytes_by_verb_dtype block shows the 1/4-bytes wire next to the
# fp32 twin), "zb" = the zero-bubble schedule engine (schedules.
# plan_schedule("zero-bubble") interpreted by schedule_grads_fn: explicit
# W/B-split backward slots instead of the AD-transposed ring; the row's
# timeline block carries the (S-1)/(3M+S-1) floor next to the 1f1b twin's
# (S-1)/(M+S-1)), "moe" = expert-parallel MoE FFNs (2*dp experts sharded
# over the data axis, all_to_all token dispatch booked per wire dtype in
# comm_bytes_by_verb_dtype; the row's moe block carries the capacity/
# placement arithmetic and the measured dropped fraction), "moe-q8" = the
# same row with the dispatch wire quantized to int8
# (GPTConfig.moe_dispatch_dtype — the dispatch rows in
# comm_bytes_by_verb_dtype land at exactly 1/4 the fp32 twin's bytes).
# Each marked config records its comm/static-hazard blocks next to the
# plain twin so the decomposed-collective structure shows up in
# scaling_table.json.
GRID = [(8, 1, 1), (8, 1, 1, 1, "zero"), (8, 1, 1, 1, "zero-q8"),
        (8, 1, 1, 1, "zero3"), (4, 2, 1),
        (8, 1, 1, 1, "moe"), (8, 1, 1, 1, "moe-q8"),
        (4, 2, 1, 1, "sp"), (2, 1, 4), (4, 1, 2, 1, "zb"),
        (1, 2, 4), (2, 1, 2, 2)]


def run_config(dp, tp, pp, cp=1, *, hidden, layers, heads, vocab, seq,
               micro_batch, n_micro, steps, sequence_parallel=False,
               zero=False, zero_level=None, reduce_dtype=None,
               pp_schedule="1f1b", moe=False, moe_dispatch_dtype=None):
    n_dev = dp * tp * pp * cp
    if len(jax.devices()) < n_dev:
        return None
    zero_level = zero_level or (2 if zero or reduce_dtype else 0)
    zero = zero_level > 0
    if pp_schedule == "zerobubble" and (tp > 1 or cp > 1 or zero or pp < 2):
        raise ValueError(
            "the zb grid row drives the pipe axis only (tp=1, cp=1, "
            "zero off, pp>1)")
    mesh = mesh_lib.make_virtual_mesh(
        n_dev, tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp,
        context_parallel_size=cp)
    try:
        # layer count must divide by pp for the stage shards; record the
        # effective value so ramped sweeps are labeled with what actually ran
        eff_layers = max(layers, pp) // pp * pp
        moe_kwargs = {}
        if moe:
            # the standard MoE mapping: experts shard over the data axis
            # (token shards ARE the expert shards, transformer/moe.py)
            moe_kwargs = dict(
                moe_num_experts=2 * dp, moe_top_k=2,
                moe_capacity_factor=1.25,
                moe_expert_axis=mesh_lib.AXIS_DATA if dp > 1 else None,
                moe_dispatch_dtype=moe_dispatch_dtype)
        cfg = GPTConfig(
            vocab_size=vocab, hidden_size=hidden,
            num_layers=eff_layers,
            num_attention_heads=heads, max_seq_len=seq, hidden_dropout=0.0,
            axis=mesh_lib.AXIS_MODEL if tp > 1 else None,
            sequence_parallel=sequence_parallel and tp > 1,
            context_axis=mesh_lib.AXIS_CONTEXT if cp > 1 else None,
            compute_dtype=jnp.bfloat16, remat=True,
            **moe_kwargs,
        )
        model = GPTModel(cfg)
        policy = amp.get_policy("O2")
        mp_opt = amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-4), policy,
            zero_axis=mesh_lib.AXIS_DATA if zero else None,
            zero_level=zero_level or 2,
            gather_dtype="bf16" if zero else None,
            reduce_dtype=reduce_dtype if zero else None)
        full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        # shared TP x PP wiring (specs, placement, pipelined loss;
        # with_aux threads MoE router losses through the ring)
        specs, params, pipe_loss = prepare_pipelined_model(
            model, full, mesh, num_microbatches=n_micro, with_aux=moe)
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        grad_axes = mesh_lib.get_gradient_reduction_axes()
        data_spec = P(mesh_lib.AXIS_DATA,
                      mesh_lib.AXIS_CONTEXT if cp > 1 else None)

        zb_vg = None
        if pp_schedule == "zerobubble":
            # the zero-bubble schedule engine: explicit W/B-split backward
            # slots via the plan executor, a drop-in for value_and_grad of
            # the pipelined loss (pp-axis only, so the "zb" grid row runs
            # tp=1)
            from apex_tpu.transformer.pipeline_parallel import (
                zero_bubble_grads_fn,
            )

            zb_vg = zero_bubble_grads_fn(model, n_micro, pp)

        def sharded_grads(p, toks, tgts, scale):
            rest = {k: v for k, v in p.items() if k != "layers"}

            if zb_vg is not None:
                loss, rg, lg = zb_vg(rest, p["layers"], toks, tgts, scale)
            else:
                def scaled_loss(rest, layers):
                    return pipe_loss(rest, layers, toks, tgts) * scale

                loss, (rg, lg) = jax.value_and_grad(
                    scaled_loss, argnums=(0, 1))(rest, p["layers"])
            rg = allreduce_gradients_by_spec(rg, rest_specs)
            lg = allreduce_gradients(lg, grad_axes)
            return collectives.pmean(loss, grad_axes), dict(rg, layers=lg)

        if zero_level >= 3:
            # ZeRO-3: the bf16 params persist as 1/dp chunk trees; each
            # layer's weights all-gather just-in-time inside the layer
            # loop and grads reduce-scatter per layer via the gather
            # transposes (no bulk post-update gather — tripwire:
            # lint.trace.zero3_gather_hazards)
            from apex_tpu.transformer.amp import build_zero_train_step

            z3 = mp_opt.zero3_init(params, mesh, specs)
            params, opt_state = z3.params, z3.opt_state
            train_step = build_zero_train_step(
                mp_opt, mesh, None, None, None,
                rest_specs=rest_specs, layer_specs=specs["layers"],
                grad_axes=grad_axes, data_spec=data_spec,
                zero_axis=mesh_lib.AXIS_DATA,
                zero3=z3, model=model, num_microbatches=n_micro)
        elif zero:
            # ZeRO: the sharded optimizer's collectives live inside the
            # step's shard_map; the data axis drops from the harness
            # reduction (the scatter IS it) — the comm_accounting block
            # below then shows psum_scatter + all_gather instead of the
            # data-axis grad psum
            from apex_tpu.transformer.amp import build_zero_train_step

            opt_state, zero_specs = mp_opt.zero_init(params, mesh, specs)
            train_step = build_zero_train_step(
                mp_opt, mesh, specs, zero_specs, pipe_loss,
                rest_specs=rest_specs, grad_axes=grad_axes,
                data_spec=data_spec, zero_axis=mesh_lib.AXIS_DATA)
        else:
            opt_state = mp_opt.init(params)
            shard_fn = jax.shard_map(
                sharded_grads, mesh=mesh,
                in_specs=(specs, data_spec, data_spec, P()),
                out_specs=(P(), specs), check_vma=False)

            @jax.jit
            def train_step(params, opt_state, tokens, targets):
                sl, sg = shard_fn(params, tokens, targets, opt_state.scaler.loss_scale)
                np_, ns, m = mp_opt.apply_gradients(opt_state, params, sg)
                return np_, ns, sl / opt_state.scaler.loss_scale, m

        batch = micro_batch * dp * n_micro
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, vocab, (batch, seq)))
        tgts = jnp.roll(toks, -1, axis=-1)
        shard = lambda a: jax.device_put(a, NamedSharding(mesh, data_spec))
        toks, tgts = shard(toks), shard(tgts)

        # compile-time collective-overlap evidence: real multi-chip runs are
        # impossible in this environment, so multi-chip readiness is argued
        # from the compiled HLO — async collective pairs (*-start/*-done
        # with instructions scheduled between them) are what lets XLA hide
        # the pipeline ring / TP allreduces behind compute on ICI. The
        # comm_accounting context rides the same trace: every collective
        # call site tallies payload bytes per mesh axis (monitor/comms.py).
        from apex_tpu.monitor.comms import comm_accounting

        with comm_accounting() as comm_acct:
            lowered = train_step.lower(params, opt_state, toks, tgts)
        compiled = lowered.compile()
        overlap = _overlap_evidence(compiled)

        params, opt_state, loss, _ = train_step(params, opt_state, toks, tgts)
        float(loss)  # compile + execute barrier
        t0 = time.perf_counter()
        step_losses = []
        for _ in range(steps):
            params, opt_state, loss, _ = train_step(params, opt_state, toks, tgts)
            step_losses.append(loss)  # scalars retained, fetched after
        loss_val = float(loss)  # host fetch forces the whole chain
        dt = (time.perf_counter() - t0) / steps
        conf = {"dp": dp, "tp": tp, "pp": pp, "layers": eff_layers}
        if cp > 1:
            conf["cp"] = cp
        if sequence_parallel and tp > 1:
            conf["sequence_parallel"] = True
        if zero:
            conf["zero"] = True
            conf["zero_level"] = zero_level
        if reduce_dtype:
            conf["reduce_dtype"] = reduce_dtype
        if pp_schedule != "1f1b":
            conf["pp_schedule"] = pp_schedule
        if moe:
            conf["moe"] = True
            if moe_dispatch_dtype:
                conf["moe_dispatch_dtype"] = moe_dispatch_dtype
        row = {
            "config": conf,
            "avg_iteration_time_s": round(dt, 4),
            "tokens_per_sec": round(batch * seq / dt, 1),
            "loss": round(loss_val, 4),
            "overlap": overlap,
            # traced payload bytes per mesh axis (per traced call site —
            # scanned sites count once; see monitor/comms.py)
            "comm_bytes_by_axis": comm_acct.by_axis(),
            # wire-dtype rollup (CommAccount.by_verb_dtype): a quantized
            # reduce's int8 payload and its fp32 scale side-channel land
            # as separate rows — monitor.report rolls these up per run
            "comm_bytes_by_verb_dtype": comm_acct.by_verb_dtype(),
        }
        try:
            # MFU/roofline verdict per config (monitor/mfu.py): cost-model
            # FLOPs+bytes for the compiled step over the measured iteration
            # time, against the platform peak spec. On the CPU virtual mesh
            # this carries source="table:cpu" — a labelled emulation number
            # under the same reading-guide caveat as tokens_per_sec.
            from apex_tpu.monitor import mfu as mfu_lib

            # the jaxpr floor guards the Pallas undercount (the cost
            # model sees zero FLOPs inside the flash-attention
            # custom-calls — 4.15 vs ~17 TFLOP on the 345M step,
            # PERF_NOTES); one extra trace, no compile
            jaxpr_flops = mfu_lib.traced_step_costs(
                train_step, params, opt_state, toks, tgts)["flops"]
            costs = mfu_lib.compiled_step_costs(compiled,
                                                jaxpr_flops=jaxpr_flops)
            row["mfu"] = mfu_lib.mfu_metrics(
                flops=costs["flops"], bytes_accessed=costs["bytes"],
                wall_s=dt, tokens=batch * seq)
            row["mfu"]["flops_method"] = costs["method"]
        except Exception as e:  # noqa: BLE001 - mfu is best-effort evidence
            row["mfu"] = {"error": str(e)[:120]}
        try:
            # step-anatomy timeline per config (monitor/tracing.py): the
            # analytic bubble floor for this pp/M shape plus the measured
            # wall decomposed into compute/exposed-comm/stall fractions
            # (cost-model FLOPs over the peak spec, traced comm bytes
            # over the ICI table — fractions sum to 1.0 by construction)
            # and the modeled comm/compute overlap fraction. Host-side
            # only; the labelled-emulation caveat of the mfu block
            # applies on the CPU virtual mesh.
            from apex_tpu.monitor import tracing as tracing_lib

            tl_sched = ("zero-bubble" if pp_schedule == "zerobubble"
                        else "interleaved")
            tl = {
                "schedule": tl_sched,
                "expected_bubble_fraction": round(
                    tracing_lib.expected_bubble_fraction(
                        tl_sched, n_micro, pp), 4) if pp > 1 else 0.0,
            }
            flops = (row.get("mfu") or {}).get("achieved_tflops")
            tl["anatomy"] = tracing_lib.step_anatomy(
                wall_s=dt,
                flops=(flops * 1e12 * dt) if flops else None,
                comm_bytes=comm_acct.total_bytes())
            row["timeline"] = tl
        except Exception as e:  # noqa: BLE001 - timeline is best-effort
            row["timeline"] = {"error": str(e)[:120]}
        try:
            # health-alert stamp per config (monitor/health.py): the
            # per-step loss trajectory replayed through the SAME
            # streaming rules the journals use, so an unhealthy row
            # (spiking/NaN-ing config) surfaces in scaling_table.json as
            # a nonzero count instead of hiding behind the final loss
            from apex_tpu.monitor import health as health_mod

            step_records = [
                {"kind": "step", "step": i, "loss": float(lv),
                 "tokens_per_sec": batch * seq / dt, "overflows": 0}
                for i, lv in enumerate(step_losses)]
            row["alerts"] = health_mod.summarize(
                health_mod.scan(step_records))
        except Exception as e:  # noqa: BLE001 - health stamp is best-effort
            row["alerts"] = {"error": str(e)[:120]}
        if moe:
            # the capacity/placement story (ISSUE 15): bucket arithmetic
            # (per-shard static dispatch shapes) next to the measured
            # dispatch wire bytes already in comm_bytes_by_verb_dtype —
            # tokens dropped vs padding waste vs wire bytes in one block
            import math

            E = cfg.moe_num_experts
            # the STATIC dispatch shape is per ROUTING CALL: each
            # microbatch's (micro_batch * seq) shard-local tokens route
            # independently (MoEMLP._route reads h2d.shape[0]); per-step
            # aggregates multiply by n_micro explicitly below
            tokens_call = micro_batch * seq
            cap = max(1, math.ceil(cfg.moe_top_k * tokens_call
                                   * cfg.moe_capacity_factor / E))
            wire_itemsize = 1 if moe_dispatch_dtype else 2  # bf16 compute
            row["moe"] = {
                "experts": E, "top_k": cfg.moe_top_k,
                "capacity_factor": cfg.moe_capacity_factor,
                "num_microbatches": n_micro,
                "tokens_per_call": tokens_call,
                "capacity_per_call": cap,
                "bucket_slots_per_call": E * cap,
                "routed_selections_per_call": cfg.moe_top_k * tokens_call,
                "slot_utilization_bound": round(
                    min(1.0, cfg.moe_top_k * tokens_call / (E * cap)), 4),
                "dispatch_wire_dtype": moe_dispatch_dtype or "bf16",
                # analytic per-shard bytes per layer per STEP: dispatch +
                # combine exchanges of the (E, C, h) bucket, once per
                # microbatch
                "dispatch_bytes_per_layer_step": 2 * E * cap * hidden
                * wire_itemsize * n_micro,
            }
        try:
            # static hazard scan per config (apex_tpu/lint/trace.py):
            # lane-padding waste at HBM/custom-call boundaries of THIS
            # step's jaxpr + weak-type/python-scalar signature leaks.
            # Trace-time only — one extra make_jaxpr, no compile.
            from apex_tpu.lint import trace as lint_trace

            row["static_hazards"] = lint_trace.step_report(
                train_step, params, opt_state, toks, tgts)
        except Exception as e:  # noqa: BLE001 - hazard scan is best-effort
            row["static_hazards"] = {"error": str(e)[:120]}
        return row
    finally:
        mesh_lib.destroy_model_parallel()


# per-chip HBM budget the placement rung prices against: 16 GiB, the
# v5e-class part the tunnel chip reports. Placement — not bandwidth — is
# the binding constraint on the co-tenant target (PERF_NOTES r5).
PLACEMENT_HBM_BYTES = 16 * 1024**3


def placement_rung(*, hidden=2560, layers=34, heads=32, vocab=50304,
                   seq=2048, dp=8, hbm_bytes=PLACEMENT_HBM_BYTES):
    """The large-model rung: a 2.7B-class GPT shape whose per-rank bytes
    place under ZeRO-3 but NOT replicated.

    This container cannot *execute* a step at this shape (a 2-core CPU
    would take ~10 min/step), and placement is a bytes argument anyway —
    so the rung prices per ZeRO stage through the PLANNER's scorer
    (``apex_tpu.plan.score_candidate``: the sharded-residency model
    pinned against ``monitor.hbm.param_state_report`` plus the
    activation floor, wire bytes and modeled step seconds — ONE cost
    model shared with ``python -m apex_tpu.plan`` and ``pretrain_gpt
    --plan auto``, no drift), and TRACES the planner's own ZeRO-3
    feasibility program at the full shape (``plan.feasibility_step`` →
    ``lint.trace.zero3_gather_hazards`` on the jaxpr: no allocation, no
    compile) to prove it gathers per layer with no model-sized bulk
    gather — the same program the ``plan`` audit tripwire walks.
    ``param_state_report`` still rides along as the per-stage persistent
    breakdown the table prints.
    """
    from apex_tpu import plan as plan_mod
    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.monitor.hbm import param_state_report

    spec = plan_mod.ModelSpec("gpt-2.7b-rung", vocab, hidden, layers,
                              heads, seq)
    report = param_state_report(plan_mod.abstract_params(spec), dp)
    n_params = report["param_count"]

    stages = {
        "replicated": plan_mod.Candidate(dp=dp),
        "zero12": plan_mod.Candidate(dp=dp, zero_level=2,
                                     gather_dtype="bf16"),
        "zero3": plan_mod.Candidate(dp=dp, zero_level=3,
                                    gather_dtype="bf16"),
    }
    placed, scores = {}, {}
    for stage, cand in stages.items():
        rec = plan_mod.score_candidate(spec, cand, hbm_bytes=hbm_bytes)
        pred = rec["predicted"]
        placed[stage] = bool(rec["feasible"])
        scores[stage] = {
            "feasible": rec["feasible"],
            "rejected_by": rec.get("rejected_by"),
            "hbm_bytes": pred["hbm_bytes"],
            "residency_bytes": pred["hbm"]["residency"]["total_bytes"],
            "comm_bytes_by_tier": pred["comm_bytes_by_tier"],
            "bubble_floor": pred["bubble_floor"],
            "step_seconds": pred["step_seconds"],
        }

    step = plan_mod.feasibility_step(spec, stages["zero3"])
    hz = lint_trace.zero3_gather_hazards(
        step["fn"], *step["args"], axes=step["axes"],
        model_elems=step["model_elems"])

    return {
        "config": {"dp": dp, "tp": 1, "pp": 1, "layers": layers,
                   "hidden": hidden, "heads": heads, "seq": seq,
                   "zero": True, "zero_level": 3, "placement_rung": True},
        "param_count": int(n_params),
        "param_state_report": report,
        "hbm_budget_bytes": int(hbm_bytes),
        "placed": placed,
        "plan_scores": scores,
        "gather_census": {"hazard": hz["hazard"],
                          "layer_gathers": hz["layer_gathers"],
                          "bulk_gathers": hz["bulk_gathers"],
                          "min_model_elems": hz["min_model_elems"]},
        "basis": ("analytic+trace: per-stage pricing from apex_tpu.plan."
                  "score_candidate (sharded residency + activation "
                  "floor), census from lint.trace.zero3_gather_hazards "
                  "on plan.feasibility_step's full-shape jaxpr; this "
                  "container cannot execute a 2.7B-class step"),
    }


def analytic_rung(*, model="gpt-13b", mesh=64,
                  hbm_bytes=PLACEMENT_HBM_BYTES, micro_batch=1,
                  num_microbatches=8, islands=1, platform=None):
    """The planner-generated 13B-class rung: a full placement search at a
    pod-slice mesh this container will never hold (mesh=64 — at mesh=8
    the 13B optimizer chunks alone blow a 16 GiB budget, and 'needs more
    chips' is itself the planner's verdict). Pure analysis — the row
    records the winner's predicted anatomy and the rejection-provenance
    histogram, not a timed run. ``islands > 1`` prices the two-tier pod
    layout per link class (ISSUE 19) — pass an explicit datasheet
    ``platform`` there so the DCN row resolves from the table, not this
    container's cpu backend."""
    from apex_tpu import plan as plan_mod

    result = plan_mod.search(
        model, mesh=mesh, hbm_bytes=hbm_bytes, micro_batch=micro_batch,
        num_microbatches=num_microbatches, islands=islands,
        platform=platform)
    winner = result["winner"]
    by = {}
    for r in result["rejected"]:
        by[r["rejected_by"]] = by.get(r["rejected_by"], 0) + 1

    def compact(rec):
        c, p = rec["candidate"], rec["predicted"]
        return {"candidate": c,
                "hbm_bytes": p["hbm_bytes"],
                "comm_bytes_by_tier": p["comm_bytes_by_tier"],
                "bubble_floor": p["bubble_floor"],
                "step_seconds": p["step_seconds"]}

    wc = winner["candidate"] if winner else {}
    return {
        "config": {"analytic_rung": True, "model": model,
                   "mesh": int(mesh),
                   **({"islands": int(islands)} if islands > 1 else {}),
                   "dp": wc.get("dp", "-"), "tp": wc.get("tp", "-"),
                   "pp": wc.get("pp", "-"),
                   "layers": result["model"]["layers"],
                   "zero_level": wc.get("zero_level", 0),
                   **({"dcn_wire": wc.get("dcn_wire")}
                      if islands > 1 else {})},
        "hbm_budget_bytes": int(hbm_bytes),
        "global_rows": result["global_rows"],
        "n_enumerated": result["n_enumerated"],
        "n_ranked": len(result["ranked"]),
        "rejected_by": by,
        "winner": compact(winner) if winner else None,
        "top": [compact(r) for r in result["ranked"][:5]],
        "peak_source": result["peak_spec"].get("source"),
        "ici_source": result["ici_spec"].get("source"),
        **({"dcn_source": (result.get("dcn_spec") or {}).get("source")}
           if islands > 1 else {}),
        "basis": ("analytic: apex_tpu.plan.search over the full "
                  f"(dp,tp,pp,schedule,zero,wire) space at mesh={mesh}; "
                  "ranked by modeled step seconds, rejections carry "
                  "named provenance; no execution at this scale"),
    }


def _overlap_evidence(compiled):
    """Count async collective pairs in the compiled HLO and pull the cost
    model's bytes — per-config artifacts (not prose) that the sharded step
    compiles to overlappable collectives (reference ethos:
    gpt_scaling_test.py:49-70 measure-and-record)."""
    import re

    hlo = compiled.as_text()
    counts = {}
    for op in ("collective-permute", "all-reduce", "all-gather",
               "reduce-scatter", "all-to-all"):
        # instruction definitions: "<shape> op(.N)(operands" — operand
        # references carry a % prefix, so a space before the op name means
        # a definition site
        starts = len(re.findall(rf" {op}-start(\.\d+)?\(", hlo))
        total = len(re.findall(rf" {op}(\.\d+)?\(", hlo)) + starts
        if starts or total:
            counts[op.replace("-", "_")] = {"total": total, "async_pairs": starts}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        counts["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
        counts["flops"] = float(cost.get("flops", 0.0))
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        pass
    return counts


# Reading guide stamped into scaling_table.json (VERDICT r4 weak #5: the
# CPU-mesh tokens/s numbers invite misreading as scaling efficiency).
_TABLE_NOTES = {
    "reading_guide": (
        "CPU-virtual-mesh artifact: the evidence columns are loss "
        "(serial-vs-sharded equivalence at each hybrid config) and the "
        "collective counts. tokens_per_sec is a single-core CPU emulation "
        "number - NOT a scaling-efficiency measurement; BASELINE target "
        "2's >=90% DDP efficiency cannot be measured on this backend at "
        "all."),
    "mfu": (
        "per-config mfu/hbm_bw_util/bound join the compiled step's XLA "
        "cost-model FLOPs+bytes with the measured iteration time against "
        "the peak-spec table (apex_tpu/monitor/mfu.py; calibrate via "
        "APEX_TPU_PEAK_FLOPS / APEX_TPU_PEAK_HBM_GBPS). peak_source "
        "'table:cpu' marks a virtual-mesh emulation number, not a TPU "
        "utilization claim."),
    "static_hazards": (
        "per-config jaxpr hazard scan (apex_tpu/lint/trace.py): "
        "lane_padding reports bytes lost to T(8,128) minor-dim tiling at "
        "step-signature and custom-call boundaries (worst offenders with "
        "waste ratios); recompile_hazards names weak-type/python-scalar "
        "leaves in the jitted signature. Both trace-time estimates, "
        "backend-independent - actionable on TPU even when measured on "
        "the CPU mesh."),
    "timeline": (
        "per-config step anatomy (apex_tpu/monitor/tracing.py): "
        "expected_bubble_fraction is the analytic fill/drain floor of "
        "the SPMD ring at this pp/num_microbatches shape; anatomy "
        "decomposes the measured iteration into compute/exposed-comm/"
        "stall fractions (summing to 1.0) from the cost model and the "
        "ICI bandwidth table (calibrate via APEX_TPU_PEAK_ICI_GBPS). "
        "MEASURED per-rank bubble fractions come from the traced tick "
        "drive (overlap_evidence.py --timeline / pretrain_gpt --trace), "
        "not this block."),
    "overlap": (
        "overlap.async_pairs reflects the CPU backend's synchronous "
        "collective lowering, not TPU behavior. TPU-targeted async "
        "evidence lives in out/overlap_evidence.json: an AOT compile of "
        "the hybrid train step against a v5e:2x4 topology shows "
        "collective-permute-start/done pairs with compute scheduled "
        "between them (benchmarks/overlap_evidence.py)."),
    "placement_rung": (
        "the 2.7B-class row prices per-rank residency per ZeRO stage "
        "through the planner's scorer (apex_tpu.plan.score_candidate — "
        "the same cost model `python -m apex_tpu.plan` and `pretrain_gpt "
        "--plan auto` rank with; sharded residency + activation floor "
        "vs a 16 GiB HBM budget) and traces the planner's ZeRO-3 "
        "feasibility program at the full shape for the per-layer-gather "
        "census — analytic+trace evidence, not a timed run (this "
        "container cannot execute that shape)."),
    "analytic_rung": (
        "the 13B-class row is a FULL planner search (apex_tpu.plan."
        "search) at mesh=64: winner anatomy + rejection-provenance "
        "histogram. At mesh=8 nothing places under 16 GiB — the 'needs "
        "more chips' verdict is the point; pure analysis, no "
        "execution. The islands=8 pod row prices the same search per "
        "link class (ICI + DCN at v4 datasheet clocks): the winner "
        "carries dcn_wire=int8 where the inter-island hop binds while "
        "the flat row stays fp32 — the tiered EQuARX pair "
        "(dcn-bound / wire-not-binding, apex_tpu.plan.search)."),
}


def run_grid(*, hidden, layers_list, heads, vocab, seq, micro_batch, n_micro,
             steps, output_dir=None, grid=GRID, big_rung=False,
             ledger=None):
    """Sweep ``grid`` × ``layers_list`` (the reference ramps layer counts per
    config, gpt_scaling_test.py:53-57). One JSON artifact per (config,
    layers) when ``output_dir`` is set, plus a combined ``scaling_table``;
    returns the result rows. ``big_rung=True`` appends the 2.7B-class
    :func:`placement_rung` row (planner-scored residency + full-shape
    gather census) and the 13B-class :func:`analytic_rung` row (full
    planner search at mesh=64) to the table. ``ledger`` appends one fingerprinted run
    record per measured config row (apex_tpu.monitor.ledger) so sweep
    trajectories track across sessions."""
    def ledger_row(res):
        if not ledger:
            return
        try:
            from apex_tpu.monitor import ledger as ledger_mod

            ledger_mod.append_scaling_row(ledger, res)
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a sweep
            print(f"ledger append failed: {e}", flush=True)

    rows = []
    for entry in grid:
        dp, tp, pp = entry[:3]
        cp = entry[3] if len(entry) > 3 else 1
        marks = set(entry[4:])
        sp = "sp" in marks
        reduce_dtype = "int8" if "zero-q8" in marks else None
        zero_level = (3 if "zero3" in marks
                      else 2 if "zero" in marks or reduce_dtype else 0)
        zero = zero_level > 0
        pp_schedule = "zerobubble" if "zb" in marks else "1f1b"
        moe = bool(marks & {"moe", "moe-q8"})
        moe_dispatch = "int8" if "moe-q8" in marks else None
        for layers in layers_list:
            res = run_config(
                dp, tp, pp, cp, hidden=hidden, layers=layers, heads=heads,
                vocab=vocab, seq=seq, micro_batch=micro_batch,
                n_micro=n_micro, steps=steps, sequence_parallel=sp,
                zero_level=zero_level, reduce_dtype=reduce_dtype,
                pp_schedule=pp_schedule, moe=moe,
                moe_dispatch_dtype=moe_dispatch)
            if res is None:
                # not enough devices — no layer count will change that;
                # record ONE skipped row for this config and move on
                res = {"config": {"dp": dp, "tp": tp, "pp": pp},
                       "skipped": "not enough devices"}
                if cp > 1:
                    res["config"]["cp"] = cp
                if sp:
                    res["config"]["sequence_parallel"] = True
                if zero:
                    res["config"]["zero"] = True
                    res["config"]["zero_level"] = zero_level
                rows.append(res)
                print(json.dumps(res), flush=True)
                break
            res["config"].setdefault("layers", layers)
            eff = res["config"]["layers"]
            # compare with cp/sp/zero DEFAULTED ON BOTH SIDES: projecting a
            # stored cp>1 (or sequence-parallel/zero) row down to a smaller
            # key set would make a later plain config look like its
            # duplicate and silently skip it
            defaults = {"cp": 1, "sequence_parallel": False, "zero": False,
                        "zero_level": 0, "reduce_dtype": None,
                        "pp_schedule": "1f1b", "moe": False,
                        "moe_dispatch_dtype": None}
            base_cfg = {"dp": dp, "tp": tp, "pp": pp, "cp": cp,
                        "sequence_parallel": sp and tp > 1, "zero": zero,
                        "zero_level": zero_level,
                        "reduce_dtype": reduce_dtype,
                        "pp_schedule": pp_schedule, "moe": moe,
                        "moe_dispatch_dtype": moe_dispatch, "layers": eff}
            if any({k: r["config"].get(k, defaults.get(k, 1))
                    for k in base_cfg} == base_cfg
                   for r in rows):
                # two requested counts rounded to the same effective config;
                # don't record the same measurement twice under two labels
                print(json.dumps({"config": {"dp": dp, "tp": tp, "pp": pp,
                                             "requested_layers": layers},
                                  "skipped": f"duplicate of layers={eff}"}),
                      flush=True)
                continue
            if eff != layers:
                res["config"]["requested_layers"] = layers
            rows.append(res)
            ledger_row(res)
            print(json.dumps(res), flush=True)
            if output_dir:
                os.makedirs(output_dir, exist_ok=True)
                cp_tag = f"_cp{cp}" if cp > 1 else ""
                cp_tag += "_sp" if sp and tp > 1 else ""
                cp_tag += ("_zero3" if zero_level >= 3
                           else "_zero_q8" if zero and reduce_dtype
                           else "_zero" if zero else "")
                cp_tag += "_zb" if pp_schedule == "zerobubble" else ""
                cp_tag += ("_moe_q8" if moe_dispatch
                           else "_moe" if moe else "")
                name = f"scaling_dp{dp}_tp{tp}_pp{pp}{cp_tag}_l{eff}.json"
                atomic_write_json(os.path.join(output_dir, name), res)
    if big_rung:
        res = placement_rung()
        rows.append(res)
        print(json.dumps(res), flush=True)
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            c = res["config"]
            name = (f"scaling_placement_dp{c['dp']}_h{c['hidden']}"
                    f"_l{c['layers']}_zero3.json")
            atomic_write_json(os.path.join(output_dir, name), res)
        res13 = analytic_rung()
        rows.append(res13)
        print(json.dumps(res13), flush=True)
        if output_dir:
            c = res13["config"]
            name = f"scaling_plan_{c['model']}_mesh{c['mesh']}.json"
            atomic_write_json(os.path.join(output_dir, name), res13)
        # the pod rung: the same 13B search priced per tier on a two-tier
        # 8x8 layout at v4 datasheet clocks — blind-picks the int8 DCN
        # wire where the inter-island hop binds (ISSUE 19)
        res13pod = analytic_rung(islands=8, num_microbatches=2,
                                 platform="v4")
        rows.append(res13pod)
        print(json.dumps(res13pod), flush=True)
        if output_dir:
            c = res13pod["config"]
            name = (f"scaling_plan_{c['model']}_mesh{c['mesh']}"
                    f"_isl{c['islands']}.json")
            atomic_write_json(os.path.join(output_dir, name), res13pod)
    if output_dir:
        # atomic (tmp + rename): a crash mid-sweep must never leave a
        # torn table for a later evidence consumer
        atomic_write_json(os.path.join(output_dir, "scaling_table.json"),
                          {"notes": _TABLE_NOTES, "rows": rows})
    # the human-readable table the reference prints as
    # "Average Iteration Time" lines (gpt_scaling_test.py:64-70)
    hdr = (f"{'dp':>3} {'tp':>3} {'pp':>3} {'cp':>3} {'mode':>5} "
           f"{'layers':>6} {'iter_s':>9} {'tok/s':>10}")
    print(hdr)
    for r in rows:
        c = r["config"]
        sp_mark = ("sp" if c.get("sequence_parallel")
                   else "zero3" if c.get("zero_level", 0) >= 3
                   else "zeroq8" if c.get("zero") and c.get("reduce_dtype")
                   else "zero" if c.get("zero")
                   else "zb" if c.get("pp_schedule") == "zerobubble"
                   else "moeq8" if c.get("moe_dispatch_dtype")
                   else "moe" if c.get("moe")
                   else "-")
        if c.get("placement_rung"):
            z3 = r["param_state_report"]["per_rank"]["zero3"]["total_bytes"]
            print(f"{c['dp']:>3} {c['tp']:>3} {c['pp']:>3} "
                  f"{c.get('cp', 1):>3} {sp_mark:>5} {c['layers']:>6} "
                  f"{'placed' if r['placed']['zero3'] else 'OVER':>9} "
                  f"{z3 / 2**30:>8.2f}G")
        elif c.get("analytic_rung"):
            w = r.get("winner")
            verdict = "plan" if w else "no-fit"
            hbm = (f"{w['hbm_bytes'] / 2**30:>8.2f}G" if w
                   else f"{'-':>9}")
            print(f"{c['dp']:>3} {c['tp']:>3} {c['pp']:>3} "
                  f"{c.get('cp', 1):>3} {'plan':>5} {c['layers']:>6} "
                  f"{verdict:>9} {hbm}")
        elif "skipped" in r:
            print(f"{c['dp']:>3} {c['tp']:>3} {c['pp']:>3} "
                  f"{c.get('cp', 1):>3} {sp_mark:>5} "
                  f"{c.get('layers', '-'):>6} {'skipped':>9}")
        else:
            print(f"{c['dp']:>3} {c['tp']:>3} {c['pp']:>3} "
                  f"{c.get('cp', 1):>3} {sp_mark:>5} {c['layers']:>6} "
                  f"{r['avg_iteration_time_s']:>9.4f} "
                  f"{r['tokens_per_sec']:>10.1f}")
    return rows


def main():
    # jax<0.5 API renames (shard_map/axis_size): installed only when the
    # harness RUNS as a program — tests importing run_config/run_grid see
    # the container's native jax surface unchanged
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=str, default="4",
                   help="comma-separated layer counts to ramp per config")
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--micro-batch", type=int, default=1)
    p.add_argument("--num-microbatches", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--output-dir", type=str, default=None,
                   help="write one JSON artifact per config plus scaling_table.json")
    p.add_argument("--no-big-rung", action="store_true",
                   help="skip the 2.7B-class placement rung and the "
                        "13B-class planner rung (analytic residency + "
                        "full-shape gather census + placement search)")
    p.add_argument("--ledger", nargs="?", const="out/ledger.jsonl",
                   default=None, metavar="PATH",
                   help="append one fingerprinted run record per measured "
                        "config row to the run ledger "
                        "(apex_tpu.monitor.ledger); "
                        "APEX_TPU_LEDGER=<path> arms it too")
    args = p.parse_args()
    if not args.ledger and os.environ.get("APEX_TPU_LEDGER"):
        args.ledger = os.environ["APEX_TPU_LEDGER"]
    run_grid(
        hidden=args.hidden,
        layers_list=[int(x) for x in args.layers.split(",")],
        heads=args.heads, vocab=args.vocab, seq=args.seq,
        micro_batch=args.micro_batch, n_micro=args.num_microbatches,
        steps=args.steps, output_dir=args.output_dir,
        big_rung=not args.no_big_rung, ledger=args.ledger)


if __name__ == "__main__":
    main()
