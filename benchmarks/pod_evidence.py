"""Pod-scale two-tier (DCN x ICI) hierarchical-collective evidence.

ISSUE 19: executable off-TPU proof, as one JSON artifact
(``out/pod_evidence.json``, ok:true), that the two-tier mesh layer
(``parallel/hierarchy.py`` — the named-axis spelling of apex's
DistributedFusedAdam intra-group reduce-scatter + inter-group all-reduce
split, distributed_fused_adam.py:397-441) does what it claims:

(a) **per-tier booking == analytic** — the hierarchical ZeRO
    reduce-scatter/all-gather pair traced under ``comm_accounting`` books
    EXACTLY the closed-form byte counts on each tier: the intra-island
    (ICI) stages carry the padded local leaf, the inter-island (DCN)
    stage carries ``1/n_ici`` of it (``CommAccount.by_tier``). The
    executed hierarchical all-reduce also bit-matches the flat tuple-axis
    ``psum`` on integer-valued payloads (association-free sums);
(b) **int8 DCN hop = exactly 1/4** — with ``wire_dtype="int8"`` the bulk
    DCN payload books exactly one quarter of the fp32 bytes (the EQuARX
    deployment point: the quantized wire exactly where the slow tier
    binds), the fp32 per-chunk scale side-channel booked separately and
    the ICI stages byte-identical (``by_verb_dtype(axis="dcn")``);
(c) **host-offloaded optimizer** — two bucketed
    ``optimizers.offload.HostOffloadedZero`` steps EXECUTE on the
    simulated two-host mesh and produce bit-identical params, masters and
    loss scale vs the resident in-HBM optimizer (dyadic SGD
    hyperparameters keep every intermediate exactly representable), the
    device-resident footprint is bounded by two buckets, and the
    timeline spans pin the prefetch discipline: bucket b+1's H2D upload
    dispatches before bucket b's apply lands;
(d) **DCN wire model** — ``tracing.dcn_spec`` resolves the slow-tier
    bandwidth (``APEX_TPU_PEAK_DCN_GBPS`` override honored) and
    ``tracing.modeled_step_seconds`` prices a DCN payload as its own
    always-exposed leg while ``step_anatomy`` splits measured exposed
    comm per link class (``ici_s`` + ``dcn_s``).

    JAX_PLATFORMS=cpu python benchmarks/pod_evidence.py

Artifacts write atomically (``utils/io.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip()

from apex_tpu.utils.compat import ensure_jax_compat  # noqa: E402
from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 - backend already up: run on it
    pass

ensure_jax_compat()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

N_DCN = 2
N_ICI = 4
AXES = ("dcn", "data")


def _mesh() -> Mesh:
    devs = np.array(jax.devices()[:N_DCN * N_ICI]).reshape(N_DCN, N_ICI)
    return Mesh(devs, AXES)


def _smap(mesh, fn, in_specs, out_specs):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def _census(mesh, fn, *args):
    from apex_tpu.monitor import comms

    with comms.comm_accounting() as acct:
        jax.make_jaxpr(
            lambda *a: jax.shard_map(
                fn, mesh=mesh,
                in_specs=tuple(P(AXES) for _ in args),
                out_specs=P(AXES), check_vma=False)(*a))(*args)
    return acct


# ---------------------------------------------------------------------------
# (a) per-tier booking == the closed-form byte counts; executed bit-match
# ---------------------------------------------------------------------------


def check_tier_booking(mesh) -> dict:
    from apex_tpu.parallel import hierarchy

    n = N_DCN * N_ICI
    local = 1024  # per-rank leaf elements; divides n, so no padding slop
    m = local // n  # flat chunk elements per rank
    x = jnp.zeros((n, local), jnp.float32)

    def scatter(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data")
        return chunk

    def gather(x):
        return hierarchy.hier_gather_chunk(
            x[:, :m].reshape(-1), (local,), jnp.float32, "dcn", "data")

    sc = _census(mesh, scatter, x).by_tier()
    ga = _census(mesh, gather, x).by_tier()
    # closed forms (fp32 wire, bytes per rank): the scatter's ICI stage
    # ships the full padded leaf and its DCN stage 1/n_ici of it; the
    # gather's DCN hop ships this rank's chunk and its ICI stage the
    # n_dcn island rows
    analytic = {
        "scatter": {"ici": local * 4, "dcn": local * 4 // N_ICI},
        "gather": {"ici": N_DCN * m * 4, "dcn": m * 4},
    }
    booked = {
        "scatter": {t: sc.get(t, {}).get("bytes", 0) for t in ("ici", "dcn")},
        "gather": {t: ga.get(t, {}).get("bytes", 0) for t in ("ici", "dcn")},
    }

    # executed equivalence: hierarchical all-reduce == flat tuple-axis
    # psum, bit-exact on integer-valued fp32 (association-free sums)
    xv = jax.random.randint(jax.random.PRNGKey(0), (n, 257), -8, 9
                            ).astype(jnp.float32)

    def flat(x):
        from apex_tpu.monitor import comms

        with comms.collective_scope("psum", AXES, x):
            return lax.psum(x, AXES)

    out_f = _smap(mesh, flat, (P(AXES),), P(AXES))(xv)
    out_h = _smap(mesh, lambda x: hierarchy.hier_psum(x, "dcn", "data"),
                  (P(AXES),), P(AXES))(xv)
    bit_match = bool(np.array_equal(np.asarray(out_f), np.asarray(out_h)))

    out = {"n_dcn": N_DCN, "n_ici": N_ICI, "leaf_elems": local,
           "analytic_bytes": analytic, "booked_bytes": booked,
           "dcn_fraction_of_ici": booked["scatter"]["dcn"]
           / max(booked["scatter"]["ici"], 1),
           "hier_psum_bitmatches_flat": bit_match}
    out["ok"] = bool(booked == analytic and bit_match)
    return out


# ---------------------------------------------------------------------------
# (b) the int8 DCN hop books exactly 1/4 the fp32 bytes
# ---------------------------------------------------------------------------


def check_int8_quarter(mesh) -> dict:
    from apex_tpu.parallel import hierarchy

    n = N_DCN * N_ICI
    x = jnp.zeros((n, 4096), jnp.float32)

    def exact(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data")
        return chunk

    def quant(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data",
                                                wire_dtype="int8")
        return chunk

    a_exact = _census(mesh, exact, x)
    a_quant = _census(mesh, quant, x)
    exact_dcn = a_exact.by_tier()["dcn"]["bytes"]
    rows = a_quant.by_verb_dtype(axis="dcn")
    bulk_int8 = rows.get("all_to_all[int8]", {}).get("bytes", 0)
    scales = rows.get("all_to_all[float32]", {}).get("bytes", 0)
    out = {
        "fp32_dcn_bytes": exact_dcn,
        "int8_dcn_bulk_bytes": bulk_int8,
        "fp32_scale_side_channel_bytes": scales,
        "compression_ratio": exact_dcn / max(bulk_int8, 1),
        "ici_bytes_identical": a_quant.by_tier()["ici"]["bytes"]
        == a_exact.by_tier()["ici"]["bytes"],
    }
    out["ok"] = bool(bulk_int8 * 4 == exact_dcn
                     and scales == N_DCN * 4
                     and out["ici_bytes_identical"])
    return out


# ---------------------------------------------------------------------------
# (c) host-offloaded optimizer: bit-match + H2D prefetch overlap
# ---------------------------------------------------------------------------


def check_offload(mesh) -> dict:
    from apex_tpu import amp as amp_mod
    from apex_tpu.monitor import tracing
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.optimizers.offload import HostOffloadedZero

    n = N_DCN * N_ICI

    def intval(key, shape):
        return jax.random.randint(key, shape, -8, 9).astype(jnp.float32)

    params = {"b": intval(jax.random.PRNGKey(1), (13,)) / 8.0,
              "v": intval(jax.random.PRNGKey(2), (11, 3)) / 4.0,
              "w": intval(jax.random.PRNGKey(3), (7, 5)) / 4.0}
    g1 = {k: intval(jax.random.PRNGKey(10 + i), (n,) + v.shape)
          for i, (k, v) in enumerate(params.items())}
    g2 = {k: intval(jax.random.PRNGKey(20 + i), (n,) + v.shape)
          for i, (k, v) in enumerate(params.items())}
    policy = amp_mod.get_policy("O2")

    def mk():
        # dyadic lr/momentum: every intermediate exactly representable, so
        # resident vs bucketed (different XLA programs) compare bit-exact
        return amp_mod.MixedPrecisionOptimizer(
            FusedSGD(lr=0.03125, momentum=0.5), policy,
            zero_axis="data", dcn_axis="dcn", dcn_wire=None)

    mp_r = mk()

    def resident(p, ga, gb):
        st = mp_r.init(p)
        s = st.scaler.loss_scale
        p1, st1, _ = mp_r.apply_gradients(
            st, p, jax.tree.map(lambda g: g[0] * s, ga))
        p2, st2, m = mp_r.apply_gradients(
            st1, p1, jax.tree.map(lambda g: g[0] * st1.scaler.loss_scale,
                                  gb))
        return p2, m["loss_scale"]

    gspec = {k: P(AXES) for k in params}
    res_p, res_s = _smap(mesh, resident, (P(), gspec, gspec),
                         ({k: P() for k in params}, P()))(params, g1, g2)

    off = HostOffloadedZero(mk(), mesh, None, num_buckets=2)
    state = off.init(params)
    s = float(state.scaler.loss_scale)
    with tracing.scoped(tracing.Tracer(None)) as tr:
        p1, state, _ = off.apply_gradients(
            state, params, jax.tree.map(lambda g: g * s, g1))
    s = float(state.scaler.loss_scale)
    p2, state, m = off.apply_gradients(
        state, p1, jax.tree.map(lambda g: g * s, g2))

    bit_match = all(
        np.array_equal(np.asarray(res_p[k]), np.asarray(p2[k]))
        for k in params) and float(res_s) == float(m["loss_scale"])

    spans = [r for r in tr.records if r.get("kind") == "span"]
    h2d = [r for r in spans if r["name"] == "offload.h2d"]
    app = [r for r in spans if r["name"] == "offload.apply"]
    # the prefetch discipline: bucket 1's upload dispatches before bucket
    # 0's apply lands (issue-ahead by one bucket)
    prefetch_ok = (len(h2d) == 2 and len(app) == 2
                   and [r["bucket"] for r in h2d] == [0, 1]
                   and h2d[1]["ts"] <= app[0]["ts"] + app[0]["dur_s"])
    host_bytes = state.host_bytes()
    out = {
        "bitmatches_resident": bool(bit_match),
        "num_buckets": len(state.host),
        "host_state_bytes": host_bytes,
        "hbm_resident_bytes": state.hbm_resident_bytes(),
        "prefetch_spans": [
            {"name": r["name"], "bucket": r["bucket"],
             "ts": round(r["ts"], 6), "dur_s": round(r["dur_s"], 6)}
            for r in sorted(h2d + app, key=lambda r: r["ts"])],
        "prefetch_issue_ahead": bool(prefetch_ok),
    }
    out["ok"] = bool(bit_match and prefetch_ok and host_bytes > 0)
    return out


# ---------------------------------------------------------------------------
# (d) the DCN wire model: spec resolution + the modeled slow-tier leg
# ---------------------------------------------------------------------------


def check_wire_model() -> dict:
    from apex_tpu.monitor import tracing

    saved = os.environ.pop(tracing.ENV_PEAK_DCN_GBPS, None)
    try:
        base = tracing.dcn_spec("tpu v4")
        os.environ[tracing.ENV_PEAK_DCN_GBPS] = "2.0"
        env = tracing.dcn_spec("tpu v4")
        modeled = tracing.modeled_step_seconds(
            flops=0.0, comm_bytes=0, dcn_bytes=4e9)
        anatomy = tracing.step_anatomy(wall_s=4.0, compute_s=1.0,
                                       comm_s=1.0, dcn_s=2.0)
    finally:
        os.environ.pop(tracing.ENV_PEAK_DCN_GBPS, None)
        if saved is not None:
            os.environ[tracing.ENV_PEAK_DCN_GBPS] = saved
    out = {
        "table_spec": base,
        "env_spec": env,
        "modeled_dcn_leg_s": modeled.get("dcn_comm_s"),
        "anatomy_tier_split": {k: anatomy.get(k)
                               for k in ("ici_s", "dcn_s", "comm_frac")},
    }
    # fully-exposed window (1 + 1+2 <= 4): the per-link-class split must
    # reconstruct the modeled legs exactly — ici_s 1.0, dcn_s 2.0
    out["ok"] = bool(
        base["dcn_bytes_per_sec"] > 0 and base["source"].startswith("table")
        and env["dcn_bytes_per_sec"] == 2.0e9 and env["source"] == "env"
        and abs(modeled["dcn_comm_s"] - 2.0) < 1e-9
        and abs(anatomy["ici_s"] - 1.0) < 1e-6
        and abs(anatomy["dcn_s"] - 2.0) < 1e-6)
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=os.path.join("out",
                                                    "pod_evidence.json"))
    args = p.parse_args()

    mesh = _mesh()
    record = {"evidence": "pod-scale two-tier DCN x ICI hierarchical "
                          "collectives (ISSUE 19)"}
    record["tier_booking"] = check_tier_booking(mesh)
    record["int8_quarter"] = check_int8_quarter(mesh)
    record["offload"] = check_offload(mesh)
    record["wire_model"] = check_wire_model()
    record["ok"] = all(record[k]["ok"] for k in
                       ("tier_booking", "int8_quarter", "offload",
                        "wire_model"))
    print(json.dumps(record))
    atomic_write_json(args.output, record)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
