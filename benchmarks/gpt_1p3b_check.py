"""BASELINE.md target #5 functional check at REAL width: GPT-3-1.3B-class
hidden size (h=2048, 32 heads) under TP x PP interleaved, loss-matched
against the unpipelined serial model.

The reference frames this target as "GPT-3 1.3B, TP=8 x PP=4 interleaved
on v5e-64: runs, loss-match vs no-pipelining" (BASELINE.md target #5; the
reference's own harness pattern is the pipeline-vs-serial equivalence of
tests/L0/run_transformer/run_pipeline_parallel_test.py:33-80 at the
gpt_scaling_test.py:49-70 model scales). Multi-chip hardware is not
available in this environment, so the check runs the REAL WIDTH (the
dimension that stresses sharded-GEMM correctness) at reduced depth/seq on
the 8-device virtual CPU mesh: tp=2 x pp=4 with interleaved vpp=2, one
full O-level-free fp32 train-step loss vs the serial model on identical
data. Depth and sequence are scaled down only for single-core CPU wall
clock; every parallel mechanism (column/row-parallel GEMMs at h=2048,
vocab-parallel embedding/CE, SPMD pipeline ring with virtual chunks)
runs at production width.

Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/gpt_1p3b_check.py --output out/gpt_1p3b_width_check.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.parallel import collectives, mesh as mesh_lib
from apex_tpu.transformer.pipeline_parallel import prepare_pipelined_model


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--layers", type=int, default=8,
                    help="must divide pp*vpp; reduced from 24 for CPU time")
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=50304)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--vpp", type=int, default=2)
    ap.add_argument("--micro", type=int, default=4,
                    help="microbatches (interleaved schedule needs a "
                         "multiple of pp)")
    ap.add_argument("--output", default=None)
    args = ap.parse_args()

    n = args.tp * args.pp
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=args.seq, hidden_dropout=0.0,
        axis=mesh_lib.AXIS_MODEL, compute_dtype=jnp.float32, remat=True)
    serial_cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=args.seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.float32, remat=True)

    model = GPTModel(cfg)
    serial_model = GPTModel(serial_cfg)
    params = serial_model.init(jax.random.PRNGKey(0))
    batch = args.micro
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, args.seq),
                                0, args.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)

    t0 = time.perf_counter()
    serial_loss = float(serial_model.loss(params, tokens, targets))
    t_serial = time.perf_counter() - t0
    print(f"serial loss {serial_loss:.6f} ({t_serial:.1f}s)", file=sys.stderr)

    mesh = mesh_lib.make_virtual_mesh(
        n, tensor_model_parallel_size=args.tp,
        pipeline_model_parallel_size=args.pp,
        virtual_pipeline_model_parallel_size=args.vpp if args.vpp > 1 else None,
    )
    try:
        specs, sharded, pipe_loss = prepare_pipelined_model(
            model, params, mesh, num_microbatches=args.micro,
            virtual_pipeline_size=args.vpp)

        def fn(p, toks, tgts):
            rest = {k: v for k, v in p.items() if k != "layers"}
            loss = pipe_loss(rest, p["layers"], toks, tgts)
            return collectives.pmean(
                loss, mesh_lib.get_gradient_reduction_axes())

        data_spec = P(mesh_lib.AXIS_DATA)
        tokens_s = jax.device_put(tokens, NamedSharding(mesh, data_spec))
        targets_s = jax.device_put(targets, NamedSharding(mesh, data_spec))
        t0 = time.perf_counter()
        piped_loss = float(jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(specs, data_spec, data_spec),
            out_specs=P(), check_vma=False))(sharded, tokens_s, targets_s))
        t_pipe = time.perf_counter() - t0
        print(f"tp{args.tp} x pp{args.pp} (vpp={args.vpp}) loss "
              f"{piped_loss:.6f} ({t_pipe:.1f}s)", file=sys.stderr)
    finally:
        mesh_lib.destroy_model_parallel()

    rel = abs(piped_loss - serial_loss) / max(abs(serial_loss), 1e-9)
    record = {
        "metric": f"gpt_h{args.hidden}_L{args.layers}_tp{args.tp}"
                  f"_pp{args.pp}_vpp{args.vpp}_loss_match",
        "hidden": args.hidden, "heads": args.heads, "layers": args.layers,
        "seq": args.seq, "tp": args.tp, "pp": args.pp, "vpp": args.vpp,
        "serial_loss": round(serial_loss, 6),
        "pipelined_loss": round(piped_loss, 6),
        "rel_err": rel,
        "ok": bool(rel < 1e-4),
    }
    print(json.dumps(record))
    if args.output:
        # atomic (tmp + rename): no torn artifacts on crash
        atomic_write_json(args.output, record)
    if not record["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
