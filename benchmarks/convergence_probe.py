"""GPT-2 345M on-chip convergence probe: warmup, discriminating endpoint,
CPU cross-check band.

VERDICT r4 ask #5: the r4 probe (loss 11.03 -> 8.01 in 300 steps, no
warmup, early 9.2 -> 15.9 spike) demonstrated numeric health but its
endpoint could not discriminate a subtle amp/master-weight bug from
healthy training. This probe
  1. uses linear lr warmup (kills the step-20 no-warmup spike),
  2. runs long enough to push loss unambiguously below random-init
     (~10.8): the acceptance bar is <= 6,
  3. replays the first K steps with IDENTICAL config + PRNG keys on the
     CPU backend in a subprocess and records the max relative loss-curve
     deviation (``cpu_curve_max_rel_dev``) under a stated band — the
     chip-vs-CPU numeric divergence of the full O2 stack as a checked
     property (reference analog: tests/L1/common/compare.py's
     loss-by-loss comparison across builds; SURVEY §7's stated
     tolerance-band adaptation).

The memorization corpus is 2 fixed batches (the r4 protocol) at
batch 2 x seq 512 — sized so the CPU leg is tractable on one core while
the model is the real 345M stack (h=1024, L=24, flash kernels, fused LN,
chunked LM-head CE, fp32 masters, dynamic scaling).

Run on the chip:
    PYTHONPATH=/root/repo:/root/.axon_site python \
        benchmarks/convergence_probe.py --output out/convergence_345m_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp


def run_probe(steps, *, lr, warmup, batch, seq, fetch_every=1):
    """Train the 345M O2 stack on the fixed 2-batch corpus; returns
    (losses, overflow_count, final_scale)."""
    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(
        vocab_size=50304, hidden_size=1024, num_layers=24,
        num_attention_heads=16, max_seq_len=seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, remat=True,
        lm_head_chunks=8)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=lr), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)
    corpus = jax.random.randint(jax.random.PRNGKey(1), (2, batch, seq),
                                0, cfg.vocab_size)

    @jax.jit
    def step(params, opt_state, tokens, lr_t):
        targets = jnp.roll(tokens, -1, axis=-1)

        def scaled(p):
            return mp_opt.scale_loss(model.loss(p, tokens, targets),
                                     opt_state)

        loss_s, grads = jax.value_and_grad(scaled)(params)
        new_p, new_s, metrics = mp_opt.apply_gradients(
            opt_state, params, grads, lr_t=lr_t)
        return new_p, new_s, loss_s / opt_state.scaler.loss_scale, metrics

    losses, overflows = [], 0
    for i in range(steps):
        lr_t = jnp.float32(lr * min(1.0, (i + 1) / max(warmup, 1)))
        params, opt_state, loss, metrics = step(
            params, opt_state, corpus[i % 2], lr_t)
        losses.append(float(loss))
        overflows += int(metrics["found_inf"])
        if i % 50 == 0:
            print(f"step {i}: loss {losses[-1]:.4f} "
                  f"scale {float(metrics['loss_scale']):.0f}",
                  file=sys.stderr)
    return losses, overflows, float(opt_state.scaler.loss_scale)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup", type=int, default=50)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--cpu-check-steps", type=int, default=6,
                    help="first-K-step CPU replay; 0 disables")
    ap.add_argument("--cpu-band", type=float, default=0.05,
                    help="accepted max relative per-step loss deviation")
    ap.add_argument("--emit-curve", type=int, default=0,
                    help="internal: run N steps, print the loss list, exit"
                         " (the CPU-leg subprocess entry)")
    ap.add_argument("--output", default=None)
    args = ap.parse_args()

    if args.emit_curve:
        losses, _, _ = run_probe(args.emit_curve, lr=args.lr,
                                 warmup=args.warmup, batch=args.batch,
                                 seq=args.seq)
        print(json.dumps(losses))
        return

    t0 = time.perf_counter()
    losses, overflows, final_scale = run_probe(
        args.steps, lr=args.lr, warmup=args.warmup, batch=args.batch,
        seq=args.seq)
    wall = time.perf_counter() - t0

    record = {
        "metric": "gpt2_345m_o2_convergence",
        "platform": jax.default_backend(),
        "steps": args.steps, "lr": args.lr, "warmup_steps": args.warmup,
        "batch": args.batch, "seq": args.seq,
        "loss_first": round(losses[0], 4),
        "loss_final": round(losses[-1], 4),
        "loss_max_after_warmup": round(max(losses[args.warmup:]), 4),
        "overflow_steps": overflows,
        "final_loss_scale": final_scale,
        "wall_seconds": round(wall, 1),
        "curve_every_10": [round(x, 4) for x in losses[::10]],
        "ok": bool(losses[-1] <= 6.0),
    }

    if args.cpu_check_steps:
        k = args.cpu_check_steps
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--emit-curve", str(k), "--lr", str(args.lr),
                 "--warmup", str(args.warmup), "--batch", str(args.batch),
                 "--seq", str(args.seq)],
                capture_output=True, text=True, env=env, timeout=3600)
            cpu_curve = json.loads(out.stdout.strip().splitlines()[-1])
            dev = max(abs(a - b) / max(abs(b), 1e-6)
                      for a, b in zip(losses[:k], cpu_curve))
            record["cpu_check"] = {
                "steps": k,
                "tpu_curve": [round(x, 4) for x in losses[:k]],
                "cpu_curve": [round(x, 4) for x in cpu_curve],
                "cpu_curve_max_rel_dev": round(dev, 5),
                "band": args.cpu_band,
                "ok": bool(dev <= args.cpu_band),
            }
            record["ok"] = bool(record["ok"] and record["cpu_check"]["ok"])
        except Exception as e:  # noqa: BLE001 - record the failure, keep probe
            record["cpu_check"] = {"error": str(e)[:300]}

    print(json.dumps(record))
    if args.output:
        # atomic (tmp + rename): a crash mid-write must never leave a
        # torn artifact for a later evidence check to trip on
        atomic_write_json(args.output, record)
    sys.exit(0 if record["ok"] else 1)


if __name__ == "__main__":
    main()
