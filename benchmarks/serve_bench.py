"""Serving evidence: open-loop load against the engine, three workloads.

ISSUE 10 laid the structural bar (shape-stable decode under open-loop
load, journal → report latency percentiles, greedy exactness). ISSUE 12
raises the LOAD and adds the production-scale claims, all off-TPU runnable
(the absolute milliseconds on a contended CPU container are not the claim;
the gated claims are structural):

1. **baseline** — the PR 9 open-loop workload, unchanged checks: every
   request served, zero page/slot leaks, shape-stable decode signature,
   journal → report serving section, compare gates a doubled-latency
   candidate.
2. **shared-prefix at ~10x load** — ~120 requests sharing a common system
   prompt, served through prefix sharing + chunked prefill + speculative
   decoding at once: prefix hit-rate > 0 and pages saved > 0 (the sharing
   claim), mean accepted draft length > 1 (the speculation claim), greedy
   sample still matches the full-context argmax, zero leaks after the
   cache drops, and the chunk/verify tick streams are shape-stable.
3. **long-prompt ITL protection** — identical workloads (short streams
   decoding + one long prompt arriving mid-run) through a MONOLITHIC
   prefill engine and a CHUNKED one: the monolithic baseline's stall
   inflates running streams' ITL tail and trips the ``report compare``
   ITL gate, while the chunked engine's self-compare holds.
4. **request-scoped tracing** (ISSUE 17) — tail sampling retains 100% of
   SLO violators and exactly 1-in-N compliant requests (rest folded into
   one bounded reqhist record), attribution fractions sum to 1.0 per
   request and in the report rollup, the Chrome export carries one lane
   per sampled request, ``report compare`` flags a queue-inflated
   candidate, and the monolithic long-prompt stall names itself in the
   worst decode tick's prefill attribution. Own atomic artifact:
   ``out/reqtrace_evidence.json``.

Writes ``out/serve_evidence.json`` (one JSON object, ``ok: true`` iff all
checks hold). Run:
    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu.utils.io import atomic_write_json  # noqa: E402

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
else:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()

from apex_tpu.lint.trace import decode_recompile_hazards
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.monitor import report as report_mod
from apex_tpu.monitor.journal import MetricsJournal
from apex_tpu.serve import Engine, Request, ServeConfig


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default="out/serve_evidence.json")
    p.add_argument("--reqtrace-output", default="out/reqtrace_evidence.json",
                   help="separate artifact for the request-scoped tracing "
                        "phase (ISSUE 17)")
    p.add_argument("--journal", default="out/serve_bench.jsonl")
    p.add_argument("--requests", type=int, default=12,
                   help="baseline-phase request count (PR 9 load)")
    p.add_argument("--shared-requests", type=int, default=120,
                   help="shared-prefix-phase request count (~10x the "
                        "baseline load)")
    p.add_argument("--shared-prefix-len", type=int, default=16,
                   help="tokens of common system prompt every shared-"
                        "phase request starts with")
    p.add_argument("--spec-k", type=int, default=3,
                   help="draft tokens per tick in the shared phase "
                        "(self-draft: target == draft)")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   help="chunk width for the chunked-prefill engines")
    p.add_argument("--long-prompt", type=int, default=448,
                   help="long-arrival prompt length in the ITL phase")
    p.add_argument("--rate", type=float, default=40.0,
                   help="open-loop arrival rate (requests/s of host "
                        "wall clock; seeded-exponential gaps)")
    p.add_argument("--max-new-tokens", type=int, default=12)
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


class OpenLoopGenerator:
    """Arrivals on the GENERATOR's clock: request i becomes visible at
    ``t0 + sum(gaps[:i])`` regardless of engine progress — the queue
    depth under load is real, not an artifact of submit-then-drain."""

    def __init__(self, args, *, n=None, prompts=None, rate=None):
        rng = np.random.default_rng(args.seed)
        n = n if n is not None else args.requests
        self.gaps = rng.exponential(1.0 / (rate or args.rate), n)
        self.arrivals = np.cumsum(self.gaps)
        self.prompts = prompts if prompts is not None else [
            list(rng.integers(0, args.vocab, int(rng.integers(3, 20))))
            for _ in range(n)]
        self.max_new = args.max_new_tokens
        self.t0 = time.perf_counter()
        self.next_idx = 0

    def poll(self, engine) -> None:
        """Submit every request whose arrival time has passed (the
        engine's on_tick hook)."""
        now = time.perf_counter() - self.t0
        while (self.next_idx < len(self.arrivals)
               and self.arrivals[self.next_idx] <= now):
            i = self.next_idx
            req = Request(prompt=self.prompts[i], max_new_tokens=self.max_new,
                          request_id=i)
            engine.submit(req)
            self.next_idx += 1

    @property
    def done(self) -> bool:
        return self.next_idx >= len(self.arrivals)


def drive_open_loop(engine, gen, journal):
    """Serve until the generator drains and the engine idles."""
    results = {}
    gen.poll(engine)
    while not gen.done or not engine.batcher.idle:
        if engine.batcher.idle:
            time.sleep(0.005)  # open-loop: wait for the next arrival
            gen.poll(engine)
            continue
        results.update(engine.run(journal=journal,
                                  max_ticks=engine.ticks + 1,
                                  on_tick=gen.poll))
        gen.poll(engine)
    return results


def greedy_matches(model, params, req) -> bool:
    seq = list(req.prompt) + req.tokens
    ref = np.asarray(jnp.argmax(
        model.apply(params, jnp.asarray([seq], jnp.int32))[0], -1))
    return all(int(ref[t - 1]) == seq[t]
               for t in range(len(req.prompt), len(seq)))


def build_model(args, max_seq_len=64):
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=max_seq_len, hidden_dropout=0.0, axis=None,
        compute_dtype=jnp.float32, remat=False)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(args.seed))


def fresh_journal(path):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    if os.path.exists(path):
        os.unlink(path)
    return path


def phase_baseline(args):
    """PR 9's open-loop workload, checks unchanged."""
    model, params = build_model(args)
    engine = Engine(model, params, ServeConfig(
        max_batch=args.max_batch, max_seq=48, block_size=8,
        seed=args.seed))
    journal = fresh_journal(args.journal)
    gen = OpenLoopGenerator(args)
    with MetricsJournal(journal, meta={
            "run": "serve_bench", "requests": args.requests,
            "rate_rps": args.rate, "max_batch": args.max_batch}) as j:
        results = drive_open_loop(engine, gen, j)
    served = len(results)

    greedy_ok = greedy_matches(model, params, results[min(results)])
    tripwire = decode_recompile_hazards(engine.decode_args, ticks=3)

    rows = MetricsJournal.read(journal)
    analysis = report_mod.analyze(rows)
    serving = analysis.get("serving") or {}
    doubled = []
    for r in rows:
        r2 = dict(r)
        if r2.get("kind") == "request":
            if isinstance(r2.get("ttft_s"), (int, float)):
                r2["ttft_s"] = 2.5 * r2["ttft_s"]
            if isinstance(r2.get("itl_s"), list):
                r2["itl_s"] = [2.5 * v for v in r2["itl_s"]
                               if isinstance(v, (int, float))]
        doubled.append(r2)
    gate = report_mod.compare(rows, doubled, threshold=0.10)
    gate_fires = (not gate["ok"]
                  and any(c in gate["regressed"]
                          for c in ("ttft_ms_p50", "itl_ms_p50")))
    self_gate = report_mod.compare(rows, rows, threshold=0.10)

    checks = {
        "served_all_requests": served == args.requests,
        "no_page_or_slot_leaks": (engine.allocator.used == 0
                                  and engine.batcher.idle),
        "greedy_matches_full_forward_argmax": bool(greedy_ok),
        "decode_signature_shape_stable": not tripwire["hazard"],
        "report_has_serving_section": bool(
            serving.get("ttft_ms") and serving.get("itl_ms")),
        "compare_gates_doubled_latency": bool(gate_fires),
        "compare_passes_self": bool(self_gate["ok"]),
    }
    return checks, {
        "decode_ticks": engine.ticks,
        "serving": serving,
        "tokens_per_sec_per_user": serving.get("tokens_per_sec_per_user"),
        "ttft_ms": serving.get("ttft_ms"),
        "itl_ms": serving.get("itl_ms"),
        "tripwire": {"hazard": tripwire["hazard"],
                     "leaves": tripwire["leaves"],
                     "ticks": tripwire["ticks"]},
        "pool_blocks": engine.allocator.num_blocks - 1,
    }


def phase_shared_prefix(args):
    """~10x load, every request opening with the same system prompt,
    served through prefix sharing + chunked prefill + speculative
    decoding at once."""
    model, params = build_model(args)
    n = args.shared_requests
    rng = np.random.default_rng(args.seed + 1)
    prefix = list(rng.integers(0, args.vocab, args.shared_prefix_len))
    prompts = [prefix + list(rng.integers(0, args.vocab,
                                          int(rng.integers(3, 9))))
               for _ in range(n)]
    engine = Engine(model, params, ServeConfig(
        max_batch=args.max_batch, max_seq=48, block_size=8,
        seed=args.seed, prefix_cache=True, spec_k=args.spec_k,
        prefill_chunk=min(args.prefill_chunk, 32)))
    journal = fresh_journal(args.journal.replace(".jsonl", "_shared.jsonl"))
    # higher arrival rate: the point IS queueing pressure at 10x requests
    gen = OpenLoopGenerator(args, n=n, prompts=prompts,
                            rate=args.rate * 4)
    with MetricsJournal(journal, meta={
            "run": "serve_bench_shared", "requests": n,
            "prefix_len": args.shared_prefix_len,
            "spec_k": args.spec_k}) as j:
        results = drive_open_loop(engine, gen, j)

    greedy_ok = greedy_matches(model, params, results[min(results)])
    tripwire = decode_recompile_hazards(
        engine.decode_args, ticks=3,
        extra_streams={"chunk": engine.chunk_args,
                       "verify": engine.spec_args})
    rows = MetricsJournal.read(journal)
    serving = report_mod.analyze(rows).get("serving") or {}
    stats = engine.stats
    engine.drop_prefix_cache()

    checks = {
        "served_all_requests": len(results) == n,
        "prefix_hit_rate_positive": (serving.get("prefix_hit_rate") or 0) > 0,
        "pages_saved_positive": (serving.get("pages_saved") or 0) > 0,
        "accepted_len_above_1": (
            (serving.get("accepted_len") or {}).get("p50") or 0) > 1,
        "greedy_matches_full_forward_argmax": bool(greedy_ok),
        "chunk_and_verify_streams_shape_stable": not tripwire["hazard"],
        "zero_leaks_after_cache_drop": (engine.allocator.used == 0
                                        and engine.batcher.idle),
    }
    return checks, {
        "requests": n,
        "decode_ticks": engine.ticks,
        "engine_stats": stats,
        "serving": {k: serving.get(k) for k in
                    ("requests", "prefix_hit_rate", "pages_saved",
                     "cow_forks", "accepted_len", "prefill_chunks",
                     "prefill_queue_delay_ms", "ttft_ms", "itl_ms")},
        "journal": journal,
    }


def phase_long_prompt_itl(args):
    """The chunked-prefill claim, gated by report compare: the SAME
    workload (short streams decoding, one long prompt arriving mid-run)
    through a monolithic engine inflates running streams' ITL tail;
    through a chunked engine it does not. Both engines warm up on a
    throwaway request first so jit compile never pollutes the measured
    ITLs."""
    max_seq = args.long_prompt + args.max_new_tokens + 64
    model, params = build_model(args, max_seq_len=max_seq)
    rng = np.random.default_rng(args.seed + 2)
    short_prompts = [list(rng.integers(0, args.vocab, 6))
                     for _ in range(args.max_batch - 1)]
    long_prompt = list(rng.integers(0, args.vocab, args.long_prompt))

    def run_engine(chunk):
        eng = Engine(model, params, ServeConfig(
            max_batch=args.max_batch, max_seq=max_seq, block_size=8,
            seed=args.seed, prefill_chunk=chunk))
        # warm-up: compile prefill, decode AND both chunk programs off the
        # record — the warm prompt must span more than one chunk so the
        # non-final (mid) chunk program compiles here, not mid-measurement
        eng.run([Request(prompt=long_prompt[:(chunk or 0) + 8],
                         max_new_tokens=2, request_id="warm")])
        journal = fresh_journal(args.journal.replace(
            ".jsonl", f"_long_{'chunk' if chunk else 'mono'}.jsonl"))
        shorts = [Request(prompt=p, max_new_tokens=40, request_id=i)
                  for i, p in enumerate(short_prompts)]
        long_req = Request(prompt=long_prompt, max_new_tokens=4,
                           request_id="long")

        def inject(engine):
            if engine.ticks == 8:  # shorts are mid-stream
                engine.submit(long_req)

        with MetricsJournal(journal, meta={
                "run": "serve_bench_long",
                "mode": "chunk" if chunk else "mono"}) as j:
            res = eng.run(shorts, journal=j, on_tick=inject)
        assert len(res) == args.max_batch, len(res)
        assert eng.allocator.used == 0 and eng.batcher.idle
        return MetricsJournal.read(journal), journal

    mono_rows, mono_journal = run_engine(None)
    chunk_rows, chunk_journal = run_engine(args.prefill_chunk)
    mono_itl = (report_mod.analyze(mono_rows).get("serving")
                or {}).get("itl_ms") or {}
    chunk_itl = (report_mod.analyze(chunk_rows).get("serving")
                 or {}).get("itl_ms") or {}
    # the machine gate: candidate = monolithic vs baseline = chunked must
    # REGRESS on ITL (p99 tail or p50); chunked self-compare must hold
    gate = report_mod.compare(chunk_rows, mono_rows, threshold=0.10)
    gate_trips = (not gate["ok"]
                  and any(c in gate["regressed"]
                          for c in ("itl_ms_p99", "itl_ms_p50")))
    self_gate = report_mod.compare(chunk_rows, chunk_rows, threshold=0.10)

    checks = {
        "monolithic_itl_gate_trips": bool(gate_trips),
        "chunked_self_compare_holds": bool(self_gate["ok"]),
        "chunked_tail_below_monolithic": (
            (chunk_itl.get("p99") or 1e9) < (mono_itl.get("p99") or 0)),
    }
    return checks, {
        "long_prompt": args.long_prompt,
        "prefill_chunk": args.prefill_chunk,
        "itl_ms_monolithic": mono_itl,
        "itl_ms_chunked": chunk_itl,
        "compare_regressed": gate["regressed"],
        "journals": {"mono": mono_journal, "chunk": chunk_journal},
    }


def phase_reqtrace(args):
    """Request-scoped tracing evidence (ISSUE 17), all structural:

    - attribution fractions sum to 1.0 per request AND in the report
      rollup;
    - tail sampling retains 100% of SLO violators and exactly
      ``ceil(n/N)`` compliant requests under shared-prefix load, with
      the rest folded into ONE bounded reqhist record;
    - the Chrome export carries one named lane per sampled request;
    - ``report compare`` flags a queue-inflated candidate through the
      queue-fraction gates and passes self-compare;
    - the chunked-vs-monolithic long-prompt ITL gap is ATTRIBUTED: the
      monolithic run's worst decode tick is prefill-dominated in its
      per-tick span attrs, and the chunked run's MEDIAN prefill-carrying
      tick does far less serialized prefill work per tick.
    """
    from apex_tpu.monitor import tracing

    model, params = build_model(args)
    rng = np.random.default_rng(args.seed + 3)
    prefix = list(rng.integers(0, args.vocab, args.shared_prefix_len))
    n = 10 * args.max_batch
    prompts = [prefix + list(rng.integers(0, args.vocab,
                                          int(rng.integers(3, 9))))
               for _ in range(n)]

    def traced_run(slo_itl_ms, sample_n, tag):
        eng = Engine(model, params, ServeConfig(
            max_batch=args.max_batch, max_seq=48, block_size=8,
            seed=args.seed, prefix_cache=True, prefill_chunk=16,
            slo_itl_ms=slo_itl_ms, trace_sample_n=sample_n))
        journal = fresh_journal(
            args.journal.replace(".jsonl", f"_rt_{tag}.jsonl"))
        reqs = [Request(prompt=p, max_new_tokens=6, request_id=i)
                for i, p in enumerate(prompts)]
        tr = tracing.Tracer(None, keep=True)
        with tracing.scoped(tr):
            with MetricsJournal(journal, meta={
                    "run": f"serve_bench_reqtrace_{tag}"}) as j:
                eng.run(reqs, journal=j)
        eng.drop_prefix_cache()
        assert eng.allocator.used == 0 and eng.batcher.idle
        return eng, tr, MetricsJournal.read(journal)

    # (a) impossible ITL target: every request violates -> 100% retention
    eng_v, tr_v, rows_v = traced_run(1e-6, 10 ** 6, "violator")
    roots_v = [r for r in tr_v.records if r.get("name") == "serve.request"]
    # (b) no violations: deterministic 1-in-N + one bounded histogram
    sample_n = 8
    eng_s, tr_s, rows_s = traced_run(1e9, sample_n, "sampled")
    roots_s = [r for r in tr_s.records if r.get("name") == "serve.request"]
    hists = [r for r in tr_s.records if r.get("kind") == "reqhist"]
    want_sampled = -(-n // sample_n)  # ceil
    folded = ((hists[0]["phases"].get("ttft") or {}).get("n")
              if hists else None)

    def frac_sums_ok(rows):
        oks = []
        for r in rows:
            if r.get("kind") != "request":
                continue
            for fr in (r.get("attribution") or {}).values():
                if isinstance(fr, dict):
                    oks.append(abs(sum(
                        v for k, v in fr.items()
                        if k.endswith("_frac")) - 1.0) < 1e-3)
        return bool(oks) and all(oks)

    sv = report_mod.analyze(rows_v).get("serving") or {}
    attr = sv.get("attribution") or {}
    rollup_ok = bool(attr) and all(
        abs(sum(v for k, v in row.items()
                if k.endswith("_frac")) - 1.0) < 1e-3
        for row in attr.values())

    # one Chrome lane per sampled request (thread_name metadata rows)
    chrome = tracing.chrome_trace(tr_s.records)
    lanes = [e for e in chrome["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"
             and str((e.get("args") or {}).get("name", "")
                     ).startswith("request ")]

    # queue-inflated candidate: shift 0.4 of every request's attribution
    # mass into the queue bucket (renormalizing the rest so each class
    # still sums to 1.0) — ONLY the queue-fraction gates may trip
    inflated = []
    for r in rows_v:
        r2 = dict(r)
        if r2.get("kind") == "request" and isinstance(
                r2.get("attribution"), dict):
            at2 = {}
            for cls, fr in r2["attribution"].items():
                if not isinstance(fr, dict):
                    continue
                fr2 = dict(fr)
                fr2["queue_frac"] = min(
                    (fr.get("queue_frac") or 0.0) + 0.4, 1.0)
                others = [k for k in fr2
                          if k.endswith("_frac") and k != "queue_frac"]
                rest = 1.0 - fr2["queue_frac"]
                tot = sum(fr.get(k) or 0.0 for k in others) or 1.0
                for k in others:
                    fr2[k] = round((fr.get(k) or 0.0) * rest / tot, 4)
                at2[cls] = fr2
            r2["attribution"] = at2
        inflated.append(r2)
    gate = report_mod.compare(rows_v, inflated, threshold=0.10)
    gate_trips = (not gate["ok"] and gate["regressed"]
                  and set(gate["regressed"]) <= {"ttft_queue_frac",
                                                 "itl_queue_frac"})
    self_gate = report_mod.compare(rows_v, rows_v, threshold=0.10)

    # (c) chunked-vs-monolithic ITL gap, ATTRIBUTED per tick: the span
    # trees' req.decode_tick attrs carry each tick's prefill/compute/
    # barrier seconds, so the monolithic stall names itself
    long_len = 192
    max_seq = long_len + args.max_new_tokens + 64
    model2, params2 = build_model(args, max_seq_len=max_seq)
    rng2 = np.random.default_rng(args.seed + 4)
    short_prompts = [list(rng2.integers(0, args.vocab, 6))
                     for _ in range(args.max_batch - 1)]
    long_prompt = list(rng2.integers(0, args.vocab, long_len))

    def tick_spans(chunk):
        eng = Engine(model2, params2, ServeConfig(
            max_batch=args.max_batch, max_seq=max_seq, block_size=8,
            seed=args.seed, prefill_chunk=chunk, slo_itl_ms=1e-6,
            trace_sample_n=10 ** 6))
        eng.run([Request(prompt=long_prompt[:(chunk or 0) + 8],
                         max_new_tokens=2, request_id="warm")])
        t0 = eng.ticks
        shorts = [Request(prompt=p, max_new_tokens=30, request_id=i)
                  for i, p in enumerate(short_prompts)]
        long_req = Request(prompt=long_prompt, max_new_tokens=4,
                           request_id="long")

        def inject(engine):
            if engine.ticks == t0 + 4:
                engine.submit(long_req)

        tr = tracing.Tracer(None, keep=True)
        with tracing.scoped(tr):
            eng.run(shorts, on_tick=inject)
        return [r for r in tr.records
                if r.get("name") == "req.decode_tick"]

    def prefill_per_tick(spans):
        """Seconds of prefill work per UNIQUE tick that carried any
        (the spans repeat per running stream)."""
        by_tick = {}
        for r in spans:
            pf = r.get("prefill_s") or 0.0
            if pf > 0:
                by_tick[r.get("tick")] = pf
        return sorted(by_tick.values())

    mono_spans = tick_spans(None)
    chunk_spans = tick_spans(32)
    mono = max(mono_spans, key=lambda r: r.get("dur_s") or 0.0)
    mono_prefill_share = ((mono.get("prefill_s") or 0.0)
                          / max(mono["dur_s"], 1e-12))
    # chunking bounds the TYPICAL per-tick prefill serialization (the
    # median over prefill-carrying ticks) even though the long request's
    # admission tick itself can spike — worst-vs-worst would compare two
    # one-off spikes, the median is the structural claim
    mono_pf = prefill_per_tick(mono_spans)
    chunk_pf = prefill_per_tick(chunk_spans)
    mono_med = mono_pf[len(mono_pf) // 2] if mono_pf else 0.0
    chunk_med = chunk_pf[len(chunk_pf) // 2] if chunk_pf else 1e9

    checks = {
        "violators_fully_retained": (
            len(roots_v) == n and eng_v.trace_violators == n),
        "compliant_sampled_1_in_n": (
            len(roots_s) == want_sampled
            and eng_s.trace_sampled == want_sampled),
        "one_bounded_histogram": (
            len(hists) == 1 and folded == n - want_sampled),
        "request_fractions_sum_to_1": (
            frac_sums_ok(rows_v) and frac_sums_ok(rows_s)),
        "report_attribution_sums_to_1": rollup_ok,
        "chrome_one_lane_per_sampled_request": (
            len(lanes) == want_sampled),
        "compare_flags_queue_inflation": bool(gate_trips),
        "compare_passes_self": bool(self_gate["ok"]),
        "monolithic_stall_attributed_to_prefill": mono_prefill_share > 0.5,
        "chunked_median_prefill_tick_below_monolithic": (
            chunk_med < mono_med),
    }
    return checks, {
        "requests": n,
        "trace_sample_n": sample_n,
        "violator_roots": len(roots_v),
        "sampled_roots": len(roots_s),
        "histogram_folded_ttft_n": folded,
        "report_attribution": attr,
        "chrome_request_lanes": len(lanes),
        "compare_regressed": gate["regressed"],
        "worst_tick_monolithic": {
            "dur_s": mono["dur_s"], "prefill_s": mono.get("prefill_s"),
            "prefill_share": round(min(mono_prefill_share, 1.0), 4)},
        "prefill_s_per_tick_median": {
            "monolithic": round(mono_med, 6), "chunked": round(chunk_med, 6),
            "monolithic_ticks": len(mono_pf), "chunked_ticks": len(chunk_pf)},
    }


def main() -> int:
    args = parse_args()
    phases = {}
    checks = {}
    for name, fn in (("baseline", phase_baseline),
                     ("shared_prefix", phase_shared_prefix),
                     ("long_prompt", phase_long_prompt_itl),
                     ("reqtrace", phase_reqtrace)):
        ph_checks, detail = fn(args)
        phases[name] = {"checks": ph_checks, **detail}
        for k, v in ph_checks.items():
            checks[f"{name}.{k}"] = v

    # the request-tracing phase ships its own atomic artifact (ISSUE 17
    # acceptance surface) in addition to riding the main record
    rt = phases["reqtrace"]
    atomic_write_json(args.reqtrace_output, {
        "bench": "serve_bench.reqtrace",
        "ok": all(rt["checks"].values()), **rt})

    record = {
        "bench": "serve_bench",
        "ok": all(checks.values()),
        "checks": checks,
        "config": {
            "requests": args.requests,
            "shared_requests": args.shared_requests,
            "shared_prefix_len": args.shared_prefix_len,
            "spec_k": args.spec_k,
            "prefill_chunk": args.prefill_chunk,
            "long_prompt": args.long_prompt,
            "rate_rps": args.rate, "max_batch": args.max_batch,
            "max_new_tokens": args.max_new_tokens,
            "model": {"hidden": args.hidden, "layers": args.layers,
                      "heads": args.heads, "vocab": args.vocab},
        },
        "phases": phases,
        "journal": args.journal,
        "note": ("latency magnitudes are a contended-CPU-container "
                 "measurement; the gated claims are the structural checks"),
    }
    # atomic (tmp + rename): a crash mid-write must never poison a
    # later `report compare` / evidence check with a torn artifact
    atomic_write_json(args.output, record)
    print(json.dumps({"ok": record["ok"],
                      "checks": {k: v for k, v in checks.items() if not v}
                      or "all passed",
                      "shared_stats": phases["shared_prefix"]["engine_stats"],
                      "itl_mono_p99": phases["long_prompt"][
                          "itl_ms_monolithic"].get("p99"),
                      "itl_chunk_p99": phases["long_prompt"][
                          "itl_ms_chunked"].get("p99"),
                      "output": args.output}))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
