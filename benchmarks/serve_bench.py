"""Serving evidence: an open-loop request generator against the engine.

ISSUE 10 performance bar: tokens/s/user and per-request p50/p99
time-to-first-token + inter-token latency for the paged-KV serving engine
(apex_tpu/serve/), measured under OPEN-LOOP load — requests arrive on the
generator's clock, not when the server is ready, so queueing and
continuous-batching admission are exercised, not idealized away. Off-TPU
runnable (virtual CPU devices): the absolute milliseconds on a contended
CPU container are not the claim; the claims the gate checks are structural:

- the engine serves every generated request to completion and releases
  every page and slot (no leaks under churn);
- the decode step's jit signature is SHAPE-STABLE across the whole run
  (``lint.trace.decode_recompile_hazards`` on the real tick argument
  stream, plus at most ONE compile journaled per program by the
  ``RecompileTracker`` criterion: tick count >> compile count);
- latency percentiles flow end-to-end through the existing journal →
  ``monitor.report`` pipeline: per-request TTFT/ITL records roll up into
  the report's serving section (p50/p99), and ``report compare`` gates a
  doubled-latency candidate;
- greedy decode still bit-matches the full-context forward argmax for a
  sampled request (the correctness gate riding along).

Writes ``out/serve_evidence.json`` (one JSON object, ``ok: true`` iff all
checks hold). Run:
    JAX_PLATFORMS=cpu python benchmarks/serve_bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
else:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()

from apex_tpu.lint.trace import decode_recompile_hazards
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.monitor import report as report_mod
from apex_tpu.monitor.journal import MetricsJournal
from apex_tpu.serve import Engine, Request, ServeConfig


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default="out/serve_evidence.json")
    p.add_argument("--journal", default="out/serve_bench.jsonl")
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--rate", type=float, default=40.0,
                   help="open-loop arrival rate (requests/s of host "
                        "wall clock; seeded-exponential gaps)")
    p.add_argument("--max-new-tokens", type=int, default=12)
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


class OpenLoopGenerator:
    """Arrivals on the GENERATOR's clock: request i becomes visible at
    ``t0 + sum(gaps[:i])`` regardless of engine progress — the queue
    depth under load is real, not an artifact of submit-then-drain."""

    def __init__(self, args):
        rng = np.random.default_rng(args.seed)
        self.gaps = rng.exponential(1.0 / args.rate, args.requests)
        self.arrivals = np.cumsum(self.gaps)
        self.prompts = [list(rng.integers(0, args.vocab,
                                          int(rng.integers(3, 20))))
                        for _ in range(args.requests)]
        self.max_new = args.max_new_tokens
        self.t0 = time.perf_counter()
        self.next_idx = 0

    def poll(self, engine) -> None:
        """Submit every request whose arrival time has passed (the
        engine's on_tick hook)."""
        now = time.perf_counter() - self.t0
        while (self.next_idx < len(self.arrivals)
               and self.arrivals[self.next_idx] <= now):
            i = self.next_idx
            req = Request(prompt=self.prompts[i], max_new_tokens=self.max_new,
                          request_id=i)
            engine.submit(req)
            self.next_idx += 1

    @property
    def done(self) -> bool:
        return self.next_idx >= len(self.arrivals)


def main() -> int:
    args = parse_args()
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=64, hidden_dropout=0.0, axis=None,
        compute_dtype=jnp.float32, remat=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))
    engine = Engine(model, params, ServeConfig(
        max_batch=args.max_batch, max_seq=48, block_size=8,
        seed=args.seed))

    os.makedirs(os.path.dirname(os.path.abspath(args.journal)),
                exist_ok=True)
    if os.path.exists(args.journal):
        os.unlink(args.journal)
    gen = OpenLoopGenerator(args)
    results = {}
    with MetricsJournal(args.journal, meta={
            "run": "serve_bench", "requests": args.requests,
            "rate_rps": args.rate, "max_batch": args.max_batch}) as journal:
        # drive until every generated request has been served; the
        # generator injects arrivals from the on_tick hook, and between
        # bursts the loop idles on the generator clock
        gen.poll(engine)
        while not gen.done or not engine.batcher.idle:
            if engine.batcher.idle:
                time.sleep(0.005)  # open-loop: wait for the next arrival
                gen.poll(engine)
                continue
            results.update(engine.run(journal=journal, max_ticks=engine.ticks + 1,
                                      on_tick=gen.poll))
            gen.poll(engine)
    served = len(results)

    # correctness rider: greedy == full-forward argmax for a sample
    sample = results[min(results)]
    seq = list(sample.prompt) + sample.tokens
    ref = np.asarray(jnp.argmax(
        model.apply(params, jnp.asarray([seq], jnp.int32))[0], -1))
    greedy_ok = all(int(ref[t - 1]) == seq[t]
                    for t in range(len(sample.prompt), len(seq)))

    # decode signature shape-stability on the REAL tick argument stream
    tripwire = decode_recompile_hazards(engine.decode_args, ticks=3)

    # journal -> report: the latency section must render, and the
    # compare gate must flag a doubled-latency candidate
    rows = MetricsJournal.read(args.journal)
    analysis = report_mod.analyze(rows)
    serving = analysis.get("serving") or {}
    doubled = []
    for r in rows:
        r2 = dict(r)
        if r2.get("kind") == "request":
            if isinstance(r2.get("ttft_s"), (int, float)):
                r2["ttft_s"] = 2.5 * r2["ttft_s"]
            if isinstance(r2.get("itl_s"), list):
                r2["itl_s"] = [2.5 * v for v in r2["itl_s"]
                               if isinstance(v, (int, float))]
        doubled.append(r2)
    gate = report_mod.compare(rows, doubled, threshold=0.10)
    gate_fires = (not gate["ok"]
                  and any(c in gate["regressed"]
                          for c in ("ttft_ms_p50", "itl_ms_p50")))
    self_gate = report_mod.compare(rows, rows, threshold=0.10)

    checks = {
        "served_all_requests": served == args.requests,
        "no_page_or_slot_leaks": (engine.allocator.used == 0
                                  and engine.batcher.idle),
        "greedy_matches_full_forward_argmax": bool(greedy_ok),
        "decode_signature_shape_stable": not tripwire["hazard"],
        "report_has_serving_section": bool(
            serving.get("ttft_ms") and serving.get("itl_ms")),
        "compare_gates_doubled_latency": bool(gate_fires),
        "compare_passes_self": bool(self_gate["ok"]),
    }
    record = {
        "bench": "serve_bench",
        "ok": all(checks.values()),
        "checks": checks,
        "config": {
            "requests": args.requests, "rate_rps": args.rate,
            "max_batch": args.max_batch, "max_new_tokens": args.max_new_tokens,
            "model": {"hidden": args.hidden, "layers": args.layers,
                      "heads": args.heads, "vocab": args.vocab},
            "pool_blocks": engine.allocator.num_blocks - 1,
            "block_size": engine.config.block_size,
        },
        "decode_ticks": engine.ticks,
        "serving": serving,
        "tokens_per_sec_per_user": serving.get("tokens_per_sec_per_user"),
        "ttft_ms": serving.get("ttft_ms"),
        "itl_ms": serving.get("itl_ms"),
        "tripwire": {"hazard": tripwire["hazard"],
                     "leaves": tripwire["leaves"],
                     "ticks": tripwire["ticks"]},
        "journal": args.journal,
        "note": ("latency magnitudes are a contended-CPU-container "
                 "measurement; the gated claims are the structural checks"),
    }
    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    with open(args.output, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({"ok": record["ok"], "served": served,
                      "ticks": engine.ticks, "checks": checks,
                      "output": args.output}))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
