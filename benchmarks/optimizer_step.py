"""Optimizer-step microbenchmark: fused tree-map step vs unfused eager Adam.

BASELINE.md target #3 ("fused-optimizer step >= 3x an unfused eager Adam")
measured directly, the way the reference frames it: its multi-tensor fused
optimizers exist to replace the per-parameter, per-op kernel launches of an
eager `torch.optim.Adam` loop (csrc/multi_tensor_apply.cuh:16-133,
tests/L0/run_optimizers/test_fused_optimizer.py).

TPU-native translation of the two sides:
- **fused**: `FusedAdam`'s whole-tree update inside one `jax.jit` — XLA
  compiles one fused elementwise pass over every parameter (the
  multi-tensor-launch-batching equivalent).
- **eager**: the same Adam math, one parameter at a time, *outside* jit —
  every `jnp` op is dispatched individually, exactly like eager torch issuing
  separate kernels per param and per op.

Run standalone (`python benchmarks/optimizer_step.py`) for a JSON line, or
call :func:`measure_speedup` (bench.py does, to record the ratio in the
driver's benchmark artifact).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp


def gpt2_like_param_tree(hidden=768, layers=12, vocab=50304, seq=1024, dtype=jnp.float32):
    """A GPT-2-124M-shaped parameter pytree (~148 leaves, ~124M params):
    realistic leaf-count/size mix for the launch-overhead comparison."""
    k = jax.random.PRNGKey(0)

    def rnd(shape):
        nonlocal k
        k, sub = jax.random.split(k)
        return (jax.random.normal(sub, shape, jnp.float32) * 0.02).astype(dtype)

    tree = {
        "wte": rnd((vocab, hidden)),
        "wpe": rnd((seq, hidden)),
        "ln_f": {"scale": jnp.ones((hidden,), dtype), "bias": jnp.zeros((hidden,), dtype)},
    }
    for i in range(layers):
        tree[f"h{i}"] = {
            "ln_1": {"scale": jnp.ones((hidden,), dtype), "bias": jnp.zeros((hidden,), dtype)},
            "attn": {
                "qkv_w": rnd((hidden, 3 * hidden)),
                "qkv_b": jnp.zeros((3 * hidden,), dtype),
                "proj_w": rnd((hidden, hidden)),
                "proj_b": jnp.zeros((hidden,), dtype),
            },
            "ln_2": {"scale": jnp.ones((hidden,), dtype), "bias": jnp.zeros((hidden,), dtype)},
            "mlp": {
                "fc_w": rnd((hidden, 4 * hidden)),
                "fc_b": jnp.zeros((4 * hidden,), dtype),
                "proj_w": rnd((4 * hidden, hidden)),
                "proj_b": jnp.zeros((hidden,), dtype),
            },
        }
    return tree


def _fetch(tree):
    """Force execution through the tunnel: device->host fetch of a scalar
    whose dependency chain covers every leaf (see PERF_NOTES.md: through the
    axon tunnel block_until_ready can ack dispatch, not execution)."""
    return float(sum(jnp.sum(l[..., :1]) for l in jax.tree.leaves(tree)))


def eager_adam_step(params, m, v, grads, t, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    """Unfused eager Adam: per-leaf python loop, no jit — each jnp op is its
    own dispatch (the eager `torch.optim.Adam` analog)."""
    flat_p, treedef = jax.tree.flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(m)
    flat_v = treedef.flatten_up_to(v)
    bc1 = 1.0 - b1 ** t
    bc2 = 1.0 - b2 ** t
    out_p, out_m, out_v = [], [], []
    for p, g, mi, vi in zip(flat_p, flat_g, flat_m, flat_v):
        mi = b1 * mi + (1.0 - b1) * g
        vi = b2 * vi + (1.0 - b2) * jnp.square(g)
        m_hat = mi / bc1
        v_hat = vi / bc2
        p = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        out_p.append(p)
        out_m.append(mi)
        out_v.append(vi)
    unflatten = treedef.unflatten
    return unflatten(out_p), unflatten(out_m), unflatten(out_v)


def measure_speedup(hidden=768, layers=12, fused_steps=10, eager_steps=3,
                    windows=3, verbose=True):
    """Returns (speedup, fused_ms, eager_ms) for one optimizer step.

    Both sides are timed as MEDIANS over ``windows`` INTERLEAVED windows
    (fused, eager, fused, eager, …) — through the shared tunnel chip a
    single un-windowed sample swings several-fold with co-tenant drift
    (observed 2.9x–38x across identical runs), and interleaving keeps the
    ratio a comparison of the same minutes (PERF_NOTES.md discipline)."""
    import optax

    from apex_tpu.optimizers import FusedAdam

    params = gpt2_like_param_tree(hidden=hidden, layers=layers)
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)

    tx = FusedAdam(lr=1e-3)
    state = tx.init(params)

    @jax.jit
    def fused_step(params, state, grads):
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    # warmups: compile the fused program, exercise the eager dispatch path
    p, s = fused_step(params, state, grads)
    _fetch(p)
    m = jax.tree.map(lambda x: jnp.zeros_like(x), params)
    v = jax.tree.map(lambda x: jnp.zeros_like(x), params)
    ep, em, ev = eager_adam_step(params, m, v, grads, t=1)
    _fetch(ep)

    fused_samples, eager_samples = [], []
    t = 2
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(fused_steps):
            p, s = fused_step(p, s, grads)
        _fetch(p)
        fused_samples.append((time.perf_counter() - t0) / fused_steps * 1e3)

        t0 = time.perf_counter()
        for _ in range(eager_steps):
            ep, em, ev = eager_adam_step(ep, em, ev, grads, t=t)
            t += 1
        _fetch(ep)
        eager_samples.append((time.perf_counter() - t0) / eager_steps * 1e3)

    import statistics

    # pair SAME-WINDOW samples: the median of per-window ratios compares
    # the two sides under the same minutes of drift, which independent
    # medians (possibly from different windows) would not
    speedup = statistics.median(
        e / f for f, e in zip(fused_samples, eager_samples))
    fused_ms = statistics.median(fused_samples)
    eager_ms = statistics.median(eager_samples)
    if verbose:
        print(
            f"optimizer step ({layers}-layer/{hidden}-hidden tree, "
            f"{len(jax.tree.leaves(params))} leaves): fused {fused_ms:.2f} ms, "
            f"eager {eager_ms:.2f} ms, speedup {speedup:.1f}x",
            file=sys.stderr,
        )
    return speedup, fused_ms, eager_ms


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    speedup, fused_ms, eager_ms = measure_speedup()
    print(
        json.dumps(
            {
                "metric": "fused_adam_step_vs_eager_adam_step",
                "value": round(speedup, 2),
                "unit": "x",
                "fused_ms": round(fused_ms, 3),
                "eager_ms": round(eager_ms, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
