"""Quick 345M placement probe: one top-rung prepare + a timed step.

Not part of the bench record — a session tool to detect when the
co-tenant HBM occupation lifts (PERF_NOTES r5) so the full headline
can be re-driven.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
import bench

try:
    advance, get_loss, n_chunks, units, state, batch, rung = (
        bench.prepare_resilient("O2", "auto", 8, 1024, 10,
                                min_batch=8, retries=0))
except Exception as e:  # noqa: BLE001
    print(f"PROBE: unplaceable ({str(e)[:120]})")
    sys.exit(1)
t0 = time.perf_counter()
advance()
get_loss()
dt = time.perf_counter() - t0
print(f"PROBE: PLACED batch={batch} rung={rung} "
      f"{units / dt:.0f} tok/s ({dt * 1e3 / (10 * n_chunks * 4):.1f} ms/step-ish)")
