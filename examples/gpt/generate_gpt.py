"""GPT serving: prompts → tokens through the paged-KV inference engine.

The decode-side sibling of pretrain_gpt.py: loads (or randomly initializes)
a GPT, builds an ``apex_tpu.serve.Engine`` (paged KV cache, flash-decode,
continuous batching over a fixed slot array), serves a prompt file, and
prints per-request tokens plus TTFT/ITL latency. TP-sharded decode with
``--tp``; sliding-window attention with ``--window``; the same ``--journal``
/ ``--trace`` observability hooks as the trainers.

ISSUE 12 knobs: ``--prefix-cache`` shares matched prompt-prefix KV blocks
by refcount (COW on divergence), ``--prefill-chunk N`` splits prompts into
N-token static chunks interleaved with decode ticks, ``--spec-k K`` drafts
K tokens per tick and verifies them in one batched forward (greedy only;
``--draft-layers`` builds a smaller randomly-initialized draft — omit it to
self-draft with the target, which demonstrates full acceptance), and
``--shared-prefix N`` prepends a common N-token system prompt to every
synthetic request so the prefix cache has something to share.

Run on 8 virtual devices:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        python examples/gpt/generate_gpt.py --tp 2 --max-new-tokens 16
Prompt file format: one request per line, space-separated token ids.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: shard_map/axis_size API renames

from apex_tpu import checkpoint
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.serve import Engine, Request, ServeConfig


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50304)
    p.add_argument("--max-seq", type=int, default=256)
    p.add_argument("--window", type=int, default=None,
                   help="sliding-window attention (flash_attention/"
                        "flash_decode window semantics)")
    p.add_argument("--pos", default="learned",
                   choices=["learned", "rope", "none"])
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; otherwise categorical at this "
                        "temperature with per-slot PRNG keys")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--prefix-cache", action="store_true",
                   help="share matched prompt-prefix KV blocks between "
                        "requests (refcounts + copy-on-write; prefill "
                        "skips to the divergence point)")
    p.add_argument("--prefill-chunk", type=int, default=None, metavar="N",
                   help="split prompts into N-token static chunks, one "
                        "per tick interleaved with decode (a long prompt "
                        "never stalls running streams)")
    p.add_argument("--spec-k", type=int, default=0, metavar="K",
                   help="speculative decoding: K draft tokens per slot "
                        "per tick, verified in one batched forward "
                        "(greedy only)")
    p.add_argument("--draft-layers", type=int, default=None, metavar="L",
                   help="with --spec-k: build an L-layer randomly-"
                        "initialized draft model (default: self-draft "
                        "with the target weights)")
    p.add_argument("--shared-prefix", type=int, default=0, metavar="N",
                   help="prepend a common N-token system prompt to every "
                        "synthetic request (the shared-prefix workload "
                        "knob for --prefix-cache)")
    p.add_argument("--prompt-file", default=None,
                   help="one request per line, space-separated token ids "
                        "(default: a few synthetic prompts)")
    p.add_argument("--load-dir", default=None,
                   help="restore {'params': ...} from a training "
                        "checkpoint dir (apex_tpu.checkpoint); ZeRO-3 "
                        "states export via Engine.params_from_zero3")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="write per-tick + per-request JSON-lines metrics "
                        "(TTFT/ITL/queue depth/occupancy; roll up with "
                        "python -m apex_tpu.monitor.report)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write serve.prefill/serve.decode spans "
                        "(apex_tpu.monitor.tracing) + a Chrome export "
                        "next to PATH")
    p.add_argument("--flight", nargs="?", const="auto", default=None,
                   metavar="PATH",
                   help="arm the flight recorder (apex_tpu.monitor."
                        "flight): recent tick/request records + "
                        "breadcrumbs dumped as strict JSON on crash/"
                        "SIGTERM/watchdog kill. Default PATH: "
                        "<journal>.flight.json")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT target in ms: with --journal, the engine "
                        "emits per-window kind=\"slo\" attainment/goodput "
                        "records (monitor.report slo section; the "
                        "slo-burn health rule gates attainment)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="ITL target in ms (see --slo-ttft-ms)")
    p.add_argument("--trace-sample-n", type=int, default=16, metavar="N",
                   help="tail-based sampling rate for request span trees "
                        "under --trace: every SLO violator keeps its full "
                        "tree, plus 1-in-N compliant requests; the rest "
                        "fold into one bounded kind=\"reqhist\" record")
    p.add_argument("--ledger", nargs="?", const="out/ledger.jsonl",
                   default=None, metavar="PATH",
                   help="append one fingerprinted run record (serve "
                        "config + environment stamp + measured TTFT/ITL "
                        "rollup) to the run ledger "
                        "(apex_tpu.monitor.ledger); "
                        "APEX_TPU_LEDGER=<path> arms it too")
    args = p.parse_args()
    if not args.ledger and os.environ.get("APEX_TPU_LEDGER"):
        args.ledger = os.environ["APEX_TPU_LEDGER"]
    if args.flight == "auto":
        args.flight = ((args.journal + ".flight.json") if args.journal
                       else "out/generate_gpt.flight.json")
    return args


def load_prompts(args) -> list:
    if args.prompt_file:
        prompts = []
        with open(args.prompt_file) as f:
            for line in f:
                toks = [int(t) % args.vocab for t in line.split()]
                if toks:
                    prompts.append(toks)
        return prompts
    rng = np.random.default_rng(args.seed)
    shared = list(rng.integers(0, args.vocab, args.shared_prefix))
    return [shared + list(rng.integers(0, args.vocab, n))
            for n in (5, 12, 3, 9, 17, 7)]


def main():
    args = parse_args()
    mesh = None
    if args.tp > 1:
        mesh = mesh_lib.make_virtual_mesh(
            len(jax.devices()), tensor_model_parallel_size=args.tp)
    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_attention_heads=args.heads,
        max_seq_len=args.max_seq,
        hidden_dropout=0.0,
        axis=mesh_lib.AXIS_MODEL if args.tp > 1 else None,
        compute_dtype=jnp.float32,
        remat=False,
        attention_window=args.window,
        position_embedding=args.pos,
    )
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))
    if args.load_dir:
        params = checkpoint.restore_checkpoint(
            args.load_dir, {"params": params})["params"]
        print(f"restored params from {args.load_dir}")

    tracer = None
    if args.trace:
        from apex_tpu.monitor import tracing

        tracer = tracing.arm(args.trace,
                             meta={"run": "generate_gpt", "tp": args.tp})
    # one serve-config dict for the journal's kind="meta" header AND the
    # ledger record's fingerprinted config block
    run_config = {"run": "generate_gpt", "tp": args.tp,
                  "max_batch": args.max_batch, "max_seq": args.max_seq,
                  "block_size": args.block_size,
                  "window": args.window or 0,
                  "prefix_cache": bool(args.prefix_cache),
                  "prefill_chunk": args.prefill_chunk or 0,
                  "spec_k": args.spec_k or 0}
    journal = None
    if args.journal:
        from apex_tpu.monitor import MetricsJournal
        from apex_tpu.monitor.health import HealthMonitor

        journal = MetricsJournal(
            args.journal,
            meta=run_config,
            # stream every tick/request/slo record through the online
            # health rules; alerts land in this journal
            health=HealthMonitor())
    if args.flight:
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.arm(args.flight,
                       meta={"run": "generate_gpt", "tp": args.tp})

    draft_model = draft_params = None
    if args.spec_k and args.draft_layers:
        import dataclasses

        draft_model = GPTModel(dataclasses.replace(
            cfg, num_layers=args.draft_layers))
        draft_params = draft_model.init(jax.random.PRNGKey(args.seed + 1))
    engine = Engine(model, params, ServeConfig(
        max_batch=args.max_batch, max_seq=args.max_seq,
        block_size=args.block_size, temperature=args.temperature,
        top_k=args.top_k, seed=args.seed,
        prefix_cache=args.prefix_cache, prefill_chunk=args.prefill_chunk,
        spec_k=args.spec_k,
        slo_ttft_ms=args.slo_ttft_ms, slo_itl_ms=args.slo_itl_ms,
        trace_sample_n=args.trace_sample_n),
        mesh=mesh,
        draft_model=draft_model, draft_params=draft_params)
    prompts = load_prompts(args)
    budget = args.max_seq - args.max_new_tokens
    reqs = [Request(prompt=pr[:max(budget, 1)],
                    max_new_tokens=args.max_new_tokens, request_id=i)
            for i, pr in enumerate(prompts)]
    results = engine.run(reqs, journal=journal)

    for rid in sorted(results):
        r = results[rid]
        itl_ms = (1e3 * float(np.median(r.itl_s)) if r.itl_s else None)
        cached = f" | cached {r.cached_tokens} tok" if r.cached_tokens else ""
        print(f"request {rid}: prompt {len(r.prompt)} tok -> "
              f"{len(r.tokens)} new | ttft {1e3 * r.ttft_s:.1f} ms | "
              f"itl p50 {itl_ms and round(itl_ms, 2)} ms{cached}")
        print(f"  tokens: {r.tokens}")
    print(f"{len(results)} request(s) in {engine.ticks} decode tick(s) | "
          f"mesh tp={args.tp} | pool "
          f"{engine.allocator.num_blocks - 1} x {args.block_size} tokens")
    stats = engine.stats
    if args.prefix_cache or args.spec_k:
        print("serving stats: " + ", ".join(
            f"{k}={v}" for k, v in stats.items()))
    engine.drop_prefix_cache()

    if journal is not None:
        journal.close()
    if args.ledger:
        try:
            from apex_tpu.monitor import ledger as ledger_mod

            measured = None
            if not args.journal:
                # journal-less serve: a minimal measured block in the
                # report-rollup key shapes (serving section percentiles)
                ttfts = sorted(1e3 * r.ttft_s for r in results.values())
                itls = sorted(1e3 * s for r in results.values()
                              for s in r.itl_s)
                mid = lambda xs: xs[len(xs) // 2] if xs else None  # noqa: E731
                serving = {"requests": len(results)}
                if ttfts:
                    serving["ttft_ms"] = {"p50": round(mid(ttfts), 3)}
                if itls:
                    serving["itl_ms"] = {"p50": round(mid(itls), 3)}
                # attribution rides the ledger even journal-less, so
                # `ledger regress` can gate TTFT-attribution drift
                from apex_tpu.monitor.report import attribution_rollup

                attr = attribution_rollup(
                    [r.attribution for r in results.values()
                     if isinstance(r.attribution, dict)])
                if attr:
                    serving["attribution"] = attr
                measured = {"step_records": engine.ticks,
                            "serving": serving}
            rec = ledger_mod.append_run(
                args.ledger, run="generate_gpt", config=run_config,
                journal=args.journal, measured=measured,
                extra={"ticks": engine.ticks})
            print(f"ledger: {rec['fingerprint']} -> {args.ledger}")
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"ledger append failed: {e}")
    if args.flight:
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.disarm()  # clean exit: restore hooks, no dump
    if tracer is not None:
        from apex_tpu.monitor import tracing

        tracing.disarm()
        try:
            tracing.write_chrome_trace(args.trace,
                                       args.trace + ".chrome.json")
            print(f"chrome trace: {args.trace}.chrome.json")
        except Exception as e:  # noqa: BLE001
            print(f"chrome export failed: {e}")
    if mesh is not None:
        mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
