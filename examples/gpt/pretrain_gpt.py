"""GPT pretraining with hybrid TP x PP x DP over a device mesh.

The flagship recipe (reference: apex/transformer/testing/standalone_gpt.py
driven by run_gpt_minimal_test.py / gpt_scaling_test.py): Megatron-style GPT
with tensor parallelism over the ``model`` axis, SPMD pipeline over ``pipe``,
data parallelism over ``data``, O2 mixed precision with fused Adam and
dynamic loss scaling, streaming token batches (native TokenLoader or
synthetic), and periodic checkpointing.

Run on 8 virtual devices:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/gpt/pretrain_gpt.py --tp 2 --pp 2 --steps 10
Run serial on one real TPU chip:
    python examples/gpt/pretrain_gpt.py --tp 1 --pp 1 --steps 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: shard_map/axis_size API renames

from apex_tpu import amp, checkpoint
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives, mesh as mesh_lib
from apex_tpu.parallel.distributed import (
    allreduce_gradients,
    allreduce_gradients_by_spec,
)
from apex_tpu.parallel.multiproc import initialize_distributed
from apex_tpu.transformer import tensor_parallel as tp_mod
from apex_tpu.transformer.pipeline_parallel import pipeline_specs, pipelined_loss_fn


def _apply_plan(args):
    """Run the static placement search (``apex_tpu.plan``, ISSUE 18) over
    this run's model shape on the ambient device count and write the
    winner's placement back onto ``args`` — the same knobs a human would
    have passed. Prints ONE strict-JSON plan line; the winner's predicted
    anatomy rides on ``args.plan_predicted`` so the ledger's predicted
    block carries the planner's numbers (hbm/bubble/comm/step-seconds)
    for the calibrate join."""
    from apex_tpu import plan as plan_mod

    spec = plan_mod.ModelSpec(
        "pretrain_gpt", args.vocab, args.hidden, args.layers, args.heads,
        args.seq, moe_experts=args.moe_experts or 0,
        moe_top_k=args.moe_top_k)
    result = plan_mod.search(
        spec, mesh=len(jax.devices()), hbm_gb=args.plan_hbm_gb,
        islands=args.mesh_islands,
        micro_batch=args.micro_batch,
        num_microbatches=args.num_microbatches,
        # this harness exposes no sequence-parallel or attention-window
        # knobs — search only what it can express
        constraints={"sp": False, "attention_window": None})
    winner = result["winner"]
    if winner is None:
        by = {}
        for r in result["rejected"]:
            by[r["rejected_by"]] = by.get(r["rejected_by"], 0) + 1
        raise SystemExit(
            f"--plan auto: no feasible placement for this shape under "
            f"{args.plan_hbm_gb} GiB/rank (rejected: {by}); raise "
            f"--plan-hbm-gb or add devices")
    c = winner["candidate"]
    args.tp, args.pp = c["tp"], c["pp"]
    if c["schedule"]:
        args.pp_schedule = c["schedule"]
        if c["schedule"] == "interleaved":
            args.vpp = c["vpp"]
    args.unroll = bool(c["unroll"])
    args.zero = c["zero_level"] > 0
    args.zero_level = c["zero_level"] or None
    args.zero3_prefetch = c["zero3_prefetch"]
    args.zero_gather = c["gather_dtype"]
    args.reduce_dtype = c["reduce_dtype"]
    if c.get("islands", 1) > 1:
        # per-tier wire verdict (the dcn-bound/EQuARX rule): the winner
        # names the DCN hop's dtype — 'none' keeps the exact fp32 hop
        args.dcn_wire = c["dcn_wire"] or "none"
    if c["moe_expert_axis"]:
        args.moe_dispatch_dtype = c["moe_dispatch_dtype"]
    args.plan_predicted = winner["predicted"]
    print(json.dumps({"plan": {
        "winner": c,
        "predicted": {
            "hbm_bytes": winner["predicted"]["hbm_bytes"],
            "comm_bytes_by_tier":
                winner["predicted"]["comm_bytes_by_tier"],
            "bubble_floor": winner["predicted"]["bubble_floor"],
            "step_seconds": winner["predicted"]["step_seconds"],
        },
        "mesh": result["mesh"],
        "hbm_budget_bytes": result["hbm_budget_bytes"],
        "n_ranked": len(result["ranked"]),
        "n_rejected": len(result["rejected"]),
        "peak_source": result["peak_spec"]["source"],
        "ici_source": result["ici_spec"]["source"]}}))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50304)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--micro-batch", type=int, default=2)
    p.add_argument("--num-microbatches", type=int, default=2)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--pp-schedule", default="1f1b",
                   choices=["gpipe", "1f1b", "interleaved", "zerobubble"],
                   help="pipeline schedule (schedule-as-data planners, "
                        "transformer/pipeline_parallel/schedules.py). "
                        "gpipe|1f1b share the compiled SPMD ring (the "
                        "AD-transposed drain IS 1F1B's cooldown); "
                        "interleaved adds vpp virtual chunks per stage "
                        "(--vpp); zerobubble drives the explicit W/B-split "
                        "executor (schedule_grads_fn: bwd_weight slots of "
                        "early microbatches fill the cooldown — needs "
                        "pp>1, tp=1, zero level < 3)")
    p.add_argument("--vpp", type=int, default=None,
                   help="virtual pipeline chunks per stage for "
                        "--pp-schedule interleaved (default 2 there, "
                        "1 otherwise); layers are interleave_stack-"
                        "permuted, checkpoints store that order")
    p.add_argument("--zero3-prefetch", type=int, default=0, metavar="N",
                   help="double-buffer the ZeRO-3 per-layer chunk "
                        "all-gathers N layers ahead (forward and backward "
                        "re-gathers; needs --zero-level 3 and --unroll — "
                        "models/_transformer._prefetched_zero3_drive)")
    p.add_argument("--unroll", action="store_true",
                   help="drive the layer stack with static slices instead "
                        "of lax.scan (kills the scan backward's grad "
                        "stacking, PERF_NOTES r5; compile time O(depth))")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO: shard fp32 masters + Adam moments over the "
                        "data axis (optimizer memory / dp; the grad "
                        "all-reduce becomes psum_scatter + all_gather)")
    p.add_argument("--zero-level", type=int, default=None, choices=(1, 2, 3),
                   help="ZeRO stage (implies --zero). 1/2: masters+moments "
                        "shard 1/dp, bf16 params replicated. 3: the bf16 "
                        "params shard too — each layer's weights are "
                        "all-gathered just-in-time inside the layer loop "
                        "and grads reduce-scatter per layer (no bulk "
                        "post-update gather)")
    p.add_argument("--zero-gather", default=None, choices=["bf16", "int8"],
                   help="compress the ZeRO param all-gather payload "
                        "(bf16 halves gather bytes; int8 quantizes to "
                        "1 B/elem at a per-chunk fp32 scale — "
                        "parallel/quantize.py; fp32 masters stay exact)")
    p.add_argument("--reduce-dtype", default=None, choices=["int8", "e5m2"],
                   help="quantize the ZeRO grad reduce-scatter wire "
                        "(requires --zero, levels 1/2): the fp32 "
                        "psum_scatter becomes the encoded all_to_all pair "
                        "at 1 B/elem + per-chunk fp32 scales, with an "
                        "error-feedback residual in the sharded optimizer "
                        "state (parallel/quantize.py)")
    p.add_argument("--mesh-islands", type=int, default=1, metavar="N",
                   help="model the mesh as N ICI islands joined by DCN "
                        "(parallel/hierarchy.py): a leading 'dcn' mesh "
                        "axis joins the data-parallel group — batches "
                        "shard over (dcn, data) and the ZeRO grad "
                        "reduction decomposes hierarchically (intra-"
                        "island reduce-scatter, ONE 1/n_ici-sized inter-"
                        "island exchange, intra-island gather) so the "
                        "slow tier never carries the full payload "
                        "(tripwire: lint.trace."
                        "flat_dcn_collective_hazards). Requires --zero "
                        "at levels 1/2")
    p.add_argument("--dcn-wire", default="int8",
                   choices=["int8", "e5m2", "none"],
                   help="wire dtype of the inter-island (DCN) gradient "
                        "hop when --mesh-islands > 1. Defaults ON at "
                        "int8 — the EQuARX deployment point: quantize "
                        "exactly where the slow tier binds, with an "
                        "error-feedback residual in the sharded "
                        "optimizer state; 'none' keeps the hop exact "
                        "fp32 (parallel/hierarchy.py hier_scatter_chunk)")
    p.add_argument("--offload-optimizer", action="store_true",
                   help="host-offload the cold ZeRO optimizer state "
                        "(optimizers/offload.py HostOffloadedZero): fp32 "
                        "masters + moments (+ residual) live in host RAM "
                        "between steps and stream through HBM in "
                        "--offload-buckets contiguous buckets, bucket "
                        "b+1's async H2D prefetched under bucket b's "
                        "update — bit-identical step math, optimizer "
                        "HBM bounded by the two largest buckets. "
                        "Requires --zero at levels 1/2")
    p.add_argument("--offload-buckets", type=int, default=2, metavar="N",
                   help="bucket count for --offload-optimizer (more "
                        "buckets = less peak HBM, more H2D/D2H trips)")
    p.add_argument("--moe-experts", type=int, default=None, metavar="E",
                   help="route every layer's FFN through a top-k MoE with "
                        "E experts (transformer/moe.py); with dp > 1 the "
                        "experts shard over the data axis and tokens "
                        "dispatch with all_to_all (expert parallelism — "
                        "EP x TP when --tp > 1); aux router losses fold "
                        "into the loss via aux_to_loss")
    p.add_argument("--moe-top-k", type=int, default=2,
                   help="experts per token (1 = Switch, 2 = GShard)")
    p.add_argument("--moe-capacity-factor", type=float, default=1.25,
                   help="capacity slack over the balanced share; tokens "
                        "over an expert's cap are dropped (the "
                        "dropped_fraction aux metric reports the rate)")
    p.add_argument("--moe-dispatch-dtype", default=None,
                   choices=["int8", "e5m2"],
                   help="quantize the expert-parallel dispatch/combine "
                        "all_to_all wire to 1 B/elem + fp32 per-block "
                        "scales (parallel/quantize.quantized_all_to_all; "
                        "needs --moe-experts and dp > 1)")
    p.add_argument("--plan", default=None, metavar="auto",
                   help="'auto': run the static placement search "
                        "(apex_tpu.plan) over THIS model shape on the "
                        "ambient device count and adopt the winner's "
                        "placement (tp/pp/schedule/zero/prefetch/wire/"
                        "unroll knobs overridden; one JSON plan line is "
                        "printed; the winner's predicted anatomy seeds "
                        "the ledger's predicted block)")
    p.add_argument("--plan-hbm-gb", type=float, default=16.0,
                   help="per-rank HBM budget the --plan search prices "
                        "candidates against (GiB)")
    p.add_argument("--data", default=None, help="dir of .bin int32 token files")
    p.add_argument("--save-dir", default=None)
    p.add_argument("--save-every", type=int, default=100)
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="write a per-step JSON-lines metrics journal "
                        "(apex_tpu.monitor: wall time, tokens/s, loss, "
                        "grad-norm, loss-scale state, HBM samples); adds "
                        "one loss fetch per step")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write a span trace (apex_tpu.monitor.tracing): "
                        "per-step spans, ZeRO grads/apply phase spans "
                        "(two-program step build), a traced pipeline "
                        "tick drive measuring per-rank bubble fraction "
                        "(pp>1, tp=1), and a Chrome trace-event export "
                        "next to PATH (chrome://tracing / Perfetto)")
    p.add_argument("--ledger", nargs="?", const="out/ledger.jsonl",
                   default=None, metavar="PATH",
                   help="append one fingerprinted run record (config + "
                        "environment stamp + measured rollup + predicted "
                        "block) to the run ledger "
                        "(apex_tpu.monitor.ledger; analyze with `python "
                        "-m apex_tpu.monitor.ledger "
                        "{list,trend,regress,calibrate}`); "
                        "APEX_TPU_LEDGER=<path> arms it too")
    p.add_argument("--flight", nargs="?", const="auto", default=None,
                   metavar="PATH",
                   help="arm the flight recorder (apex_tpu.monitor."
                        "flight): a bounded in-memory ring of recent "
                        "journal/span records + breadcrumbs dumped as "
                        "strict JSON on unhandled exception, SIGTERM, or "
                        "watchdog kill — with an HBM snapshot and the "
                        "last loss-scale state. Default PATH: "
                        "<journal>.flight.json")
    args = p.parse_args()
    if args.plan:
        if args.plan != "auto":
            p.error("--plan accepts 'auto' (the static placement search)")
        _apply_plan(args)
    if not args.ledger and os.environ.get("APEX_TPU_LEDGER"):
        args.ledger = os.environ["APEX_TPU_LEDGER"]
    if args.flight == "auto":
        args.flight = ((args.journal + ".flight.json") if args.journal
                       else "out/pretrain_gpt.flight.json")
    if args.zero_level is not None:
        args.zero = True
    elif args.zero:
        args.zero_level = 2
    if args.zero_gather and not args.zero:
        p.error("--zero-gather requires --zero")
    if args.reduce_dtype and not args.zero:
        p.error("--reduce-dtype requires --zero (it is the ZeRO grad "
                "reduce-scatter wire dtype)")
    if args.vpp is None:
        args.vpp = 2 if args.pp_schedule == "interleaved" else 1
    if args.vpp > 1 and args.pp_schedule != "interleaved":
        p.error("--vpp > 1 is the interleaved schedule's knob")
    if args.pp_schedule == "interleaved" and args.vpp < 2:
        p.error("--pp-schedule interleaved needs --vpp >= 2")
    if args.pp_schedule == "zerobubble":
        if args.pp < 2 or args.tp > 1:
            p.error("--pp-schedule zerobubble needs --pp >= 2 and --tp 1 "
                    "(the explicit-backward executor drives the pipe axis "
                    "only)")
        if (args.zero_level or 0) >= 3:
            p.error("--pp-schedule zerobubble composes with ZeRO levels "
                    "1/2 only (level 3 rebuilds the pipelined loss)")
    if args.zero3_prefetch:
        if (args.zero_level or 0) < 3:
            p.error("--zero3-prefetch requires --zero-level 3 (it "
                    "double-buffers the per-layer chunk gathers)")
        if not args.unroll:
            p.error("--zero3-prefetch requires --unroll (the prefetch "
                    "schedule is a static unrolled structure)")
    if args.mesh_islands > 1:
        if not args.zero or (args.zero_level or 0) >= 3:
            p.error("--mesh-islands > 1 requires --zero at levels 1/2: "
                    "the hierarchical grad path is the ZeRO optimizer's "
                    "dcn_axis (amp.MixedPrecisionOptimizer; level 3's "
                    "per-layer gather transposes have no two-tier "
                    "decomposition)")
        if args.reduce_dtype:
            p.error("--reduce-dtype is the FLAT quantized wire; on a "
                    "two-tier mesh the grad wire is per TIER — use "
                    "--dcn-wire for the inter-island hop (the intra-"
                    "island stages stay exact)")
        if args.moe_experts:
            p.error("--mesh-islands does not compose with --moe-experts "
                    "(expert-parallel dispatch over the combined group "
                    "has no two-hop spelling in this harness yet — "
                    "transformer/moe.py MoEMLP(dcn_axis=) is the "
                    "library seam)")
    if args.offload_optimizer:
        if not args.zero or (args.zero_level or 0) >= 3:
            p.error("--offload-optimizer requires --zero at levels 1/2 "
                    "(the offloaded state IS the ZeRO chunk tree; at "
                    "level 3 grads arrive inside the backward, not in "
                    "one apply phase)")
        if args.moe_experts:
            p.error("--offload-optimizer requires every param replicated "
                    "over the zero group — expert-sharded MoE masters "
                    "are the local shard and stay resident")
        if args.save_dir:
            p.error("--offload-optimizer does not checkpoint: the "
                    "optimizer state is host-resident numpy, outside "
                    "the device checkpoint tree")
    if args.moe_dispatch_dtype and not args.moe_experts:
        p.error("--moe-dispatch-dtype requires --moe-experts (it is the "
                "expert-parallel dispatch wire dtype)")
    if args.moe_experts:
        if (args.zero_level or 0) >= 3:
            p.error("--moe-experts composes with ZeRO levels 1/2 only "
                    "(level 3's chunk drive has no expert-shard story)")
        if args.pp_schedule == "zerobubble":
            p.error("--moe-experts does not compose with --pp-schedule "
                    "zerobubble (the W/B-split executor has no aux-loss "
                    "plumbing)")
    return args


def main():
    args = parse_args()
    initialize_distributed()  # no-op single-process
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_virtual_mesh(
        n_dev,
        tensor_model_parallel_size=args.tp,
        pipeline_model_parallel_size=args.pp,
        islands=args.mesh_islands,
    )
    dp = mesh_lib.get_data_parallel_world_size()
    islands = mesh_lib.get_island_world_size()
    assert args.layers % max(args.pp * args.vpp, 1) == 0

    moe_kwargs = {}
    if args.moe_experts:
        # experts shard over the data axis (the standard MoE mapping:
        # token shards ARE the expert shards) when dp > 1; serial experts
        # otherwise (one code path — the serial twin of the same config)
        moe_kwargs = dict(
            moe_num_experts=args.moe_experts,
            moe_top_k=args.moe_top_k,
            moe_capacity_factor=args.moe_capacity_factor,
            moe_expert_axis=mesh_lib.AXIS_DATA if dp > 1 else None,
            moe_dispatch_dtype=args.moe_dispatch_dtype,
        )
    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_attention_heads=args.heads,
        max_seq_len=args.seq,
        hidden_dropout=0.0,
        axis=mesh_lib.AXIS_MODEL if args.tp > 1 else None,
        compute_dtype=jnp.bfloat16 if args.opt_level in ("O1", "O2", "O3") else jnp.float32,
        remat=True,
        unroll_layers=args.unroll,
        zero3_prefetch=args.zero3_prefetch,
        **moe_kwargs,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy(args.opt_level)
    # journaled runs also want the global grad-norm AND the per-group
    # breakdown (overflow forensics, monitor/diagnose.py) in the metrics;
    # un-journaled programs stay byte-identical (both flags default off)
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=args.lr), policy,
        log_grad_norm=bool(args.journal),
        log_group_norms=bool(args.journal),
        zero_axis=mesh_lib.AXIS_DATA if args.zero else None,
        zero_level=args.zero_level or 2,
        gather_dtype=args.zero_gather,
        reduce_dtype=args.reduce_dtype,
        # two-tier mesh (parallel/hierarchy.py): the island axis joins
        # the zero group and every bulk collective decomposes — the DCN
        # hop carries 1/n_ici of the payload, quantized by default
        dcn_axis=mesh_lib.AXIS_DCN if islands > 1 else None,
        dcn_wire=None if args.dcn_wire == "none" else args.dcn_wire)

    full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    all_specs = model.specs()
    specs = dict(
        {k: v for k, v in all_specs.items() if k != "layers"},
        layers=pipeline_specs(all_specs["layers"]),
    )
    if args.vpp > 1:
        # interleaved chunk placement: stage s chunk c holds serial slab
        # c*pp + s; training/checkpointing in this order is
        # self-consistent (schedules.interleave_stack)
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            interleave_stack,
        )

        full = dict(full, layers=interleave_stack(
            full["layers"], args.pp, args.vpp))
    params = tp_mod.shard_params(full, specs, mesh)

    tracer = None
    if args.trace:
        from apex_tpu.monitor import tracing

        tracer = tracing.arm(
            args.trace,
            meta={"run": "pretrain_gpt", "tp": args.tp, "pp": args.pp,
                  "islands": islands,
                  "zero_level": args.zero_level or 0})
    if args.flight:
        # black box (monitor/flight.py): journal/span records and
        # breadcrumbs ring in memory; a crash/SIGTERM/watchdog kill dumps
        # them with an HBM snapshot — disarmed runs are byte-identical
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.arm(args.flight,
                       meta={"run": "pretrain_gpt", "tp": args.tp,
                             "pp": args.pp, "dp": dp, "islands": islands,
                             "zero_level": args.zero_level or 0})

    # global data parallelism spans both tiers on an island mesh: batch
    # rows shard over ("dcn", "data") and each island sees dp shards
    batch = args.micro_batch * dp * islands * args.num_microbatches
    data_spec = P(mesh_lib.get_data_parallel_axes())
    rest_specs = {k: v for k, v in all_specs.items() if k != "layers"}
    grad_axes = mesh_lib.get_gradient_reduction_axes()
    # MoE layers emit router aux losses: thread them through the ring and
    # fold with aux_to_loss (run_layers refuses to drop them silently)
    with_aux = bool(args.moe_experts)
    pipe_loss = pipelined_loss_fn(
        embed=model.embed,
        run_layers=(lambda lp, h: model.run_layers(lp, h, return_aux=True))
        if with_aux else (lambda lp, h: model.run_layers(lp, h)),
        head_loss=lambda p, h, t: model.head(p, h, t),
        num_microbatches=args.num_microbatches,
        virtual_pipeline_size=args.vpp,
        aux_to_loss=model.aux_to_loss if with_aux else None,
    )
    zb_vg = None
    if args.pp_schedule == "zerobubble":
        # schedule-as-data: the zero-bubble plan (W/B-split backward
        # slots) interpreted by the compiled executor, a drop-in for
        # value_and_grad of the pipelined loss
        from apex_tpu.transformer.pipeline_parallel import (
            plan_schedule,
            zero_bubble_grads_fn,
        )

        zb_plan = plan_schedule("zero-bubble", args.num_microbatches,
                                args.pp)
        zb_vg = zero_bubble_grads_fn(model, args.num_microbatches, args.pp)
        from apex_tpu.monitor.tracing import expected_bubble_fraction

        print(f"pp-schedule zerobubble: {zb_plan.ticks} ticks, "
              f"{zb_plan.idle_slots()[0]} idle/rank (analytic bubble "
              f"{expected_bubble_fraction('zero-bubble', args.num_microbatches, args.pp):.4f} "
              f"vs 1f1b "
              f"{expected_bubble_fraction('1f1b', args.num_microbatches, args.pp):.4f})")

    def sharded_grads(p, toks, tgts, scale):
        rest = {k: v for k, v in p.items() if k != "layers"}
        if zb_vg is not None:
            loss, rest_g, layer_g = zb_vg(rest, p["layers"], toks, tgts,
                                          scale)
        else:
            def scaled_loss(rest, layers):
                return pipe_loss(rest, layers, toks, tgts) * scale

            loss, (rest_g, layer_g) = jax.value_and_grad(
                scaled_loss, argnums=(0, 1))(rest, p["layers"])
        rest_g = allreduce_gradients_by_spec(rest_g, rest_specs)
        layer_g = allreduce_gradients(layer_g, grad_axes)
        return collectives.pmean(loss, grad_axes), dict(rest_g, layers=layer_g)

    offload = None
    if args.offload_optimizer:
        # host-offloaded ZeRO (optimizers/offload.py): grads compute in
        # ONE jitted shard_map that returns them STACKED over a leading
        # group axis (the global spelling of each rank's own unreduced
        # local-mean grad), then the host driver streams the bucketed
        # state — bucket b+1's async H2D in flight under bucket b's
        # scatter→update→gather (its scatter IS the group reduction)
        from apex_tpu.optimizers.offload import HostOffloadedZero
        from apex_tpu.transformer.amp import MeshGradScaler

        group_axes = mesh_lib.get_data_parallel_axes()
        nonzero_axes = tuple(a for a in grad_axes if a not in group_axes)

        def stacked_grads(p, toks, tgts, scale):
            rest = {k: v for k, v in p.items() if k != "layers"}
            if zb_vg is not None:
                loss, rest_g, layer_g = zb_vg(rest, p["layers"], toks,
                                              tgts, scale)
            else:
                def scaled_loss(rest, layers):
                    return pipe_loss(rest, layers, toks, tgts) * scale

                loss, (rest_g, layer_g) = jax.value_and_grad(
                    scaled_loss, argnums=(0, 1))(rest, p["layers"])
            # the group axes stay UNREDUCED — the offload driver's
            # scatter is the reduction over them; only context partials
            # and pipe embedding ties reduce here
            rest_g = allreduce_gradients_by_spec(
                rest_g, rest_specs, data_axes=nonzero_axes)
            layer_g = allreduce_gradients(layer_g, nonzero_axes)
            g = jax.tree.map(lambda x: x[None],
                             dict(rest_g, layers=layer_g))
            return collectives.pmean(loss, grad_axes), g

        stacked_specs = jax.tree.map(
            lambda sp: P(group_axes, *sp), specs,
            is_leaf=lambda x: isinstance(x, P))
        grads_fn = jax.jit(jax.shard_map(
            stacked_grads, mesh=mesh,
            in_specs=(specs, data_spec, data_spec, P()),
            out_specs=(P(), stacked_specs), check_vma=False))
        offload = HostOffloadedZero(
            mp_opt, mesh, specs, num_buckets=args.offload_buckets,
            found_inf_reducer=MeshGradScaler().found_inf_reducer)
        opt_state = offload.init(params)

        def train_step(params, opt_state, tokens, targets):
            scale = opt_state.scaler.loss_scale
            loss, scaled_g = grads_fn(params, tokens, targets, scale)
            new_p, new_state, metrics = offload.apply_gradients(
                opt_state, params, scaled_g)
            return new_p, new_state, loss / scale, metrics
    elif args.zero:
        # ZeRO: the whole step — backward, spec-aware reduction over every
        # NON-data axis, and the sharded optimizer (psum_scatter → chunked
        # Adam → compressed all_gather) — runs inside ONE shard_map; the
        # shared builder drops the data axis from the harness reduction
        # (the scatter IS it) and OR-reduces the overflow flag over the
        # model/pipe axes like the reference's model-parallel GradScaler.
        from apex_tpu.transformer.amp import build_zero_train_step

        if args.zero_level >= 3:
            # ZeRO-3: the bf16 params persist as 1/dp chunk trees and
            # each layer's weights gather just-in-time inside the layer
            # loop (models/_transformer.run_layers chunk_meta); grads
            # reduce-scatter per layer via the gather transposes, and
            # the updated chunks ARE the state — no post-update gather
            # (tripwire: lint.trace.zero3_gather_hazards)
            z3 = mp_opt.zero3_init(params, mesh, specs)
            params = z3.params
            opt_state = z3.opt_state
            train_step = build_zero_train_step(
                mp_opt, mesh, None, None, None,
                rest_specs=rest_specs, layer_specs=specs["layers"],
                grad_axes=grad_axes,
                data_spec=data_spec, zero_axis=mesh_lib.AXIS_DATA,
                zero3=z3, model=model,
                num_microbatches=args.num_microbatches,
                # the layer stack is interleave_stack-permuted when
                # vpp > 1: the rebuilt pipelined loss must drive it with
                # the same chunk placement
                virtual_pipeline_size=args.vpp,
                traced=bool(args.trace), tracer=tracer)
        else:
            opt_state, state_specs = mp_opt.zero_init(params, mesh, specs)
            train_step = build_zero_train_step(
                mp_opt, mesh, specs, state_specs, pipe_loss,
                rest_specs=rest_specs, grad_axes=grad_axes,
                data_spec=data_spec, zero_axis=mesh_lib.AXIS_DATA,
                traced=bool(args.trace), tracer=tracer,
                pipe_value_and_grad=zb_vg)
    else:
        opt_state = mp_opt.init(params)
        shard_fn = jax.shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(specs, data_spec, data_spec, P()),
            out_specs=(P(), specs), check_vma=False,
        )

        @jax.jit
        def train_step(params, opt_state, tokens, targets):
            scaled_loss, scaled_grads = shard_fn(
                params, tokens, targets, opt_state.scaler.loss_scale)
            new_params, new_state, metrics = mp_opt.apply_gradients(
                opt_state, params, scaled_grads)
            return new_params, new_state, scaled_loss / opt_state.scaler.loss_scale, metrics

    if args.data:
        from apex_tpu.csrc import TokenLoader
        files = sorted(
            os.path.join(args.data, f) for f in os.listdir(args.data)
            if f.endswith(".bin"))
        batches = iter(TokenLoader(files, (batch, args.seq + 1), loop=True))

        def next_batch():
            arr = jnp.asarray(next(batches) % args.vocab)
            return arr[:, :-1], arr[:, 1:]
    else:
        rng = np.random.default_rng(0)

        def next_batch():
            toks = jnp.asarray(rng.integers(0, args.vocab, (batch, args.seq)))
            return toks, jnp.roll(toks, -1, axis=-1)

    shard = lambda a: jax.device_put(a, NamedSharding(mesh, data_spec))
    start = 0
    if args.save_dir and (step := checkpoint.latest_step(args.save_dir)) is not None:
        restored = checkpoint.restore_checkpoint(
            args.save_dir, {"params": params, "opt": opt_state})
        params, opt_state = restored["params"], restored["opt"]
        start = step
        print(f"resumed from step {step}")

    # one config dict, two consumers: the journal's kind="meta" header
    # and the ledger record's fingerprinted config block (same knobs →
    # same fingerprint, so journal and ledger join trivially)
    run_config = {"run": "pretrain_gpt", "tp": args.tp, "pp": args.pp,
                  "dp": dp, "hidden": args.hidden, "layers": args.layers,
                  "seq": args.seq, "batch": batch,
                  "schedule": args.pp_schedule, "vpp": args.vpp,
                  "unroll": bool(args.unroll), "zero": bool(args.zero),
                  "zero_level": args.zero_level or 0,
                  "zero3_prefetch": args.zero3_prefetch or 0,
                  "reduce_dtype": args.reduce_dtype or "fp32",
                  "moe_experts": args.moe_experts or 0,
                  "moe_dispatch_dtype": args.moe_dispatch_dtype or "none",
                  "islands": islands,
                  "dcn_wire": (args.dcn_wire if islands > 1 else "none"),
                  "offload": bool(args.offload_optimizer)}
    ledger_pred = {}  # predicted block, filled at arm time (off-TPU math)
    if getattr(args, "plan_predicted", None):
        # the planner's predicted anatomy seeds the ledger keys the
        # calibrate join reads; traced statics (journal arming below)
        # overwrite the comm figure with the booked census when available
        pred = args.plan_predicted
        ledger_pred.setdefault("hbm_peak_bytes", pred["hbm_bytes"])
        ledger_pred.setdefault("bubble_floor", pred["bubble_floor"])
        ledger_pred.setdefault("comm_bytes_per_step",
                               pred["comm_bytes_by_tier"]["ici"])
        if pred["comm_bytes_by_tier"].get("dcn"):
            ledger_pred.setdefault("dcn_bytes_per_step",
                                   pred["comm_bytes_by_tier"]["dcn"])
        ledger_pred.setdefault("modeled_step_s", pred["step_seconds"])
    journal = forensics = None
    if args.journal:
        from apex_tpu.monitor import (
            MetricsJournal,
            OverflowForensics,
            RecompileTracker,
        )
        from apex_tpu.monitor import mfu as mfu_lib

        from apex_tpu.monitor.health import HealthMonitor

        journal = MetricsJournal(
            args.journal, sample_hbm_every=10,
            meta=run_config,
            # online health rules (monitor/health.py): every record
            # streams through the detectors; kind="alert" rows land in
            # this same journal for report's alerts section and the
            # `report compare --max-alerts` gate
            health=HealthMonitor())
        try:
            # per-rank residency footprints (monitor/hbm.py): the ZeRO
            # bytes/rank ÷ dp claim — and under --zero-level 3 the
            # param bytes/rank ÷ dp claim — as journaled numbers, rolled
            # up by `python -m apex_tpu.monitor.report`
            from apex_tpu.monitor.hbm import opt_state_bytes, param_bytes

            journal.set_opt_state_bytes(
                # offloaded state lives in host RAM: the honest HBM
                # figure is the two-largest-buckets residency bound
                opt_state.hbm_resident_bytes() if offload is not None
                else opt_state_bytes(opt_state))
            journal.set_param_bytes(param_bytes(params))
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"residency-bytes arming failed: {e}")
        # diagnostics engine (monitor/diagnose.py): overflow/loss-spike
        # forensics keyed off the per-group grad norms above, plus the
        # shape-churn detector around the jitted step — both host-side
        forensics = OverflowForensics(journal)
        try:
            # one extra TRACE (no compile) arms per-step MFU/roofline
            # fields: jaxpr FLOPs/bytes per token joined against the
            # peak-spec table (env-calibratable, monitor/mfu.py). Traced
            # BEFORE the recompile wrapper so arming never journals as a
            # spurious compile, and on zeros so no real batch from
            # --data is consumed just for tracing (bench.py's
            # _register_window_costs idiom)
            from apex_tpu.monitor import comm_accounting

            z = shard(jnp.zeros((batch, args.seq), jnp.int32))
            # the same trace also books collective payload bytes, so the
            # journal's step-anatomy fields (compute/comm/stall fractions
            # + overlap, monitor/tracing.py step_anatomy) arm for free
            if offload is not None:
                # the host bucket drive doesn't trace as one jaxpr; the
                # jitted grads program is the step's on-device anatomy
                scale0 = opt_state.scaler.loss_scale
                with comm_accounting() as acct:
                    costs = mfu_lib.traced_step_costs(
                        lambda p, a, b: grads_fn(p, a, b, scale0),
                        params, z, z)
                    # the grad wire lives in the bucket apply programs —
                    # trace them abstractly so the census is whole-step
                    offload.abstract_step(params, opt_state)
            else:
                with comm_accounting() as acct:
                    costs = mfu_lib.traced_step_costs(
                        train_step, params, opt_state, z, z)
            journal.set_step_costs(
                flops_per_token=costs["flops"] / (batch * args.seq),
                bytes_per_token=costs["bytes"] / (batch * args.seq),
                method=costs["method"])
            # per-link-class split (CommAccount.by_tier): the dcn arm
            # prices the exposed DCN seconds report/compare gate on
            dcn_bytes = acct.by_tier().get("dcn", {}).get("bytes", 0)
            journal.set_step_comm(acct.total_bytes(),
                                  dcn_bytes_per_step=dcn_bytes)
            # the same statics ARE the ledger's predicted block
            ledger_pred.update(flops_per_step=costs["flops"],
                               bytes_per_step=costs["bytes"],
                               comm_bytes_per_step=acct.total_bytes())
            if dcn_bytes:
                ledger_pred["dcn_bytes_per_step"] = dcn_bytes
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"mfu arming failed (journal continues without): {e}")
        train_step = RecompileTracker(journal).wrap(train_step,
                                                    name="train_step")

    if (args.trace and args.pp > 1 and args.tp == 1
            and (args.zero_level or 0) < 3):
        # measure the pipeline's per-rank bubble fraction for real: one
        # tick-by-tick traced drive of the SELECTED schedule (the ring
        # drive for interleaved/vpp; the plan executor for the vpp=1
        # planners incl. zerobubble), spans into the trace file, the
        # measured-vs-analytic stamp into every journal record
        try:
            from apex_tpu.monitor import tracing as tracing_mod
            from apex_tpu.transformer.pipeline_parallel import (
                plan_schedule,
                traced_pipeline_timeline,
                traced_schedule_timeline,
            )

            probe_rows = args.micro_batch * args.num_microbatches
            ptoks = jnp.zeros((probe_rows, args.seq), jnp.int32)
            if args.pp_schedule == "interleaved":
                _, _, anatomy = traced_pipeline_timeline(
                    mesh, embed=model.embed,
                    run_layers=lambda lp, h: model.run_layers(lp, h),
                    head_loss=lambda p, h, t: model.head(p, h, t),
                    rest_params={k: v for k, v in params.items()
                                 if k != "layers"},
                    layers=params["layers"], layer_specs=specs["layers"],
                    batch=ptoks, targets=ptoks,
                    num_microbatches=args.num_microbatches,
                    virtual_pipeline_size=args.vpp,
                    tracer=tracer, step=-1)
            else:
                probe_plan = plan_schedule(
                    "zero-bubble" if args.pp_schedule == "zerobubble"
                    else args.pp_schedule,
                    args.num_microbatches, args.pp)
                _, _, anatomy = traced_schedule_timeline(
                    probe_plan, mesh, embed=model.embed,
                    run_layers=lambda lp, h: model.run_layers(lp, h),
                    head_loss=lambda p, h, t: model.head(p, h, t),
                    rest_params={k: v for k, v in params.items()
                                 if k != "layers"},
                    layers=params["layers"], layer_specs=specs["layers"],
                    batch=ptoks, targets=ptoks, tracer=tracer, step=-1)
            print(f"measured bubble fraction "
                  f"{anatomy['bubble_fraction']['mean']} "
                  f"(analytic floor {anatomy['expected_bubble_fraction']})")
            if journal is not None:
                journal.set_bubble_fraction(
                    anatomy["bubble_fraction"]["mean"],
                    anatomy["expected_bubble_fraction"])
            ledger_pred.setdefault(
                "bubble_floor", anatomy["expected_bubble_fraction"])
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"bubble probe failed (run continues without): {e}")

    t0 = time.perf_counter()
    for i in range(start, start + args.steps):
        toks, tgts = next_batch()
        if journal is not None:
            journal.step_start()
        if tracer is not None:
            from apex_tpu.monitor.tracing import maybe_span

            tracer.step = i
            with maybe_span(tracer, "step", step=i) as sp:
                params, opt_state, loss, metrics = train_step(
                    params, opt_state, shard(toks), shard(tgts))
                sp.barrier(loss)
        else:
            params, opt_state, loss, metrics = train_step(
                params, opt_state, shard(toks), shard(tgts))
        if journal is not None:
            # the journal's float(loss) IS the step's execution barrier
            # (tunnel discipline); metrics/scaler fetches ride after it
            journal.step_end(step=i, loss=loss, tokens=batch * args.seq,
                             metrics=metrics, scaler=opt_state.scaler)
            forensics.observe(step=i, loss=loss, metrics=metrics)
        if i == start:
            float(loss)  # exclude compile
            t0 = time.perf_counter()
        if i % 5 == 0 or i == start + args.steps - 1:
            print(f"step {i:5d} loss {float(loss):.4f} "
                  f"scale {float(metrics['loss_scale']):.0f}")
        if args.save_dir and (i + 1) % args.save_every == 0:
            checkpoint.save_checkpoint(
                args.save_dir, i + 1, {"params": params, "opt": opt_state})
    if journal is not None:
        journal.close()
    if tracer is not None:
        from apex_tpu.monitor import tracing as tracing_mod

        tracing_mod.disarm()  # flush + close
        try:
            tracing_mod.write_chrome_trace(
                args.trace, args.trace + ".chrome.json")
            print(f"chrome trace: {args.trace}.chrome.json")
        except Exception as e:  # noqa: BLE001
            print(f"chrome export failed: {e}")
    if args.flight:
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.disarm()  # clean exit: restore hooks, no dump
    n_done = max(args.steps - 1, 1)
    dt = (time.perf_counter() - t0) / n_done
    print(f"{batch * args.seq / dt:.0f} tokens/s | mesh: tp={args.tp} pp={args.pp} "
          f"dp={dp}{f' islands={islands}' if islands > 1 else ''} | "
          f"{dt * 1e3:.1f} ms/step")
    if args.ledger:
        try:
            from apex_tpu.monitor import ledger as ledger_mod

            # journal-less runs still ledger: a minimal measured block
            # in the report-rollup key shapes regress/trend read
            measured = None
            if not args.journal:
                measured = {"step_records": args.steps,
                            "tokens_per_sec":
                                {"p50": round(batch * args.seq / dt, 1)},
                            "wall_s": {"p50": round(dt, 6)},
                            "loss": {"last": float(loss)}}
            rec = ledger_mod.append_run(
                args.ledger, run="pretrain_gpt", config=run_config,
                journal=args.journal, measured=measured,
                predicted=ledger_pred)
            print(f"ledger: {rec['fingerprint']} -> {args.ledger}")
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"ledger append failed: {e}")
    mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
