"""MoE GPT pretraining with expert parallelism over the data axis.

New-capability recipe (the reference has no MoE): GPT whose FFNs are top-k
routed expert layers (transformer/moe.py), experts sharded over the mesh's
``data`` axis with all_to_all dispatch, amp O2 mixed precision, FusedAdam,
and the Switch load-balancing + router z losses folded into training.

Run on 4 virtual devices (tokens and experts both sharded over ``data``):
    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
        python examples/moe/pretrain_moe_gpt.py --experts 8 --steps 10
Run serial on one real TPU chip (experts local, no all_to_all):
    python examples/moe/pretrain_moe_gpt.py --experts 8 --ep 1 --steps 10
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: shard_map/axis_size API renames

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives, mesh as mesh_lib
from apex_tpu.parallel.distributed import allreduce_gradients_by_spec


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument("--ep", type=int, default=0,
                   help="expert-parallel size (0 = all devices; 1 = serial)")
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    args = p.parse_args()

    ep = args.ep or len(jax.devices())
    serial = ep == 1
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=args.seq, hidden_dropout=0.0, axis=None,
        compute_dtype=jnp.bfloat16, remat=True,
        moe_num_experts=args.experts, moe_top_k=args.top_k,
        moe_capacity_factor=args.capacity_factor,
        moe_expert_axis=None if serial else mesh_lib.AXIS_DATA,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=args.lr), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, args.vocab, (args.batch, args.seq)))
    tgts = jnp.roll(toks, -1, axis=-1)

    if serial:
        @jax.jit
        def train_step(params, opt_state, toks, tgts):
            ls, gs = jax.value_and_grad(
                lambda q: mp_opt.scale_loss(model.loss(q, toks, tgts),
                                            opt_state))(params)
            params, opt_state, _ = mp_opt.apply_gradients(opt_state, params, gs)
            return params, opt_state, ls / opt_state.scaler.loss_scale
    else:
        mesh = mesh_lib.make_virtual_mesh(ep)  # experts over the data axis
        specs = model.specs()
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda v: isinstance(v, P))
        params = jax.device_put(params, shardings)
        # optimizer state (masters, moments) mirrors the param layout —
        # replicating it would gather/scatter every expert weight each step
        from apex_tpu.amp.frontend import MPOptState

        param_sh = shardings
        opt_state = jax.device_put(
            opt_state,
            MPOptState(
                inner=type(opt_state.inner)(
                    NamedSharding(mesh, P()), param_sh, param_sh),
                master=param_sh,
                scaler=jax.tree.map(lambda _: NamedSharding(mesh, P()),
                                    opt_state.scaler),
            ))
        data_spec = P(mesh_lib.AXIS_DATA)

        def sharded_grads(p, toks, tgts, scale):
            # local-mean loss + spec-aware reduction: replicated grads
            # pmean over data; expert-sharded grads skip the psum but keep
            # the averaging factor (the MoE gradient convention,
            # transformer/moe.py apply_expert_parallel docstring)
            loss, g = jax.value_and_grad(
                lambda q: model.loss(q, toks, tgts) * scale)(p)
            g = allreduce_gradients_by_spec(g, specs)
            return collectives.pmean(loss, (mesh_lib.AXIS_DATA,)), g

        shard_fn = jax.shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(specs, data_spec, data_spec, P()),
            out_specs=(P(), specs), check_vma=False)

        @jax.jit
        def train_step(params, opt_state, toks, tgts):
            sl, sg = shard_fn(params, toks, tgts,
                              opt_state.scaler.loss_scale)
            params, opt_state, _ = mp_opt.apply_gradients(opt_state, params, sg)
            return params, opt_state, sl / opt_state.scaler.loss_scale

    t0 = time.perf_counter()
    for step in range(args.steps):
        params, opt_state, loss = train_step(params, opt_state, toks, tgts)
        if step % max(1, args.steps // 5) == 0 or step == args.steps - 1:
            print(f"step {step:3d} loss {float(loss):.4f} "
                  f"scale {float(opt_state.scaler.loss_scale):.0f}")
    print(f"{args.steps} steps in {time.perf_counter() - t0:.1f}s "
          f"({'serial' if serial else f'expert-parallel x{ep}'}, "
          f"{args.experts} experts, top-{args.top_k})")
    if not serial:
        mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
