"""Minimal data-parallel training example
(reference: examples/simple/distributed/distributed_data_parallel.py).

The reference wraps a 10-line model in apex DDP under
``torch.distributed.launch``; here the same 10-line model trains over the
``data`` mesh axis with ``DistributedDataParallel.value_and_grad`` inside
``shard_map`` — gradients come back already averaged.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/simple/distributed_data_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import DistributedDataParallel


def main():
    mesh = mesh_lib.make_virtual_mesh(len(jax.devices()))

    def model(params, x):
        return jnp.tanh(x @ params["w1"]) @ params["w2"]

    def loss_fn(params, x, y):
        return jnp.mean(jnp.square(model(params, x) - y))

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "w1": jax.random.normal(k1, (16, 32)) * 0.1,
        "w2": jax.random.normal(k2, (32, 1)) * 0.1,
    }
    x = jax.random.normal(k3, (64, 16))
    y = jnp.sum(x, axis=1, keepdims=True) + 0.1 * jax.random.normal(k4, (64, 1))

    opt = FusedSGD(lr=0.05, momentum=0.9)
    opt_state = opt.init(params)
    ddp = DistributedDataParallel(loss_fn)  # grads pre-averaged over 'data'

    def sharded_step(params, opt_state, x, y):
        loss, grads = ddp.value_and_grad(params, x, y)
        updates, opt_state = opt.transform.update(grads, opt_state, params)
        import optax
        return optax.apply_updates(params, updates), opt_state, \
            jax.lax.pmean(loss, mesh_lib.AXIS_DATA)

    data, rep = P(mesh_lib.AXIS_DATA), P()
    step = jax.jit(jax.shard_map(
        sharded_step, mesh=mesh,
        in_specs=(rep, rep, data, data), out_specs=(rep, rep, rep),
        check_vma=False))

    shard = lambda a: jax.device_put(a, NamedSharding(mesh, data))
    x, y = shard(x), shard(y)
    for i in range(20):
        params, opt_state, loss = step(params, opt_state, x, y)
        if i % 5 == 0:
            print(f"step {i:3d} loss {float(loss):.5f}")
    print(f"final loss {float(loss):.5f} over {len(jax.devices())}-way DP")
    mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
