"""Long-context GPT training: ring-attention context parallelism + streamed
flash kernels.

The capability recipe the reference cannot express (its long-sequence story
is activation checkpointing plus the sk<=2048 fused-softmax fallback,
apex/transformer/functional/fused_softmax.py:151-171): sequences shard over
the ``context`` mesh axis, attention runs as a ppermute ring with exact
cross-shard causal masking, and per-shard attention uses the STREAMED Pallas
flash kernels (K/V loop in the grid, VMEM block-bounded) so a single shard
handles 8k-16k tokens. Padding masks ride the ring as segment ids — no
(sq, SK) bias ever materializes.

Run on 8 virtual devices (cp=4 x dp=2, 4096-token context, 1024/shard):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/longcontext/train_long_context.py --cp 4 --dp 2 \
        --seq 4096 --steps 3
Run serial on one real TPU chip at 8k context (streamed kernels engage):
    python examples/longcontext/train_long_context.py --cp 1 --dp 1 \
        --seq 8192 --steps 3
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import allreduce_gradients_by_spec
from apex_tpu.transformer import tensor_parallel as tp_mod


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cp", type=int, default=4, help="context-parallel size")
    ap.add_argument("--dp", type=int, default=2, help="data-parallel size")
    ap.add_argument("--seq", type=int, default=4096, help="GLOBAL context length")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch (default: dp)")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--sp-impl", choices=["ring", "ulysses"], default="ring")
    ap.add_argument("--lm-head-chunks", type=int, default=None,
                    help="chunked LM-head CE (at 32k tokens the full "
                         "(tokens, vocab) logits tensor alone is ~2 GB; "
                         "chunking keeps the head's peak HBM flat). "
                         "Size chunks to >=16k tokens each: every chunk "
                         "pays a read+write of the full dW_out gradient "
                         "(h x vocab) in backward, so over-chunking is "
                         "DMA-bound — measured at 1M tokens: 1024 chunks "
                         "27k tok/s, 32 chunks 288k tok/s, same loss")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention (GPTConfig."
                         "attention_window): O(s*window) attention cost "
                         "instead of O(s^2) — the local-attention pairing "
                         "for very long contexts")
    ap.add_argument("--pos", choices=["learned", "rope", "none"],
                    default="learned",
                    help="position encoding; rope has NO position table "
                         "(a learned table at 1M tokens is ~3.75 GB of "
                         "params + Adam state)")
    ap.add_argument("--output", default=None,
                    help="write a JSON measurement record")
    args = ap.parse_args()

    n = args.cp * args.dp
    batch = args.batch or args.dp
    serial = n == 1

    cfg = GPTConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        num_layers=args.layers,
        num_attention_heads=args.heads,
        max_seq_len=args.seq,
        hidden_dropout=0.0,
        axis=None,
        context_axis=None if serial else mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=args.sp_impl,
        compute_dtype=jnp.bfloat16,
        remat=True,
        lm_head_chunks=args.lm_head_chunks,
        attention_window=args.window,
        position_embedding=args.pos,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-4), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, args.seq), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=-1)

    if serial:
        @jax.jit
        def step(params, opt_state, toks, tgts):
            def scaled(p):
                return mp_opt.scale_loss(model.loss(p, toks, tgts), opt_state)

            ls, gs = jax.value_and_grad(scaled)(params)
            new_p, new_s, _ = mp_opt.apply_gradients(opt_state, params, gs)
            return new_p, new_s, ls / opt_state.scaler.loss_scale
    else:
        mesh = mesh_lib.make_virtual_mesh(
            n, context_parallel_size=args.cp)
        specs = model.specs()
        params = tp_mod.shard_params(params, specs, mesh)
        opt_state = mp_opt.init(params)
        data_spec = P(mesh_lib.AXIS_DATA, mesh_lib.AXIS_CONTEXT)
        tokens = jax.device_put(tokens, NamedSharding(mesh, data_spec))
        targets = jax.device_put(targets, NamedSharding(mesh, data_spec))
        grad_axes = mesh_lib.get_gradient_reduction_axes()

        def sharded(p, toks, tgts, scale):
            # local-mean loss + spec-aware gradient reduction (the repo's
            # standard data/context recipe — CLAUDE.md conventions)
            def scaled(p):
                return model.loss(p, toks, tgts) * scale

            ls, gs = jax.value_and_grad(scaled)(p)
            gs = allreduce_gradients_by_spec(gs, specs)
            from apex_tpu.parallel import collectives

            return collectives.pmean(ls, grad_axes), gs

        shard_fn = jax.shard_map(
            sharded, mesh=mesh,
            in_specs=(specs, data_spec, data_spec, P()),
            out_specs=(P(), specs), check_vma=False)

        @jax.jit
        def step(params, opt_state, toks, tgts):
            ls, gs = shard_fn(params, toks, tgts,
                              opt_state.scaler.loss_scale)
            new_p, new_s, _ = mp_opt.apply_gradients(opt_state, params, gs)
            return new_p, new_s, ls / opt_state.scaler.loss_scale

    loss = None
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        loss_val = float(loss)  # device->host fetch: the tunnel-safe barrier
        if i == 0:
            t0 = time.perf_counter()  # exclude compile
        print(f"step {i}: loss {loss_val:.4f}", file=sys.stderr)
    steps_timed = max(args.steps - 1, 1)
    dt = (time.perf_counter() - t0) / steps_timed
    mode = "serial" if serial else args.sp_impl
    tok_s = batch * args.seq / dt
    print(f"{tok_s:.0f} tokens/s at context {args.seq} "
          f"(cp={args.cp}, dp={args.dp}, {mode})")
    if args.output:
        import json

        os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
        with open(args.output, "w") as f:
            json.dump({
                "metric": "longcontext_train_tokens_per_sec",
                "platform": jax.default_backend(),
                "seq": args.seq, "cp": args.cp, "dp": args.dp,
                "mode": mode, "batch": batch,
                "hidden": args.hidden, "layers": args.layers,
                "lm_head_chunks": args.lm_head_chunks,
                "window": args.window,
                "position_embedding": args.pos,
                "steps_timed": steps_timed,
                "tokens_per_sec": round(tok_s, 1),
                "loss_final": round(float(loss), 4),
            }, f, indent=1)
    if not serial:
        mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
