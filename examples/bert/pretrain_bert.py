"""BERT pretraining with FusedLAMB + fused LayerNorm (BASELINE.md config 3).

Reference workload: BERT-large MLM+NSP pretraining with apex FusedLAMB and
FusedLayerNorm (the apex README's flagship BERT recipe). Synthetic masked
batches by default.

    JAX_PLATFORMS=cpu python examples/bert/pretrain_bert.py --steps 10
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.models import BertConfig, BertModel
from apex_tpu.optimizers import FusedLAMB


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO: data-parallel over every device with fp32 "
                        "masters + LAMB moments sharded 1/dp "
                        "(LAMB trust-ratio norms psum across the shards); "
                        "batch must divide the device count")
    p.add_argument("--zero-level", type=int, default=None, choices=(1, 2, 3),
                   help="ZeRO stage (implies --zero). 3 shards the bf16 "
                        "params too: 1/dp chunk trees with per-layer "
                        "just-in-time weight gathers in the layer loop")
    p.add_argument("--reduce-dtype", default=None, choices=["int8", "e5m2"],
                   help="quantize the ZeRO grad reduce-scatter wire "
                        "(requires --zero, levels 1/2): encoded all_to_all "
                        "at 1 B/elem + per-chunk fp32 scales, with an "
                        "error-feedback residual in the sharded state "
                        "(parallel/quantize.py)")
    p.add_argument("--mesh-islands", type=int, default=1, metavar="N",
                   help="model the devices as N ICI islands joined by DCN "
                        "(parallel/hierarchy.py): a leading 'dcn' mesh "
                        "axis joins the ZeRO group — batches shard over "
                        "(dcn, data) and the grad reduction decomposes "
                        "hierarchically so the slow tier carries only "
                        "the 1/n_ici pre-reduced shard (LAMB trust-ratio "
                        "norms psum over the whole group). Requires "
                        "--zero at levels 1/2")
    p.add_argument("--dcn-wire", default="int8",
                   choices=["int8", "e5m2", "none"],
                   help="wire dtype of the inter-island (DCN) gradient "
                        "hop when --mesh-islands > 1; defaults ON at "
                        "int8 with an error-feedback residual (the "
                        "EQuARX rule); 'none' keeps the hop exact fp32")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="write a per-step JSON-lines metrics journal "
                        "(apex_tpu.monitor: wall time, tokens/s, loss, "
                        "loss-scale state, HBM samples, online health "
                        "alerts); adds one loss fetch per step")
    p.add_argument("--ledger", nargs="?", const="out/ledger.jsonl",
                   default=None, metavar="PATH",
                   help="append one fingerprinted run record (config + "
                        "environment stamp + measured rollup + predicted "
                        "block) to the run ledger "
                        "(apex_tpu.monitor.ledger); "
                        "APEX_TPU_LEDGER=<path> arms it too")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write a span trace (apex_tpu.monitor.tracing): "
                        "one barriered span per step plus a Chrome "
                        "trace-event export next to PATH")
    p.add_argument("--flight", nargs="?", const="auto", default=None,
                   metavar="PATH",
                   help="arm the flight recorder (apex_tpu.monitor."
                        "flight): recent records + breadcrumbs dumped as "
                        "strict JSON on crash/SIGTERM/watchdog kill. "
                        "Default PATH: out/pretrain_bert.flight.json")
    args = p.parse_args()
    if not args.ledger and os.environ.get("APEX_TPU_LEDGER"):
        args.ledger = os.environ["APEX_TPU_LEDGER"]
    if args.flight == "auto":
        args.flight = "out/pretrain_bert.flight.json"
    if args.zero_level is not None:
        args.zero = True
    elif args.zero:
        args.zero_level = 2
    if args.reduce_dtype and not args.zero:
        p.error("--reduce-dtype requires --zero (it is the ZeRO grad "
                "reduce-scatter wire dtype)")
    if args.mesh_islands > 1:
        if not args.zero or (args.zero_level or 0) >= 3:
            p.error("--mesh-islands > 1 requires --zero at levels 1/2: "
                    "the hierarchical grad path is the ZeRO optimizer's "
                    "dcn_axis")
        if args.reduce_dtype:
            p.error("--reduce-dtype is the FLAT quantized wire; on a "
                    "two-tier mesh use --dcn-wire for the inter-island "
                    "hop (the intra-island stages stay exact)")
    return args


def synthetic_batch(rng, batch, seq, vocab):
    toks = rng.integers(0, vocab, (batch, seq))
    attn = np.ones((batch, seq), np.int32)
    lmask = (rng.random((batch, seq)) < 0.15).astype(np.int32)
    labels = rng.integers(0, vocab, (batch, seq))
    nsp = rng.integers(0, 2, (batch,))
    types = np.zeros((batch, seq), np.int32)
    return tuple(jnp.asarray(a) for a in (toks, attn, lmask, labels, nsp, types))


def main():
    args = parse_args()
    cfg = BertConfig(
        hidden_size=args.hidden, num_layers=args.layers,
        num_attention_heads=args.heads, max_seq_len=args.seq,
        hidden_dropout=0.0, axis=None,
        compute_dtype=jnp.bfloat16 if args.opt_level != "O0" else jnp.float32,
        remat=True,
    )
    model = BertModel(cfg)
    policy = amp.get_policy(args.opt_level)
    if args.zero:
        # ZeRO over every local device: local-mean loss per batch shard,
        # unreduced grads into the sharded LAMB step (the psum_scatter is
        # the gradient averaging; norm_psum_axis restores exact per-tensor
        # trust ratios across the chunks), bf16-compressed param gather
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.parallel import collectives
        from apex_tpu.utils.compat import ensure_jax_compat

        ensure_jax_compat()  # jax<0.5: shard_map/axis_size API renames
        n_dev = len(jax.devices())
        if args.batch % n_dev:
            raise SystemExit(f"--batch {args.batch} must divide the "
                             f"device count {n_dev} under --zero")
        isl = args.mesh_islands
        if n_dev % max(isl, 1):
            raise SystemExit(f"--mesh-islands {isl} must divide the "
                             f"device count {n_dev}")
        if isl > 1:
            # two-tier topology (parallel/hierarchy.py): 'dcn' leads so
            # island-mates stay contiguous; the ZeRO group spans both
            # axes and LAMB's trust-ratio norms psum over the whole group
            mesh = Mesh(np.asarray(jax.devices()).reshape(isl, -1),
                        ("dcn", "data"))
            zero_group = ("dcn", "data")
        else:
            mesh = Mesh(np.asarray(jax.devices()), ("data",))
            zero_group = "data"
        mp_opt = amp.MixedPrecisionOptimizer(
            FusedLAMB(lr=args.lr, weight_decay=0.01,
                      norm_psum_axis=zero_group),
            policy, zero_axis="data",
            zero_level=args.zero_level,
            dcn_axis="dcn" if isl > 1 else None,
            dcn_wire=None if args.dcn_wire == "none" else args.dcn_wire,
            # bf16 gather is free only when the model params already live
            # in half precision (cast O2/O3); for fp32-param policies
            # (O0/O1) it would round the weights every step.
            gather_dtype="bf16" if policy.cast_model_type is not None
            else None,
            reduce_dtype=args.reduce_dtype)
        params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        pspecs = jax.tree.map(lambda _: P(), params)
        data_spec = P(zero_group)

        if args.zero_level >= 3:
            # fully-sharded: the bf16 params persist as 1/dp chunk trees;
            # each layer's weights gather just-in-time inside the layer
            # loop (run_layers chunk_meta) and grads arrive per-layer
            # reduce-scattered via the gather transposes
            from apex_tpu.optimizers.distributed import gather_chunked_tree

            z3 = mp_opt.zero3_init(params, mesh, pspecs)
            layer_meta = z3.meta.subtree("layers")
            rest_meta = z3.meta.select(
                [k for k in z3.meta.shapes if k != "layers"])
            params, state = z3.params, z3.opt_state
            pspecs, zero_specs = z3.param_specs, z3.state_specs

            def zero_step(p, s, toks, attn, lmask, labels, nsp, types):
                rest_c = {k: v for k, v in p.items() if k != "layers"}

                def scaled(rest_c, layer_c):
                    rest = gather_chunked_tree(rest_c, rest_meta)
                    return mp_opt.scale_loss(
                        model.loss(dict(rest, layers=layer_c), toks, attn,
                                   lmask, labels, nsp, types,
                                   layer_chunk_meta=layer_meta), s)

                ls, (rg, lg) = jax.value_and_grad(scaled, argnums=(0, 1))(
                    rest_c, p["layers"])
                np_, ns, m = mp_opt.apply_gradients(
                    s, p, dict(rg, layers=lg))
                return np_, ns, collectives.pmean(ls, zero_group), m
        else:
            state, zero_specs = mp_opt.zero_init(params, mesh, pspecs)

            def zero_step(p, s, toks, attn, lmask, labels, nsp, types):
                def scaled(p):
                    return mp_opt.scale_loss(
                        model.loss(p, toks, attn, lmask, labels, nsp,
                                   types), s)

                ls, gs = jax.value_and_grad(scaled)(p)
                np_, ns, m = mp_opt.apply_gradients(s, p, gs)
                return np_, ns, collectives.pmean(ls, zero_group), m

        zero_fn = jax.shard_map(
            zero_step, mesh=mesh,
            in_specs=(pspecs, zero_specs) + (data_spec,) * 6,
            out_specs=(pspecs, zero_specs, P(), P()), check_vma=False)

        @jax.jit
        def train_step(p, s, *batch):
            np_, ns, ls, m = zero_fn(p, s, *batch)
            return np_, ns, ls / s.scaler.loss_scale, m
    else:
        # FusedLAMB: the layer-adaptive optimizer the reference pairs
        # with BERT
        mp_opt = amp.MixedPrecisionOptimizer(
            FusedLAMB(lr=args.lr, weight_decay=0.01), policy)
        params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        state = mp_opt.init(params)

        @jax.jit
        def train_step(p, s, toks, attn, lmask, labels, nsp, types):
            def scaled(p):
                return mp_opt.scale_loss(
                    model.loss(p, toks, attn, lmask, labels, nsp, types), s)

            ls, gs = jax.value_and_grad(scaled)(p)
            np_, ns, m = mp_opt.apply_gradients(s, p, gs)
            return np_, ns, ls / s.scaler.loss_scale, m

    if args.steps < 2:
        raise SystemExit("--steps must be >= 2 (step 0 is compile warmup)")
    tracer = None
    if args.trace:
        from apex_tpu.monitor import tracing

        tracer = tracing.arm(args.trace,
                             meta={"run": "pretrain_bert",
                                   "zero_level": args.zero_level or 0})
    if args.flight:
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.arm(args.flight,
                       meta={"run": "pretrain_bert",
                             "zero_level": args.zero_level or 0})
    # one config dict for the journal's kind="meta" header AND the
    # ledger record's fingerprinted config block
    run_config = {"run": "pretrain_bert", "hidden": args.hidden,
                  "layers": args.layers, "seq": args.seq,
                  "batch": args.batch, "opt_level": args.opt_level,
                  "zero": bool(args.zero),
                  "zero_level": args.zero_level or 0,
                  "reduce_dtype": args.reduce_dtype or "fp32",
                  "islands": args.mesh_islands,
                  "dcn_wire": (args.dcn_wire if args.mesh_islands > 1
                               else "none")}
    ledger_pred = {}
    journal = None
    if args.journal:
        from apex_tpu.monitor import MetricsJournal
        from apex_tpu.monitor import mfu as mfu_lib
        from apex_tpu.monitor.health import HealthMonitor

        journal = MetricsJournal(args.journal, sample_hbm_every=10,
                                 meta=run_config, health=HealthMonitor())
        try:
            # one extra trace (no compile) arms per-step MFU/anatomy
            # fields and fills the ledger's predicted block
            from apex_tpu.monitor import comm_accounting

            probe = synthetic_batch(np.random.default_rng(1), args.batch,
                                    args.seq, cfg.vocab_size)
            with comm_accounting() as acct:
                costs = mfu_lib.traced_step_costs(
                    train_step, params, state, *probe)
            toks_per_step = args.batch * args.seq
            journal.set_step_costs(
                flops_per_token=costs["flops"] / toks_per_step,
                bytes_per_token=costs["bytes"] / toks_per_step,
                method=costs["method"])
            dcn_bytes = acct.by_tier().get("dcn", {}).get("bytes", 0)
            journal.set_step_comm(acct.total_bytes(),
                                  dcn_bytes_per_step=dcn_bytes)
            ledger_pred.update(flops_per_step=costs["flops"],
                               bytes_per_step=costs["bytes"],
                               comm_bytes_per_step=acct.total_bytes())
            if dcn_bytes:
                ledger_pred["dcn_bytes_per_step"] = dcn_bytes
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"mfu arming failed (journal continues without): {e}")
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for i in range(args.steps):
        batch = synthetic_batch(rng, args.batch, args.seq, cfg.vocab_size)
        if journal is not None:
            journal.step_start()
        if tracer is not None:
            from apex_tpu.monitor.tracing import maybe_span

            tracer.step = i
            with maybe_span(tracer, "step", step=i) as sp:
                params, state, loss, metrics = train_step(
                    params, state, *batch)
                sp.barrier(loss)
        else:
            params, state, loss, metrics = train_step(params, state, *batch)
        if journal is not None:
            # float(loss) inside step_end is the step's execution barrier
            journal.step_end(step=i, loss=loss,
                             tokens=args.batch * args.seq,
                             metrics=metrics, scaler=state.scaler)
        if i == 0:
            float(loss)
            t0 = time.perf_counter()
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d} mlm+nsp loss {float(loss):.4f} "
                  f"scale {float(metrics['loss_scale']):.0f}")
    if tracer is not None:
        from apex_tpu.monitor import tracing

        tracing.disarm()
        try:
            tracing.write_chrome_trace(args.trace,
                                       args.trace + ".chrome.json")
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"chrome export failed: {e}")
    if args.flight:
        from apex_tpu.monitor import flight as flight_mod

        flight_mod.disarm()  # clean exit: restore hooks, no dump
    if journal is not None:
        journal.close()
    n = max(args.steps - 1, 1)
    dt = (time.perf_counter() - t0) / n
    print(f"{args.batch * args.seq / dt:.0f} tokens/s "
          f"({args.opt_level}, FusedLAMB, {dt*1e3:.1f} ms/step)")
    if args.ledger:
        try:
            from apex_tpu.monitor import ledger as ledger_mod

            measured = None
            if not args.journal:
                measured = {"step_records": args.steps,
                            "tokens_per_sec":
                                {"p50": round(args.batch * args.seq / dt, 1)},
                            "wall_s": {"p50": round(dt, 6)},
                            "loss": {"last": float(loss)}}
            rec = ledger_mod.append_run(
                args.ledger, run="pretrain_bert", config=run_config,
                journal=args.journal, measured=measured,
                predicted=ledger_pred)
            print(f"ledger: {rec['fingerprint']} -> {args.ledger}")
        except Exception as e:  # noqa: BLE001 - telemetry must not kill a run
            print(f"ledger append failed: {e}")


if __name__ == "__main__":
    main()
