"""ImageNet-style ResNet training with amp + data parallelism.

TPU-native port of the reference recipe ``examples/imagenet/main_amp.py``
(543 LoC: torchvision ResNet + ``amp.initialize(opt_level=...)`` + apex DDP +
optional ``convert_syncbn_model`` + SGD). The moving parts map as:

    torchvision.models.resnet50()      -> apex_tpu.models.ResNet50 (NHWC)
    amp.initialize(model, opt, "O2")   -> amp.get_policy("O2") + cast_params
                                          + MixedPrecisionOptimizer
    apex.parallel.DistributedDataParallel -> shard_map over the 'data' mesh
                                          axis + allreduce_gradients
    convert_syncbn_model(model)        -> ResNet(axis_name='data')
    torch.optim.SGD / FusedSGD         -> apex_tpu.optimizers.FusedSGD
    with amp.scale_loss(...): backward -> mp_opt.scale_loss + value_and_grad
    optimizer.step()                   -> mp_opt.apply_gradients (lax.cond
                                          skip-step on overflow)

Data is synthetic imagenet-shaped by default (the reference's ``--prof`` /
dummy-data path); point ``--data-dir`` at a directory of ``.npz`` files (keys
``images``/``labels``) to stream real data through the prefetching loader.

Run (8 virtual devices, CPU):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/imagenet/main_amp.py --arch resnet50 --opt-level O2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

# Plugin platforms registered by sitecustomize (the axon TPU tunnel) ignore a
# plain JAX_PLATFORMS env var; force the selection before first backend use.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import resnet as resnet_mod
from apex_tpu.ops.xentropy import softmax_cross_entropy
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import collectives
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import allreduce_gradients


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet18", "resnet34", "resnet50", "resnet101"])
    p.add_argument("--opt-level", default="O2", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--batch-size", type=int, default=64, help="global batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--sync-bn", action="store_true",
                   help="SyncBatchNorm over the data axis (convert_syncbn_model)")
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--data-dir", default=None,
                   help="dir of .npz batch files (images/labels keys)")
    return p.parse_args()


ARCHS = {
    "resnet18": resnet_mod.ResNet18,
    "resnet34": resnet_mod.ResNet34,
    "resnet50": resnet_mod.ResNet50,
    "resnet101": resnet_mod.ResNet101,
}


def main():
    args = parse_args()
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_virtual_mesh(n_dev)  # pure DP: data axis = all chips
    assert args.batch_size % n_dev == 0, "global batch must divide over devices"

    overrides = {}
    if args.keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = args.keep_batchnorm_fp32 == "True"
    if args.loss_scale is not None:
        overrides["loss_scale"] = (
            "dynamic" if args.loss_scale == "dynamic" else float(args.loss_scale)
        )
    policy = amp.get_policy(args.opt_level, **overrides)

    model = ARCHS[args.arch](
        num_classes=args.num_classes,
        axis_name=mesh_lib.AXIS_DATA if args.sync_bn else None,
        dtype=policy.op_dtype("conv"),
    )
    opt = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay, nesterov=True)
    mp_opt = amp.MixedPrecisionOptimizer(opt, policy)

    shape = (args.batch_size, args.image_size, args.image_size, 3)
    # param/batch_stats shapes are batch-independent: init at batch 1
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1,) + shape[1:], jnp.float32)
    )
    params = amp.cast_params(variables["params"], policy)
    batch_stats = variables["batch_stats"]
    opt_state = mp_opt.init(params)

    data_spec = P(mesh_lib.AXIS_DATA)

    def sharded_step(params, batch_stats, opt_state, images, labels):
        def scaled_loss(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                mutable=["batch_stats"],
            )
            loss = jnp.mean(softmax_cross_entropy(logits, labels))
            return mp_opt.scale_loss(loss, opt_state), mutated["batch_stats"]

        (scaled, new_stats), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
        grads = allreduce_gradients(grads, (mesh_lib.AXIS_DATA,))
        loss = collectives.pmean(scaled, (mesh_lib.AXIS_DATA,)) / opt_state.scaler.loss_scale
        new_params, new_opt, metrics = mp_opt.apply_gradients(opt_state, params, grads)
        # running stats are already identical across ranks under sync-BN; under
        # local BN each rank tracks its shard (reference local-BN semantics).
        return new_params, new_stats, new_opt, loss, metrics

    rep = P()  # params/opt-state replicated: pure DP
    step = jax.jit(jax.shard_map(
        sharded_step, mesh=mesh,
        in_specs=(rep, rep, rep, data_spec, data_spec),
        out_specs=(rep, rep, rep, rep, rep),
        check_vma=False,
    ))

    if args.data_dir:
        from apex_tpu.data import NpyBatchLoader
        batches = iter(NpyBatchLoader(args.data_dir, batch_shape=shape, loop=True))
    else:
        rng = np.random.default_rng(0)

        def synthetic():
            while True:
                yield (
                    rng.standard_normal(shape, dtype=np.float32),
                    rng.integers(0, args.num_classes, (args.batch_size,)),
                )
        batches = synthetic()

    shard = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))
    t0 = time.perf_counter()
    seen = 0
    for i, (images, labels) in zip(range(args.steps), batches):
        images = shard(jnp.asarray(images), data_spec)
        labels = shard(jnp.asarray(labels, jnp.int32), data_spec)
        params, batch_stats, opt_state, loss, metrics = step(
            params, batch_stats, opt_state, images, labels
        )
        if i == 0:  # exclude compile (and step 0's batch) from throughput
            # device->host fetch, not bare block_until_ready: through the
            # tunnel the latter can ack dispatch rather than execution
            float(loss)
            t0 = time.perf_counter()
        else:
            seen += args.batch_size
        if i % 5 == 0:
            print(f"step {i:4d} loss {float(loss):.4f} "
                  f"loss_scale {float(metrics['loss_scale']):.0f}")
    float(loss)  # stop the clock on a device->host fetch (tunnel-safe)
    dt = time.perf_counter() - t0
    print(f"{seen / dt:.1f} imgs/sec total, {seen / dt / n_dev:.1f} imgs/sec/chip "
          f"({args.arch}, {args.opt_level}, {n_dev}-way DP)")
    mesh_lib.destroy_model_parallel()


if __name__ == "__main__":
    main()
