"""DCGAN with amp — multiple models, optimizers, and losses
(reference: examples/dcgan/main_amp.py).

The reference example exists to exercise amp with TWO models (G, D), TWO
optimizers, and THREE backward passes per iteration (D-real, D-fake, G),
each with its own loss scaler (``amp.initialize([netD, netG], [optD, optG],
num_losses=3``). Functionally: each (model, optimizer) pair owns a
``MixedPrecisionOptimizer`` state; the D step sums its two scaled losses
under one scaler, G uses its own — the same skip/update independence the
reference gets from per-loss scalers.

    JAX_PLATFORMS=cpu python examples/dcgan/main_amp.py --steps 10
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
from flax import linen as nn

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


class Generator(nn.Module):
    ngf: int = 16
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, z):  # z: (B, nz) -> (B, 16, 16, 1)
        x = nn.Dense(4 * 4 * self.ngf * 2, dtype=self.dtype)(z)
        x = x.reshape(z.shape[0], 4, 4, self.ngf * 2)
        x = nn.relu(nn.ConvTranspose(self.ngf, (4, 4), (2, 2), dtype=self.dtype)(x))
        x = nn.ConvTranspose(1, (4, 4), (2, 2), dtype=self.dtype)(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    ndf: int = 16
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, img):  # (B, 16, 16, 1) -> (B,) logits
        x = nn.leaky_relu(nn.Conv(self.ndf, (4, 4), (2, 2), dtype=self.dtype)(img), 0.2)
        x = nn.leaky_relu(nn.Conv(self.ndf * 2, (4, 4), (2, 2), dtype=self.dtype)(x), 0.2)
        return nn.Dense(1, dtype=jnp.float32)(x.reshape(x.shape[0], -1))[:, 0]


def bce_logits(logits, target):
    # O1 keeps losses fp32 (lists/functional_overrides.py:29-68)
    logits = logits.astype(jnp.float32)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * target + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--nz", type=int, default=32)
    args = p.parse_args()

    policy = amp.get_policy("O2")
    G, D = Generator(), Discriminator()
    gp = amp.cast_params(G.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, args.nz)))["params"], policy)
    dp = amp.cast_params(D.init(jax.random.PRNGKey(1),
                                jnp.zeros((1, 16, 16, 1)))["params"], policy)
    opt_g = amp.MixedPrecisionOptimizer(FusedAdam(lr=2e-4, betas=(0.5, 0.999)), policy)
    opt_d = amp.MixedPrecisionOptimizer(FusedAdam(lr=2e-4, betas=(0.5, 0.999)), policy)
    gs, ds = opt_g.init(gp), opt_d.init(dp)

    def real_batch(key):  # synthetic "data": blurred noise blobs
        return jnp.tanh(jax.random.normal(key, (args.batch, 16, 16, 1)))

    @jax.jit
    def train_step(gp, dp, gs, ds, key):
        kz, kr, kz2 = jax.random.split(key, 3)
        z = jax.random.normal(kz, (args.batch, args.nz))
        real = real_batch(kr)

        # --- D step: two losses, one scaler (losses 0 and 1) ---
        def d_loss(dpar):
            fake = G.apply({"params": gp}, z)
            l_real = bce_logits(D.apply({"params": dpar}, real), 1.0)
            l_fake = bce_logits(D.apply({"params": dpar}, jax.lax.stop_gradient(fake)), 0.0)
            return opt_d.scale_loss(l_real + l_fake, ds)

        sd, d_grads = jax.value_and_grad(d_loss)(dp)
        dp_new, ds_new, d_metrics = opt_d.apply_gradients(ds, dp, d_grads)

        # --- G step: its own scaler (loss 2) ---
        def g_loss(gpar):
            z2 = jax.random.normal(kz2, (args.batch, args.nz))
            fake = G.apply({"params": gpar}, z2)
            return opt_g.scale_loss(bce_logits(D.apply({"params": dp_new}, fake), 1.0), gs)

        sg, g_grads = jax.value_and_grad(g_loss)(gp)
        gp_new, gs_new, g_metrics = opt_g.apply_gradients(gs, gp, g_grads)
        return (gp_new, dp_new, gs_new, ds_new,
                sd / ds.scaler.loss_scale, sg / gs.scaler.loss_scale)

    key = jax.random.PRNGKey(42)
    for i in range(args.steps):
        key, sub = jax.random.split(key)
        gp, dp, gs, ds, ld, lg = train_step(gp, dp, gs, ds, sub)
        if i % 2 == 0:
            print(f"step {i:3d} loss_D {float(ld):.4f} loss_G {float(lg):.4f} "
                  f"scales D={float(ds.scaler.loss_scale):.0f} "
                  f"G={float(gs.scaler.loss_scale):.0f}")
    print("done: two models, two optimizers, independent loss scalers")


if __name__ == "__main__":
    main()
