"""Headline benchmark: GPT-2 345M mixed-precision training step on one chip,
plus the two non-GPT BASELINE configs (ResNet-50 O2+FusedSGD imgs/sec,
BERT-large FusedLAMB tokens/sec) and an on-chip Pallas-kernel numerics
selftest.

Measures the framework's core promise — the reference's amp-O2 + fused-kernel
recipe (BASELINE.md targets 3/4: fused step vs unfused eager) — as tokens/sec
for a full train step (forward + backward + FusedAdam + dynamic loss scaling)
on GPT-2 345M, bf16 O2 policy with Pallas flash attention and fused LN.

``vs_baseline`` is the speedup over the same model trained the "Python-only
build" way the reference warns is slower (README.md:134-139): fp32 O0, unfused
XLA attention/LN, plain optax Adam.

Measurement discipline (PERF_NOTES.md): every throughput number is the
MEDIAN over >=3 timed windows on the same compiled program, with min/max
spread recorded, so round-over-round deltas are attributable to code rather
than co-tenant noise on the shared chip. ``vs_baseline`` is a ratio of
same-session medians.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — plus
"spread", "resnet50_o2_imgs_per_sec", "bert_large_lamb_tokens_per_sec",
"fused_opt_step_vs_eager", and a "selftest" block of per-kernel max-error
measurements (Pallas vs XLA fallback, fwd AND bwd, compiled on this chip).
"effective_batch" appears when OOM retries shrank a config's batch (the
ratio is then re-measured at the common batch so vs_baseline stays
apples-to-apples).

Crash discipline: the GPT headline (and, if it cannot fit, the degraded
rung under its own "gpt_degraded" key — never substituted for the
headline) each run in a FRESH SUBPROCESS that owns the chip alone, before
the parent touches the backend; the parent then gathers the
small-footprint evidence (selftest, optimizer microbench, ResNet floor-4,
BERT, pyprof scope seconds) with every stage individually wrapped. Stage
failures land in "errors"; the JSON line always prints and the process
always exits 0. The headline's O2/O0 windows are interleaved in time so
vs_baseline is robust to co-tenant drift ("interleaved": true in spread).

Baseline discipline (VERDICT r4 ask #1): the fp32 O0 leg is as
indestructible as the O2 headline. When the interleaved/sequential
in-process baseline fails, a FRESH "--gpt-o0" subprocess (its own OOM
ladder + sleep-retries, nothing else in its HBM) retries the 345M fp32
leg; a ratio from that path is marked spread.ratio_mode =
"cross_process_sequential" with both batches stated. If the 345M ratio is
still missing — or was never interleaved — the degraded rung (which
co-resides easily) supplies an INTERLEAVED ratio under
"vs_baseline_degraded": clearly labelled, never substituted for
"vs_baseline".

The headline subprocess also records MEASURED per-scope/per-op-kind
device seconds for the real 345M step (pyprof trace-join, VERDICT r4 ask
#2), and the ResNet/BERT rungs are bracketed by a fixed chained-matmul
canary program whose TF/s is recorded alongside them, so cross-round
drift in those single-config rungs is attributable to co-tenant load
(VERDICT r4 ask #6).

Telemetry (r6): the watchdog/checkpoint machinery is the library's now
(apex_tpu/monitor/watchdog.py — this file adapts it and adds a heartbeat
beat per stage; BENCH_STALL arms the stale-heartbeat kill). Setting
BENCH_JOURNAL=<path> makes every timed window, across all subprocess
phases, append one JSON-lines record (wall time, tok/s, loss, loss-scale
state, grad-norm, HBM occupancy sample — plus, for the GPT rungs,
mfu/hbm_bw_util/bound joined from one extra trace against the peak-spec
table, monitor/mfu.py; override the tunnel chip's measured ceiling via
APEX_TPU_PEAK_FLOPS / APEX_TPU_PEAK_HBM_GBPS) to that file via
apex_tpu.monitor.MetricsJournal; BENCH_TRACE=<path> additionally lands
one measured span per timed window in a monitor.tracing span file
(chrome://tracing-exportable); BENCH_FLIGHT=<path> arms the flight
recorder (apex_tpu/monitor/flight.py): journal/span records and
breadcrumbs ring in memory and dump to <path> as strict JSON when a
phase crashes, is SIGTERMed, or is killed by the watchdog (the parent
writes the kill dump from the structured heartbeat when SIGKILL took
the child's ring). Unset, the compiled programs are byte-identical to
un-instrumented rounds. Journals analyze offline with
`python -m apex_tpu.monitor.report <path>` (percentiles, stalls, spikes,
HBM trend) and gate with `... report compare A B` (exit 1 on regression).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Plugin platforms registered by sitecustomize (the axon TPU tunnel) ignore a
# plain JAX_PLATFORMS env var; force the selection before first backend use.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

WINDOWS = int(os.environ.get("BENCH_WINDOWS", "3"))

# process-global step journal (apex_tpu.monitor.journal), armed by
# BENCH_JOURNAL=<path>. Subprocess phases inherit the env, so every stage
# appends (O_APPEND, one JSON object per line) to ONE shared journal file;
# False means "tried and failed, stay off".
_JOURNAL = None

# process-global span tracer (apex_tpu.monitor.tracing), armed by
# BENCH_TRACE=<path>: every timed window lands one measured span (the
# window's device-barriered wall time), shareable across subprocess
# phases like the journal; chrome-exportable via
# monitor.tracing.write_chrome_trace. Unset: byte-identical programs.
_TRACER = None


def _get_tracer():
    global _TRACER
    path = os.environ.get("BENCH_TRACE")
    if not path:
        return None
    if _TRACER is None:
        try:
            from apex_tpu.monitor import tracing

            _TRACER = tracing.arm(path)
        except Exception as e:  # noqa: BLE001 - telemetry must not kill bench
            print(f"bench tracer disabled: {e}", file=sys.stderr)
            _TRACER = False
    return _TRACER or None


def _get_journal():
    global _JOURNAL
    path = os.environ.get("BENCH_JOURNAL")
    if not path:
        return None
    if _JOURNAL is None:
        try:
            from apex_tpu.monitor.journal import MetricsJournal

            _JOURNAL = MetricsJournal(path, sample_hbm_every=1)
        except Exception as e:  # noqa: BLE001 - telemetry must not kill bench
            print(f"bench journal disabled: {e}", file=sys.stderr)
            _JOURNAL = False
    return _JOURNAL or None


def _state_metrics(state):
    """Metrics getter for journaled GPT runs: ``_prepare`` appends the last
    step's metrics dict (loss_scale/found_inf/grad_norm) as ``state[3]``
    only when the journal is armed."""
    if len(state) > 3:
        return lambda: state[3]
    return None


# per-token FLOP/byte totals per journal label ("gpt_O2"/"gpt_O0"), traced
# once per prepared config when BENCH_JOURNAL is armed, so every timed
# window's record carries mfu/hbm_bw_util/bound (monitor/mfu.py). Keyed by
# label because the interleaved headline times two configs through one
# journal. Host/trace-side only: the compiled programs are untouched.
_WINDOW_COSTS = {}


def _register_window_costs(label, step, params, opt_state, batch, seq):
    try:
        from apex_tpu.monitor import mfu as mfu_lib

        tokens = jnp.zeros((batch, seq), jnp.int32)
        costs = mfu_lib.traced_step_costs(step, params, opt_state,
                                          tokens, tokens)
        _WINDOW_COSTS[label] = {
            "flops_per_token": costs["flops"] / (batch * seq),
            "bytes_per_token": costs["bytes"] / (batch * seq),
            "spec": mfu_lib.peak_spec(),
            "method": costs["method"],
        }
    except Exception as e:  # noqa: BLE001 - telemetry must not kill bench
        print(f"mfu costs unavailable for {label}: {e}", file=sys.stderr)


def _window_mfu(label, per_window_units, dt):
    costs = _WINDOW_COSTS.get(label)
    if not costs:
        return {}
    try:
        from apex_tpu.monitor import mfu as mfu_lib

        fields = mfu_lib.mfu_metrics(
            flops=costs["flops_per_token"] * per_window_units,
            bytes_accessed=costs["bytes_per_token"] * per_window_units,
            wall_s=dt, spec=costs["spec"])
        if costs.get("method"):
            fields["mfu_method"] = costs["method"]
        return fields
    except Exception:  # noqa: BLE001
        return {}


def _stats(rates):
    """Median/min/max over timed windows (rounded for the JSON line)."""
    s = sorted(rates)
    n = len(s)
    med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
    return {
        "median": round(med, 1),
        "min": round(s[0], 1),
        "max": round(s[-1], 1),
        "windows": n,
    }


def _zero_env_level():
    """(zero, zero_level) from BENCH_ZERO — ONE value mapping for the
    program builder and the rung provenance ('3' -> level 3, any other
    non-empty value -> level 2, unset -> off)."""
    zero_env = os.environ.get("BENCH_ZERO", "")
    zero = bool(zero_env)
    return zero, (3 if zero_env.strip() == "3" else 2 if zero else 0)


def _qcomm_env():
    """Wire dtype of the ZeRO grad reduce-scatter from BENCH_QCOMM
    ('int8'/'e5m2'; '1' -> 'int8'; unset/empty -> None = exact fp32 wire).
    Only meaningful with BENCH_ZERO armed at level 1/2 — the builder
    rejects other combinations, same as the library knob."""
    v = os.environ.get("BENCH_QCOMM", "").strip().lower()
    if not v:
        return None
    return "int8" if v == "1" else v


def _is_oom(e: Exception) -> bool:
    # walk the cause chain: the ladder re-raises OOMs as RuntimeError with
    # the jaxlib RESOURCE_EXHAUSTED as __cause__
    seen = 0
    while e is not None and seen < 8:
        if "RESOURCE_EXHAUSTED" in str(e) or "OOM even at batch" in str(e):
            return True
        e, seen = e.__cause__, seen + 1
    return False


def _timed_windows(advance, get_loss, *, steps, windows, per_window_units,
                   label="", get_metrics=None):
    """The shared window-timing protocol: warmup happened already (caller
    ran one step/chunk and fetched); each window runs ``advance()``
    ``steps`` times, then stops the clock on a device→host fetch of the
    loss (whose dependency chain covers every step — tunnel discipline,
    PERF_NOTES.md). Returns per-window rates in ``per_window_units/s``.

    With BENCH_JOURNAL armed, each window lands one journal record (wall
    time, units/s, loss, the step metrics from ``get_metrics``, an HBM
    sample) AFTER the loss fetch — the device is drained, so the journal
    adds zero syncs to the timed region. The recorded loss is exactly the
    value the barrier fetched: for the GPT rungs that is the SCALED loss
    (divide by the record's ``loss_scale`` for a comparable curve)."""
    rates = []
    journal = _get_journal()
    for i in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            advance()
        loss_val = float(get_loss())
        dt = time.perf_counter() - t0
        assert jnp.isfinite(loss_val), "non-finite loss in bench"
        rates.append(per_window_units / dt)
        tracer = _get_tracer()
        if tracer is not None:
            # the loss fetch above already barriered the device; the span
            # is the window's measured wall, post-hoc
            tracer.record("window", dur_s=dt, cat="host",
                          label=label or "window", window=i, steps=steps,
                          rate=round(per_window_units / dt, 1))
        if journal is not None:
            journal.step_end(
                loss=loss_val, wall_s=dt, tokens=per_window_units,
                metrics=(get_metrics() if get_metrics else None),
                label=label or "window", window=i, steps=steps,
                **_window_mfu(label, per_window_units, dt))
    return rates


def _oom_halving(run, batch, *, min_batch, label):
    """Run ``run(batch)``, halving the batch on RESOURCE_EXHAUSTED — the
    shared co-tenant degradation ladder tail."""
    while True:
        try:
            return run(batch)
        except Exception as e:  # noqa: BLE001 - jaxlib error types vary
            if not _is_oom(e) or batch <= min_batch:
                raise
            print(f"{label}: OOM at batch {batch}", file=sys.stderr)
            batch //= 2


def build(policy_level: str, impl: str, remat_policy=None, hidden=None,
          layers=None, unroll=False):
    import optax

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    fused = policy_level == "O2"
    # BENCH_ZERO=1 arms the ZeRO optimizer path (fp32 masters + moments
    # sharded over a data mesh, psum_scatter/bf16-gather inside the step).
    # BENCH_ZERO=3 arms the fully-sharded (ZeRO-3) drive on top: the bf16
    # params persist as chunk trees and each layer's weights all-gather
    # just-in-time inside the layer loop (run_layers chunk_meta). On this
    # single-chip target the data axis has size 1 — the collectives are
    # degenerate — but the rung exercises the exact end-to-end program a
    # dp>1 pod runs, through the tunnel, with rung provenance recording
    # it. Off by default: the headline program stays byte-identical.
    # BENCH_QCOMM=int8|e5m2 (with BENCH_ZERO at level 1/2) additionally
    # quantizes the grad reduce-scatter wire: encoded all_to_all +
    # per-chunk fp32 scales + error-feedback residual in the sharded
    # state (parallel/quantize.py).
    zero, zero_level = _zero_env_level()
    zero_level = zero_level or 2
    qcomm = _qcomm_env()
    if qcomm and not zero:
        # a silently-dropped knob would make a "quantized vs baseline"
        # comparison two identical fp32 runs — fail loudly instead, same
        # as pretrain_gpt's --reduce-dtype-requires---zero arg check
        raise SystemExit(
            "BENCH_QCOMM requires BENCH_ZERO (levels 1/2): the quantized "
            "wire is the ZeRO grad reduce-scatter")
    cfg = GPTConfig(
        vocab_size=50304,
        hidden_size=hidden or int(os.environ.get("BENCH_HIDDEN", "1024")),
        num_layers=layers or int(os.environ.get("BENCH_LAYERS", "24")),
        num_attention_heads=16,
        max_seq_len=1024,
        hidden_dropout=0.0,
        axis=None,
        compute_dtype=jnp.bfloat16 if fused else jnp.float32,
        remat=True,
        remat_policy=remat_policy,
        attention_impl=impl,
        # unrolled layer drive kills the scan backward's ~28 ms of grad
        # stacking (PERF_NOTES r5); ladder falls back to scan under OOM
        unroll_layers=unroll,
        # fused chunked LM-head CE: ~6% throughput and ~0.8 GB less peak HBM
        # (survives pressure from co-tenants on the shared chip) — PERF_NOTES.md
        lm_head_chunks=8 if fused else None,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy(policy_level)
    opt = FusedAdam(lr=1e-4) if fused else optax.adam(1e-4)
    # grad-norm in the step metrics only when the journal is armed: the
    # extra tree reduction is noise next to the step's matmuls, but the
    # un-journaled headline program must stay byte-identical to pre-journal
    # rounds so cross-round deltas attribute to code under test
    mp_opt = amp.MixedPrecisionOptimizer(
        opt, policy, log_grad_norm=bool(os.environ.get("BENCH_JOURNAL")),
        zero_axis="data" if zero else None,
        zero_level=zero_level,
        gather_dtype="bf16" if (zero and fused) else None,
        reduce_dtype=qcomm if zero else None)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)

    if zero:
        import numpy as _np
        from jax.sharding import Mesh, PartitionSpec as _P

        mesh = Mesh(_np.array(jax.devices()[:1]), ("data",))
        pspecs = jax.tree.map(lambda _: _P(), params)

        if zero_level >= 3:
            from apex_tpu.optimizers.distributed import gather_chunked_tree

            z3 = mp_opt.zero3_init(params, mesh, pspecs)
            layer_meta = z3.meta.subtree("layers")
            rest_meta = z3.meta.select(
                [k for k in z3.meta.shapes if k != "layers"])
            params, opt_state = z3.params, z3.opt_state
            pspecs, zero_specs = z3.param_specs, z3.state_specs

            def zero_step(p, s, tokens, targets):
                rest_c = {k: v for k, v in p.items() if k != "layers"}

                def scaled_loss(rest_c, layer_c):
                    rest = gather_chunked_tree(rest_c, rest_meta)
                    return mp_opt.scale_loss(
                        model.loss(dict(rest, layers=layer_c), tokens,
                                   targets, layer_chunk_meta=layer_meta), s)

                loss_s, (rg, lg) = jax.value_and_grad(
                    scaled_loss, argnums=(0, 1))(rest_c, p["layers"])
                new_p, new_s, metrics = mp_opt.apply_gradients(
                    s, p, dict(rg, layers=lg))
                return new_p, new_s, loss_s, metrics
        else:
            opt_state, zero_specs = mp_opt.zero_init(params, mesh, pspecs)

            def zero_step(p, s, tokens, targets):
                def scaled_loss(p):
                    return mp_opt.scale_loss(
                        model.loss(p, tokens, targets), s)

                loss_s, grads_s = jax.value_and_grad(scaled_loss)(p)
                new_p, new_s, metrics = mp_opt.apply_gradients(s, p, grads_s)
                return new_p, new_s, loss_s, metrics

        step = jax.shard_map(
            zero_step, mesh=mesh,
            in_specs=(pspecs, zero_specs, _P(), _P()),
            out_specs=(pspecs, zero_specs, _P(), _P()), check_vma=False)
        return step, params, opt_state

    opt_state = mp_opt.init(params)

    def step(params, opt_state, tokens, targets):
        def scaled_loss(p):
            return mp_opt.scale_loss(model.loss(p, tokens, targets), opt_state)

        loss_s, grads_s = jax.value_and_grad(scaled_loss)(params)
        new_params, new_state, metrics = mp_opt.apply_gradients(
            opt_state, params, grads_s
        )
        return new_params, new_state, loss_s, metrics

    return step, params, opt_state


def _prepare(step, params, opt_state, batch, seq, steps=10, scan_chunk=4):
    """Build + warm up (compile and run one chunk) a GPT train-step
    measurement; returns ``(advance, get_loss, n_chunks, per_window_units,
    state)`` so callers can run windows themselves — the interleaved
    headline alternates windows between two prepared configs.

    The scan matters twice over through the axon tunnel: it amortizes
    per-dispatch overhead, and — since the tunnel backend rejects buffer
    donation — it is the only way the params/optimizer state update
    in-place (the scan carry lives inside one program) instead of being
    rewritten to fresh buffers every step. ~5% end-to-end (PERF_NOTES.md).
    """
    from jax import lax

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    targets = jnp.roll(tokens, -1, axis=-1)

    # journal armed: the chunk also returns the LAST step's metrics dict
    # (loss_scale/found_inf/grad_norm — three scalars already computed by
    # the step) so windows can journal loss-scale state without a second
    # program. Un-journaled programs keep the exact pre-journal outputs.
    journaled = bool(os.environ.get("BENCH_JOURNAL"))
    if scan_chunk > 1:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            def body(carry, _):
                p, s = carry
                p, s, loss, m = step(p, s, tokens, targets)
                return (p, s), ((loss, m) if journaled else loss)

            (params, opt_state), ys = lax.scan(
                body, (params, opt_state), None, length=scan_chunk)
            if journaled:
                losses, ms = ys
                return (params, opt_state, losses[-1],
                        jax.tree.map(lambda x: x[-1], ms))
            return params, opt_state, ys[-1]

    else:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            p, s, loss, m = step(params, opt_state, tokens, targets)
            if journaled:
                return p, s, loss, m
            return p, s, loss

    # round the requested step count up to whole chunks (never time fewer
    # steps than asked); normalization below uses the actual count run
    n_chunks = max(1, -(-steps // scan_chunk))
    state = [params, opt_state, None]

    def advance():
        state[:] = run_chunk(state[0], state[1], tokens, targets)

    # warmup / compile. Through remote-device tunnels (axon),
    # block_until_ready can ack dispatch rather than execution, so force a
    # device->host transfer of a value that depends on the whole chain.
    advance()
    float(state[2])
    return (advance, lambda: state[2], n_chunks,
            batch * seq * n_chunks * scan_chunk, state)


_LADDERS = {
    # (remat_policy, scan_chunk, unroll_layers) from fastest to most
    # memory-frugal. The unroll rung drives the stacked layers with static
    # slices instead of lax.scan: the scan backward's dynamic-update-slice
    # grad stacking cost ~28 ms of the 345M grad step (230 -> 188 ms
    # measured on-chip, PERF_NOTES r5); under unroll prevent_cse also lets
    # XLA elide remat recompute where memory allows, so full remat leads.
    # save_attn keeps the flash kernel outputs so backward skips the
    # attention recompute (~5% when HBM allows it); scan 8 amortizes
    # another ~1-1.5% of dispatch/carry cost over scan 4 (A/B/A bracket:
    # 30.6k vs 30.1-30.4k tok/s same session) at the price of a larger
    # program for the first rung.
    # Both ladders lead with the SAME (unroll, scan 8) harness so the
    # O2/O0 ratio compares like with like — an asymmetric drive would
    # inflate vs_baseline by the harness's own amortization, not the
    # optimizations under test.
    "O2": [(None, 8, True), ("save_attn", 8, False), ("save_attn", 4, False),
           (None, 4, False), (None, 1, False)],
    "O0": [(None, 8, True), (None, 8, False), (None, 4, False),
           (None, 1, False)],
}


def prepare_resilient(level, impl, batch, seq, steps, *, min_batch=1,
                      hidden=None, layers=None, retries=1, retry_sleep=25):
    """Ladder-degrading ``_prepare``: selective remat → full remat, scanned
    dispatch → per-step dispatch, then halve the batch, until the config
    compiles and warms up under today's co-tenant HBM pressure. When the
    whole ladder OOMs, sleep and retry it once from the top — through the
    tunnel, buffer frees land asynchronously and co-tenant spikes pass
    within tens of seconds (both observed live in r4: a config that OOM'd
    at batch 1 ran at 64k tok/s in the same process minutes later).
    Returns ``(advance, get_loss, n_chunks, units, state, batch, rung)``
    where ``rung`` records which ladder configuration actually ran (the
    BENCH record must show whether the unroll rung or a fallback
    produced each number)."""
    import gc

    batch0 = batch
    attempt = 0
    last_oom = ""
    while True:
        for remat_policy, scan_chunk, unroll in _LADDERS[level]:
            try:
                step, params, opt_state = build(level, impl, remat_policy,
                                                hidden, layers, unroll=unroll)
                prep = _prepare(step, params, opt_state,
                                batch, seq, steps, scan_chunk=scan_chunk)
                if os.environ.get("BENCH_JOURNAL"):
                    # one extra TRACE (no compile) arms per-window MFU
                    _register_window_costs(f"gpt_{level}", step,
                                           prep[4][0], prep[4][1], batch, seq)
                zero, zero_level = _zero_env_level()
                return prep + (batch, {"remat": remat_policy or "full",
                                       "scan": scan_chunk,
                                       "unroll": unroll,
                                       "zero": zero,
                                       "zero_level": zero_level,
                                       "reduce_dtype": (_qcomm_env() or
                                                        "fp32") if zero
                                       else None})
            except Exception as e:  # noqa: BLE001 - jaxlib error types vary
                if not _is_oom(e):
                    raise
                # keep only a STRING: retaining the exception object keeps
                # its traceback frames — and with them the failed attempt's
                # device buffers — alive into the next, smaller rung, which
                # then OOMs against the ghost of this one
                last_oom = str(e)[:500]
                del e
                gc.collect()
                print(f"{level}: OOM at remat_policy={remat_policy} "
                      f"scan={scan_chunk} unroll={unroll}, batch {batch}",
                      file=sys.stderr)
        if batch <= min_batch:
            if attempt < retries:
                attempt += 1
                print(f"{level}: ladder exhausted; sleeping {retry_sleep}s "
                      f"(async tunnel frees / co-tenant spike), retry "
                      f"{attempt}/{retries} from batch {batch0}",
                      file=sys.stderr)
                time.sleep(retry_sleep)
                batch = batch0
                continue
            raise RuntimeError(
                f"{level}: OOM even at batch {batch}; last: {last_oom}")
        batch //= 2


def measure_resilient(level, impl, batch, seq, steps, windows=WINDOWS,
                      hidden=None, layers=None, retries=1, retry_sleep=25):
    """``prepare_resilient`` (build + warm up one config down the OOM
    ladder) + timed windows, re-degrading if co-tenant pressure arrives
    between warmup and the windows."""
    import gc

    while True:
        (advance, get_loss, n_chunks, units, _state, batch,
         rung) = prepare_resilient(
            level, impl, batch, seq, steps, hidden=hidden, layers=layers,
            retries=retries, retry_sleep=retry_sleep)
        try:
            rates = _timed_windows(advance, get_loss, steps=n_chunks,
                                   windows=windows, per_window_units=units,
                                   label=f"gpt_{level}",
                                   get_metrics=_state_metrics(_state))
            return rates, batch, rung
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e) or batch <= 1:
                raise
            print(f"{level}: OOM during windows at batch {batch}",
                  file=sys.stderr)
            batch //= 2
            # drop this attempt's program + buffers before re-preparing
            del advance, get_loss, _state
            gc.collect()


def gpt_headline(batch, seq, steps, windows=WINDOWS, hidden=None, layers=None):
    """O2-fused vs O0-fp32-unfused GPT train step, with the two configs'
    timed windows INTERLEAVED (O2, O0, O2, O0, …) so ``vs_baseline`` is a
    ratio of medians measured under the same minutes of co-tenant drift
    (VERDICT r3 #8). Falls back to sequential measurement when both
    programs cannot be resident in HBM together; the fallback is recorded
    as ``"interleaved": false`` in the spread block.

    Returns ``(value_stats, base_stats, common_batch, interleaved)``;
    ``base_stats`` is None when the fp32 baseline cannot fit at all (the
    O2 value is still reported — losing the ratio must not lose the
    headline, VERDICT r3 ask #1)."""
    prep2 = prepare_resilient("O2", "auto", batch, seq, steps,
                              hidden=hidden, layers=layers)
    b2, rung2 = prep2[-2], prep2[-1]
    # time the headline VALUE first, before any baseline attempt can churn
    # HBM (observed: the O0-345M fp32 leg can be unplaceable for minutes
    # while O2 bf16 runs fine)
    solo2 = dict(_stats(_timed_windows(prep2[0], prep2[1], steps=prep2[2],
                                       windows=windows,
                                       per_window_units=prep2[3],
                                       label="gpt_O2",
                                       get_metrics=_state_metrics(prep2[4]))),
                 rung=rung2)
    interleaved = True
    prep0 = None
    try:
        # co-resident attempt: fail FAST (no sleep-retry) — laddering O0
        # while the O2 program occupies HBM fights a doomed residency; the
        # sequential fallback frees O2 first and ladders with retries
        prep0 = prepare_resilient("O0", "xla", b2, seq, steps, min_batch=b2,
                                  hidden=hidden, layers=layers, retries=0)
    except Exception as e:  # noqa: BLE001
        if not _is_oom(e):
            raise
        interleaved = False
    if prep0 is None:
        # Could not co-reside at O2's batch. Measure sequentially, re-doing
        # whichever config sits at the larger batch until both were timed
        # at the SAME batch (the ladder can halve during re-measurement).
        import gc

        del prep2
        gc.collect()
        try:
            b = b2
            while True:
                # the fp32 leg has a ~5.6 GB batch-independent floor
                # (params + Adam moments): give it extra sleep-retries so
                # a co-tenant pressure dip within ~2 minutes still yields
                # a ratio instead of a value-only record
                rates0, b0, rung0 = measure_resilient(
                    "O0", "xla", b, seq, steps, windows, hidden=hidden,
                    layers=layers, retries=2, retry_sleep=45)
                rates2, b, rung2b = measure_resilient(
                    "O2", "auto", b0, seq, steps, windows, hidden=hidden,
                    layers=layers)
                if b == b0:
                    return (dict(_stats(rates2), rung=rung2b),
                            dict(_stats(rates0), rung=rung0), b, False)
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e):
                raise
            print("headline: fp32 baseline unplaceable; reporting the O2 "
                  "value without a ratio", file=sys.stderr)
            return solo2, None, b2, False
    # min_batch=b2 on the co-resident prepare means success implies the
    # same batch; the unequal-batch case always goes through the
    # sequential fallback above
    assert prep0[-2] == b2, (prep0[-2], b2)
    b0 = b2
    rung0 = prep0[-1]
    adv2, loss2, n2, u2, _s2, _, _ = prep2
    adv0, loss0, n0, u0, _s0, _, _ = prep0
    rates2, rates0 = [], []
    try:
        for _ in range(windows):
            rates2 += _timed_windows(adv2, loss2, steps=n2, windows=1,
                                     per_window_units=u2, label="gpt_O2",
                                     get_metrics=_state_metrics(_s2))
            rates0 += _timed_windows(adv0, loss0, steps=n0, windows=1,
                                     per_window_units=u0, label="gpt_O0",
                                     get_metrics=_state_metrics(_s0))
    except Exception as e:  # noqa: BLE001
        if not _is_oom(e):
            raise
        if not (rates2 and rates0):
            print("headline: OOM before any interleaved pair completed; "
                  "reporting the solo O2 value without a ratio",
                  file=sys.stderr)
            return solo2, None, b2, False
        # keep only COMPLETED pairs: an unpaired O2 window measured before
        # the OOM spike would bias the ratio the interleave exists to guard
        n = min(len(rates2), len(rates0))
        rates2, rates0 = rates2[:n], rates0[:n]
        print(f"headline: OOM mid-interleave after {n} paired windows; "
              "reporting the completed pairs", file=sys.stderr)
    return (dict(_stats(rates2), rung=rung2),
            dict(_stats(rates0), rung=rung0), b2, interleaved)


def _canary(windows=3):
    """Fixed chained-matmul program (4096x4096 bf16, 100 links in one
    scan) timed with the tunnel fetch discipline — the SAME program every
    round, so its median TF/s is a co-tenant drift reference. Recorded
    next to the single-config ResNet/BERT rungs (VERDICT r4 weak #4:
    1,721 -> 1,667 imgs/s across rounds was unattributable). Each link is
    rescaled by 1/sqrt(n) so bf16 magnitudes stay ~1 over 100 links; the
    scalar-sum return forces the whole chain on fetch. Returns median
    TF/s (2*4096^3*100 ≈ 13.7 TFLOP/call ≈ 200 ms on this chip: long
    enough that the ~40 ms per-program tunnel dispatch does not
    dominate)."""
    import math

    from jax import lax

    n, chain = 4096, 100
    a = jax.random.normal(jax.random.PRNGKey(3), (n, n), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(4), (n, n), jnp.bfloat16)

    @jax.jit
    def run(a, w):
        inv = jnp.bfloat16(1.0 / math.sqrt(n))

        def body(c, _):
            return (c @ w) * inv, None

        out, _ = lax.scan(body, a, None, length=chain)
        return jnp.sum(out.astype(jnp.float32))

    assert jnp.isfinite(float(run(a, w)))  # compile + execute
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        v = float(run(a, w))
        dt = time.perf_counter() - t0
        assert jnp.isfinite(v), "canary chain went non-finite"
        rates.append(2 * n ** 3 * chain / dt / 1e12)
    return _stats(rates)["median"]


# ---------------------------------------------------------------------------
# ResNet-50 O2 + FusedSGD (BASELINE.md configs 1-2: the named headline
# metric "ResNet-50 imgs/sec/chip (amp O2-equivalent)"). Single chip, so
# SyncBatchNorm's cross-shard merge is the identity; the conv/NHWC/BN path
# is what is being measured. Reference recipe:
# examples/imagenet/main_amp.py:281+ (ours: examples/imagenet/main_amp.py).
# ---------------------------------------------------------------------------


def bench_resnet50(batch=None, steps=10, windows=WINDOWS):
    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet50
    from apex_tpu.ops.xentropy import softmax_cross_entropy
    from apex_tpu.optimizers import FusedSGD

    batch = batch or int(os.environ.get("BENCH_RESNET_BATCH", "64"))
    policy = amp.get_policy("O2")
    model = ResNet50(num_classes=1000, dtype=policy.op_dtype("conv"))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4, nesterov=True),
        policy)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3), jnp.float32))
    params = amp.cast_params(variables["params"], policy)
    batch_stats = variables["batch_stats"]
    opt_state = mp_opt.init(params)

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        def scaled_loss(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy(logits, labels))
            return mp_opt.scale_loss(loss, opt_state), mutated["batch_stats"]

        (scaled, new_stats), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params)
        new_params, new_opt, metrics = mp_opt.apply_gradients(
            opt_state, params, grads)
        return (new_params, new_stats, new_opt,
                scaled / opt_state.scaler.loss_scale)

    def run(batch):
        images = jax.random.normal(jax.random.PRNGKey(1),
                                   (batch, 224, 224, 3), jnp.float32)
        labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)
        state = [params, batch_stats, opt_state, None]

        def advance():
            state[:] = step(state[0], state[1], state[2], images, labels)

        advance()
        float(state[3])  # compile + execute barrier
        rates = _timed_windows(advance, lambda: state[3], steps=steps,
                               windows=windows,
                               per_window_units=batch * steps,
                               label="resnet50")
        return dict(_stats(rates), batch=batch)

    return _oom_halving(run, batch, min_batch=4, label="resnet50")


# ---------------------------------------------------------------------------
# BERT-large-ish + FusedLAMB (BASELINE.md config 3: BERT pretraining with
# FusedLAMB + FusedLayerNorm). Reference recipe: the L0 BERT minimal test
# (run_bert_minimal_test.py) at bert-large shapes.
# ---------------------------------------------------------------------------


def bench_bert_lamb(batch=None, steps=10, windows=WINDOWS, hidden=None,
                    layers=None):
    import gc

    from apex_tpu import amp
    from apex_tpu.models import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB

    batch = batch or int(os.environ.get("BENCH_BERT_BATCH", "8"))
    seq = 512
    hidden = hidden or 1024
    layers = layers or 24

    def build_step(unroll):
        cfg = BertConfig(
            vocab_size=30592, hidden_size=hidden, num_layers=layers,
            num_attention_heads=16, max_seq_len=seq, hidden_dropout=0.0,
            axis=None, compute_dtype=jnp.bfloat16, remat=True,
            unroll_layers=unroll)
        model = BertModel(cfg)
        policy = amp.get_policy("O2")
        mp_opt = amp.MixedPrecisionOptimizer(FusedLAMB(lr=1e-3), policy)
        params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        opt_state = mp_opt.init(params)

        @jax.jit
        def step(params, opt_state, toks, lmask, labels, nsp):
            def scaled_loss(p):
                return mp_opt.scale_loss(
                    model.loss(p, toks, None, lmask, labels, nsp), opt_state)

            loss_s, grads = jax.value_and_grad(scaled_loss)(params)
            new_params, new_state, _ = mp_opt.apply_gradients(
                opt_state, params, grads)
            return new_params, new_state, loss_s / opt_state.scaler.loss_scale

        return cfg, step, params, opt_state

    def attempt(unroll, batch):
        """One (config, batch) measurement in its OWN frame, so a failed
        attempt's ~5 GB of buffers (params + LAMB masters/moments + jitted
        step) die with the frame before the fallback allocates — the
        buffer-pinning trap prepare_resilient documents."""
        cfg, step, params, opt_state = build_step(unroll)
        ks = jax.random.split(jax.random.PRNGKey(1), 4)
        toks = jax.random.randint(ks[0], (batch, seq), 0, cfg.vocab_size)
        lmask = (jax.random.uniform(ks[1], (batch, seq))
                 < 0.15).astype(jnp.int32)
        labels = jax.random.randint(ks[2], (batch, seq), 0, cfg.vocab_size)
        nsp = jax.random.randint(ks[3], (batch,), 0, 2)
        state = [params, opt_state, None]

        def advance():
            state[:] = step(state[0], state[1], toks, lmask, labels, nsp)

        advance()
        float(state[2])
        rates = _timed_windows(advance, lambda: state[2], steps=steps,
                               windows=windows,
                               per_window_units=batch * seq * steps,
                               label="bert")
        return dict(_stats(rates), batch=batch, unroll=unroll)

    def run(batch):
        # mini-ladder mirroring _LADDERS' shape: the unrolled drive first
        # (kills the layer scan's grad-stacking DUS), scan fallback at the
        # SAME batch before the outer halving shrinks it
        last_msg = ""
        for unroll in (True, False):
            try:
                return attempt(unroll, batch)
            except Exception as e:  # noqa: BLE001
                if not _is_oom(e):
                    raise
                # keep only a STRING (the exception's traceback pins the
                # failed attempt's device buffers)
                last_msg = str(e)[:300]
                del e
                gc.collect()
                print(f"bert: OOM at unroll={unroll} batch {batch}",
                      file=sys.stderr)
        # phrase with the marker _is_oom matches, so the outer halving
        # ladder recognizes this as memory pressure even when last_msg's
        # truncation lost the RESOURCE_EXHAUSTED text
        raise RuntimeError(
            f"bert: OOM even at batch {batch}; last: {last_msg}")

    return _oom_halving(run, batch, min_batch=1, label="bert")


# The shared (hidden, layers) shrink ladder for EVERY degraded leg — GPT
# headline, BERT, and the profile ((768, 12) ≈ 110M-ish/bert-base-wide,
# then a 4-layer floor that co-resides with anything). One constant so a
# rung retune cannot leave the legs degrading through different shapes.
_DEGRADED_RUNGS = ((768, 12), (512, 4))

# BERT rungs, flagship first. Each rung still runs bench_bert_lamb's own
# unroll + batch-halving ladder before the next rung shrinks the model.
_BERT_RUNGS = ((None, None),) + _DEGRADED_RUNGS


def bench_bert_resilient(batch=None, steps=10, windows=WINDOWS,
                         measure=None):
    """``bench_bert_lamb`` under the degraded-rung ladder (VERDICT r5
    top_next: occupation-proof the official record). When the flagship
    BERT-large cannot fit even at batch 1, smaller configs still produce a
    number — recorded WITH rung provenance (``degraded.hidden/layers`` and
    the flagship's OOM message), never silently substituted for the
    flagship shape. ``measure`` exists for the unit test (a stub rung)."""
    import gc

    measure = measure or bench_bert_lamb
    flagship_oom = last_oom = ""
    for hid, lay in _BERT_RUNGS:
        try:
            rec = measure(batch, steps, windows, hidden=hid, layers=lay)
            if hid is not None:
                rec["degraded"] = {"hidden": hid, "layers": lay,
                                   "flagship_oom": flagship_oom}
            return rec
        except Exception as e:  # noqa: BLE001 - jaxlib error types vary
            if not _is_oom(e):
                raise
            # keep only STRINGS (the traceback pins the rung's buffers):
            # the flagship's for rung provenance, the most recent for the
            # exhausted-ladder raise below
            last_oom = str(e)[:300]
            flagship_oom = flagship_oom or last_oom
            del e
            gc.collect()
            print(f"bert: rung (hidden={hid}, layers={lay}) OOM; degrading",
                  file=sys.stderr)
    raise RuntimeError(
        f"bert: OOM even at the smallest degraded rung; last: {last_oom}")


# ---------------------------------------------------------------------------
# On-chip kernel numerics selftest: the COMPILED Pallas kernels (TPU tiling,
# MXU accumulation) vs their XLA fallbacks, fwd AND bwd — the coverage
# interpret-mode CPU tests cannot give (reference pattern: the
# elementwise-tolerance tests of tests/L0/run_fused_layer_norm/).
# ---------------------------------------------------------------------------


def _max_errs(a, b):
    """(max abs error, scale-normalized error): the normalized form divides
    by the reference tensor's max magnitude, the right yardstick for bf16
    tensors whose values span decades (pointwise relative error explodes on
    near-zero entries; plain abs error penalizes large-magnitude grads)."""
    a = np.asarray(jax.device_get(a), np.float64)
    b = np.asarray(jax.device_get(b), np.float64)
    if not a.size:
        return 0.0, 0.0
    abs_err = float(np.max(np.abs(a - b)))
    scale = max(float(np.max(np.abs(b))), 1e-6)
    return abs_err, abs_err / scale


def _compare(fn_pallas, fn_xla, args, tol_norm, grad_argnums=None):
    """fwd + bwd max abs / scale-normalized error between two impls of the
    same math; ``ok`` gates on the normalized error."""
    fwd_p = jax.jit(fn_pallas)(*args)
    fwd_x = jax.jit(fn_xla)(*args)
    abs_err, norm_err = _max_errs(fwd_p, fwd_x)
    entry = {"fwd_max_abs_err": round(abs_err, 6),
             "fwd_norm_err": round(norm_err, 6)}
    if grad_argnums is not None:
        # random (fixed-key) cotangent: grads of sum(out * w)
        w = jax.random.normal(jax.random.PRNGKey(7), fwd_p.shape,
                              jnp.float32).astype(fwd_p.dtype)

        def loss(fn):
            return lambda *a: jnp.sum(fn(*a).astype(jnp.float32)
                                      * w.astype(jnp.float32))

        g_p = jax.jit(jax.grad(loss(fn_pallas), argnums=grad_argnums))(*args)
        g_x = jax.jit(jax.grad(loss(fn_xla), argnums=grad_argnums))(*args)
        g_abs = g_norm = 0.0
        for a, b in zip(jax.tree.leaves(g_p), jax.tree.leaves(g_x)):
            ae, ne = _max_errs(a, b)
            g_abs, g_norm = max(g_abs, ae), max(g_norm, ne)
        entry["bwd_max_abs_err"] = round(g_abs, 6)
        entry["bwd_norm_err"] = round(g_norm, 6)
    entry["tol_norm"] = tol_norm
    worst = max(v for k, v in entry.items() if k.endswith("norm_err"))
    entry["ok"] = bool(worst <= tol_norm)
    return entry


def selftest():
    """Per-kernel compiled-vs-fallback max errors on THIS backend."""
    from functools import partial

    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.ops.layer_norm import layer_norm, rms_norm
    from apex_tpu.ops.lm_head_loss import (
        lm_head_cross_entropy,
        lm_head_cross_entropy_reference,
    )
    from apex_tpu.ops.softmax import scaled_masked_softmax
    from apex_tpu.ops.xentropy import softmax_cross_entropy

    results = {"platform": jax.default_backend()}
    key = jax.random.PRNGKey(0)

    def entry(name, fn):
        """Isolate each kernel's comparison: one OOM/compile failure must
        not wipe the other kernels' evidence (degrade, don't die)."""
        try:
            results[name] = fn()
        except Exception as e:  # noqa: BLE001
            results[name] = {"error": str(e)[:200]}

    # flash attention: bf16 production dtype, causal (the GPT path)
    b, h, s, d = 2, 8, 1024, 64
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
    entry("flash_attention", lambda: _compare(
        partial(flash_attention, causal=True, impl="pallas"),
        partial(flash_attention, causal=True, impl="xla"),
        (q, k, v), tol_norm=2e-2, grad_argnums=(0, 1, 2)))

    # long-sequence STREAMED flash attention: s=8192, packed segment ids +
    # causal — exactly the config that hit the resident layout's 16 MB VMEM
    # wall in r3 (VERDICT r3 ask #3 done-criterion). Compared against the
    # XLA mask at small heads so the dense reference fits HBM.
    def long_stream():
        b8, h8, s8, d8 = 1, 2, 8192, 64
        q8 = jax.random.normal(kq, (b8, h8, s8, d8), jnp.bfloat16)
        k8 = jax.random.normal(kk, (b8, h8, s8, d8), jnp.bfloat16)
        v8 = jax.random.normal(kv, (b8, h8, s8, d8), jnp.bfloat16)
        seg = jnp.repeat(jnp.arange(8, dtype=jnp.int32), s8 // 8)[None]
        return _compare(
            partial(flash_attention, segment_ids=(seg, seg), causal=True,
                    contiguous_segments=True, impl="pallas",
                    stream="always"),
            partial(flash_attention, segment_ids=(seg, seg), causal=True,
                    impl="xla"),
            (q8, k8, v8), tol_norm=2e-2, grad_argnums=(0, 1, 2))

    entry("flash_attention_8k_segments_streamed", long_stream)

    # fused LN / RMSNorm: bf16 x, fp32 gamma/beta (the MixedFused contract)
    x = jax.random.normal(key, (512, 1024), jnp.bfloat16)
    wln = 1.0 + 0.1 * jax.random.normal(kq, (1024,), jnp.float32)
    bln = 0.1 * jax.random.normal(kk, (1024,), jnp.float32)
    entry("layer_norm", lambda: _compare(
        partial(layer_norm, impl="pallas"), partial(layer_norm, impl="xla"),
        (x, wln, bln), tol_norm=2e-2, grad_argnums=(0, 1, 2)))
    entry("rms_norm", lambda: _compare(
        partial(rms_norm, impl="pallas"), partial(rms_norm, impl="xla"),
        (x, wln), tol_norm=2e-2, grad_argnums=(0, 1)))

    # scaled-mask softmax (causal, the Megatron kernel pair)
    logits = jax.random.normal(key, (4, 8, 256, 256), jnp.bfloat16)
    entry("scaled_masked_softmax", lambda: _compare(
        partial(scaled_masked_softmax, scale=0.125, causal=True,
                impl="pallas"),
        partial(scaled_masked_softmax, scale=0.125, causal=True, impl="xla"),
        (logits,), tol_norm=2e-2, grad_argnums=(0,)))

    # fused label-smoothing CE (fp32 logits like the vocab head)
    vlog = jax.random.normal(key, (1024, 8192), jnp.float32)
    labels = jax.random.randint(kq, (1024,), 0, 8192)
    entry("xentropy", lambda: _compare(
        partial(softmax_cross_entropy, smoothing=0.1, impl="pallas"),
        partial(softmax_cross_entropy, smoothing=0.1, impl="xla"),
        (vlog, labels), tol_norm=1e-3, grad_argnums=(0,)))

    # chunked LM-head CE vs the unchunked reference (both XLA; the chunk
    # scan's accumulation order is what is under test)
    hs = jax.random.normal(key, (4, 256, 512), jnp.bfloat16)
    wte = jax.random.normal(kk, (8192, 512), jnp.bfloat16)
    tgt = jax.random.randint(kv, (4, 256), 0, 8192)
    entry("lm_head_loss", lambda: _compare(
        lambda hh, ww: lm_head_cross_entropy(hh, ww, tgt, num_chunks=8),
        lambda hh, ww: lm_head_cross_entropy_reference(hh, ww, tgt),
        (hs, wte), tol_norm=2e-2, grad_argnums=(0, 1)))

    results["all_ok"] = all(
        v.get("ok", False if "error" in v else True)
        for v in results.values() if isinstance(v, dict))
    return results


def _profile_345m(batch, seq, steps=3, hidden=None, layers=None):
    """MEASURED per-scope and per-op-kind device seconds for the REAL
    345M train step (VERDICT r4 ask #2: the toy-model profile said nothing
    about where the headline's ~260 ms goes). Runs inside the headline
    subprocess, which owns the chip; single-step dispatch (no scan), so
    total_ms is device time per step. Tries the remat ladder and a halved
    batch before giving up; ``hidden``/``layers`` let the caller profile a
    degraded-rung model when the flagship shape is unplaceable."""
    import gc

    if jax.default_backend() != "tpu":
        return None, {}
    from apex_tpu.pyprof.prof import _measured_join

    errs = {}
    for remat_policy, b, unroll in ((None, batch, True),
                                    ("save_attn", batch, False),
                                    (None, batch, False),
                                    (None, max(batch // 2, 1), False)):
        try:
            step, params, opt_state = build("O2", "auto", remat_policy,
                                            hidden, layers, unroll=unroll)
            tokens = jax.random.randint(jax.random.PRNGKey(1), (b, seq),
                                        0, 50304)
            targets = jnp.roll(tokens, -1, axis=-1)

            def prof_fn(params, opt_state, tokens, targets):
                # loss first so the execution barrier fetches a scalar;
                # params/state returned too so the optimizer update is
                # not dead-code-eliminated out of the profiled program
                p, s, loss, _ = step(params, opt_state, tokens, targets)
                return loss, p, s

            scopes, kinds = _measured_join(
                prof_fn, params, opt_state, tokens, targets,
                steps=steps, depth=2)
            total = scopes.pop("<total_device>", 0.0)
            kinds.pop("<total_device>", None)
            top = dict(sorted(scopes.items(), key=lambda kv: -kv[1])[:10])
            hid = hidden or int(os.environ.get("BENCH_HIDDEN", "1024"))
            lay = layers or int(os.environ.get("BENCH_LAYERS", "24"))
            label = ("gpt2_345m" if (hid, lay) == (1024, 24)
                     else f"gpt_h{hid}_L{lay}")
            errs.pop("pyprof_345m", None)  # an earlier rung's OOM is not
            # an error once a later rung delivered the profile
            return {
                "model": label, "batch": b, "seq": seq,
                "remat": remat_policy or "full", "unroll": unroll,
                "dispatch_mode": "single_step",
                "total_ms": round(total * 1e3, 3),
                "scopes_ms": {k: round(v * 1e3, 3) for k, v in top.items()},
                "kinds_ms": {k: round(v * 1e3, 3)
                             for k, v in sorted(kinds.items(),
                                                key=lambda kv: -kv[1])[:12]},
            }, errs
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e):
                raise
            errs["pyprof_345m"] = str(e)[:200]
            print(f"profile_345m: OOM at remat={remat_policy} b={b} "
                  f"unroll={unroll}", file=sys.stderr)
            gc.collect()
    return None, errs


def _gpt_headline_evidence(batch, seq, steps):
    """345M interleaved headline. Returns ``(result_fragment, errors)``."""
    frag, errs = {}, {}
    try:
        fused, base, common, inter = gpt_headline(batch, seq, steps)
        frag["value"] = fused["median"]
        if base is not None:
            frag["vs_baseline"] = round(fused["median"] / base["median"], 3)
            frag["spread"] = {"o2": fused, "o0": base, "interleaved": inter}
        else:
            frag["spread"] = {"o2": fused, "interleaved": False}
            errs["baseline"] = ("fp32 O0 leg unplaceable under current HBM "
                               "pressure; vs_baseline omitted")
        if common != batch:
            frag["effective_batch"] = common
        print(f"headline: {frag['value']} tok/s "
              f"x{frag.get('vs_baseline')}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        if not _is_oom(e):
            raise
        errs["headline"] = str(e)[:300]
        print(f"headline FAILED: {e}", file=sys.stderr)
    return frag, errs


# profile rungs, flagship first (the shared shrink ladder): a profile of
# the 110M-ish or 4-layer step still answers "where do the milliseconds
# go" when the 345M shape is unplaceable
_PROFILE_RUNGS = ((None, None),) + _DEGRADED_RUNGS


def _gpt_profile_evidence(batch, seq, steps):
    """The 345M measured profile in its OWN fresh process. Running it at
    the tail of the headline subprocess OOM'd under pressure even though
    the headline itself fit — by then that process had churned through
    the O2 prep plus every failed O0 ladder rung, and a long process
    cannot allocate what a fresh one can (PERF_NOTES r4: below-Python HBM
    accumulation through the tunnel). Under occupation the degraded rungs
    (VERDICT r5 top_next) profile a smaller model rather than leaving the
    round with an errors entry — provenance rides the record. Returns
    ``(frag, errors)``."""
    frag, errs = {}, {}
    flagship_oom = ""
    try:
        for hid, lay in _PROFILE_RUNGS:
            prof, perrs = _profile_345m(batch, seq, hidden=hid, layers=lay)
            if prof is not None:
                if hid is not None:
                    prof["degraded"] = {"hidden": hid, "layers": lay,
                                        "flagship_oom": flagship_oom}
                frag["pyprof_scope_seconds"] = prof
                print(f"pyprof profile [{prof['model']}]: total "
                      f"{prof['total_ms']} ms", file=sys.stderr)
                return frag, errs
            if not perrs:
                # non-TPU backend: nothing to profile, nothing to degrade
                return frag, errs
            flagship_oom = flagship_oom or perrs.get("pyprof_345m", "")[:300]
            print(f"profile rung (hidden={hid}, layers={lay}) OOM; "
                  f"degrading", file=sys.stderr)
        errs["pyprof_345m"] = (f"OOM at every profile rung; flagship: "
                               f"{flagship_oom}")
    except Exception as e:  # noqa: BLE001
        if not _is_oom(e):
            raise
        errs["pyprof_345m"] = str(e)[:200]
    return frag, errs


def _gpt_o0_evidence(batch, seq, steps):
    """The fp32 O0 baseline leg in its OWN fresh process (VERDICT r4 ask
    #1: one co-tenant spike must not delete the ratio for the round). The
    full ladder plus sleep-retries gets the ~5.6 GB batch-independent
    fp32 footprint placed once transient pressure passes; the parent
    computes the per-token ratio from the two processes' medians."""
    frag, errs = {}, {}
    try:
        rates, b0, rung0 = measure_resilient("O0", "xla", batch, seq, steps,
                                             retries=2, retry_sleep=45)
        frag["o0"] = dict(_stats(rates), batch=b0, rung=rung0)
        print(f"o0 baseline: {frag['o0']}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        if not _is_oom(e):
            raise
        errs["o0_baseline"] = str(e)[:300]
        print(f"o0 baseline FAILED: {e}", file=sys.stderr)
    return frag, errs


def _gpt_degraded_evidence(batch, seq, steps):
    """Degraded rungs: 110M-ish (h=768, L=12), then the 4-layer config the
    r3 judge saw run under the pressure that OOM'd the 345M. Reported
    under their OWN key, never substituted for the headline (VERDICT r3
    ask #1). Returns ``(result_fragment, errors)``."""
    frag, errs = {}, {}
    for hid, lay in _DEGRADED_RUNGS:
        try:
            fused, base, common, inter = gpt_headline(
                max(batch // 2, 1), seq, steps, hidden=hid, layers=lay)
            entry = {
                "tokens_per_sec": fused["median"],
                "spread": {"o2": fused, "interleaved": inter},
                "batch": common, "hidden": hid, "layers": lay}
            if base is not None:
                entry["vs_baseline"] = round(
                    fused["median"] / base["median"], 3)
                entry["spread"]["o0"] = base
            frag["gpt_degraded"] = entry
            print(f"gpt_degraded: {frag['gpt_degraded']}", file=sys.stderr)
            break
        except Exception as e:  # noqa: BLE001
            if not _is_oom(e):
                raise
            errs["gpt_degraded"] = str(e)[:300]
            print(f"gpt_degraded h={hid} FAILED: {e}", file=sys.stderr)
    return frag, errs


def main():
    """Degrade, don't die (CLAUDE.md): round 3's entire on-chip record was
    lost because the 345M headline ran first, unprotected, and OOM'd
    (VERDICT r3 weak #1). Now the GPT phases run in fresh subprocesses
    that own the chip alone (see stage 0 below for the measured why),
    every parent stage is individually wrapped, failures land in an
    ``"errors"`` field, and the JSON line ALWAYS prints with exit 0."""
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = 1024
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    result = {
        "metric": "gpt2_345m_o2_train_tokens_per_sec",
        "value": None,
        "unit": "tokens/s",
        "vs_baseline": None,
    }
    errors = {}
    try:
        from apex_tpu.monitor.watchdog import Heartbeat, write_checkpoint

        hb = Heartbeat.from_env("BENCH_HEARTBEAT_PATH")
    except Exception:  # noqa: BLE001 - telemetry import must not kill bench
        hb = None
        write_checkpoint = lambda *a, **k: False  # noqa: E731

    def checkpoint(stage_name="checkpoint"):
        """Persist the partial record after every stage (the library's
        atomic checkpoint-file protocol, monitor/watchdog.py): when the
        tunnel WEDGES (observed r5: even a 4k matmul never returns — no
        exception, nothing to catch), the watchdog parent kills this
        process and prints the last checkpoint instead of nothing. Also
        beats the heartbeat so a parent running with BENCH_STALL can tell
        wedged from slow-but-alive."""
        rec = dict(result)
        if errors:
            rec["errors"] = dict(errors)
        write_checkpoint(rec, var="BENCH_PARTIAL_PATH")
        if hb is not None:
            hb.beat(stage_name)

    # first beat BEFORE any work: the stall clock must start from "alive
    # at t=0", not from the first completed stage
    checkpoint("start")

    def stage(key, fn):
        """Run one evidence stage; on failure record the error and move on.
        gc between stages so a finished (or failed) stage's device buffers
        are truly returned before the next stage allocates."""
        import gc

        if hb is not None:
            hb.beat(f"{key}:start")
        try:
            result[key] = fn()
            print(f"{key}: {result[key]}", file=sys.stderr)
            return result[key]
        except Exception as e:  # noqa: BLE001 - never lose the record
            print(f"{key} FAILED: {e}", file=sys.stderr)
            errors[key] = str(e)[:300]
            return None
        finally:
            gc.collect()
            checkpoint(key)

    try:
        # 0. the GPT headline — FIRST, each phase in a FRESH SUBPROCESS
        # that owns the chip alone. Measured live in r4: configs that OOM
        # at batch 1 inside (or concurrently with) a long bench process
        # run at 65k+ tok/s in a fresh process seconds later, with
        # jax.live_arrays() empty both times — a long process holds HBM
        # below the Python layer through the tunnel. The parent has not
        # touched the backend yet at this point, and its later stages are
        # individually wrapped, so the r3 failure mode (headline crash
        # wipes the round's record) cannot recur.
        def run_sub(flag, update=True, timeout=2700, env=None):
            import subprocess

            # stay inside the watchdog's budget: finishing early with
            # this phase marked failed beats being killed mid-stage with
            # the later phases silently dropped
            deadline_at = float(os.environ.get("BENCH_DEADLINE_AT", "inf"))
            remaining = deadline_at - time.time() - 120
            timeout = max(60, min(timeout, remaining))
            if hb is not None:
                # one beat per subprocess phase: these are the longest
                # silent stretches (up to 2700 s), and each carries its
                # own timeout, so "alive at phase entry" is the honest
                # stall signal while it runs
                hb.beat(f"{flag}:start")
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), flag],
                capture_output=True, text=True, timeout=timeout,
                env=None if env is None else dict(os.environ, **env))
            sys.stderr.write(out.stderr[-4000:])
            frag = json.loads(out.stdout.strip().splitlines()[-1])
            errors.update(frag.pop("errors", {}))
            if update:
                result.update(frag)
            return frag

        degraded_attempted = False
        try:
            frag = run_sub("--gpt-headline")
            if "value" not in frag:
                degraded_attempted = True
                run_sub("--gpt-degraded")
            elif "vs_baseline" not in frag:
                # the in-process fp32 leg died; a FRESH subprocess that
                # owns the chip alone retries it with the full ladder +
                # sleep-retries (VERDICT r4 ask #1 — the ratio must not
                # vanish with one co-tenant spike). Cross-process medians
                # are sequential, not interleaved: labelled as such, with
                # both legs' batches stated.
                try:
                    # seed the fresh process at the O2 leg's EFFECTIVE
                    # batch so the ratio compares like with like when the
                    # fp32 leg fits there (its own ladder can still halve)
                    o0 = run_sub(
                        "--gpt-o0", update=False, timeout=1800,
                        env={"BENCH_BATCH":
                             str(result.get("effective_batch", batch))})
                except Exception as e:  # noqa: BLE001
                    o0 = {}
                    errors["o0_subprocess"] = str(e)[:200]
                if "o0" in o0:
                    base = o0["o0"]
                    result["vs_baseline"] = round(
                        result["value"] / base["median"], 3)
                    errors.pop("baseline", None)
                    sp = result.setdefault("spread", {})
                    sp["o0"] = base
                    sp["o2_batch"] = result.get("effective_batch", batch)
                    sp["interleaved"] = False
                    sp["ratio_mode"] = "cross_process_sequential"
            if (result.get("vs_baseline") is None
                    or not result.get("spread", {}).get("interleaved")):
                # no interleaved 345M ratio this session: the degraded
                # rung's two small programs co-reside easily, so it
                # supplies INTERLEAVED ratio evidence (recorded under
                # vs_baseline_degraded below — never substituted). Skip
                # if this round already attempted (and failed) it: a
                # back-to-back identical retry under the same pressure
                # just burns the timeout twice.
                if not degraded_attempted:
                    run_sub("--gpt-degraded")
        except Exception as e:  # noqa: BLE001 - spawn/parse failure
            print(f"gpt subprocess FAILED ({e}); running in-process",
                  file=sys.stderr)
            errors["gpt_subprocess"] = str(e)[:200]
            frag, errs = _gpt_headline_evidence(batch, seq, steps)
            result.update(frag)
            errors.update(errs)
            if "value" not in frag or "vs_baseline" not in frag:
                frag, errs = _gpt_degraded_evidence(batch, seq, steps)
                result.update(frag)
                errors.update(errs)
        d = result.get("gpt_degraded") or {}
        if "vs_baseline" in d:
            result["vs_baseline_degraded"] = d["vs_baseline"]

        # measured profile of the real 345M step, in a FRESH process (a
        # churned one cannot allocate what a fresh one can — see
        # _gpt_profile_evidence)
        if "value" in result and result.get("value") is not None:
            try:
                # seed at the headline's EFFECTIVE batch so the profile
                # attributes the step that was actually benchmarked
                run_sub("--gpt-profile", timeout=1200,
                        env={"BENCH_BATCH":
                             str(result.get("effective_batch", batch))})
            except Exception as e:  # noqa: BLE001
                errors["pyprof_345m_subprocess"] = str(e)[:200]
        checkpoint()

        print(f"platform: {jax.default_backend()}", file=sys.stderr)

        # 1. compiled-kernel numerics: tiny footprint, highest evidence value
        stage("selftest", selftest)

        # 2. fused whole-tree optimizer step vs unfused per-leaf eager Adam
        # (BASELINE.md target #3; benchmarks/optimizer_step.py)
        def opt_micro():
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
            from optimizer_step import measure_speedup

            speedup, _, _ = measure_speedup(fused_steps=5, eager_steps=2)
            return round(speedup, 2)

        stage("fused_opt_step_vs_eager", opt_micro)

        # 3-4. BASELINE.md configs 1-3: conv/BN and LAMB paths, own OOM
        # ladders with batch floors well below the headline's footprint.
        # Both rungs are BRACKETED by the fixed canary program so their
        # cross-round drift is attributable (VERDICT r4 weak #4).
        def safe_canary():
            try:
                return _canary()
            except Exception as e:  # noqa: BLE001
                print(f"canary FAILED: {e}", file=sys.stderr)
                return None

        c_pre = safe_canary()
        stage("resnet50_o2_imgs_per_sec", bench_resnet50)
        c_mid = safe_canary()
        # degraded-rung ladder (VERDICT r5 top_next): under occupation the
        # record carries a smaller-config number with rung provenance
        # instead of an errors entry
        stage("bert_large_lamb_tokens_per_sec", bench_bert_resilient)
        c_post = safe_canary()
        for key, before, after in (
                ("resnet50_o2_imgs_per_sec", c_pre, c_mid),
                ("bert_large_lamb_tokens_per_sec", c_mid, c_post)):
            if isinstance(result.get(key), dict):
                result[key]["canary_tf_s"] = {"before": before,
                                              "after": after}

        # 4b. MEASURED per-scope seconds (pyprof trace-join, VERDICT r3
        # ask #5). The headline subprocess already profiled the REAL 345M
        # step (r4 ask #2); this toy-model stage is only the fallback so
        # a round whose headline died still records SOME measured scopes.
        def pyprof_seconds():
            from apex_tpu import pyprof
            from apex_tpu.models import GPTConfig, GPTModel

            cfg = GPTConfig(
                vocab_size=50304, hidden_size=512, num_layers=4,
                num_attention_heads=8, max_seq_len=1024, hidden_dropout=0.0,
                axis=None, compute_dtype=jnp.bfloat16, remat=False)
            m = GPTModel(cfg)
            p = m.init(jax.random.PRNGKey(0))
            toks = jax.random.randint(jax.random.PRNGKey(1), (2, 1024),
                                      0, 50304)
            secs = pyprof.measured_scope_seconds(
                lambda p: jax.value_and_grad(m.loss)(
                    p, toks, jnp.roll(toks, -1, -1)),
                p, steps=3, depth=2)
            total = secs.pop("<total_device>", 0.0)
            top = dict(sorted(secs.items(), key=lambda kv: -kv[1])[:6])
            return {"total_ms": round(total * 1e3, 3),
                    "scopes_ms": {k: round(v * 1e3, 3)
                                  for k, v in top.items()}}

        if "pyprof_scope_seconds" not in result:
            stage("pyprof_scope_seconds", pyprof_seconds)

    except BaseException as e:  # noqa: BLE001 - emit the record even then
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            errors["fatal"] = type(e).__name__
        else:
            errors["fatal"] = str(e)[:300]
        print(f"FATAL: {e}", file=sys.stderr)

    if errors:
        result["errors"] = errors
    # BENCH_LEDGER: one fingerprinted run record per bench round so the
    # on-chip trajectory is tracked across sessions (monitor/ledger.py);
    # stderr-only chatter — the stdout JSON line stays the contract
    if os.environ.get("BENCH_LEDGER") or os.environ.get("APEX_TPU_LEDGER"):
        try:
            from apex_tpu.monitor import ledger as ledger_mod

            lpath = (os.environ.get("BENCH_LEDGER")
                     or os.environ["APEX_TPU_LEDGER"])
            cfg = {"run": "bench", "batch": batch, "seq": seq,
                   "steps": steps,
                   "zero": os.environ.get("BENCH_ZERO", "0"),
                   "qcomm": os.environ.get("BENCH_QCOMM", "none")}
            measured = None
            if not os.environ.get("BENCH_JOURNAL"):
                measured = {"step_records": steps}
                if isinstance(result.get("value"), (int, float)):
                    measured["tokens_per_sec"] = {"p50": result["value"]}
            rec = ledger_mod.append_run(
                lpath, run="bench", config=cfg,
                journal=os.environ.get("BENCH_JOURNAL"),
                measured=measured,
                extra={"metric": result.get("metric"),
                       "vs_baseline": result.get("vs_baseline")})
            print(f"ledger: {rec['fingerprint']} -> {lpath}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - never lose the record
            print(f"ledger append failed: {e}", file=sys.stderr)
    print(json.dumps(result))
    sys.exit(0)


def _watchdog(cmd=None, env_extra=None):
    """Run ``main()`` in a CHILD process under the library watchdog
    (apex_tpu/monitor/watchdog.py — this pattern's extraction, r6) and
    print ITS json line — or, if the child hangs past the deadline, dies
    silently, or (with BENCH_STALL set) stops beating its heartbeat, kill
    the whole tree and print the partial record it checkpointed after
    every stage.

    Why: the r5 sessions showed a failure mode the stage wrappers cannot
    catch — the tunnel WEDGES and a device call simply never returns (a
    4096^2 matmul probe sat for 10+ minutes; no OOM, no exception). Under
    that regime the old main() would hang mid-stage and the round would
    end with no JSON line at all. The subprocess phases already carry
    their own timeouts; this covers the parent's in-process stages.
    ``cmd``/``env_extra`` exist for the unit test (a stub child)."""
    from apex_tpu.monitor.watchdog import run_under_watchdog

    # the hard deadline must exceed the worst-case SUM of the child's own
    # subprocess timeouts (headline 2700 + degraded 2700 + o0 1800 +
    # profile 1200 = 8400 s) plus the in-process stages — a retry-heavy
    # but HEALTHY round must not be killed mid-stage. run_sub additionally
    # caps each subprocess timeout to the remaining budget via
    # BENCH_DEADLINE_AT. BENCH_STALL (seconds, default off) arms the
    # faster heartbeat check: main() beats at start, at every stage
    # entry/checkpoint, and before each subprocess phase — but a phase is
    # SILENT while it runs, so BENCH_STALL must exceed the longest single
    # stage (the 2700 s headline subprocess), or a healthy round gets
    # killed mid-phase.
    deadline = int(os.environ.get("BENCH_DEADLINE", "10800"))
    stall = os.environ.get("BENCH_STALL")
    env = dict(os.environ, BENCH_WATCHDOG="0",
               BENCH_DEADLINE_AT=str(time.time() + deadline))
    env.update(env_extra or {})
    res = run_under_watchdog(
        cmd or [sys.executable, os.path.abspath(__file__)],
        deadline=deadline,
        stall_timeout=float(stall) if stall else None,
        checkpoint_env="BENCH_PARTIAL_PATH",
        heartbeat_env="BENCH_HEARTBEAT_PATH",
        env=env,
        # BENCH_FLIGHT: the child arms its flight recorder from
        # APEX_TPU_FLIGHT (lazy, monitor/flight.py); after a kill the
        # parent publishes the kill dump from the structured heartbeat
        flight_path=os.environ.get("BENCH_FLIGHT") or None,
    )
    lines = (res.stdout or "").strip().splitlines()
    if res.status == "ok" and lines and lines[-1].lstrip().startswith("{"):
        sys.stdout.write(res.stdout)
        return 0
    # killed (wedge/stall), or the child DIED without a record (segfault/
    # abort in the native plugin — same failure family): recover the
    # last per-stage checkpoint so the round still has a JSON line
    rec = res.record or {"metric": "gpt2_345m_o2_train_tokens_per_sec",
                         "value": None, "unit": "tokens/s",
                         "vs_baseline": None}
    reason = res.reason or (f"child exited rc={res.returncode} with no "
                            "JSON line")
    rec.setdefault("errors", {})["watchdog"] = (
        reason + "; printing the last per-stage checkpoint")
    if res.flight:
        rec["flight"] = res.flight  # where the black-box dump landed
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    # jax<0.5 API renames (shard_map/axis_size): installed only when bench
    # RUNS, not when tests import its helpers — the suite's behavior must
    # not change from an import side effect
    try:
        from apex_tpu.utils.compat import ensure_jax_compat

        ensure_jax_compat()
    except Exception:  # noqa: BLE001 - bench must start even if apex_tpu broke
        pass
    # BENCH_FLIGHT maps onto the library's lazy env arming so every phase
    # (parent AND the fresh-process GPT subprocesses, which inherit the
    # env) rings recent records for the crash dump
    if os.environ.get("BENCH_FLIGHT"):
        os.environ.setdefault("APEX_TPU_FLIGHT", os.environ["BENCH_FLIGHT"])
    # BENCH_LEDGER rides the same env-mapping pattern: one spelling for
    # the bench driver, the library knob for everything it spawns
    if os.environ.get("BENCH_LEDGER"):
        os.environ.setdefault("APEX_TPU_LEDGER", os.environ["BENCH_LEDGER"])
    if "--selftest" in sys.argv:
        print(json.dumps({"selftest": selftest()}))
    elif ("--gpt-headline" in sys.argv or "--gpt-degraded" in sys.argv
          or "--gpt-o0" in sys.argv or "--gpt-profile" in sys.argv):
        # the subprocess entries main() spawns for the GPT phases (fresh
        # process = fresh HBM through the tunnel)
        fn = (_gpt_headline_evidence if "--gpt-headline" in sys.argv
              else _gpt_o0_evidence if "--gpt-o0" in sys.argv
              else _gpt_profile_evidence if "--gpt-profile" in sys.argv
              else _gpt_degraded_evidence)
        frag, errs = fn(int(os.environ.get("BENCH_BATCH", "8")), 1024,
                        int(os.environ.get("BENCH_STEPS", "10")))
        if errs:
            frag["errors"] = errs
        print(json.dumps(frag))
    elif os.environ.get("BENCH_WATCHDOG", "1") != "0":
        sys.exit(_watchdog())
    else:
        main()
