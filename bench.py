"""Headline benchmark: GPT-2 345M mixed-precision training step on one chip,
plus the two non-GPT BASELINE configs (ResNet-50 O2+FusedSGD imgs/sec,
BERT-large FusedLAMB tokens/sec) and an on-chip Pallas-kernel numerics
selftest.

Measures the framework's core promise — the reference's amp-O2 + fused-kernel
recipe (BASELINE.md targets 3/4: fused step vs unfused eager) — as tokens/sec
for a full train step (forward + backward + FusedAdam + dynamic loss scaling)
on GPT-2 345M, bf16 O2 policy with Pallas flash attention and fused LN.

``vs_baseline`` is the speedup over the same model trained the "Python-only
build" way the reference warns is slower (README.md:134-139): fp32 O0, unfused
XLA attention/LN, plain optax Adam.

Measurement discipline (PERF_NOTES.md): every throughput number is the
MEDIAN over >=3 timed windows on the same compiled program, with min/max
spread recorded, so round-over-round deltas are attributable to code rather
than co-tenant noise on the shared chip. ``vs_baseline`` is a ratio of
same-session medians.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — plus
"spread", "resnet50_o2_imgs_per_sec", "bert_large_lamb_tokens_per_sec",
"fused_opt_step_vs_eager", and a "selftest" block of per-kernel max-error
measurements (Pallas vs XLA fallback, fwd AND bwd, compiled on this chip).
"effective_batch" appears when OOM retries shrank a config's batch (the
ratio is then re-measured at the common batch so vs_baseline stays
apples-to-apples).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Plugin platforms registered by sitecustomize (the axon TPU tunnel) ignore a
# plain JAX_PLATFORMS env var; force the selection before first backend use.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

WINDOWS = int(os.environ.get("BENCH_WINDOWS", "3"))


def _stats(rates):
    """Median/min/max over timed windows (rounded for the JSON line)."""
    s = sorted(rates)
    n = len(s)
    med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
    return {
        "median": round(med, 1),
        "min": round(s[0], 1),
        "max": round(s[-1], 1),
        "windows": n,
    }


def _is_oom(e: Exception) -> bool:
    return "RESOURCE_EXHAUSTED" in str(e)


def _timed_windows(advance, get_loss, *, steps, windows, per_window_units):
    """The shared window-timing protocol: warmup happened already (caller
    ran one step/chunk and fetched); each window runs ``advance()``
    ``steps`` times, then stops the clock on a device→host fetch of the
    loss (whose dependency chain covers every step — tunnel discipline,
    PERF_NOTES.md). Returns per-window rates in ``per_window_units/s``."""
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            advance()
        loss_val = float(get_loss())
        dt = time.perf_counter() - t0
        assert jnp.isfinite(loss_val), "non-finite loss in bench"
        rates.append(per_window_units / dt)
    return rates


def _oom_halving(run, batch, *, min_batch, label):
    """Run ``run(batch)``, halving the batch on RESOURCE_EXHAUSTED — the
    shared co-tenant degradation ladder tail."""
    while True:
        try:
            return run(batch)
        except Exception as e:  # noqa: BLE001 - jaxlib error types vary
            if not _is_oom(e) or batch <= min_batch:
                raise
            print(f"{label}: OOM at batch {batch}", file=sys.stderr)
            batch //= 2


def build(policy_level: str, impl: str, remat_policy=None):
    import optax

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    fused = policy_level == "O2"
    cfg = GPTConfig(
        vocab_size=50304,
        hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
        num_layers=int(os.environ.get("BENCH_LAYERS", "24")),
        num_attention_heads=16,
        max_seq_len=1024,
        hidden_dropout=0.0,
        axis=None,
        compute_dtype=jnp.bfloat16 if fused else jnp.float32,
        remat=True,
        remat_policy=remat_policy,
        attention_impl=impl,
        # fused chunked LM-head CE: ~6% throughput and ~0.8 GB less peak HBM
        # (survives pressure from co-tenants on the shared chip) — PERF_NOTES.md
        lm_head_chunks=8 if fused else None,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy(policy_level)
    opt = FusedAdam(lr=1e-4) if fused else optax.adam(1e-4)
    mp_opt = amp.MixedPrecisionOptimizer(opt, policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)

    def step(params, opt_state, tokens, targets):
        def scaled_loss(p):
            return mp_opt.scale_loss(model.loss(p, tokens, targets), opt_state)

        loss_s, grads_s = jax.value_and_grad(scaled_loss)(params)
        new_params, new_state, metrics = mp_opt.apply_gradients(
            opt_state, params, grads_s
        )
        return new_params, new_state, loss_s, metrics

    return step, params, opt_state


def measure(step, params, opt_state, batch, seq, steps=10, scan_chunk=4,
            windows=WINDOWS):
    """Time ``windows`` windows of ``steps`` train steps each, dispatched as
    scanned chunks of ``scan_chunk`` steps per program when possible;
    returns the per-window tokens/sec list.

    The scan matters twice over through the axon tunnel: it amortizes
    per-dispatch overhead, and — since the tunnel backend rejects buffer
    donation — it is the only way the params/optimizer state update
    in-place (the scan carry lives inside one program) instead of being
    rewritten to fresh buffers every step. ~5% end-to-end (PERF_NOTES.md).
    Falls back to single-step dispatch (scan_chunk=1) if the scanned
    program does not fit.
    """
    from jax import lax

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    targets = jnp.roll(tokens, -1, axis=-1)

    if scan_chunk > 1:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            def body(carry, _):
                p, s = carry
                p, s, loss, _ = step(p, s, tokens, targets)
                return (p, s), loss

            (params, opt_state), losses = lax.scan(
                body, (params, opt_state), None, length=scan_chunk)
            return params, opt_state, losses[-1]

    else:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            p, s, loss, _ = step(params, opt_state, tokens, targets)
            return p, s, loss

    # round the requested step count up to whole chunks (never time fewer
    # steps than asked); normalization below uses the actual count run
    n_chunks = max(1, -(-steps // scan_chunk))
    state = [params, opt_state, None]

    def advance():
        state[:] = run_chunk(state[0], state[1], tokens, targets)

    # warmup / compile. Through remote-device tunnels (axon),
    # block_until_ready can ack dispatch rather than execution, so force a
    # device->host transfer of a value that depends on the whole chain.
    advance()
    float(state[2])
    return _timed_windows(
        advance, lambda: state[2], steps=n_chunks, windows=windows,
        per_window_units=batch * seq * n_chunks * scan_chunk)


def measure_resilient(level, impl, batch, seq, steps, windows=WINDOWS):
    """The chip is shared: co-tenant HBM pressure can OOM a config that
    normally fits. Degrade gracefully — selective remat → full remat,
    scanned dispatch → per-step dispatch, then halve the batch (tokens/s is
    per-token normalized) — rather than lose the round's record."""
    # (remat_policy, scan_chunk) from fastest to most memory-frugal.
    # save_attn keeps the flash kernel outputs so backward skips the
    # attention recompute (~5% when HBM allows it).
    ladder = ([("save_attn", 4), (None, 4), (None, 1)] if level == "O2"
              else [(None, 4), (None, 1)])
    last_oom = None
    while True:
        for remat_policy, scan_chunk in ladder:
            try:
                rates = measure(*build(level, impl, remat_policy), batch, seq,
                                steps, scan_chunk=scan_chunk, windows=windows)
                return rates, batch
            except Exception as e:  # noqa: BLE001 - jaxlib error types vary
                if not _is_oom(e):
                    raise
                last_oom = e
                print(f"{level}: OOM at remat_policy={remat_policy} "
                      f"scan={scan_chunk}, batch {batch}", file=sys.stderr)
        if batch <= 1:
            # keep the jaxlib allocator diagnostics on the chained cause
            raise RuntimeError(f"{level}: OOM even at batch 1") from last_oom
        batch //= 2


# ---------------------------------------------------------------------------
# ResNet-50 O2 + FusedSGD (BASELINE.md configs 1-2: the named headline
# metric "ResNet-50 imgs/sec/chip (amp O2-equivalent)"). Single chip, so
# SyncBatchNorm's cross-shard merge is the identity; the conv/NHWC/BN path
# is what is being measured. Reference recipe:
# examples/imagenet/main_amp.py:281+ (ours: examples/imagenet/main_amp.py).
# ---------------------------------------------------------------------------


def bench_resnet50(batch=None, steps=10, windows=WINDOWS):
    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet50
    from apex_tpu.ops.xentropy import softmax_cross_entropy
    from apex_tpu.optimizers import FusedSGD

    batch = batch or int(os.environ.get("BENCH_RESNET_BATCH", "64"))
    policy = amp.get_policy("O2")
    model = ResNet50(num_classes=1000, dtype=policy.op_dtype("conv"))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4, nesterov=True),
        policy)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3), jnp.float32))
    params = amp.cast_params(variables["params"], policy)
    batch_stats = variables["batch_stats"]
    opt_state = mp_opt.init(params)

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        def scaled_loss(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy(logits, labels))
            return mp_opt.scale_loss(loss, opt_state), mutated["batch_stats"]

        (scaled, new_stats), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params)
        new_params, new_opt, metrics = mp_opt.apply_gradients(
            opt_state, params, grads)
        return (new_params, new_stats, new_opt,
                scaled / opt_state.scaler.loss_scale)

    def run(batch):
        images = jax.random.normal(jax.random.PRNGKey(1),
                                   (batch, 224, 224, 3), jnp.float32)
        labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)
        state = [params, batch_stats, opt_state, None]

        def advance():
            state[:] = step(state[0], state[1], state[2], images, labels)

        advance()
        float(state[3])  # compile + execute barrier
        rates = _timed_windows(advance, lambda: state[3], steps=steps,
                               windows=windows,
                               per_window_units=batch * steps)
        return dict(_stats(rates), batch=batch)

    return _oom_halving(run, batch, min_batch=4, label="resnet50")


# ---------------------------------------------------------------------------
# BERT-large-ish + FusedLAMB (BASELINE.md config 3: BERT pretraining with
# FusedLAMB + FusedLayerNorm). Reference recipe: the L0 BERT minimal test
# (run_bert_minimal_test.py) at bert-large shapes.
# ---------------------------------------------------------------------------


def bench_bert_lamb(batch=None, steps=10, windows=WINDOWS):
    from apex_tpu import amp
    from apex_tpu.models import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB

    batch = batch or int(os.environ.get("BENCH_BERT_BATCH", "8"))
    seq = 512
    cfg = BertConfig(
        vocab_size=30592, hidden_size=1024, num_layers=24,
        num_attention_heads=16, max_seq_len=seq, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, remat=True)
    model = BertModel(cfg)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedLAMB(lr=1e-3), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)

    @jax.jit
    def step(params, opt_state, toks, lmask, labels, nsp):
        def scaled_loss(p):
            return mp_opt.scale_loss(
                model.loss(p, toks, None, lmask, labels, nsp), opt_state)

        loss_s, grads = jax.value_and_grad(scaled_loss)(params)
        new_params, new_state, _ = mp_opt.apply_gradients(
            opt_state, params, grads)
        return new_params, new_state, loss_s / opt_state.scaler.loss_scale

    def run(batch):
        ks = jax.random.split(jax.random.PRNGKey(1), 4)
        toks = jax.random.randint(ks[0], (batch, seq), 0, cfg.vocab_size)
        lmask = (jax.random.uniform(ks[1], (batch, seq)) < 0.15).astype(jnp.int32)
        labels = jax.random.randint(ks[2], (batch, seq), 0, cfg.vocab_size)
        nsp = jax.random.randint(ks[3], (batch,), 0, 2)
        state = [params, opt_state, None]

        def advance():
            state[:] = step(state[0], state[1], toks, lmask, labels, nsp)

        advance()
        float(state[2])
        rates = _timed_windows(advance, lambda: state[2], steps=steps,
                               windows=windows,
                               per_window_units=batch * seq * steps)
        return dict(_stats(rates), batch=batch)

    return _oom_halving(run, batch, min_batch=1, label="bert")


# ---------------------------------------------------------------------------
# On-chip kernel numerics selftest: the COMPILED Pallas kernels (TPU tiling,
# MXU accumulation) vs their XLA fallbacks, fwd AND bwd — the coverage
# interpret-mode CPU tests cannot give (reference pattern: the
# elementwise-tolerance tests of tests/L0/run_fused_layer_norm/).
# ---------------------------------------------------------------------------


def _max_errs(a, b):
    """(max abs error, scale-normalized error): the normalized form divides
    by the reference tensor's max magnitude, the right yardstick for bf16
    tensors whose values span decades (pointwise relative error explodes on
    near-zero entries; plain abs error penalizes large-magnitude grads)."""
    a = np.asarray(jax.device_get(a), np.float64)
    b = np.asarray(jax.device_get(b), np.float64)
    if not a.size:
        return 0.0, 0.0
    abs_err = float(np.max(np.abs(a - b)))
    scale = max(float(np.max(np.abs(b))), 1e-6)
    return abs_err, abs_err / scale


def _compare(fn_pallas, fn_xla, args, tol_norm, grad_argnums=None):
    """fwd + bwd max abs / scale-normalized error between two impls of the
    same math; ``ok`` gates on the normalized error."""
    fwd_p = jax.jit(fn_pallas)(*args)
    fwd_x = jax.jit(fn_xla)(*args)
    abs_err, norm_err = _max_errs(fwd_p, fwd_x)
    entry = {"fwd_max_abs_err": round(abs_err, 6),
             "fwd_norm_err": round(norm_err, 6)}
    if grad_argnums is not None:
        # random (fixed-key) cotangent: grads of sum(out * w)
        w = jax.random.normal(jax.random.PRNGKey(7), fwd_p.shape,
                              jnp.float32).astype(fwd_p.dtype)

        def loss(fn):
            return lambda *a: jnp.sum(fn(*a).astype(jnp.float32)
                                      * w.astype(jnp.float32))

        g_p = jax.jit(jax.grad(loss(fn_pallas), argnums=grad_argnums))(*args)
        g_x = jax.jit(jax.grad(loss(fn_xla), argnums=grad_argnums))(*args)
        g_abs = g_norm = 0.0
        for a, b in zip(jax.tree.leaves(g_p), jax.tree.leaves(g_x)):
            ae, ne = _max_errs(a, b)
            g_abs, g_norm = max(g_abs, ae), max(g_norm, ne)
        entry["bwd_max_abs_err"] = round(g_abs, 6)
        entry["bwd_norm_err"] = round(g_norm, 6)
    entry["tol_norm"] = tol_norm
    worst = max(v for k, v in entry.items() if k.endswith("norm_err"))
    entry["ok"] = bool(worst <= tol_norm)
    return entry


def selftest():
    """Per-kernel compiled-vs-fallback max errors on THIS backend."""
    from functools import partial

    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.ops.layer_norm import layer_norm, rms_norm
    from apex_tpu.ops.lm_head_loss import (
        lm_head_cross_entropy,
        lm_head_cross_entropy_reference,
    )
    from apex_tpu.ops.softmax import scaled_masked_softmax
    from apex_tpu.ops.xentropy import softmax_cross_entropy

    results = {"platform": jax.default_backend()}
    key = jax.random.PRNGKey(0)

    # flash attention: bf16 production dtype, causal (the GPT path)
    b, h, s, d = 2, 8, 1024, 64
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
    results["flash_attention"] = _compare(
        partial(flash_attention, causal=True, impl="pallas"),
        partial(flash_attention, causal=True, impl="xla"),
        (q, k, v), tol_norm=2e-2, grad_argnums=(0, 1, 2))

    # fused LN / RMSNorm: bf16 x, fp32 gamma/beta (the MixedFused contract)
    x = jax.random.normal(key, (512, 1024), jnp.bfloat16)
    wln = 1.0 + 0.1 * jax.random.normal(kq, (1024,), jnp.float32)
    bln = 0.1 * jax.random.normal(kk, (1024,), jnp.float32)
    results["layer_norm"] = _compare(
        partial(layer_norm, impl="pallas"), partial(layer_norm, impl="xla"),
        (x, wln, bln), tol_norm=2e-2, grad_argnums=(0, 1, 2))
    results["rms_norm"] = _compare(
        partial(rms_norm, impl="pallas"), partial(rms_norm, impl="xla"),
        (x, wln), tol_norm=2e-2, grad_argnums=(0, 1))

    # scaled-mask softmax (causal, the Megatron kernel pair)
    logits = jax.random.normal(key, (4, 8, 256, 256), jnp.bfloat16)
    results["scaled_masked_softmax"] = _compare(
        partial(scaled_masked_softmax, scale=0.125, causal=True,
                impl="pallas"),
        partial(scaled_masked_softmax, scale=0.125, causal=True, impl="xla"),
        (logits,), tol_norm=2e-2, grad_argnums=(0,))

    # fused label-smoothing CE (fp32 logits like the vocab head)
    vlog = jax.random.normal(key, (1024, 8192), jnp.float32)
    labels = jax.random.randint(kq, (1024,), 0, 8192)
    results["xentropy"] = _compare(
        partial(softmax_cross_entropy, smoothing=0.1, impl="pallas"),
        partial(softmax_cross_entropy, smoothing=0.1, impl="xla"),
        (vlog, labels), tol_norm=1e-3, grad_argnums=(0,))

    # chunked LM-head CE vs the unchunked reference (both XLA; the chunk
    # scan's accumulation order is what is under test)
    hs = jax.random.normal(key, (4, 256, 512), jnp.bfloat16)
    wte = jax.random.normal(kk, (8192, 512), jnp.bfloat16)
    tgt = jax.random.randint(kv, (4, 256), 0, 8192)
    results["lm_head_loss"] = _compare(
        lambda hh, ww: lm_head_cross_entropy(hh, ww, tgt, num_chunks=8),
        lambda hh, ww: lm_head_cross_entropy_reference(hh, ww, tgt),
        (hs, wte), tol_norm=2e-2, grad_argnums=(0, 1))

    results["all_ok"] = all(
        v.get("ok", True) for v in results.values() if isinstance(v, dict))
    return results


def main():
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = 1024
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    print(f"platform: {jax.default_backend()}", file=sys.stderr)

    fused_rates, fused_batch = measure_resilient("O2", "auto", batch, seq, steps)
    fused = _stats(fused_rates)
    print(f"O2+fused: {fused} (batch {fused_batch})", file=sys.stderr)
    base_rates, base_batch = measure_resilient("O0", "xla", batch, seq, steps)
    base = _stats(base_rates)
    print(f"O0 fp32 unfused: {base} (batch {base_batch})", file=sys.stderr)

    ratio_fused, ratio_base = fused["median"], base["median"]
    if fused_batch != base_batch:
        # batch size changes utilization: re-measure the larger-batch config
        # at the common (smaller) batch so the ratio compares like with like
        common = min(fused_batch, base_batch)
        if fused_batch > common:
            r, _ = measure_resilient("O2", "auto", common, seq, steps)
            ratio_fused = _stats(r)["median"]
        else:
            r, _ = measure_resilient("O0", "xla", common, seq, steps)
            ratio_base = _stats(r)["median"]
        print(f"ratio re-measured at common batch {common}", file=sys.stderr)

    result = {
        "metric": "gpt2_345m_o2_train_tokens_per_sec",
        "value": fused["median"],
        "unit": "tokens/s",
        "vs_baseline": round(ratio_fused / ratio_base, 3),
        # same-session medians + spread: the noise band that makes
        # round-over-round deltas attributable (VERDICT r2 weak #4)
        "spread": {"o2": fused, "o0": base},
    }
    if fused_batch != batch or base_batch != batch:
        # record the actually-measured config when OOM retries shrank it
        result["effective_batch"] = {"o2": fused_batch, "o0": base_batch}

    # BASELINE.md configs 1-3, measured on the same chip/session
    # (VERDICT r2 weak #1: the conv/BN and LAMB paths need TPU numbers)
    for key, fn in (("resnet50_o2_imgs_per_sec", bench_resnet50),
                    ("bert_large_lamb_tokens_per_sec", bench_bert_lamb)):
        try:
            result[key] = fn()
            print(f"{key}: {result[key]}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - never lose the headline metric
            print(f"{key} failed: {e}", file=sys.stderr)

    # BASELINE.md target #3, measured directly: fused whole-tree optimizer
    # step vs unfused per-leaf eager Adam (benchmarks/optimizer_step.py).
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        from optimizer_step import measure_speedup

        speedup, _, _ = measure_speedup(fused_steps=5, eager_steps=2)
        result["fused_opt_step_vs_eager"] = round(speedup, 2)
    except Exception as e:  # noqa: BLE001 - never lose the headline metric
        print(f"optimizer-step microbench failed: {e}", file=sys.stderr)

    # compiled-kernel numerics on this chip (VERDICT r2 weak #2)
    try:
        result["selftest"] = selftest()
        print(f"selftest all_ok={result['selftest']['all_ok']}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"selftest failed: {e}", file=sys.stderr)
        result["selftest"] = {"error": str(e)[:200]}

    print(json.dumps(result))


if __name__ == "__main__":
    if "--selftest" in sys.argv:
        print(json.dumps({"selftest": selftest()}))
    else:
        main()
