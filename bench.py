"""Headline benchmark: GPT-2 345M mixed-precision training step on one chip.

Measures the framework's core promise — the reference's amp-O2 + fused-kernel
recipe (BASELINE.md targets 3/4: fused step vs unfused eager) — as tokens/sec
for a full train step (forward + backward + FusedAdam + dynamic loss scaling)
on GPT-2 345M, bf16 O2 policy with Pallas flash attention and fused LN.

``vs_baseline`` is the speedup over the same model trained the "Python-only
build" way the reference warns is slower (README.md:134-139): fp32 O0, unfused
XLA attention/LN, plain optax Adam.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — plus
"effective_batch" when OOM retries shrank a config's batch (the ratio is
then re-measured at the common batch so vs_baseline stays apples-to-apples).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Plugin platforms registered by sitecustomize (the axon TPU tunnel) ignore a
# plain JAX_PLATFORMS env var; force the selection before first backend use.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp


def build(policy_level: str, impl: str, remat_policy=None):
    import optax

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    fused = policy_level == "O2"
    cfg = GPTConfig(
        vocab_size=50304,
        hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
        num_layers=int(os.environ.get("BENCH_LAYERS", "24")),
        num_attention_heads=16,
        max_seq_len=1024,
        hidden_dropout=0.0,
        axis=None,
        compute_dtype=jnp.bfloat16 if fused else jnp.float32,
        remat=True,
        remat_policy=remat_policy,
        attention_impl=impl,
        # fused chunked LM-head CE: ~6% throughput and ~0.8 GB less peak HBM
        # (survives pressure from co-tenants on the shared chip) — PERF_NOTES.md
        lm_head_chunks=8 if fused else None,
    )
    model = GPTModel(cfg)
    policy = amp.get_policy(policy_level)
    opt = FusedAdam(lr=1e-4) if fused else optax.adam(1e-4)
    mp_opt = amp.MixedPrecisionOptimizer(opt, policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)

    def step(params, opt_state, tokens, targets):
        def scaled_loss(p):
            return mp_opt.scale_loss(model.loss(p, tokens, targets), opt_state)

        loss_s, grads_s = jax.value_and_grad(scaled_loss)(params)
        new_params, new_state, metrics = mp_opt.apply_gradients(
            opt_state, params, grads_s
        )
        return new_params, new_state, loss_s, metrics

    return step, params, opt_state


def measure(step, params, opt_state, batch, seq, steps=10, scan_chunk=4) -> float:
    """Time ``steps`` train steps, dispatched as scanned chunks of
    ``scan_chunk`` steps per program when possible.

    The scan matters twice over through the axon tunnel: it amortizes
    per-dispatch overhead, and — since the tunnel backend rejects buffer
    donation — it is the only way the params/optimizer state update
    in-place (the scan carry lives inside one program) instead of being
    rewritten to fresh buffers every step. ~5% end-to-end (PERF_NOTES.md).
    Falls back to single-step dispatch (scan_chunk=1) if the scanned
    program does not fit.
    """
    from jax import lax

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 50304)
    targets = jnp.roll(tokens, -1, axis=-1)

    if scan_chunk > 1:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            def body(carry, _):
                p, s = carry
                p, s, loss, _ = step(p, s, tokens, targets)
                return (p, s), loss

            (params, opt_state), losses = lax.scan(
                body, (params, opt_state), None, length=scan_chunk)
            return params, opt_state, losses[-1]

    else:

        @jax.jit
        def run_chunk(params, opt_state, tokens, targets):
            p, s, loss, _ = step(params, opt_state, tokens, targets)
            return p, s, loss

    # round the requested step count up to whole chunks (never time fewer
    # steps than asked); normalization below uses the actual count run
    n_chunks = max(1, -(-steps // scan_chunk))
    # warmup / compile. Through remote-device tunnels (axon),
    # block_until_ready can ack dispatch rather than execution, so force a
    # device->host transfer of a value that depends on the whole chain.
    params, opt_state, loss = run_chunk(params, opt_state, tokens, targets)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        params, opt_state, loss = run_chunk(params, opt_state, tokens, targets)
    # the final loss depends on every prior step's params: fetching it to the
    # host forces full execution before the clock stops.
    loss_val = float(loss)
    dt = (time.perf_counter() - t0) / (n_chunks * scan_chunk)
    assert jnp.isfinite(loss_val), "non-finite loss in bench"
    return batch * seq / dt


def measure_resilient(level, impl, batch, seq, steps):
    """The chip is shared: co-tenant HBM pressure can OOM a config that
    normally fits. Degrade gracefully — selective remat → full remat,
    scanned dispatch → per-step dispatch, then halve the batch (tokens/s is
    per-token normalized) — rather than lose the round's record."""
    # (remat_policy, scan_chunk) from fastest to most memory-frugal.
    # save_attn keeps the flash kernel outputs so backward skips the
    # attention recompute (~5% when HBM allows it).
    ladder = ([("save_attn", 4), (None, 4), (None, 1)] if level == "O2"
              else [(None, 4), (None, 1)])
    last_oom = None
    while True:
        for remat_policy, scan_chunk in ladder:
            try:
                tps = measure(*build(level, impl, remat_policy), batch, seq,
                              steps, scan_chunk=scan_chunk)
                return tps, batch
            except Exception as e:  # noqa: BLE001 - jaxlib error types vary
                if "RESOURCE_EXHAUSTED" not in str(e):
                    raise
                last_oom = e
                print(f"{level}: OOM at remat_policy={remat_policy} "
                      f"scan={scan_chunk}, batch {batch}", file=sys.stderr)
        if batch <= 1:
            # keep the jaxlib allocator diagnostics on the chained cause
            raise RuntimeError(f"{level}: OOM even at batch 1") from last_oom
        batch //= 2


def main():
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = 1024
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    print(f"platform: {jax.default_backend()}", file=sys.stderr)

    fused_tps, fused_batch = measure_resilient("O2", "auto", batch, seq, steps)
    print(f"O2+fused: {fused_tps:.0f} tokens/s (batch {fused_batch})", file=sys.stderr)
    base_tps, base_batch = measure_resilient("O0", "xla", batch, seq, steps)
    print(f"O0 fp32 unfused: {base_tps:.0f} tokens/s (batch {base_batch})", file=sys.stderr)

    ratio_fused, ratio_base = fused_tps, base_tps
    if fused_batch != base_batch:
        # batch size changes utilization: re-measure the larger-batch config
        # at the common (smaller) batch so the ratio compares like with like
        common = min(fused_batch, base_batch)
        if fused_batch > common:
            ratio_fused, _ = measure_resilient("O2", "auto", common, seq, steps)
        else:
            ratio_base, _ = measure_resilient("O0", "xla", common, seq, steps)
        print(f"ratio re-measured at common batch {common}", file=sys.stderr)

    result = {
        "metric": "gpt2_345m_o2_train_tokens_per_sec",
        "value": round(fused_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(ratio_fused / ratio_base, 3),
    }
    if fused_batch != batch or base_batch != batch:
        # record the actually-measured config when OOM retries shrank it
        result["effective_batch"] = {"o2": fused_batch, "o0": base_batch}

    # BASELINE.md target #3, measured directly: fused whole-tree optimizer
    # step vs unfused per-leaf eager Adam (benchmarks/optimizer_step.py).
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        from optimizer_step import measure_speedup

        speedup, _, _ = measure_speedup(fused_steps=5, eager_steps=2)
        result["fused_opt_step_vs_eager"] = round(speedup, 2)
    except Exception as e:  # noqa: BLE001 - never lose the headline metric
        print(f"optimizer-step microbench failed: {e}", file=sys.stderr)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
