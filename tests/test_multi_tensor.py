"""Tree-level fused ops vs numpy (reference: tests/L0/run_amp/test_multi_tensor_*)."""

import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import (
    tree_axpby,
    tree_l2norm,
    tree_l2norm_per_tensor,
    tree_nonfinite,
    tree_scale,
)
from apex_tpu.ops.multi_tensor import tree_clip_by_global_norm


def _tree():
    return {
        "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": {"c": jnp.ones((4,), jnp.bfloat16) * 2},
    }


def test_tree_scale():
    out, inf = tree_scale(_tree(), 0.5)
    assert not bool(inf)
    np.testing.assert_allclose(np.asarray(out["a"]), np.arange(6).reshape(2, 3) * 0.5)
    assert out["b"]["c"].dtype == jnp.bfloat16


def test_tree_scale_overflow_flag():
    t = _tree()
    t["a"] = t["a"].at[0, 0].set(jnp.nan)
    _, inf = tree_scale(t, 1.0)
    assert bool(inf)


def test_tree_axpby():
    x = {"w": jnp.array([1.0, 2.0])}
    y = {"w": jnp.array([10.0, 20.0])}
    out, inf = tree_axpby(2.0, x, 0.5, y)
    assert not bool(inf)
    np.testing.assert_allclose(np.asarray(out["w"]), [7.0, 14.0])


def test_tree_l2norm():
    t = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    assert float(tree_l2norm(t)) == 5.0
    per = tree_l2norm_per_tensor(t)
    assert float(per["a"]) == 3.0 and float(per["b"]) == 4.0


def test_tree_nonfinite():
    assert not bool(tree_nonfinite(_tree()))
    assert bool(tree_nonfinite({"x": jnp.array([jnp.inf])}))


def test_clip_by_global_norm():
    t = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    clipped, gnorm = tree_clip_by_global_norm(t, 1.0)
    assert abs(float(gnorm) - 5.0) < 1e-5
    total = np.sqrt(
        np.asarray(clipped["a"]) ** 2 + np.asarray(clipped["b"]) ** 2
    ).item()
    assert abs(total - 1.0) < 1e-4


def test_tree_scale_scalar_leaves():
    """Regression: python-float leaves must not crash tree ops."""
    from apex_tpu.ops.multi_tensor import tree_scale

    out, inf = tree_scale({"w": jnp.ones(3), "aux": 0.5}, 2.0)
    assert float(out["aux"]) == 1.0
    assert not bool(inf)
