"""Megatron-argument-surface tests (reference: apex/transformer/testing/
arguments.py). Pins full flag parity — every flag name the reference parser
registers must parse here — plus the post-parse derivations."""

import re

import jax.numpy as jnp
import pytest

from apex_tpu.transformer.testing.arguments import parse_args, validate_args

# every --flag the reference's 808-line parser registers (extracted from
# apex/transformer/testing/arguments.py add_argument calls)
REFERENCE_FLAGS = """
--num-layers --hidden-size --ffn-hidden-size --num-attention-heads
--kv-channels --max-position-embeddings --make-vocab-size-divisible-by
--layernorm-epsilon --apply-residual-connection-post-layernorm --openai-gelu
--onnx-safe --bert-no-binary-head --log-params-norm --log-num-zeros-in-grad
--tensorboard-log-interval --tensorboard-queue-size --log-timers-to-tensorboard
--log-batch-size-to-tensorboard --no-log-learnig-rate-to-tensorboard
--no-log-loss-scale-to-tensorboard --log-validation-ppl-to-tensorboard
--log-memory-to-tensorboard --attention-dropout --hidden-dropout
--weight-decay --clip-grad --adam-beta1 --adam-beta2 --adam-eps
--sgd-momentum --micro-batch-size --batch-size --global-batch-size
--rampup-batch-size --checkpoint-activations
--distribute-checkpointed-activations --activations-checkpoint-method
--activations-checkpoint-num-layers --train-iters --train-samples
--log-interval --exit-interval --exit-duration-in-mins --tensorboard-dir
--no-masked-softmax-fusion --no-bias-gelu-fusion --no-bias-dropout-fusion
--optimizer --dataloader-type --no-async-tensor-model-parallel-allreduce
--seed --init-method-std --init-method-xavier-uniform --lr --lr-decay-style
--lr-decay-iters --lr-decay-samples --lr-warmup-fraction --lr-warmup-iters
--lr-warmup-samples --warmup --min-lr --override-lr-scheduler
--use-checkpoint-lr-scheduler --save --save-interval --no-save-optim
--no-save-rng --load --no-load-optim --no-load-rng --finetune --fp16 --bf16
--loss-scale --initial-loss-scale --min-loss-scale --loss-scale-window
--hysteresis --fp32-residual-connection --no-query-key-layer-scaling
--attention-softmax-in-fp32 --accumulate-allreduce-grads-in-fp32
--fp16-lm-cross-entropy --tensor-model-parallel-size
--pipeline-model-parallel-size --pipeline-model-parallel-split-rank
--model-parallel-size --num-layers-per-virtual-pipeline-stage
--distributed-backend --DDP-impl --no-contiguous-buffers-in-local-ddp
--no-scatter-gather-tensors-in-pipeline --local_rank --lazy-mpu-init
--use-cpu-initialization --cpu-offload --empty-unused-memory-level
--eval-iters --eval-interval --data-path --split --vocab-file --merge-file
--vocab-extra-ids --seq-length --encoder-seq-length --decoder-seq-length
--retriever-seq-length --sample-rate --mask-prob --short-seq-prob
--mmap-warmup --num-workers --tokenizer-type --data-impl
--reset-position-ids --reset-attention-mask --eod-mask-loss
--adlr-autoresume --adlr-autoresume-interval --ict-head-size
--biencoder-projection-dim --biencoder-shared-query-context-model
--ict-load --bert-load --titles-data-path --query-in-block-prob
--use-one-sent-docs --evidence-data-path --retriever-report-topk-accuracies
--retriever-score-scaling --block-data-path --embedding-path
--indexer-batch-size --indexer-log-interval --num-classes --img-dim
--num-channels --patch-dim
""".split()


def test_every_reference_flag_is_registered():
    import apex_tpu.transformer.testing.arguments as mod
    import inspect

    src = inspect.getsource(mod)
    registered = set(re.findall(r'"(--[\w-]+|--local_rank)"', src))
    missing = [f for f in REFERENCE_FLAGS if f not in registered]
    assert not missing, f"flags missing vs reference parser: {missing}"


def test_store_true_flags_parse():
    ns = parse_args(["--checkpoint-activations", "--openai-gelu",
                     "--log-params-norm", "--mmap-warmup", "--finetune",
                     "--fp32-residual-connection", "--eod-mask-loss"])
    assert ns.checkpoint_activations and ns.openai_gelu
    # --checkpoint-activations rewrites to the uniform method
    assert ns.activations_checkpoint_method == "uniform"
    assert ns.recompute_activations


def test_negative_flags_flip_positive_dests():
    ns = parse_args(["--no-masked-softmax-fusion", "--no-bias-gelu-fusion",
                     "--no-query-key-layer-scaling",
                     "--no-contiguous-buffers-in-local-ddp"])
    assert not ns.masked_softmax_fusion
    assert not ns.bias_gelu_fusion
    assert not ns.apply_query_key_layer_scaling
    assert not ns.use_contiguous_buffers_in_local_ddp
    dflt = parse_args([])
    assert dflt.masked_softmax_fusion and dflt.bias_gelu_fusion


def test_deprecated_flags_error():
    with pytest.raises(ValueError, match="micro-batch-size"):
        parse_args(["--batch-size", "8"])
    with pytest.raises(ValueError, match="lr-warmup-fraction"):
        parse_args(["--warmup", "10"])
    with pytest.raises(ValueError, match="tensor-model-parallel-size"):
        parse_args(["--model-parallel-size", "2"])


def test_world_size_derivations(monkeypatch):
    monkeypatch.setenv("WORLD_SIZE", "8")
    ns = parse_args(["--tensor-model-parallel-size", "2",
                     "--pipeline-model-parallel-size", "2"])
    assert ns.world_size == 8 and ns.data_parallel_size == 2
    # global batch defaults to micro * dp
    ns = parse_args(["--micro-batch-size", "4",
                     "--tensor-model-parallel-size", "2"])
    assert ns.data_parallel_size == 4 and ns.global_batch_size == 16
    monkeypatch.delenv("WORLD_SIZE")
    # no launcher: world defaults to the model-parallel footprint
    ns = parse_args(["--tensor-model-parallel-size", "4",
                     "--pipeline-model-parallel-size", "2"])
    assert ns.world_size == 8 and ns.data_parallel_size == 1


def test_virtual_pipeline_sizing():
    ns = parse_args(["--num-layers", "16", "--pipeline-model-parallel-size",
                     "4", "--num-layers-per-virtual-pipeline-stage", "2"])
    assert ns.virtual_pipeline_model_parallel_size == 2
    with pytest.raises(ValueError, match="divide"):
        parse_args(["--num-layers", "6", "--pipeline-model-parallel-size",
                    "4", "--num-layers-per-virtual-pipeline-stage", "2"])


def test_precision_dtype_and_vocab_padding():
    ns = parse_args(["--bf16", "--vocab-size", "50257",
                     "--tensor-model-parallel-size", "2"])
    assert ns.params_dtype == jnp.bfloat16
    # padded to a multiple of 128 * tp = 256
    assert ns.padded_vocab_size == 50432
    assert parse_args(["--fp16"]).params_dtype == jnp.float16
    assert parse_args([]).params_dtype == jnp.float32
    with pytest.raises(ValueError, match="mutually exclusive"):
        parse_args(["--fp16", "--bf16"])


def test_derived_model_dims():
    ns = parse_args(["--hidden-size", "1024", "--num-attention-heads", "16",
                     "--seq-length", "512"])
    assert ns.ffn_hidden_size == 4096
    assert ns.kv_channels == 64
    assert ns.max_position_embeddings == 512


def test_rampup_batch_size_int_coercion_and_arity():
    ns = parse_args(["--rampup-batch-size", "16", "16", "300"])
    assert ns.rampup_batch_size == [16, 16, 300]
    with pytest.raises(ValueError, match="exactly 3"):
        parse_args(["--rampup-batch-size", "16", "16"])


def test_async_tp_allreduce_positive_dest():
    assert parse_args([]).async_tensor_model_parallel_allreduce
    ns = parse_args(["--no-async-tensor-model-parallel-allreduce"])
    assert not ns.async_tensor_model_parallel_allreduce


def test_defaults_dict_and_extra_args_provider():
    def extra(p):
        p.add_argument("--my-extra", type=int, default=7)

    ns = parse_args(extra, {"num_layers": 12, "seed": 99}, False,
                    ["--seed", "4321"])
    assert ns.my_extra == 7
    assert ns.num_layers == 12      # filled from defaults
    assert ns.seed == 4321          # command line wins over defaults
    ns = parse_args(ignore_unknown_args=True,
                    args=["--not-a-real-flag", "1", "--lr", "0.1"])
    assert ns.lr == 0.1
