"""Tests for apex_tpu.monitor — journal schema round-trip, HBM leak
detection, collective byte accounting + trace-join scope attribution, the
library watchdog (hung child killed, checkpoint recovered, heartbeat
stall), and the bench.py/amp integration hooks. All CPU-mesh safe (the
conftest forces 8 virtual CPU devices)."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.monitor import (
    Heartbeat,
    HBMMonitor,
    MetricsJournal,
    comm_accounting,
    lane_padded_bytes,
    live_array_stats,
    run_under_watchdog,
    scaler_state,
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def test_journal_schema_round_trip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with MetricsJournal(path, meta={"run": "t"}, sample_hbm_every=2) as j:
        for step in range(4):
            j.step_start()
            loss = jnp.asarray(1.0 / (step + 1), jnp.float32)
            metrics = {"found_inf": jnp.asarray(step == 2),
                       "loss_scale": jnp.asarray(65536.0, jnp.float32),
                       "grad_norm": jnp.asarray(0.5, jnp.float32)}
            rec = j.step_end(step=step, loss=loss, tokens=1024,
                             metrics=metrics)
            assert rec["wall_s"] >= 0
    rows = MetricsJournal.read(path)
    assert rows[0]["kind"] == "meta" and rows[0]["run"] == "t"
    steps = [r for r in rows if r["kind"] == "step"]
    assert [r["step"] for r in steps] == [0, 1, 2, 3]
    last = steps[-1]
    # the required record surface: time, throughput, loss, scale state,
    # grad norm, overflow counter, rank info
    for field in ("ts", "wall_s", "tokens_per_sec", "loss", "loss_scale",
                  "grad_norm", "overflows", "rank", "rank_info"):
        assert field in last, field
    assert isinstance(last["loss"], float)
    assert isinstance(last["loss_scale"], float)
    assert last["found_inf"] in (False, True)
    assert last["overflows"] == 1  # exactly the step-2 found_inf
    # sample_hbm_every=2: records 2 and 4 carry occupancy samples
    assert "hbm" in steps[1] and "hbm" in steps[3]
    assert "hbm" not in steps[0]
    assert steps[3]["hbm"]["live_bytes"] >= 0


def test_journal_scaler_state_and_shared_file(tmp_path):
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler.create(loss_scale="dynamic")
    st = scaler_state(scaler)
    assert st["loss_scale"] == 2.0 ** 16 and st["unskipped"] == 0

    path = str(tmp_path / "shared.jsonl")
    # two journal instances appending to one path (the bench subprocess
    # pattern) interleave whole lines
    j1, j2 = MetricsJournal(path), MetricsJournal(path)
    j1.log({"src": 1})
    j2.log({"src": 2})
    j1.close()
    j2.close()
    assert sorted(r["src"] for r in MetricsJournal.read(path)) == [1, 2]


def test_journal_never_raises_on_weird_values(tmp_path):
    path = str(tmp_path / "w.jsonl")
    with MetricsJournal(path) as j:
        j.log({"arr": jnp.arange(3), "obj": object(), "nested": {"x": 1}})
    (row,) = MetricsJournal.read(path)
    assert row["arr"] == [0, 1, 2]  # small arrays list-ify
    assert isinstance(row["obj"], str)  # default=str fallback


def test_journal_sanitizes_nonfinite_to_strict_json(tmp_path):
    """A NaN loss (or inf metric) must not poison the journal with bare
    ``NaN`` tokens: every line stays STRICT JSON — non-finite floats
    become null and their paths land in ``nonfinite_keys`` (the field
    the overflow forensics keys off)."""
    path = str(tmp_path / "nan.jsonl")
    with MetricsJournal(path) as j:
        j.step_end(step=0, loss=jnp.asarray(float("nan")), tokens=64,
                   wall_s=0.1,
                   metrics={"grad_norm": jnp.asarray(float("inf")),
                            "nested": {"deep": [1.0, float("nan")]}})
        j.step_end(step=1, loss=jnp.asarray(1.5), tokens=64, wall_s=0.1)
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    for line in lines:
        # parse_constant raises on non-strict NaN/Infinity tokens
        json.loads(line, parse_constant=lambda t: (_ for _ in ()).throw(
            ValueError(f"non-strict token {t}")))
    rows = MetricsJournal.read(path)
    bad, good = rows[0], rows[1]
    assert bad["loss"] is None and bad["grad_norm"] is None
    assert bad["nested"]["deep"] == [1.0, None]
    assert sorted(bad["nonfinite_keys"]) == [
        "grad_norm", "loss", "nested.deep[1]"]
    # finite records carry no sanitization residue
    assert good["loss"] == 1.5 and "nonfinite_keys" not in good


def test_journal_read_tolerates_truncated_final_line(tmp_path):
    """Crash-/watchdog-kill-time journals end mid-line; the good prefix
    must still parse, with the damage flagged."""
    path = str(tmp_path / "torn.jsonl")
    with MetricsJournal(path) as j:
        for step in range(3):
            j.step_end(step=step, loss=jnp.asarray(1.0), tokens=8,
                       wall_s=0.1)
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "step", "step": 3, "wal')  # torn write
    rows = MetricsJournal.read(path)
    assert [r["step"] for r in rows] == [0, 1, 2]
    assert rows.truncated is True and rows.bad_lines == 1

    # a corrupt MID-file line is dropped and counted, but does not mark
    # the journal truncated (the tail is intact); a torn fragment that
    # happens to parse as scalar JSON ("42") is equally not a record
    mid = str(tmp_path / "mid.jsonl")
    with open(mid, "w") as f:
        f.write('{"kind": "step", "step": 0}\n')
        f.write("garbage not json\n")
        f.write("42\n")
        f.write('{"kind": "step", "step": 1}\n')
    rows = MetricsJournal.read(mid)
    assert [r["step"] for r in rows] == [0, 1]
    assert rows.truncated is False and rows.bad_lines == 2


# ---------------------------------------------------------------------------
# hbm
# ---------------------------------------------------------------------------


def test_lane_padded_bytes():
    # minor pads to 128 lanes: a (512, 1) f32 column costs 128x
    assert lane_padded_bytes((512, 1), 4) == 512 * 128 * 4
    # second-minor pads to the dtype sublane count (f32: 8, bf16: 16)
    assert lane_padded_bytes((3, 128), 4) == 8 * 128 * 4
    assert lane_padded_bytes((3, 128), 2) == 16 * 128 * 2
    # aligned shapes pay no tax; leading dims multiply through
    assert lane_padded_bytes((4, 8, 8, 128), 4) == 4 * 8 * 8 * 128 * 4
    # rank-1 lays out as one (1, n) tile row
    assert lane_padded_bytes((100,), 4) == 8 * 128 * 4


def test_hbm_monitor_detects_retained_leak():
    leak = HBMMonitor()
    leak.sample("baseline")
    retained = []
    for i in range(4):
        retained.append(jnp.ones((128, 128), jnp.float32) + i)
        leak.sample(f"iter{i}")
    assert leak.growth_bytes() >= 4 * 128 * 128 * 4
    # visible growth is monotone across the retaining iterations
    curve = [s["live_bytes"] for s in leak.samples]
    assert all(b >= a for a, b in zip(curve, curve[1:]))

    flat = HBMMonitor()
    flat.sample("baseline")
    for _ in range(4):
        _ = float(jnp.sum(jnp.ones((128, 128), jnp.float32)))
        flat.sample("iter")
    assert abs(flat.growth_bytes()) < 128 * 128 * 4
    del retained


def test_hbm_monitor_journals_samples(tmp_path):
    path = str(tmp_path / "h.jsonl")
    with MetricsJournal(path) as j:
        mon = HBMMonitor(journal=j, label="toy")
        mon.sample("before")
        mon.sample("after")
    rows = [r for r in MetricsJournal.read(path) if r["kind"] == "hbm"]
    assert [r["tag"] for r in rows] == ["before", "after"]
    assert all(r["label"] == "toy" and "padded_bytes" in r for r in rows)


def test_live_array_stats_counts_padded():
    keep = jnp.ones((256, 1), jnp.float32)  # 128x lane-padding tax
    stats = live_array_stats()
    assert stats["count"] >= 1
    assert stats["padded_bytes"] >= stats["live_bytes"]
    del keep


def test_hbm_monitor_empty_baseline(monkeypatch):
    """A monitor started before ANY array exists (fresh process, no
    backend traffic yet: ``jax.live_arrays()`` empty) must report growth
    against the zero baseline and a well-defined peak — and the
    degenerate no-/one-sample cases must not divide or index into
    nothing."""
    from apex_tpu.monitor import hbm as hbm_mod

    feed = iter([
        {"live_bytes": 0, "padded_bytes": 0, "count": 0, "largest_bytes": 0},
        {"live_bytes": 4096, "padded_bytes": 8192, "count": 1,
         "largest_bytes": 4096},
        {"live_bytes": 1024, "padded_bytes": 2048, "count": 1,
         "largest_bytes": 1024},
    ])
    monkeypatch.setattr(hbm_mod, "live_array_stats", lambda: dict(next(feed)))

    mon = hbm_mod.HBMMonitor()
    assert mon.growth_bytes() == 0 and mon.peak_bytes() == 0  # no samples
    assert mon.baseline is None
    mon.sample("empty-baseline")
    assert mon.growth_bytes() == 0  # one sample: nothing to diff yet
    assert mon.peak_bytes() == 0
    mon.sample("allocated")
    assert mon.growth_bytes() == 4096  # growth FROM the empty baseline
    assert mon.peak_bytes() == 4096
    mon.sample("freed")
    assert mon.growth_bytes() == 1024  # last-minus-baseline, not peak
    assert mon.peak_bytes() == 4096   # peak remembers the high-water mark


# ---------------------------------------------------------------------------
# comms
# ---------------------------------------------------------------------------


def test_comm_accounting_by_axis_and_verb():
    from apex_tpu.parallel import collectives

    def fn(x):
        y = collectives.psum(x, "i")
        return collectives.all_gather(jnp.sum(y, -1), "i")

    x = jnp.ones((2, 4, 8), jnp.float32)
    with comm_accounting() as acct:
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(x)
    by_axis = acct.by_axis()
    assert by_axis["i"]["calls"] == 2
    assert by_axis["i"]["bytes"] == 4 * 8 * 4 + 4 * 4
    by_verb = acct.by_verb()
    assert by_verb["psum"]["bytes"] == 4 * 8 * 4
    assert by_verb["all_gather"]["bytes"] == 4 * 4
    assert acct.total_bytes() == 4 * 8 * 4 + 4 * 4
    # outside the context nothing records
    jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(x)
    assert acct.by_axis()["i"]["calls"] == 2


def test_comm_accounting_tallies_sequence_parallel_psum_scatter():
    """The sequence-parallel conjugates triple the reduce-scatter traffic
    on the TP axis (ISSUE 4): every ``psum_scatter`` payload must land in
    the per-axis tally like the psums it replaces — forward AND the
    custom-VJP backward call sites."""
    from apex_tpu.transformer import tensor_parallel as tp

    x = jnp.ones((2, 8, 4), jnp.float32)
    nbytes = 2 * 8 * 4 * 4

    def fwd(x):
        y = tp.reduce_scatter_to_sequence_parallel_region(x, "model")
        return tp.gather_from_sequence_parallel_region(y, "model")

    with comm_accounting() as acct:
        jax.make_jaxpr(fwd, axis_env=[("model", 4)])(x)
    by_verb = acct.by_verb()
    assert by_verb["psum_scatter"] == {"bytes": nbytes, "calls": 1}
    # the gather sees the (2, 2, 4) shard
    assert by_verb["all_gather"] == {"bytes": nbytes // 4, "calls": 1}
    assert acct.by_axis()["model"]["calls"] == 2

    # the backward of the gather is ALSO a psum_scatter — attributed to
    # the same axis through the grad trace
    def loss(x):
        y = tp.gather_from_sequence_parallel_region(x, "model")
        return jnp.sum(y * y)

    with comm_accounting() as acct:
        jax.make_jaxpr(jax.grad(loss), axis_env=[("model", 4)])(x)
    assert acct.by_verb()["psum_scatter"]["calls"] == 1
    assert acct.by_verb()["psum_scatter"]["bytes"] == nbytes * 4  # gathered


def test_comm_per_layer_gather_bytes_match_bulk_gather():
    """The ZeRO-3 conservation law: L per-layer JIT gathers move exactly
    the bytes of the one whole-stack gather they replace (chunk layouts
    agree row for row when the row size divides the axis), and both book
    at the CAST wire dtype — the compressed-gather claim stays a reported
    number on the per-layer path too."""
    from apex_tpu.optimizers.distributed import (
        gather_leaf,
        gather_stacked_leaf,
    )

    L, row, n = 4, (16, 32), 8  # 512 elems/row, divisible by n: no padding
    k = 16 * 32 // n
    chunks = jnp.ones((L, k), jnp.float32)

    def per_layer(c):
        return jnp.stack([gather_leaf(c[i], row, jnp.float32, "data",
                                      gather_dtype=jnp.bfloat16)
                          for i in range(L)])

    def bulk(c):
        return gather_stacked_leaf(c, row, jnp.float32, "data",
                                   gather_dtype=jnp.bfloat16)

    with comm_accounting() as acct_layer:
        jax.make_jaxpr(per_layer, axis_env=[("data", n)])(chunks)
    with comm_accounting() as acct_bulk:
        jax.make_jaxpr(bulk, axis_env=[("data", n)])(chunks)
    a, b = acct_layer.by_axis()["data"], acct_bulk.by_axis()["data"]
    assert a["bytes"] == b["bytes"] == L * k * 2  # bf16 wire: 2 B/elem
    assert a["calls"] == L and b["calls"] == 1

    # without gather_dtype the wire payload doubles — the tally sees it
    with comm_accounting() as acct_fp32:
        jax.make_jaxpr(
            lambda c: jnp.stack([gather_leaf(c[i], row, jnp.float32, "data")
                                 for i in range(L)]),
            axis_env=[("data", n)])(chunks)
    assert acct_fp32.by_axis()["data"]["bytes"] == L * k * 4


def test_comm_accounting_books_quantized_wire_dtypes():
    """The quantized-collective accounting contract (mirror of the
    bf16-gather half-bytes test above, one notch further): an int8 reduce
    books exactly 1/4 the fp32 psum_scatter bytes, e5m2 the same 1/4, and
    the fp32 per-chunk scale side-channel lands as its OWN
    (verb, dtype) row — so the compression ratio and the side-channel's
    cost both read straight off ``CommAccount.by_verb_dtype``."""
    from apex_tpu.optimizers.distributed import scatter_chunk
    from apex_tpu.parallel.quantize import quantized_reduce_scatter

    n, elems = 8, 64 * 128  # divides n: padded == logical
    g = jnp.ones((64, 128), jnp.float32)

    with comm_accounting() as acct_fp32:
        jax.make_jaxpr(lambda x: scatter_chunk(x, n, "data"),
                       axis_env=[("data", n)])(g)
    fp32_bytes = acct_fp32.by_verb_dtype()["psum_scatter[float32]"]["bytes"]
    assert fp32_bytes == elems * 4

    for wire, dtype_label in (("int8", "int8"), ("e5m2", "float8_e5m2")):
        with comm_accounting() as acct:
            jax.make_jaxpr(
                lambda x: quantized_reduce_scatter(x, n, "data", wire)[0],
                axis_env=[("data", n)])(g)
        table = acct.by_verb_dtype()
        payload = table[f"all_to_all[{dtype_label}]"]
        scales = table["all_to_all[float32]"]
        assert payload["bytes"] * 4 == fp32_bytes, (wire, table)
        assert payload["calls"] == scales["calls"] == 1
        # side-channel: one fp32 scale per destination chunk
        assert scales["bytes"] == n * 4, (wire, table)
    # summary() carries the rollup for journal/report consumers
    assert "by_verb_dtype" in acct.summary()


def test_report_rolls_up_comm_bytes_by_verb_dtype():
    """report.analyze aggregates comm_bytes_by_verb_dtype tables across
    records (the scaling-harness zero-q8 rows), keeping payload and scale
    side-channel rows distinct."""
    from apex_tpu.monitor import report

    rows = [
        {"kind": "step", "step": 0, "wall_s": 0.1, "loss": 2.0,
         "tokens": 100, "tokens_per_sec": 1000.0, "overflows": 0,
         "comm_bytes_by_verb_dtype": {
             "all_to_all[int8]": {"bytes": 1000, "calls": 2},
             "all_to_all[float32]": {"bytes": 32, "calls": 2}}},
        {"kind": "step", "step": 1, "wall_s": 0.1, "loss": 1.9,
         "tokens": 100, "tokens_per_sec": 1000.0, "overflows": 0,
         "comm_bytes_by_verb_dtype": {
             "all_to_all[int8]": {"bytes": 1000, "calls": 2}}},
    ]
    analysis = report.analyze(rows)
    table = analysis["comm_bytes_by_verb_dtype"]
    assert table["all_to_all[int8]"] == {"bytes": 2000, "calls": 4}
    assert table["all_to_all[float32]"] == {"bytes": 32, "calls": 2}


def test_report_compare_loss_threshold_gate():
    """The convergence machine check: --loss-threshold arms a final-loss
    comparison denominated in the baseline's loss drop; off by default."""
    from apex_tpu.monitor import report

    def run(first, last, n=8):
        losses = [first + (last - first) * i / (n - 1) for i in range(n)]
        return [{"kind": "step", "step": i, "wall_s": 0.1, "loss": l,
                 "tokens": 100, "tokens_per_sec": 1000.0, "overflows": 0}
                for i, l in enumerate(losses)]

    base = run(2.0, 1.0)          # drop = 1.0
    good = run(2.0, 1.05)         # gives back 5% of the drop
    bad = run(2.0, 1.5)           # gives back 50%

    # default: no loss check at all (timing gates tolerate loss noise)
    res = report.compare(base, bad)
    assert not any(c["check"] == "loss_last" for c in res["checks"])
    # armed: the 10%-of-drop gate passes the close run, fails the far one
    assert report.compare(base, good, loss_threshold=0.1)["ok"]
    res_bad = report.compare(base, bad, loss_threshold=0.1)
    assert not res_bad["ok"] and "loss_last" in res_bad["regressed"]
    # CLI spelling (the driver-facing gate)
    import contextlib
    import io
    import json as _json
    import os
    import tempfile

    d = tempfile.mkdtemp(prefix="apex_tpu_qgate_")
    try:
        pa, pb = os.path.join(d, "a.jsonl"), os.path.join(d, "b.jsonl")
        for path, rows in ((pa, base), (pb, bad)):
            with open(path, "w") as f:
                for r in rows:
                    f.write(_json.dumps(dict(r, ts=0.0, v=1)) + "\n")
        with contextlib.redirect_stdout(io.StringIO()):
            assert report.main(["compare", pa, pb]) == 0
            assert report.main(["compare", pa, pb,
                                "--loss-threshold", "0.1"]) == 1
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)


def test_sequence_parallel_activation_report():
    """The tp-x memory claim as a number: per-layer sequence-region bytes
    shrink by exactly tp (both sides use the same lane-padded shape
    algebra, so the ratio is exact when s/tp keeps the dims tile-aligned)."""
    from apex_tpu.monitor.hbm import (
        SEQUENCE_REGION_SITES,
        sequence_parallel_activation_report,
        sequence_region_layer_bytes,
    )

    rep = sequence_parallel_activation_report(
        batch=8, seq=1024, hidden=1024, num_layers=24, tp=8)
    assert rep["ratio"] == 8.0
    assert rep["plain_per_layer_bytes"] == 8 * rep["sp_per_layer_bytes"]
    assert rep["plain_total_bytes"] == 24 * rep["plain_per_layer_bytes"]
    assert rep["sites_per_layer"] == len(SEQUENCE_REGION_SITES)

    plain = sequence_region_layer_bytes(8, 1024, 1024, tp=8,
                                        sequence_parallel=False)
    sp = sequence_region_layer_bytes(8, 1024, 1024, tp=8,
                                     sequence_parallel=True)
    assert plain["seq_local"] == 1024 and sp["seq_local"] == 128
    # unpadded bf16 site: b*s*h*2 bytes
    unpadded = sequence_region_layer_bytes(8, 1024, 1024, padded=False)
    assert unpadded["per_site_bytes"] == 8 * 1024 * 1024 * 2


def test_optimizer_state_report_flagship_ratio():
    """The ZeRO memory claim as a number (ISSUE 5 evidence): fp32
    master+moment bytes/rank at the 345M flagship shape are ~4.2 GB
    replicated and divide by dp under ZeRO chunking (1-D chunks tile as a
    single row, so the lane-padded footprint shrinks ~dp too)."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.hbm import (
        OPTIMIZER_STATE_COPIES,
        optimizer_state_report,
    )

    # bench.py's flagship config (hidden 1024 x 24 layers, vocab 50304):
    # eval_shape only — no 345M of buffers are materialized
    model = GPTModel(GPTConfig(
        vocab_size=50304, hidden_size=1024, num_layers=24,
        num_attention_heads=16, max_seq_len=1024, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16))
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    rep = optimizer_state_report(abstract, dp=8)
    assert rep["param_count"] > 340e6  # the 345M shape
    assert rep["state_copies"] == OPTIMIZER_STATE_COPIES == 3
    # master + m + v in fp32: > 4 GB/rank replicated...
    assert rep["replicated_bytes_per_rank"] > 4e9
    # ...and exactly /dp under ZeRO (up to per-leaf chunk padding)
    assert 7.9 < rep["ratio"] <= 8.0
    assert rep["zero_bytes_per_rank"] < rep["replicated_bytes_per_rank"] / 7.9
    assert rep["savings_bytes_per_rank"] == (
        rep["replicated_bytes_per_rank"] - rep["zero_bytes_per_rank"])
    # padded accounting present and also ~1/dp
    assert rep["zero_padded_bytes_per_rank"] < \
        rep["replicated_padded_bytes_per_rank"] / 7


def test_param_state_report_flagship_zero3_ratio():
    """param_state_report: the WORKING params (bf16 under O2) divide by dp
    under ZeRO-3 while ZeRO-1/2 keeps them replicated — the >=4x per-rank
    param-bytes reduction at dp=8 the ZeRO-3 evidence bar requires, on
    the 345M flagship shape via eval_shape alone."""
    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.hbm import param_state_report

    model = GPTModel(GPTConfig(
        vocab_size=50304, hidden_size=1024, num_layers=24,
        num_attention_heads=16, max_seq_len=1024, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16))
    abstract = jax.eval_shape(
        lambda k: amp.cast_params(model.init(k), amp.get_policy("O2")),
        jax.random.PRNGKey(0))
    rep = param_state_report(abstract, dp=8)
    assert rep["param_count"] > 340e6
    t = rep["per_rank"]
    # bf16 working copy: ~2 bytes/param replicated, ~/dp under ZeRO-3
    assert t["replicated"]["param_bytes"] > 0.6e9
    assert t["zero12"]["param_bytes"] == t["replicated"]["param_bytes"]
    assert rep["param_ratio"] >= 4.0  # the evidence-bar floor (dp=8: ~8x)
    assert t["zero3"]["param_bytes"] < t["replicated"]["param_bytes"] / 4
    # fp32 master+moment chunks shared by zero12 and zero3
    assert t["zero12"]["opt_bytes"] == t["zero3"]["opt_bytes"]
    assert t["replicated"]["opt_bytes"] > 4e9
    # the residency ordering the three modes exist to produce
    assert t["zero3"]["total_bytes"] < t["zero12"]["total_bytes"] \
        < t["replicated"]["total_bytes"]


def test_opt_state_bytes_reports_per_rank_shards():
    """opt_state_bytes: a ZeRO-sharded leaf books its per-device chunk,
    a replicated leaf books the full array — so the same call reports the
    honest per-rank footprint for both paths."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from apex_tpu.monitor.hbm import opt_state_bytes

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharded = jax.device_put(
        jnp.zeros((8 * 16,), jnp.float32),
        NamedSharding(mesh, P("data")))
    replicated = jax.device_put(
        jnp.zeros((8 * 16,), jnp.float32), NamedSharding(mesh, P()))
    assert opt_state_bytes({"chunk": sharded}) == 16 * 4
    assert opt_state_bytes({"full": replicated}) == 8 * 16 * 4
    assert opt_state_bytes({"a": sharded, "b": replicated}) \
        == 16 * 4 + 8 * 16 * 4


def test_journal_carries_opt_state_bytes(tmp_path):
    """set_opt_state_bytes arms a per-step field (like set_step_costs);
    un-armed journals are unchanged."""
    path = str(tmp_path / "j.jsonl")
    with MetricsJournal(path) as j:
        j.step_start()
        j.step_end(step=0, loss=jnp.float32(1.0), tokens=64)
        j.set_opt_state_bytes(123456)
        j.step_start()
        j.step_end(step=1, loss=jnp.float32(0.9), tokens=64)
    rows = [r for r in MetricsJournal.read(path) if r["kind"] == "step"]
    assert "opt_state_bytes" not in rows[0]
    assert rows[1]["opt_state_bytes"] == 123456


def test_journal_carries_param_bytes_and_report_rolls_up(tmp_path):
    """set_param_bytes stamps per-step param residency; report.analyze
    rolls it up and compare flags a run whose footprint GREW (the
    silently-dropped-ZeRO-3 regression no throughput check would see)."""
    from apex_tpu.monitor import report

    def write(path, nbytes):
        with MetricsJournal(path) as j:
            j.set_param_bytes(nbytes)
            j.set_opt_state_bytes(nbytes * 6)
            for step in range(4):
                j.log({"kind": "step", "step": step, "wall_s": 0.1,
                       "loss": 2.0, "tokens": 64, "tokens_per_sec": 640.0,
                       "overflows": 0, "param_bytes": nbytes,
                       "opt_state_bytes": nbytes * 6})

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    write(a, 100_000_000)   # ZeRO-3 run
    write(b, 800_000_000)   # params re-replicated: 8x the footprint
    ra = report.analyze(MetricsJournal.read(a))
    assert ra["param_bytes"] == {"last": 100_000_000, "peak": 100_000_000}
    cmp = report.compare(MetricsJournal.read(a), MetricsJournal.read(b))
    assert "param_bytes_last" in cmp["regressed"], cmp
    assert "opt_state_bytes_last" in cmp["regressed"], cmp
    # same-footprint candidate passes
    assert report.compare(MetricsJournal.read(a),
                          MetricsJournal.read(a))["ok"]


def test_comm_account_reentrancy():
    """Nested accounting contexts both observe every call, nested
    ``collective_scope``s on the SAME axis each tally their own call
    site, and an inner context's exit never unhooks the outer one."""
    from apex_tpu.monitor.comms import collective_scope

    x = jnp.ones((4, 8), jnp.float32)
    nbytes = 4 * 8 * 4
    with comm_accounting() as outer:
        with collective_scope("psum", "data", x):
            # nested scope on the same axis (the broadcast-inside-gather
            # shape): a distinct call site, tallied separately
            with collective_scope("all_gather", "data", x):
                pass
        with comm_accounting() as inner:
            with collective_scope("pmean", "data", x):
                pass
        # inner closed; outer must still be live
        with collective_scope("psum", "model", x):
            pass
    assert inner.by_verb() == {"pmean": {"bytes": nbytes, "calls": 1}}
    by_axis = outer.by_axis()
    assert by_axis["data"] == {"bytes": 3 * nbytes, "calls": 3}
    assert by_axis["model"] == {"bytes": nbytes, "calls": 1}
    assert outer.by_verb()["psum"]["calls"] == 2
    # after both contexts exit, scopes no longer tally anywhere
    with collective_scope("psum", "data", x):
        pass
    assert outer.total_bytes() == 4 * nbytes
    assert inner.total_bytes() == nbytes


def test_comm_scopes_reach_trace_join_keys():
    """The comm:<verb>[<axis>] scopes must be visible both to the jaxpr
    scope walk (per_scope_costs) and to the compiled HLO op_name metadata
    (the join key measured_scope_seconds uses) — that is what lets the
    trace-join attribute measured comm seconds per mesh axis."""
    from apex_tpu.parallel import collectives
    from apex_tpu.pyprof import per_scope_costs

    def fn(x):
        return collectives.pmean(collectives.psum(x, "i"), "i")

    x = jnp.ones((2, 8, 16), jnp.float32)
    costs = per_scope_costs(jax.vmap(fn, axis_name="i"), x)
    keys = " ".join(costs)
    assert "comm:psum[i]" in keys and "comm:pmean[i]" in keys
    hlo = jax.jit(jax.vmap(fn, axis_name="i")).lower(x).compile().as_text()
    assert "comm:psum[i]" in hlo


def test_comm_scopes_on_tp_mappings():
    """The conjugate TP collectives in tensor_parallel/mappings.py carry
    the same scopes (per-axis attribution of Megatron-style TP traffic)."""
    from apex_tpu.pyprof import per_scope_costs
    from apex_tpu.transformer.tensor_parallel.mappings import (
        gather_from_tensor_model_parallel_region,
        reduce_from_tensor_model_parallel_region,
    )

    def fn(x):
        y = reduce_from_tensor_model_parallel_region(x, "model")
        return gather_from_tensor_model_parallel_region(y, "model")

    x = jnp.ones((2, 4, 8), jnp.float32)
    with comm_accounting() as acct:
        costs = per_scope_costs(jax.vmap(fn, axis_name="model"), x)
    keys = " ".join(costs)
    assert "comm:psum[model]" in keys and "comm:all_gather[model]" in keys
    assert acct.by_axis()["model"]["calls"] == 2


def test_sharded_train_path_accounts_per_axis():
    """End-to-end: tracing the dryrun-style sharded grad step under
    comm_accounting yields per-mesh-axis byte rows — the dp/tp attribution
    the ISSUE asks the trace-join to carry."""
    from apex_tpu.parallel import collectives, mesh as mesh_lib
    from apex_tpu.parallel.distributed import allreduce_gradients

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map not available on this jax")
    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=2)
    try:
        from jax.sharding import PartitionSpec as P

        def grads_fn(g, loss):
            g = allreduce_gradients(g, (mesh_lib.AXIS_DATA,))
            return g, collectives.pmean(loss, (mesh_lib.AXIS_DATA,))

        g = jnp.ones((8, 16), jnp.float32)
        loss = jnp.asarray(1.0, jnp.float32)
        fn = jax.shard_map(grads_fn, mesh=mesh, in_specs=(P("data"), P()),
                           out_specs=(P("data"), P()), check_vma=False)
        with comm_accounting() as acct:
            jax.make_jaxpr(fn)(g, loss)
        axes = acct.by_axis()
        assert any("data" in k for k in axes), axes
        assert acct.total_bytes() > 0
    finally:
        mesh_lib.destroy_model_parallel()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

# -S skips sitecustomize (which can import an accelerator plugin and take
# seconds) so stub children start fast enough to beat short deadlines
PY = [sys.executable, "-S", "-c"]


def test_watchdog_healthy_child_ok():
    res = run_under_watchdog(PY + ["print('fine')"], deadline=30)
    assert res.status == "ok" and res.returncode == 0
    assert "fine" in res.stdout
    assert res.record is None and res.reason == ""


def test_watchdog_kills_hung_child_and_recovers_checkpoint():
    code = (
        "import json, os, time\n"
        "with open(os.environ['APEX_TPU_CHECKPOINT_PATH'], 'w') as f:\n"
        "    json.dump({'stage': 'resnet', 'value': 3.5}, f)\n"
        "time.sleep(60)\n"
    )
    t0 = time.time()
    res = run_under_watchdog(PY + [code], deadline=2, poll_s=0.1)
    assert time.time() - t0 < 30  # killed at the deadline, not the sleep
    assert res.status == "deadline"
    assert "deadline" in res.reason
    assert res.record == {"stage": "resnet", "value": 3.5}


def test_watchdog_heartbeat_stall_beats_deadline():
    """A child that beats once and then wedges is killed by the STALL
    check (with the hard deadline still far away) and the last beaten
    stage is named in the reason — 'wedged' vs 'slow but alive'."""
    code = (
        "import json, os, time\n"
        "hb = os.environ['APEX_TPU_HEARTBEAT_PATH']\n"
        "with open(hb, 'w') as f:\n"
        "    json.dump({'ts': time.time(), 'stage': 'selftest'}, f)\n"
        "time.sleep(60)\n"
    )
    t0 = time.time()
    res = run_under_watchdog(PY + [code], deadline=300, stall_timeout=1.5,
                             poll_s=0.1)
    assert time.time() - t0 < 30
    assert res.status == "stalled"
    assert "selftest" in res.reason
    assert res.heartbeat["stage"] == "selftest"


def test_watchdog_stall_with_no_beat_uses_start_time():
    res = run_under_watchdog(PY + ["import time; time.sleep(60)"],
                             deadline=300, stall_timeout=1.0, poll_s=0.1)
    assert res.status == "stalled"
    assert "<no beat yet>" in res.reason


def test_heartbeat_beat_and_read(tmp_path):
    path = str(tmp_path / "hb.json")
    hb = Heartbeat(path)
    hb.beat("stage1", record={"v": 1})
    got = Heartbeat.read(path)
    assert got["stage"] == "stage1" and got["record"] == {"v": 1}
    assert got["ts"] <= time.time()
    assert Heartbeat.read(str(tmp_path / "missing.json")) is None


def test_monitor_selftest_runs_green():
    from apex_tpu.monitor import selftest

    res = selftest.run()
    assert res["all_ok"], res


# ---------------------------------------------------------------------------
# integration hooks: amp grad-norm, bench journal plumbing
# ---------------------------------------------------------------------------


def test_amp_metrics_include_grad_norm_when_asked():
    import optax

    from apex_tpu import amp
    from apex_tpu.ops.multi_tensor import tree_l2norm

    params = {"w": jnp.ones((4, 4), jnp.float32) * 0.1}
    grads = {"w": jnp.ones((4, 4), jnp.float32) * 2.0}
    policy = amp.get_policy("O0")

    plain = amp.MixedPrecisionOptimizer(optax.sgd(0.1), policy)
    st = plain.init(params)
    _, _, metrics = plain.apply_gradients(st, params, grads)
    assert "grad_norm" not in metrics  # opt-in: default programs unchanged

    inst = amp.MixedPrecisionOptimizer(optax.sgd(0.1), policy,
                                       log_grad_norm=True)
    st = inst.init(params)
    _, _, metrics = inst.apply_gradients(st, params, grads)
    np.testing.assert_allclose(float(metrics["grad_norm"]),
                               float(tree_l2norm(grads)), rtol=1e-6)


def test_bench_timed_windows_journal(tmp_path, monkeypatch):
    """bench's shared window loop journals one record per window (wall
    time, units/s, loss, the step metrics) when BENCH_JOURNAL is armed —
    the CPU-side proof of the acceptance criterion's journal surface."""
    import bench

    path = str(tmp_path / "bench.jsonl")
    monkeypatch.setenv("BENCH_JOURNAL", path)
    monkeypatch.setattr(bench, "_JOURNAL", None)

    loss = jnp.asarray(2.0, jnp.float32)
    metrics = {"loss_scale": jnp.asarray(1024.0, jnp.float32),
               "found_inf": jnp.asarray(False),
               "grad_norm": jnp.asarray(0.25, jnp.float32)}
    rates = bench._timed_windows(
        lambda: None, lambda: loss, steps=2, windows=3,
        per_window_units=2048, label="gpt_O2",
        get_metrics=lambda: metrics)
    bench._JOURNAL.close()
    monkeypatch.setattr(bench, "_JOURNAL", None)
    assert len(rates) == 3
    rows = MetricsJournal.read(path)
    assert [r["window"] for r in rows] == [0, 1, 2]
    for r in rows:
        assert r["label"] == "gpt_O2"
        assert r["loss"] == 2.0
        assert r["loss_scale"] == 1024.0
        assert r["grad_norm"] == 0.25
        assert r["tokens"] == 2048 and r["tokens_per_sec"] > 0
        assert "hbm" in r  # occupancy sample rides every record


def test_bench_journal_disabled_by_default(monkeypatch):
    import bench

    monkeypatch.delenv("BENCH_JOURNAL", raising=False)
    monkeypatch.setattr(bench, "_JOURNAL", None)
    assert bench._get_journal() is None
    assert bench._state_metrics([1, 2, 3]) is None  # un-journaled state
    m = {"loss_scale": 1.0}
    assert bench._state_metrics([1, 2, 3, m])() is m


def test_bench_windows_carry_mfu_when_costs_registered(tmp_path, monkeypatch):
    """The GPT-rung path: prepare registers per-token costs once (one
    trace), then every timed window's journal record carries
    mfu/hbm_bw_util/bound — and unregistered labels (resnet/bert rungs)
    stay mfu-free."""
    import bench

    path = str(tmp_path / "mfu.jsonl")
    monkeypatch.setenv("BENCH_JOURNAL", path)
    monkeypatch.setattr(bench, "_JOURNAL", None)
    monkeypatch.setattr(bench, "_WINDOW_COSTS", {})
    monkeypatch.setenv("APEX_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("APEX_TPU_PEAK_HBM_GBPS", "100")

    batch, seq, hidden = 2, 8, 4

    def step(params, opt_state, tokens, targets):
        # toy "train step" with a real matmul so traced costs are nonzero
        h = jnp.einsum("bs,sh->bh", tokens.astype(jnp.float32), params)
        return params - 0.0 * h.sum(), opt_state, h.sum(), {}

    params = jnp.ones((seq, hidden), jnp.float32)
    bench._register_window_costs("gpt_O2", step, params, (), batch, seq)
    assert "gpt_O2" in bench._WINDOW_COSTS
    assert bench._WINDOW_COSTS["gpt_O2"]["flops_per_token"] > 0
    assert bench._WINDOW_COSTS["gpt_O2"]["spec"]["source"] == "env"

    loss = jnp.asarray(1.0, jnp.float32)
    bench._timed_windows(lambda: None, lambda: loss, steps=1, windows=2,
                         per_window_units=batch * seq, label="gpt_O2")
    bench._timed_windows(lambda: None, lambda: loss, steps=1, windows=1,
                         per_window_units=64, label="resnet50")
    bench._JOURNAL.close()
    monkeypatch.setattr(bench, "_JOURNAL", None)
    rows = MetricsJournal.read(path)
    gpt = [r for r in rows if r.get("label") == "gpt_O2"]
    other = [r for r in rows if r.get("label") == "resnet50"]
    assert len(gpt) == 2 and all("mfu" in r and "bound" in r for r in gpt)
    assert all(r["peak_source"] == "env" for r in gpt)
    assert other and all("mfu" not in r for r in other)


def test_rank_info_str_reflects_mesh():
    from apex_tpu.parallel import mesh as mesh_lib

    assert mesh_lib.get_rank_info_str() == ""
    mesh_lib.make_virtual_mesh(8, tensor_model_parallel_size=2,
                               pipeline_model_parallel_size=2)
    try:
        info = mesh_lib.get_rank_info_str()
        assert "pp2" in info and "tp2" in info and "dp2" in info
    finally:
        mesh_lib.destroy_model_parallel()
    assert mesh_lib.get_rank_info_str() == ""
