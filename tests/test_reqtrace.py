"""Request-scoped serving traces (ISSUE 17).

The tier-1 gate for the serve observability vertical: the serializable
TraceContext round-trips (the cross-worker handoff seam), per-request
TTFT/ITL attribution fractions sum to 1.0 per class, tail-based sampling
retains every SLO violator plus a deterministic 1-in-N compliant sample
(the rest folding into ONE bounded reqhist record), a disarmed engine
emits byte-identical token streams, journal request records carry
trace_id + attribution into report.analyze's serving-attribution rollup,
report.compare gates queue-fraction growth (and degrades a mixed
serve/train pair to a skip note while a crashed serve candidate still
fails), monitor.status surfaces the worst in-flight request, the
slo-burn alert names its dominant phase, the flight recorder dumps the
in-flight request table, Chrome export gives each sampled request its
own lane, and ledger regress gates attribution drift.
"""

import json

import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.monitor import report, tracing
from apex_tpu.monitor.journal import MetricsJournal
from apex_tpu.serve import Engine, Request, ServeConfig
from apex_tpu.serve.reqtrace import (
    HIST_EDGES_S,
    PhaseHistogram,
    TraceContext,
    attribution_fractions,
)

TINY = dict(vocab_size=41, hidden_size=16, num_layers=1,
            num_attention_heads=2, max_seq_len=32, hidden_dropout=0.0,
            axis=None, compute_dtype=jnp.float32, remat=False)
SCFG = dict(max_batch=2, max_seq=24, block_size=8)


def make_requests():
    return [Request(prompt=[3, 1, 4, 1, 5], max_new_tokens=4,
                    request_id="a"),
            Request(prompt=[2, 7], max_new_tokens=3, request_id="b"),
            Request(prompt=[6, 2, 8], max_new_tokens=3, request_id="c")]


def frac_sum(fr):
    return sum(v for k, v in fr.items() if k.endswith("_frac"))


class TestPureHelpers:
    def test_trace_context_round_trip(self):
        ctx = TraceContext.new("r1")
        assert ctx.trace_id.startswith("req-r1-")
        d = ctx.child("span-7").to_dict()
        back = TraceContext.from_dict(json.loads(json.dumps(d)))
        assert back.trace_id == ctx.trace_id
        assert back.parent_span == "span-7"
        assert TraceContext.new("r2").trace_id != ctx.trace_id

    def test_attribution_fractions_sum_and_clip(self):
        fr = attribution_fractions(
            1.0, {"queue": 0.25, "compute": 0.5, "barrier": 0.1},
            residual="prefill_serial")
        assert frac_sum(fr) == pytest.approx(1.0, abs=1e-9)
        assert fr["queue_frac"] == 0.25 and fr["compute_frac"] == 0.5
        # components clip cumulatively to the wall; residual floors at 0
        over = attribution_fractions(
            1.0, {"compute": 5.0, "barrier": 3.0}, residual="queue")
        assert over["compute_frac"] == 1.0 and over["barrier_frac"] == 0.0
        assert over["queue_frac"] == 0.0
        assert attribution_fractions(0.0, {"compute": 1.0},
                                     residual="queue") is None

    def test_phase_histogram_bounded(self):
        h = PhaseHistogram()
        assert h.empty
        for s in (1e-6, 1e-3, 0.5, 100.0):
            h.add("ttft", s)
        h.add("itl", 0.002)
        rec = h.record()
        assert rec["kind"] == "reqhist"
        assert rec["edges_s"] == list(HIST_EDGES_S)
        ttft = rec["phases"]["ttft"]
        assert len(ttft["counts"]) == len(HIST_EDGES_S) + 1
        assert ttft["n"] == sum(ttft["counts"]) == 4
        h.reset()
        assert h.empty


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """Three runs of the same tiny workload: every-request-violates
    (full retention), nothing-violates (1-in-2 sampling), disarmed."""
    model = GPTModel(GPTConfig(**TINY))
    params = model.init(jax.random.PRNGKey(0))
    d = tmp_path_factory.mktemp("reqtrace")

    vj = str(d / "violator.jsonl")
    eng_v = Engine(model, params, ServeConfig(
        slo_itl_ms=1e-6, trace_sample_n=10 ** 6, **SCFG))
    tr_v = tracing.Tracer(None, keep=True)
    with tracing.scoped(tr_v):
        with MetricsJournal(vj, meta={"run": "reqtrace_test"}) as j:
            res_v = eng_v.run(make_requests(), journal=j)

    eng_s = Engine(model, params, ServeConfig(
        slo_itl_ms=1e9, trace_sample_n=2, **SCFG))
    tr_s = tracing.Tracer(None, keep=True)
    with tracing.scoped(tr_s):
        res_s = eng_s.run(make_requests())

    eng_d = Engine(model, params, ServeConfig(**SCFG))
    res_d = eng_d.run(make_requests())
    return dict(vj=vj, eng_v=eng_v, tr_v=tr_v, res_v=res_v,
                eng_s=eng_s, tr_s=tr_s, res_s=res_s,
                eng_d=eng_d, res_d=res_d)


class TestEngineTracing:
    def test_violators_fully_retained(self, served):
        roots = [r for r in served["tr_v"].records
                 if r.get("name") == "serve.request"]
        assert len(roots) == 3
        assert served["eng_v"].trace_violators == 3
        assert all(r.get("sampled") == "slo_violation" for r in roots)
        kids = [r for r in served["tr_v"].records
                if r.get("cat") == "serve-req" and r.get("depth") == 1]
        names = {r["name"] for r in kids}
        assert {"req.queue", "req.prefill", "req.first_token_barrier",
                "req.decode_tick"} <= names, names
        assert all(r.get("request") for r in kids)

    def test_deterministic_sampling_and_histogram(self, served):
        roots = [r for r in served["tr_s"].records
                 if r.get("name") == "serve.request"]
        hists = [r for r in served["tr_s"].records
                 if r.get("kind") == "reqhist"]
        assert len(roots) == 2  # ceil(3/2) with trace_sample_n=2
        assert served["eng_s"].trace_sampled == 2
        assert len(hists) == 1
        ttft = hists[0]["phases"]["ttft"]
        assert ttft["n"] == 1  # the one non-sampled request folded here

    def test_disarmed_byte_identity_and_attribution(self, served):
        for rid, req in served["res_d"].items():
            assert req.tokens == served["res_v"][rid].tokens
            assert (req.trace or {}).get("trace_id")
            for fr in (req.attribution or {}).values():
                assert frac_sum(fr) == pytest.approx(1.0, abs=1e-3)

    def test_external_trace_context_propagates(self, served):
        """The ROADMAP item 4 seam: a context provided at submit rides
        through unchanged instead of being reassigned."""
        ext = Request(prompt=[2, 7], max_new_tokens=2, request_id="x",
                      trace={"trace_id": "upstream-1",
                             "parent_span": "root-span"})
        res = served["eng_d"].run([ext])
        assert res["x"].trace == {"trace_id": "upstream-1",
                                  "parent_span": "root-span"}

    def test_chrome_export_one_lane_per_request(self, served):
        chrome = tracing.chrome_trace(served["tr_v"].records)
        lanes = [e for e in chrome["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "thread_name"
                 and str((e.get("args") or {}).get("name", "")
                         ).startswith("request ")]
        assert len(lanes) == 3
        req_spans = [e for e in chrome["traceEvents"]
                     if e.get("ph") == "X"
                     and (e.get("args") or {}).get("request")]
        assert req_spans and all(e["tid"] >= 16 for e in req_spans)


class TestJournalAndReport:
    def test_request_records_carry_trace_and_attribution(self, served):
        rows = MetricsJournal.read(served["vj"])
        reqs = [r for r in rows if r.get("kind") == "request"]
        assert len(reqs) == 3
        for r in reqs:
            assert r.get("trace_id")
            for fr in (r.get("attribution") or {}).values():
                assert frac_sum(fr) == pytest.approx(1.0, abs=1e-3)
        attr = (report.analyze(rows).get("serving") or {}).get(
            "attribution") or {}
        assert set(attr) == {"ttft", "itl"}
        for row in attr.values():
            assert frac_sum(row) == pytest.approx(1.0, abs=1e-3)
            assert row["n"] == 3 and row["wall_s_mean"] > 0

    def test_compare_gates_queue_inflation_and_passes_self(self, served):
        rows = MetricsJournal.read(served["vj"])
        assert report.compare(rows, rows, threshold=0.1)["ok"]
        inflated = []
        for r in rows:
            r2 = dict(r)
            if r2.get("kind") == "request" and isinstance(
                    r2.get("attribution"), dict):
                at2 = {}
                for cls, fr in r2["attribution"].items():
                    fr2 = dict(fr)
                    fr2["queue_frac"] = min(
                        (fr.get("queue_frac") or 0.0) + 0.5, 1.0)
                    others = [k for k in fr2 if k.endswith("_frac")
                              and k != "queue_frac"]
                    rest = 1.0 - fr2["queue_frac"]
                    tot = sum(fr.get(k) or 0.0 for k in others) or 1.0
                    for k in others:
                        fr2[k] = round((fr.get(k) or 0.0) * rest / tot, 4)
                    at2[cls] = fr2
                r2["attribution"] = at2
            inflated.append(r2)
        res = report.compare(rows, inflated, threshold=0.1)
        assert not res["ok"]
        assert "itl_queue_frac" in res["regressed"]
        # ONLY attribution differs, so only the queue gates may trip
        assert set(res["regressed"]) <= {"ttft_queue_frac",
                                         "itl_queue_frac"}

    def test_compare_mixed_serve_train_pair_skips_with_note(self, served):
        rows = MetricsJournal.read(served["vj"])
        train = [{"kind": "meta", "run": "train"},
                 {"kind": "step", "step": 0, "loss": 2.0, "ts": 1.0},
                 {"kind": "step", "step": 1, "loss": 1.5, "ts": 2.0}]
        for a, b, which in ((rows, train, "b"), (train, rows, "a")):
            res = report.compare(a, b, threshold=0.1)
            assert res["ok"], res["regressed"]
            note = [c for c in res["checks"]
                    if c["check"] == "serve_requests" and c.get("skipped")]
            assert note and f"no serving records in {which}" in \
                note[0]["skipped"]
            assert not any(c["check"].endswith("_queue_frac")
                           for c in res["checks"])

    def test_compare_crashed_serve_candidate_still_fails(self, served):
        rows = MetricsJournal.read(served["vj"])
        crashed = [r for r in rows if r.get("kind") != "request"]
        res = report.compare(rows, crashed, threshold=0.1)
        assert "serve_requests" in res["regressed"]


class TestOperatorSurfaces:
    def test_status_once_json_machine_parseable(self, served, capsys):
        from apex_tpu.monitor import status

        rc = status.main([served["vj"], "--once", "--format", "json"])
        assert rc == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["step_records"] > 0
        assert isinstance(snap.get("queue_depth"), (int, float))
        assert isinstance((snap.get("slo") or {}).get("attainment"),
                          (int, float))
        wr = snap.get("worst_request")
        assert isinstance(wr, dict), snap
        assert wr.get("id") is not None
        assert wr.get("phase") in ("queued", "prefill", "decode")
        assert isinstance(wr.get("age_s"), (int, float))
        assert "slot" in wr

    def test_slo_burn_alert_names_dominant_phase(self, served):
        from apex_tpu.monitor import health

        rows = MetricsJournal.read(served["vj"])
        slo_rows = [r for r in rows if r.get("kind") == "slo"]
        assert slo_rows and all(
            r.get("dominant_phase") in ("queue", "prefill_serial",
                                        "compute", "barrier")
            for r in slo_rows)
        burns = [a for a in health.scan(rows) if a["rule"] == "slo-burn"]
        assert burns and "-dominated: " in burns[0]["message"]

    def test_flight_dump_carries_inflight_table(self, served, tmp_path):
        from apex_tpu.monitor import flight

        path = str(tmp_path / "reqtrace.flight.json")
        flight.arm(path, meta={"run": "reqtrace_test"}, hooks=False)
        seen = []

        def on_tick(engine):
            if not seen:  # dump once, mid-run, with slots occupied
                seen.append(flight.dump("test"))

        try:
            served["eng_d"].run(make_requests(), on_tick=on_tick)
        finally:
            flight.disarm()
        assert seen == [path]
        dumpd = flight.load(path)
        table = dumpd.get("inflight_requests")
        assert isinstance(table, list) and table
        for row in table:
            assert row.get("phase") in ("queued", "prefill", "decode")
            assert "id" in row and "age_s" in row
        # disarm cleared the provider: a later snapshot has no table
        assert not flight.armed()

    def test_ledger_regress_gates_attribution_drift(self, served,
                                                    tmp_path):
        from apex_tpu.monitor import ledger

        path = str(tmp_path / "ledger.jsonl")
        cfg = {"run": "reqtrace_test", "tp": 1}

        def measured(queue_frac):
            return {"step_records": 4, "serving": {
                "requests": 3,
                "attribution": {"ttft": {"n": 3, "wall_s_mean": 0.1,
                                         "queue_frac": queue_frac}}}}

        for q in (0.1, 0.1, 0.5):
            ledger.append_run(path, run="reqtrace_test", config=cfg,
                              measured=measured(q))
        res = ledger.regress(ledger.read(path))
        assert "ttft_queue_frac" in res["regressed"]
