"""Pipeline-parallel schedule tests.

Reference pattern: tests/L0/run_transformer/run_pipeline_parallel_test.py —
sweep {no_pipelining, 1F1B, interleaved} and assert loss parity; the SPMD
pipeline must match the serial model bit-for-tolerance (forward AND grads)
because it computes the identical function.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import allreduce_gradients_by_spec
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    pipeline_specs,
    pipelined_loss_fn,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    deinterleave_stack,
    interleave_stack,
)

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=4,
    num_attention_heads=4,
    max_seq_len=16,
    hidden_dropout=0.0,
    compute_dtype=jnp.float32,
    remat=False,
)


def _setup(pp, tp_size=1, **cfg_overrides):
    mesh = mesh_lib.make_virtual_mesh(
        pp * tp_size, tensor_model_parallel_size=tp_size,
        pipeline_model_parallel_size=pp,
    )
    axis = "model" if tp_size > 1 else None
    cfg = dict(TINY, **cfg_overrides)
    serial = GPTModel(GPTConfig(axis=None, **cfg))
    par = GPTModel(GPTConfig(axis=axis, **cfg))
    params = serial.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)
    return mesh, serial, par, params, toks, tgt


def _pipeline_value_and_grad(par, mesh, params, toks, tgt, M, vpp=1):
    specs = par.specs()
    layer_specs = pipeline_specs(specs["layers"])
    rest_specs = {k: v for k, v in specs.items() if k != "layers"}
    layers = params["layers"]
    if vpp > 1:
        layers = interleave_stack(layers, mesh.shape["pipe"], vpp)
    rest = {k: v for k, v in params.items() if k != "layers"}
    sharded_layers = tp.shard_params(layers, layer_specs, mesh)
    sharded_rest = tp.shard_params(rest, rest_specs, mesh)

    loss_fn = pipelined_loss_fn(
        embed=par.embed,
        run_layers=lambda lp, h: par.run_layers(lp, h),
        head_loss=lambda p, h, t: par.head(p, h, t),
        num_microbatches=M,
        virtual_pipeline_size=vpp,
    )

    def step(rest, layers, toks, tgt):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            rest, layers, toks, tgt
        )
        rest_g, layer_g = grads
        rest_g = allreduce_gradients_by_spec(rest_g, rest_specs)
        return loss, rest_g, layer_g

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(rest_specs, layer_specs, P(), P()),
        out_specs=(P(), rest_specs, layer_specs),
        check_vma=False,
    ))
    loss, rest_g, layer_g = fn(sharded_rest, sharded_layers, toks, tgt)
    layer_g = jax.device_get(layer_g)
    if vpp > 1:
        layer_g = deinterleave_stack(layer_g, mesh.shape["pipe"], vpp)
    return float(loss), jax.device_get(rest_g), layer_g


@pytest.mark.parametrize("pp,vpp", [(2, 1), (4, 1), (2, 2)])
def test_pipeline_matches_serial(pp, vpp):
    mesh, serial, par, params, toks, tgt = _setup(pp)
    try:
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        loss, rest_g, layer_g = _pipeline_value_and_grad(
            par, mesh, params, toks, tgt, M=4, vpp=vpp
        )
        np.testing.assert_allclose(float(v_s), loss, rtol=1e-5)
        for name in ("embedding", "position", "ln_f"):
            a = jax.tree.leaves(g_s[name])
            b = jax.tree.leaves(rest_g[name])
            for x, y in zip(a, b):
                np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4, atol=2e-4,
                                           err_msg=name)
        for x, y in zip(jax.tree.leaves(g_s["layers"]), jax.tree.leaves(layer_g)):
            np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_pipeline_with_tensor_parallel():
    """Hybrid PP×TP on 8 virtual devices (the gpt_scaling_test.py (2,1,4) /
    (1,2,4) configs)."""
    mesh, serial, par, params, toks, tgt = _setup(pp=2, tp_size=2)
    try:
        v_s = float(serial.loss(params, toks, tgt))
        loss, _, _ = _pipeline_value_and_grad(par, mesh, params, toks, tgt, M=2)
        np.testing.assert_allclose(v_s, loss, rtol=1e-5)
    finally:
        mesh_lib.destroy_model_parallel()


def test_no_pipelining_grad_accumulation_matches_full_batch():
    model = GPTModel(GPTConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)
    loss_fn = lambda p, b, t: model.loss(p, b, t)
    l_acc, g_acc = forward_backward_no_pipelining(loss_fn, params, toks, tgt, 4)
    l_full, g_full = jax.value_and_grad(model.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(l_full), float(l_acc), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_acc)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-5, atol=1e-6)


def test_interleave_stack_round_trip():
    layers = {"w": jnp.arange(8.0)[:, None] * jnp.ones((8, 3))}
    perm = interleave_stack(layers, 2, 2)
    # stage 0 (first half) must hold slabs 0 and 2; stage 1 slabs 1 and 3
    np.testing.assert_array_equal(np.asarray(perm["w"][:, 0]),
                                  [0, 1, 4, 5, 2, 3, 6, 7])
    back = deinterleave_stack(perm, 2, 2)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(layers["w"]))


def test_microbatch_calculators():
    c = build_num_microbatches_calculator(64, 4, 2)
    assert isinstance(c, ConstantNumMicroBatches)
    assert c.get() == 8
    r = build_num_microbatches_calculator(64, 4, 2, rampup_batch_size=[16, 16, 300])
    assert isinstance(r, RampupBatchsizeNumMicroBatches)
    assert r.get_current_global_batch_size() == 16
    r.update(150, True)
    assert r.get_current_global_batch_size() == 32
    r.update(400, True)
    assert r.get_current_global_batch_size() == 64
    assert r.get() == 8
    with pytest.raises(ValueError):
        build_num_microbatches_calculator(63, 4, 2)


def test_pipeline_o2_with_mesh_grad_scaler():
    """The dtype x grad-scaler leg of the reference sweep
    (run_pipeline_parallel_test.py:33-80): bf16 O2 pipelined step matches
    the serial O2 loss and the scaler stays on its clean-step schedule.
    (Uniform cross-stage skip is covered by test_mesh_grad_scaler.py on both
    the model and pipe axes.)"""
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    cfg = dict(TINY)
    cfg["compute_dtype"] = jnp.bfloat16
    mesh = mesh_lib.make_virtual_mesh(2, pipeline_model_parallel_size=2)
    try:
        serial = GPTModel(GPTConfig(axis=None, **cfg))
        par = GPTModel(GPTConfig(axis=None, **cfg))
        policy = amp.get_policy("O2")
        mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy)
        params = amp.cast_params(serial.init(jax.random.PRNGKey(0)), policy)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        tgt = jnp.roll(toks, -1, axis=-1)

        # serial O2 reference loss
        v_s = float(serial.loss(params, toks, tgt))

        specs = par.specs()
        layer_specs = pipeline_specs(specs["layers"])
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        all_specs = dict(rest_specs, layers=layer_specs)
        sharded = tp.shard_params(params, all_specs, mesh)
        opt_state = mp_opt.init(sharded)

        loss_fn = pipelined_loss_fn(
            embed=par.embed,
            run_layers=lambda lp, h: par.run_layers(lp, h),
            head_loss=lambda p, h, t: par.head(p, h, t),
            num_microbatches=4,
        )

        def sharded_grads(p, toks, tgt, scale):
            rest = {k: v for k, v in p.items() if k != "layers"}

            def scaled(rest, layers):
                return loss_fn(rest, layers, toks, tgt) * scale

            loss, (rg, lg) = jax.value_and_grad(scaled, argnums=(0, 1))(
                rest, p["layers"])
            rg = allreduce_gradients_by_spec(rg, rest_specs)
            return jax.lax.pmean(loss, "pipe"), dict(rg, layers=lg)

        shard_fn = jax.shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(all_specs, P(), P(), P()),
            out_specs=(P(), all_specs), check_vma=False)

        @jax.jit
        def train_step(params, opt_state, toks, tgt):
            sl, sg = shard_fn(params, toks, tgt, opt_state.scaler.loss_scale)
            np_, ns, m = mp_opt.apply_gradients(opt_state, params, sg)
            return np_, ns, sl / opt_state.scaler.loss_scale, m

        new_params, new_state, loss, metrics = train_step(
            sharded, opt_state, toks, tgt)
        np.testing.assert_allclose(float(loss), v_s, rtol=2e-5)
        assert not bool(metrics["found_inf"])
        assert float(new_state.scaler.loss_scale) == 2.0 ** 16
        # params actually moved
        delta = jnp.abs(
            new_params["position"].astype(jnp.float32)
            - jax.device_get(sharded["position"]).astype(jnp.float32)).max()
        assert float(delta) > 0
    finally:
        mesh_lib.destroy_model_parallel()


def test_deep_interleaved_pipeline_matches_serial():
    """The BASELINE config-5 shape at test scale: pp=4 with 2 virtual chunks
    per stage (8 layer slabs), loss AND all grads must match serial."""
    mesh, serial, par, params, toks, tgt = _setup(pp=4, num_layers=8)
    try:
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        loss, rest_g, layer_g = _pipeline_value_and_grad(
            par, mesh, params, toks, tgt, M=4, vpp=2)
        np.testing.assert_allclose(float(v_s), loss, rtol=1e-5)
        for name in ("embedding", "position", "ln_f"):
            for x, y in zip(jax.tree.leaves(g_s[name]),
                            jax.tree.leaves(rest_g[name])):
                np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4,
                                           atol=2e-4, err_msg=name)
        for x, y in zip(jax.tree.leaves(g_s["layers"]), jax.tree.leaves(layer_g)):
            np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.parametrize("schedule,unroll", [
    ("gpipe", False), ("1f1b", True),
    ("zero-bubble", False), ("zero-bubble", True),
], ids=["gpipe-scan", "1f1b-unroll", "zb-scan", "zb-unroll"])
def test_plan_executor_matches_serial(schedule, unroll):
    """The schedule-as-data COMPILED drive (schedule_grads_fn: one scan
    interpreting the plan arrays, explicit backward slots — the
    zero-bubble entries exercising the W/B-split VJP factoring) computes
    the serial model's loss AND grads, on the scan and unroll layer
    drives."""
    from apex_tpu.transformer.pipeline_parallel import (
        plan_schedule,
        schedule_grads_fn,
    )

    S, M = 2, 4
    mesh, serial, par, params, toks, tgt = _setup(
        S, unroll_layers=unroll)
    try:
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        specs = par.specs()
        layer_specs = pipeline_specs(specs["layers"])
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        rest = {k: v for k, v in params.items() if k != "layers"}
        layers_sh = tp.shard_params(params["layers"], layer_specs, mesh)

        fn = schedule_grads_fn(
            plan_schedule(schedule, M, S),
            embed=par.embed,
            run_layers=lambda lp, h: par.run_layers(lp, h),
            head_loss=lambda p, h, t: par.head(p, h, t))

        def step(rest, layers, toks, tgt):
            loss, rest_g, layer_g = fn(rest, layers, toks, tgt)
            rest_g = allreduce_gradients_by_spec(rest_g, rest_specs)
            return loss, rest_g, layer_g

        sm = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(rest_specs, layer_specs, P(), P()),
            out_specs=(P(), rest_specs, layer_specs), check_vma=False))
        loss, rest_g, layer_g = sm(rest, layers_sh, toks, tgt)
        np.testing.assert_allclose(float(v_s), float(loss), rtol=1e-5)
        for name in ("embedding", "position", "ln_f"):
            for x, y in zip(jax.tree.leaves(g_s[name]),
                            jax.tree.leaves(rest_g[name])):
                np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4,
                                           atol=2e-4, err_msg=name)
        for x, y in zip(jax.tree.leaves(g_s["layers"]),
                        jax.tree.leaves(layer_g)):
            np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4,
                                       atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_plan_executor_loss_scale_seeds_grads():
    """The executor's scale argument must scale loss AND grads exactly
    (the harness loss-scaling contract value_and_grad provides for
    free)."""
    from apex_tpu.transformer.pipeline_parallel import (
        plan_schedule,
        schedule_grads_fn,
    )

    S, M = 2, 2
    mesh, serial, par, params, toks, tgt = _setup(S)
    try:
        specs = par.specs()
        layer_specs = pipeline_specs(specs["layers"])
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        rest = {k: v for k, v in params.items() if k != "layers"}
        layers_sh = tp.shard_params(params["layers"], layer_specs, mesh)
        fn = schedule_grads_fn(
            plan_schedule("zero-bubble", M, S),
            embed=par.embed,
            run_layers=lambda lp, h: par.run_layers(lp, h),
            head_loss=lambda p, h, t: par.head(p, h, t))
        sm = jax.jit(jax.shard_map(
            lambda r, l, b, t, s: fn(r, l, b, t, s),
            mesh=mesh,
            in_specs=(rest_specs, layer_specs, P(), P(), P()),
            out_specs=(P(), rest_specs, layer_specs), check_vma=False),
            static_argnums=())
        l1, _, g1 = sm(rest, layers_sh, toks, tgt,
                       jnp.asarray(1.0, jnp.float32))
        l4, _, g4 = sm(rest, layers_sh, toks, tgt,
                       jnp.asarray(4.0, jnp.float32))
        np.testing.assert_allclose(float(l4), 4.0 * float(l1), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g4), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), 4.0 * np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
    finally:
        mesh_lib.destroy_model_parallel()


def _scan_lengths(jaxpr):
    """All lax.scan trip counts in a (closed) jaxpr, recursively."""

    lengths = []
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            lengths.append(eqn.params["length"])
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for item in vs:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    lengths.extend(_scan_lengths(item))
    return lengths


def test_interleaved_tick_count_shrinks_bubble():
    """The interleaved schedule must run in vpp*M + S - 1 ticks, strictly
    fewer than the vpp*(M + S - 1) of sequential per-chunk rings (the
    reference's whole reason for fwd_bwd_pipelining_with_interleaving)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_tick_count,
    )

    S, M, vpp = 4, 4, 2
    assert pipeline_tick_count(M, S, vpp) == vpp * M + S - 1 == 11
    assert pipeline_tick_count(M, S, vpp) < vpp * (M + S - 1) == 14

    # and the traced program really scans that many ticks
    mesh, serial, par, params, toks, tgt = _setup(pp=S, num_layers=8)
    try:
        specs = par.specs()
        layer_specs = pipeline_specs(specs["layers"])
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        layers = interleave_stack(params["layers"], S, vpp)
        rest = {k: v for k, v in params.items() if k != "layers"}

        loss_fn = pipelined_loss_fn(
            embed=par.embed,
            run_layers=lambda lp, h: par.run_layers(lp, h),
            head_loss=lambda p, h, t: par.head(p, h, t),
            num_microbatches=M,
            virtual_pipeline_size=vpp,
        )
        fn = jax.shard_map(
            loss_fn, mesh=mesh,
            in_specs=(rest_specs, layer_specs, P(), P()),
            out_specs=P(), check_vma=False,
        )
        jaxpr = jax.make_jaxpr(fn)(rest, layers, toks, tgt)
        lengths = _scan_lengths(jaxpr)
        assert lengths, "no scan found in pipelined loss"
        assert max(lengths) == pipeline_tick_count(M, S, vpp)
        assert vpp * (M + S - 1) not in lengths
    finally:
        mesh_lib.destroy_model_parallel()


def test_sharded_head_flops_match_serial():
    """With the pipe-sharded LM head, total pipelined FLOPs at pp=4 must be
    within ~1.15x of the serial step (VERDICT round-1 criterion); with the
    replicated head they are several x (head paid S times)."""
    S, M = 4, 16
    cfg = dict(TINY, vocab_size=2048, num_layers=4)
    mesh = mesh_lib.make_virtual_mesh(S, pipeline_model_parallel_size=S)
    try:
        serial = GPTModel(GPTConfig(axis=None, **cfg))
        par = GPTModel(GPTConfig(axis=None, **cfg))
        params = serial.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (32, 16), 0, 2048)
        tgt = jnp.roll(toks, -1, axis=-1)

        def compiled_flops(compiled):
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax returns [dict]
                ca = ca[0]
            return ca["flops"]

        serial_flops = compiled_flops(
            jax.jit(jax.value_and_grad(serial.loss))
            .lower(params, toks, tgt).compile()
        )

        specs = par.specs()
        layer_specs = pipeline_specs(specs["layers"])
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        rest = {k: v for k, v in params.items() if k != "layers"}

        def per_device_flops(shard_head):
            loss_fn = pipelined_loss_fn(
                embed=par.embed,
                run_layers=lambda lp, h: par.run_layers(lp, h),
                head_loss=lambda p, h, t: par.head(p, h, t),
                num_microbatches=M,
                shard_head=shard_head,
            )
            fn = jax.jit(jax.shard_map(
                lambda r, l, b, t: jax.value_and_grad(loss_fn, argnums=(0, 1))(
                    r, l, b, t),
                mesh=mesh,
                in_specs=(rest_specs, layer_specs, P(), P()),
                out_specs=(P(), (rest_specs, layer_specs)),
                check_vma=False,
            ))
            return compiled_flops(
                fn.lower(rest, params["layers"], toks, tgt).compile())

        # cost_analysis reports the per-device SPMD program; x S for totals
        sharded_total = per_device_flops(True) * S
        replicated_total = per_device_flops(False) * S
        assert sharded_total <= 1.15 * serial_flops, (
            f"sharded-head pipeline {sharded_total/serial_flops:.2f}x serial")
        assert replicated_total >= 2.0 * serial_flops, (
            "replicated head should cost ~S x the serial head; got "
            f"{replicated_total/serial_flops:.2f}x — test no longer discriminates")
    finally:
        mesh_lib.destroy_model_parallel()
