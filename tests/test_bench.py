"""Unit tests for bench.py's degradation machinery (no TPU, no heavy
compute): the OOM-cause chain walk, the headline salvage contract (the O2
value must survive an unplaceable fp32 baseline — VERDICT r3 ask #1), and
the degraded-rung ladder. The measurement paths themselves are exercised
on-chip by the driver's bench run.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def test_stats_median_min_max():
    s = bench._stats([3.0, 1.0, 2.0])
    assert s == {"median": 2.0, "min": 1.0, "max": 3.0, "windows": 3}
    s = bench._stats([4.0, 1.0, 2.0, 3.0])
    assert s["median"] == 2.5


def test_qcomm_env_value_mapping(monkeypatch):
    """BENCH_QCOMM: '1' aliases int8, explicit dtypes pass through,
    unset/empty means the exact fp32 wire."""
    monkeypatch.delenv("BENCH_QCOMM", raising=False)
    assert bench._qcomm_env() is None
    monkeypatch.setenv("BENCH_QCOMM", "")
    assert bench._qcomm_env() is None
    monkeypatch.setenv("BENCH_QCOMM", "1")
    assert bench._qcomm_env() == "int8"
    monkeypatch.setenv("BENCH_QCOMM", "e5m2")
    assert bench._qcomm_env() == "e5m2"
    monkeypatch.setenv("BENCH_QCOMM", "INT8")
    assert bench._qcomm_env() == "int8"


def test_is_oom_walks_cause_chain():
    assert bench._is_oom(RuntimeError("RESOURCE_EXHAUSTED: TPU oom"))
    # the ladder re-raises with the allocator message embedded
    assert bench._is_oom(RuntimeError("O2: OOM even at batch 1; last: x"))
    inner = ValueError("RESOURCE_EXHAUSTED: hbm")
    outer = RuntimeError("wrapper without the marker")
    outer.__cause__ = inner
    assert bench._is_oom(outer)
    assert not bench._is_oom(ValueError("unrelated failure"))


def _stats_of(m):
    return {"median": m, "min": m, "max": m, "windows": 3}


def test_headline_evidence_full_record(monkeypatch):
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(100.0), _stats_of(40.0), 8, True))
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert errs == {}
    assert frag["value"] == 100.0
    assert frag["vs_baseline"] == 2.5
    assert frag["spread"]["interleaved"] is True
    assert "effective_batch" not in frag  # common == requested batch


def test_headline_evidence_salvages_value_without_baseline(monkeypatch):
    """When the fp32 leg is unplaceable, the O2 value is still reported
    and vs_baseline is omitted with an errors.baseline note — losing the
    ratio must not lose the headline."""
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(100.0), None, 4, False))
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert frag["value"] == 100.0
    assert "vs_baseline" not in frag
    assert frag["effective_batch"] == 4
    assert "baseline" in errs


def test_headline_evidence_records_total_failure(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("O2: OOM even at batch 1; last: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "gpt_headline", boom)
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert frag == {}
    assert "headline" in errs


def test_headline_evidence_reraises_non_oom(monkeypatch):
    def boom(*a, **k):
        raise ValueError("a real bug, not memory pressure")

    monkeypatch.setattr(bench, "gpt_headline", boom)
    with pytest.raises(ValueError):
        bench._gpt_headline_evidence(8, 1024, 10)


def test_watchdog_passes_through_child_json(monkeypatch, capsys):
    """A healthy child's JSON line is printed verbatim."""
    # -S skips sitecustomize (which imports the axon plugin and takes
    # seconds) so the stub children start fast enough to beat the deadline
    code = "import json; print(json.dumps({'value': 42}))"
    monkeypatch.setenv("BENCH_DEADLINE", "30")
    rc = bench._watchdog(cmd=[sys.executable, "-S", "-c", code])
    assert rc == 0
    assert '"value": 42' in capsys.readouterr().out


def test_watchdog_prints_partial_on_hang(monkeypatch, capsys):
    """A WEDGED child (the r5 tunnel regime: device calls never return)
    is killed at the deadline and its last per-stage checkpoint is
    printed with a watchdog error — the JSON line survives no matter
    what."""
    import json as _json

    code = (
        "import json, os, time\n"
        "with open(os.environ['BENCH_PARTIAL_PATH'], 'w') as f:\n"
        "    json.dump({'value': 7.0, 'metric': 'm'}, f)\n"
        "time.sleep(60)\n"
    )
    monkeypatch.setenv("BENCH_DEADLINE", "5")
    rc = bench._watchdog(cmd=[sys.executable, "-S", "-c", code])
    assert rc == 0
    rec = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 7.0
    assert "watchdog" in rec["errors"]


def test_watchdog_recovers_partial_on_child_crash(monkeypatch, capsys):
    """A child that DIES with no stdout (segfault/abort in the native
    plugin) must not end the round with no JSON line — the partial
    checkpoint is recovered exactly as in the hang case."""
    import json as _json

    code = (
        "import json, os, sys\n"
        "with open(os.environ['BENCH_PARTIAL_PATH'], 'w') as f:\n"
        "    json.dump({'value': 9.0, 'metric': 'm'}, f)\n"
        "os._exit(134)\n"  # simulated SIGABRT death, nothing printed
    )
    monkeypatch.setenv("BENCH_DEADLINE", "30")
    rc = bench._watchdog(cmd=[sys.executable, "-S", "-c", code])
    assert rc == 0
    rec = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 9.0
    assert "no JSON line" in rec["errors"]["watchdog"]


def test_watchdog_hang_before_any_checkpoint(monkeypatch, capsys):
    import json as _json

    monkeypatch.setenv("BENCH_DEADLINE", "2")
    rc = bench._watchdog(
        cmd=[sys.executable, "-S", "-c", "import time; time.sleep(30)"])
    assert rc == 0
    rec = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "watchdog" in rec["errors"]


def test_o0_evidence_success(monkeypatch):
    """The fresh-process fp32 leg returns stats + the batch it landed at
    (the parent states both batches when computing the per-token ratio)."""
    rung = {"remat": "full", "scan": 8, "unroll": True}
    monkeypatch.setattr(bench, "measure_resilient",
                        lambda *a, **k: ([40.0, 41.0, 42.0], 4, rung))
    frag, errs = bench._gpt_o0_evidence(8, 1024, 10)
    assert errs == {}
    assert frag["o0"]["median"] == 41.0
    assert frag["o0"]["batch"] == 4
    assert frag["o0"]["rung"] == rung  # the record shows WHICH rung ran


def test_o0_evidence_records_oom(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("O0: OOM even at batch 1; last: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "measure_resilient", boom)
    frag, errs = bench._gpt_o0_evidence(8, 1024, 10)
    assert frag == {}
    assert "o0_baseline" in errs


def test_o0_evidence_reraises_non_oom(monkeypatch):
    def boom(*a, **k):
        raise ValueError("a real bug, not memory pressure")

    monkeypatch.setattr(bench, "measure_resilient", boom)
    with pytest.raises(ValueError):
        bench._gpt_o0_evidence(8, 1024, 10)


def test_degraded_evidence_falls_to_smaller_rung(monkeypatch):
    calls = []

    def fake(batch, seq, steps, windows=3, hidden=None, layers=None):
        calls.append((hidden, layers))
        if hidden == 768:
            raise RuntimeError("O2: OOM even at batch 1; last: RESOURCE_EXHAUSTED")
        return _stats_of(50.0), _stats_of(25.0), 2, True

    monkeypatch.setattr(bench, "gpt_headline", fake)
    frag, errs = bench._gpt_degraded_evidence(4, 1024, 10)
    assert calls == [(768, 12), (512, 4)]
    d = frag["gpt_degraded"]
    assert d["hidden"] == 512 and d["layers"] == 4
    assert d["tokens_per_sec"] == 50.0 and d["vs_baseline"] == 2.0
    # the 768 failure is recorded even though the 512 rung succeeded
    assert "gpt_degraded" in errs


def test_degraded_evidence_handles_missing_baseline(monkeypatch):
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(50.0), None, 2, False))
    frag, _ = bench._gpt_degraded_evidence(4, 1024, 10)
    d = frag["gpt_degraded"]
    assert d["tokens_per_sec"] == 50.0
    assert "vs_baseline" not in d and "o0" not in d["spread"]


# -- BERT + profile degraded-rung ladders (VERDICT r5 top_next: every
# flagship config must carry a number with rung provenance, not an errors
# entry, under simulated co-tenant OOM) ------------------------------------


def _oom(msg="RESOURCE_EXHAUSTED: simulated co-tenant occupation"):
    raise RuntimeError(msg)


def test_bert_resilient_flagship_passes_through():
    """A healthy flagship run gains NO degraded marker."""
    def measure(batch, steps, windows, hidden=None, layers=None):
        assert hidden is None and layers is None
        return dict(_stats_of(9000.0), batch=8, unroll=True)

    rec = bench.bench_bert_resilient(8, 10, 3, measure=measure)
    assert rec["median"] == 9000.0
    assert "degraded" not in rec


def test_bert_resilient_degrades_with_provenance():
    """Flagship OOM (even at batch 1) → the 768/12 rung's number is
    recorded WITH rung provenance including the flagship's OOM message."""
    calls = []

    def measure(batch, steps, windows, hidden=None, layers=None):
        calls.append((hidden, layers))
        if hidden is None:
            _oom("bert: OOM even at batch 1; last: RESOURCE_EXHAUSTED")
        return dict(_stats_of(4000.0), batch=4, unroll=True)

    rec = bench.bench_bert_resilient(8, 10, 3, measure=measure)
    assert calls == [(None, None), (768, 12)]
    assert rec["median"] == 4000.0
    assert rec["degraded"]["hidden"] == 768
    assert rec["degraded"]["layers"] == 12
    assert "RESOURCE_EXHAUSTED" in rec["degraded"]["flagship_oom"]


def test_bert_resilient_exhausted_ladder_raises_oom_marker():
    def measure(batch, steps, windows, hidden=None, layers=None):
        _oom()

    with pytest.raises(RuntimeError, match="smallest degraded rung"):
        bench.bench_bert_resilient(8, 10, 3, measure=measure)


def test_bert_resilient_reraises_non_oom():
    def measure(batch, steps, windows, hidden=None, layers=None):
        raise ValueError("a real bug, not memory pressure")

    with pytest.raises(ValueError):
        bench.bench_bert_resilient(8, 10, 3, measure=measure)


def test_profile_evidence_degrades_with_provenance(monkeypatch):
    """The --gpt-profile leg: flagship-shape OOM (the whole internal remat/
    batch ladder exhausted) → the 768/12 rung's profile is the record, with
    rung provenance, and the leg reports NO error."""
    def fake_profile(batch, seq, steps=3, hidden=None, layers=None):
        if hidden is None:
            return None, {"pyprof_345m": "RESOURCE_EXHAUSTED: hbm"}
        return {"model": f"gpt_h{hidden}_L{layers}", "batch": batch,
                "seq": seq, "total_ms": 42.0}, {}

    monkeypatch.setattr(bench, "_profile_345m", fake_profile)
    frag, errs = bench._gpt_profile_evidence(8, 1024, 10)
    assert errs == {}
    prof = frag["pyprof_scope_seconds"]
    assert prof["total_ms"] == 42.0
    assert prof["degraded"]["hidden"] == 768
    assert "RESOURCE_EXHAUSTED" in prof["degraded"]["flagship_oom"]


def test_profile_evidence_flagship_passes_through(monkeypatch):
    monkeypatch.setattr(
        bench, "_profile_345m",
        lambda batch, seq, steps=3, hidden=None, layers=None: (
            {"model": "gpt2_345m", "total_ms": 260.0}, {}))
    frag, errs = bench._gpt_profile_evidence(8, 1024, 10)
    assert errs == {}
    assert frag["pyprof_scope_seconds"]["total_ms"] == 260.0
    assert "degraded" not in frag["pyprof_scope_seconds"]


def test_profile_evidence_all_rungs_oom(monkeypatch):
    monkeypatch.setattr(
        bench, "_profile_345m",
        lambda batch, seq, steps=3, hidden=None, layers=None: (
            None, {"pyprof_345m": "RESOURCE_EXHAUSTED: hbm"}))
    frag, errs = bench._gpt_profile_evidence(8, 1024, 10)
    assert frag == {}
    assert "OOM at every profile rung" in errs["pyprof_345m"]


def test_profile_evidence_non_tpu_noop(monkeypatch):
    """Off-TPU the profile returns (None, {}) — no degradation loop, no
    error entry."""
    monkeypatch.setattr(
        bench, "_profile_345m",
        lambda batch, seq, steps=3, hidden=None, layers=None: (None, {}))
    frag, errs = bench._gpt_profile_evidence(8, 1024, 10)
    assert frag == {} and errs == {}
