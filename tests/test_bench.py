"""Unit tests for bench.py's degradation machinery (no TPU, no heavy
compute): the OOM-cause chain walk, the headline salvage contract (the O2
value must survive an unplaceable fp32 baseline — VERDICT r3 ask #1), and
the degraded-rung ladder. The measurement paths themselves are exercised
on-chip by the driver's bench run.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def test_stats_median_min_max():
    s = bench._stats([3.0, 1.0, 2.0])
    assert s == {"median": 2.0, "min": 1.0, "max": 3.0, "windows": 3}
    s = bench._stats([4.0, 1.0, 2.0, 3.0])
    assert s["median"] == 2.5


def test_is_oom_walks_cause_chain():
    assert bench._is_oom(RuntimeError("RESOURCE_EXHAUSTED: TPU oom"))
    # the ladder re-raises with the allocator message embedded
    assert bench._is_oom(RuntimeError("O2: OOM even at batch 1; last: x"))
    inner = ValueError("RESOURCE_EXHAUSTED: hbm")
    outer = RuntimeError("wrapper without the marker")
    outer.__cause__ = inner
    assert bench._is_oom(outer)
    assert not bench._is_oom(ValueError("unrelated failure"))


def _stats_of(m):
    return {"median": m, "min": m, "max": m, "windows": 3}


def test_headline_evidence_full_record(monkeypatch):
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(100.0), _stats_of(40.0), 8, True))
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert errs == {}
    assert frag["value"] == 100.0
    assert frag["vs_baseline"] == 2.5
    assert frag["spread"]["interleaved"] is True
    assert "effective_batch" not in frag  # common == requested batch


def test_headline_evidence_salvages_value_without_baseline(monkeypatch):
    """When the fp32 leg is unplaceable, the O2 value is still reported
    and vs_baseline is omitted with an errors.baseline note — losing the
    ratio must not lose the headline."""
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(100.0), None, 4, False))
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert frag["value"] == 100.0
    assert "vs_baseline" not in frag
    assert frag["effective_batch"] == 4
    assert "baseline" in errs


def test_headline_evidence_records_total_failure(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("O2: OOM even at batch 1; last: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "gpt_headline", boom)
    frag, errs = bench._gpt_headline_evidence(8, 1024, 10)
    assert frag == {}
    assert "headline" in errs


def test_headline_evidence_reraises_non_oom(monkeypatch):
    def boom(*a, **k):
        raise ValueError("a real bug, not memory pressure")

    monkeypatch.setattr(bench, "gpt_headline", boom)
    with pytest.raises(ValueError):
        bench._gpt_headline_evidence(8, 1024, 10)


def test_o0_evidence_success(monkeypatch):
    """The fresh-process fp32 leg returns stats + the batch it landed at
    (the parent states both batches when computing the per-token ratio)."""
    monkeypatch.setattr(bench, "measure_resilient",
                        lambda *a, **k: ([40.0, 41.0, 42.0], 4))
    frag, errs = bench._gpt_o0_evidence(8, 1024, 10)
    assert errs == {}
    assert frag["o0"]["median"] == 41.0
    assert frag["o0"]["batch"] == 4


def test_o0_evidence_records_oom(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("O0: OOM even at batch 1; last: RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "measure_resilient", boom)
    frag, errs = bench._gpt_o0_evidence(8, 1024, 10)
    assert frag == {}
    assert "o0_baseline" in errs


def test_o0_evidence_reraises_non_oom(monkeypatch):
    def boom(*a, **k):
        raise ValueError("a real bug, not memory pressure")

    monkeypatch.setattr(bench, "measure_resilient", boom)
    with pytest.raises(ValueError):
        bench._gpt_o0_evidence(8, 1024, 10)


def test_degraded_evidence_falls_to_smaller_rung(monkeypatch):
    calls = []

    def fake(batch, seq, steps, windows=3, hidden=None, layers=None):
        calls.append((hidden, layers))
        if hidden == 768:
            raise RuntimeError("O2: OOM even at batch 1; last: RESOURCE_EXHAUSTED")
        return _stats_of(50.0), _stats_of(25.0), 2, True

    monkeypatch.setattr(bench, "gpt_headline", fake)
    frag, errs = bench._gpt_degraded_evidence(4, 1024, 10)
    assert calls == [(768, 12), (512, 4)]
    d = frag["gpt_degraded"]
    assert d["hidden"] == 512 and d["layers"] == 4
    assert d["tokens_per_sec"] == 50.0 and d["vs_baseline"] == 2.0
    # the 768 failure is recorded even though the 512 rung succeeded
    assert "gpt_degraded" in errs


def test_degraded_evidence_handles_missing_baseline(monkeypatch):
    monkeypatch.setattr(bench, "gpt_headline", lambda *a, **k: (
        _stats_of(50.0), None, 2, False))
    frag, _ = bench._gpt_degraded_evidence(4, 1024, 10)
    d = frag["gpt_degraded"]
    assert d["tokens_per_sec"] == 50.0
    assert "vs_baseline" not in d and "o0" not in d["spread"]
