"""Native runtime tests (reference: apex_C flatten/unflatten used by
tests/distributed/DDP; loader covered by example recipes)."""

import numpy as np
import pytest

from apex_tpu import csrc


def test_native_library_builds():
    """g++ is baked into the image: the native path must actually load."""
    assert csrc.available()


def _arrays():
    rng = np.random.default_rng(0)
    return [
        rng.standard_normal((17, 3)).astype(np.float32),
        rng.integers(0, 100, (5,)).astype(np.int64),
        rng.standard_normal((2, 2, 2)).astype(np.float64),
        np.asarray(rng.standard_normal((8,)), dtype=np.float16),
    ]


def test_flatten_unflatten_roundtrip():
    arrays = _arrays()
    flat = csrc.flatten(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = csrc.unflatten(flat, arrays)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_flatten_matches_python_fallback():
    arrays = _arrays()
    native = csrc.flatten(arrays, threads=4)
    manual = np.concatenate([a.view(np.uint8).reshape(-1) for a in arrays])
    np.testing.assert_array_equal(native, manual)


def test_unflatten_size_mismatch_errors():
    with pytest.raises(ValueError):
        csrc.unflatten(np.zeros(10, np.uint8), [np.zeros((4,), np.float32)])


def test_token_loader_streams_all_batches(tmp_path):
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 1000, (3 * 64 + 10,)).astype(np.int32)  # ragged tail
    # shard across two files with an uneven split
    (tmp_path / "a.bin").write_bytes(tokens[:100].tobytes())
    (tmp_path / "b.bin").write_bytes(tokens[100:].tobytes())

    loader = csrc.TokenLoader(
        [tmp_path / "a.bin", tmp_path / "b.bin"], batch_shape=(4, 16))
    batches = list(loader)
    loader.close()
    assert len(batches) == 3  # 202 tokens -> 3 full 64-token batches
    got = np.concatenate([b.reshape(-1) for b in batches])
    np.testing.assert_array_equal(got, tokens[: 3 * 64])


def test_token_loader_loop_mode(tmp_path):
    tokens = np.arange(32, dtype=np.int32)
    (tmp_path / "t.bin").write_bytes(tokens.tobytes())
    loader = csrc.TokenLoader([tmp_path / "t.bin"], batch_shape=(16,), loop=True)
    it = iter(loader)
    first = next(it)
    np.testing.assert_array_equal(first, np.arange(16))
    for _ in range(5):  # wraps repeatedly without exhausting
        batch = next(it)
        assert batch.shape == (16,)
    loader.close()


def test_token_loader_concurrent_iterators_independent(tmp_path):
    tokens = np.arange(64, dtype=np.int32)
    (tmp_path / "t.bin").write_bytes(tokens.tobytes())
    loader = csrc.TokenLoader([tmp_path / "t.bin"], batch_shape=(16,))
    it1, it2 = iter(loader), iter(loader)
    a1 = next(it1)
    b1 = next(it2)  # starting it2 must not kill it1's stream
    a2 = next(it1)
    np.testing.assert_array_equal(a1, tokens[:16])
    np.testing.assert_array_equal(b1, tokens[:16])
    np.testing.assert_array_equal(a2, tokens[16:32])
    loader.close()


def test_token_loader_python_fallback_equivalence(tmp_path):
    tokens = np.arange(200, dtype=np.int32)
    (tmp_path / "t.bin").write_bytes(tokens.tobytes())
    native = list(csrc.TokenLoader([tmp_path / "t.bin"], batch_shape=(8, 8)))
    fb = csrc.TokenLoader([tmp_path / "t.bin"], batch_shape=(8, 8))
    fb._lib = None  # force python path
    python = list(fb)
    assert len(native) == len(python) == 3
    for a, b in zip(native, python):
        np.testing.assert_array_equal(a, b)
