"""Model zoo tests.

Reference patterns: tests/L0/run_mlp/test_mlp.py (MLP vs sequential Linear),
tests/L0/run_transformer/run_gpt_minimal_test.py (GPT runs + loss sane),
serial-vs-sharded equivalence as in run_layers_test.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import MLP, FusedDense, FusedDenseGeluDense, GPTConfig, GPTModel
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    hidden_dropout=0.0,
    compute_dtype=jnp.float32,
    remat=False,
)


def _data(key, batch=4, seq=16, vocab=64):
    toks = jax.random.randint(key, (batch, seq), 0, vocab)
    return toks, jnp.roll(toks, -1, axis=-1)


def test_gpt_serial_forward_and_loss():
    model = GPTModel(GPTConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    logits = model.apply(params, toks)
    assert logits.shape == (4, 16, 64)
    loss = model.loss(params, toks, tgt)
    assert 3.0 < float(loss) < 6.0  # ~ln(64)=4.16 at init


def test_gpt_unroll_matches_scan():
    """unroll_layers drives the SAME stacked params with static slices;
    loss AND grads must match the lax.scan drive (the on-chip win is the
    scan backward's dynamic-update-slice grad stacking, not different
    math — PERF_NOTES r5)."""
    scan_m = GPTModel(GPTConfig(axis=None, **TINY))
    unroll_m = GPTModel(GPTConfig(axis=None, unroll_layers=True, **TINY))
    params = scan_m.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    l_s, g_s = jax.value_and_grad(scan_m.loss)(params, toks, tgt)
    l_u, g_u = jax.value_and_grad(unroll_m.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(l_s), float(l_u), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_u)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_gpt_unroll_matches_scan_remat_and_dropout():
    """Equivalence holds with remat on and REAL dropout: the unrolled
    branch must consume the same per-layer split keys in the same order —
    with a nonzero rate, any key reordering/reuse changes the loss."""
    cfg = dict(TINY)
    cfg.pop("remat")
    cfg["hidden_dropout"] = 0.1
    scan_m = GPTModel(GPTConfig(axis=None, remat=True, **cfg))
    unroll_m = GPTModel(
        GPTConfig(axis=None, remat=True, unroll_layers=True, **cfg))
    params = scan_m.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(7)
    l_s = float(scan_m.loss(params, toks, tgt, dropout_key=key))
    l_u = float(unroll_m.loss(params, toks, tgt, dropout_key=key))
    np.testing.assert_allclose(l_s, l_u, rtol=1e-6)
    # sanity: the key actually matters at rate 0.1 (guards against the
    # comparison passing vacuously)
    l_k2 = float(scan_m.loss(params, toks, tgt,
                             dropout_key=jax.random.PRNGKey(8)))
    assert abs(l_k2 - l_s) > 1e-7


def test_gpt_tp_matches_serial():
    serial = GPTModel(GPTConfig(axis=None, **TINY))
    par = GPTModel(GPTConfig(axis="model", **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        specs = par.specs()
        sharded = tp.shard_params(params, specs, mesh)
        fn = jax.jit(jax.shard_map(
            jax.value_and_grad(par.loss), mesh=mesh,
            in_specs=(specs, P(), P()), out_specs=(P(), specs), check_vma=False,
        ))
        v_p, g_p = fn(sharded, toks, tgt)
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
        flat_s, _ = jax.tree_util.tree_flatten(g_s)
        flat_p, _ = jax.tree_util.tree_flatten(jax.device_get(g_p))
        for a, b in zip(flat_s, flat_p):
            np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.parametrize("pos,unroll", [
    ("learned", False),
    # one combined variant keeps the tier-1 wall-clock budget: rope
    # (positions enter on the GATHERED sequence inside attention — the
    # sequence-parallel shard offset must NOT leak into them) + unroll
    # (the gathers/reduce-scatters thread a Python loop body instead of a
    # scanned one)
    ("rope", True),
])
def test_gpt_sequence_parallel_matches_serial_and_tp(pos, unroll):
    """ISSUE 4 equivalence gate: serial, plain TP, and sequence-parallel
    TP share the same modules and must agree on loss AND grads. The SP
    path swaps every forward TP all-reduce for the reduce-scatter/
    all-gather conjugates and runs LN/dropout/residual sequence-sharded —
    including the vocab-parallel embedding scatter and the LM-head gather
    at the two ends."""
    cfg = dict(TINY, position_embedding=pos, unroll_layers=unroll)
    serial = GPTModel(GPTConfig(axis=None, **cfg))
    seqp = GPTModel(GPTConfig(axis="model", sequence_parallel=True, **cfg))
    params = serial.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))

    # the full 3-way gate runs once (tier-1 wall-clock budget); the rope+
    # unroll combo pins SP==serial, with SP==plain following transitively
    # through test_gpt_tp_matches_serial
    models = [seqp]
    if (pos, unroll) == ("learned", False):
        models.insert(0, GPTModel(GPTConfig(axis="model", **cfg)))

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        specs = seqp.specs()
        sharded = tp.shard_params(params, specs, mesh)
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        for model in models:
            fn = jax.jit(jax.shard_map(
                jax.value_and_grad(model.loss), mesh=mesh,
                in_specs=(specs, P(), P()), out_specs=(P(), specs),
                check_vma=False))
            v_p, g_p = fn(sharded, toks, tgt)
            np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
            for a, b in zip(jax.tree.leaves(g_s),
                            jax.tree.leaves(jax.device_get(g_p))):
                np.testing.assert_allclose(a, np.asarray(b),
                                           rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_gpt_sequence_parallel_with_context_axis_matches_serial():
    """SP composes with context parallelism: tokens sharded over 'context'
    (dim 1), each context shard further sequence-sharded over 'model' by
    the embedding reduce-scatter — the learned-position offsets compose
    (TransformerBase._seq_shard_start)."""
    serial = GPTModel(GPTConfig(axis=None, **TINY))
    par = GPTModel(GPTConfig(
        axis="model", sequence_parallel=True,
        context_axis=mesh_lib.AXIS_CONTEXT, **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    # 4 devices: tp=2 × cp=2 (cp shards of 8 tokens, sp shards of 4)
    mesh = mesh_lib.make_virtual_mesh(
        4, tensor_model_parallel_size=2, context_parallel_size=2)
    try:
        specs = par.specs()
        sharded = tp.shard_params(params, specs, mesh)

        def step(p, toks, tgt):
            loss, g = jax.value_and_grad(par.loss)(p, toks, tgt)
            return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                    jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

        seq_spec = P(None, mesh_lib.AXIS_CONTEXT)
        fn = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(specs, seq_spec, seq_spec),
            out_specs=(P(), specs), check_vma=False))
        v_p, g_p = fn(sharded, toks, tgt)
        v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
        np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
        for a, b in zip(jax.tree.leaves(g_s),
                        jax.tree.leaves(jax.device_get(g_p))):
            np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_gpt_sequence_parallel_dropout_deterministic_and_decorrelated():
    """Rank-offset dropout RNG (tensor_parallel/random.py
    sequence_parallel_key): same key → same loss (reproducible through
    remat), different key → different loss, and the SP loss differs from
    the plain-TP loss at the same key (the per-rank fold actually changes
    the masks — otherwise the seq shards would reuse one mask pattern)."""
    cfg = dict(TINY)
    cfg["hidden_dropout"] = 0.2
    plain = GPTModel(GPTConfig(axis="model", **cfg))
    seqp = GPTModel(GPTConfig(axis="model", sequence_parallel=True, **cfg))
    params = GPTModel(GPTConfig(axis=None, **cfg)).init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        specs = seqp.specs()
        sharded = tp.shard_params(params, specs, mesh)

        def runner(model):
            return jax.jit(jax.shard_map(
                lambda p, t, g, k: model.loss(p, t, g, dropout_key=k),
                mesh=mesh, in_specs=(specs, P(), P(), P()), out_specs=P(),
                check_vma=False))

        k = jax.random.PRNGKey(7)
        sp_fn, tp_fn = runner(seqp), runner(plain)
        l1, l2 = float(sp_fn(sharded, toks, tgt, k)), \
            float(sp_fn(sharded, toks, tgt, k))
        l3 = float(sp_fn(sharded, toks, tgt, jax.random.PRNGKey(8)))
        l_tp = float(tp_fn(sharded, toks, tgt, k))
        assert l1 == l2
        assert l1 != l3
        assert l1 != l_tp
    finally:
        mesh_lib.destroy_model_parallel()


def test_gpt_sequence_parallel_rejects_moe():
    with pytest.raises(ValueError, match="sequence_parallel"):
        GPTModel(GPTConfig(axis="model", sequence_parallel=True,
                           moe_num_experts=4, **TINY))


def test_gpt_trains_serial():
    model = GPTModel(GPTConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    opt = optax.adam(1e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(model.loss)(p, toks, tgt)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    first = None
    for _ in range(25):
        params, state, loss = step(params, state)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.7


def test_gpt_dropout_determinism():
    cfg = dict(TINY)
    cfg["hidden_dropout"] = 0.1
    model = GPTModel(GPTConfig(axis=None, **cfg))
    params = model.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    k = jax.random.PRNGKey(7)
    l1 = model.loss(params, toks, tgt, dropout_key=k)
    l2 = model.loss(params, toks, tgt, dropout_key=k)
    l3 = model.loss(params, toks, tgt, dropout_key=jax.random.PRNGKey(8))
    assert float(l1) == float(l2)
    assert float(l1) != float(l3)
    # eval mode (no key) = deterministic, differs from train
    le = model.loss(params, toks, tgt)
    assert float(le) != float(l1)


def test_gpt_stage_decomposition_matches_apply():
    """embed → run_layers(slice0) → run_layers(slice1) → head must equal
    apply — the invariant pipeline schedules rely on."""
    model = GPTModel(GPTConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, tgt = _data(jax.random.PRNGKey(1))
    full = model.apply(params, toks, tgt)
    h = model.embed(params, toks)
    sl0 = jax.tree.map(lambda x: x[:1], params["layers"])
    sl1 = jax.tree.map(lambda x: x[1:], params["layers"])
    h = model.run_layers(sl0, h)
    h = model.run_layers(sl1, h)
    staged = model.head(params, h, tgt)
    np.testing.assert_allclose(np.asarray(full), np.asarray(staged), rtol=1e-5)


def test_mlp_matches_sequential_reference():
    """apex tests/L0/run_mlp/test_mlp.py: MLP vs chain of Linears, fwd+bwd."""
    sizes = (12, 24, 8)
    mlp = MLP(sizes, activation="relu")
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 12))

    def ref(params, x):
        for p in params:
            x = jax.nn.relu(x @ p["kernel"] + p["bias"])
        return x

    np.testing.assert_allclose(np.asarray(mlp.apply(params, x)),
                               np.asarray(ref(params, x)), rtol=1e-6)
    g1 = jax.grad(lambda p: jnp.sum(mlp.apply(p, x) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(ref(p, x) ** 2))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_mlp_no_bias_sigmoid():
    mlp = MLP((4, 4), bias=False, activation="sigmoid")
    p = mlp.init(jax.random.PRNGKey(0))
    y = mlp.apply(p, jnp.ones((2, 4)))
    assert y.shape == (2, 4)
    assert float(jnp.min(y)) > 0.0 and float(jnp.max(y)) < 1.0


def test_fused_dense_layers():
    fd = FusedDense(8, 16)
    p = fd.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 8))
    np.testing.assert_allclose(
        np.asarray(fd.apply(p, x)), np.asarray(x @ p["kernel"] + p["bias"]), rtol=1e-6
    )
    fgd = FusedDenseGeluDense(8, 32, 8)
    p2 = fgd.init(jax.random.PRNGKey(2))
    y = fgd.apply(p2, x)
    ref = jax.nn.gelu(x @ p2["dense1"]["kernel"] + p2["dense1"]["bias"])
    ref = ref @ p2["dense2"]["kernel"] + p2["dense2"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)
