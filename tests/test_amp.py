"""amp end-to-end: O2 master weights, loss scaling, overflow skip.

Mirrors tests/L0/run_amp (checkpointing, master-param coherence) and
tests/distributed/amp_master_params (masters == model.half() invariant).
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import fused_sgd


def _model():
    def apply_fn(params, x):
        h = x @ params["w1"].astype(x.dtype)
        h = jax.nn.relu(h)
        return h @ params["w2"].astype(x.dtype)

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": jax.random.normal(k1, (8, 16), jnp.float32) * 0.1,
        "w2": jax.random.normal(k2, (16, 4), jnp.float32) * 0.1,
    }
    return apply_fn, params


def test_initialize_o2_casts_and_bundles():
    apply_fn, params = _model()
    ts = amp.initialize(
        params, fused_sgd(lr=0.1, momentum=0.9), opt_level="O2", apply_fn=apply_fn
    )
    assert ts.params["w1"].dtype == jnp.bfloat16
    assert ts.opt_state.master["w1"].dtype == jnp.float32
    assert ts.scaler.dynamic


def test_o2_train_step_decreases_loss():
    apply_fn, params = _model()
    ts = amp.initialize(
        params, fused_sgd(lr=0.05, momentum=0.9), opt_level="O2", apply_fn=apply_fn
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 8), jnp.bfloat16)
    y = jax.random.normal(jax.random.PRNGKey(2), (32, 4), jnp.float32)

    @jax.jit
    def step(ts, x, y):
        def loss_fn(p):
            pred = ts.apply_fn(p, x)
            loss = jnp.mean((pred.astype(jnp.float32) - y) ** 2)
            return ts.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(ts.params)
        ts, metrics = ts.apply_gradients(grads)
        return ts, loss, metrics

    losses = []
    for _ in range(20):
        ts, loss, metrics = step(ts, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9
    assert not bool(metrics["found_inf"])
    # master/model coherence: model params == masters cast down
    for m, p in zip(jax.tree.leaves(ts.opt_state.master), jax.tree.leaves(ts.params)):
        np.testing.assert_array_equal(
            np.asarray(m.astype(jnp.bfloat16)), np.asarray(p)
        )


def test_overflow_skips_step_and_halves_scale():
    apply_fn, params = _model()
    ts = amp.initialize(
        params, fused_sgd(lr=0.1), opt_level="O2", apply_fn=apply_fn
    )
    scale_before = float(ts.scaler.loss_scale)
    params_before = jax.tree.map(np.asarray, ts.params)

    bad_grads = jax.tree.map(lambda p: jnp.full_like(p, jnp.inf), ts.params)
    ts, metrics = ts.apply_gradients(bad_grads)

    assert bool(metrics["found_inf"])
    assert float(ts.scaler.loss_scale) == scale_before / 2
    for before, after in zip(
        jax.tree.leaves(params_before), jax.tree.leaves(ts.params)
    ):
        np.testing.assert_array_equal(before, np.asarray(after))


def test_o0_passthrough():
    apply_fn, params = _model()
    ts = amp.initialize(params, fused_sgd(lr=0.1), opt_level="O0", apply_fn=apply_fn)
    assert ts.params["w1"].dtype == jnp.float32
    assert ts.opt_state.master is None
    assert not ts.scaler.dynamic


def test_state_dict_roundtrip():
    apply_fn, params = _model()
    ts = amp.initialize(params, fused_sgd(lr=0.1), opt_level="O2", apply_fn=apply_fn)
    bad_grads = jax.tree.map(lambda p: jnp.full_like(p, jnp.inf), ts.params)
    ts, _ = ts.apply_gradients(bad_grads)
    payload = ts.mp_optimizer.state_dict(ts.opt_state)

    ts2 = amp.initialize(params, fused_sgd(lr=0.1), opt_level="O2", apply_fn=apply_fn)
    restored = ts2.mp_optimizer.load_state_dict(ts2.opt_state, payload)
    assert float(restored.scaler.loss_scale) == float(ts.scaler.loss_scale)
