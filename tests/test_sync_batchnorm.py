"""SyncBatchNorm: sync-vs-local equivalence on the virtual CPU mesh.

Models tests/distributed/synced_batchnorm/ (python vs fused vs
torch.nn.BatchNorm on 1-2 GPUs, fp16, uneven batch, group_size<world) as
single-process shard_map tests: the sharded SyncBatchNorm over the 'data'
axis must match a plain BatchNorm over the full (gathered) batch, in both
forward values and input/param gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, convert_syncbn_model
from flax import linen as nn


@pytest.fixture
def mesh():
    m = parallel.initialize_model_parallel()  # 8-way data parallel
    yield m
    parallel.destroy_model_parallel()


def _reference_bn(x, weight, bias, eps, c_ax):
    dims = tuple(d for d in range(x.ndim) if d != c_ax)
    x32 = x.astype(jnp.float32)
    mean = x32.mean(dims)
    var = x32.var(dims)
    shape = [1] * x.ndim
    shape[c_ax] = x.shape[c_ax]
    y = (x32 - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    return y * weight.reshape(shape) + bias.reshape(shape)


@pytest.mark.parametrize("channel_last", [False, True])
def test_forward_matches_full_batch_bn(mesh, channel_last):
    rng = np.random.default_rng(0)
    c_ax = -1 if channel_last else 1
    x = jnp.asarray(rng.normal(size=(16, 6, 5, 7)).astype(np.float32))
    if channel_last:
        x = jnp.moveaxis(x, 1, -1)  # NHWC

    bn = SyncBatchNorm(axis_name="data", channel_last=channel_last)
    variables = bn.init(jax.random.PRNGKey(0), x)
    # distinctive affine params
    nf = x.shape[c_ax]
    w = jnp.asarray(rng.normal(size=(nf,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(nf,)).astype(np.float32))
    variables = {"params": {"scale": w, "bias": b}, "batch_stats": variables["batch_stats"]}

    def body(v, xs):
        y, updates = bn.apply(v, xs, mutable=["batch_stats"])
        return y, updates

    y, updates = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P("data"), P()),
        check_vma=False,
    )(variables, x)

    expected = _reference_bn(x, w, b, 1e-5, c_ax if c_ax >= 0 else x.ndim - 1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected), atol=1e-5)
    # running stats updated with global batch stats (momentum 0.1)
    dims = tuple(d for d in range(x.ndim) if d != (c_ax % x.ndim))
    gmean = np.asarray(x, np.float32).mean(dims)
    n = x.size // x.shape[c_ax]
    gvar = np.asarray(x, np.float32).var(dims) * n / (n - 1)
    np.testing.assert_allclose(
        np.asarray(updates["batch_stats"]["mean"]), 0.1 * gmean, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(updates["batch_stats"]["var"]), 0.9 * 1.0 + 0.1 * gvar, atol=1e-4
    )


def test_gradients_match_full_batch_bn(mesh):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, 4, 3)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))
    b = jnp.zeros((4,), jnp.float32)
    bn = SyncBatchNorm(axis_name="data", track_running_stats=False)
    cot = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))

    def sharded_grads(params, xs, cots):
        def loss(p, xv):
            y = bn.apply({"params": p}, xv)
            return jnp.sum(y * cots)

        g_p, g_x = jax.grad(loss, argnums=(0, 1))(params, xs)
        # replicated-param grads: each shard holds its local contribution
        # (plus the cross-shard moment path via the psum transpose); the
        # global grad is the psum — the DDP reduction step.
        return jax.lax.psum(g_p, "data"), g_x

    grads = jax.shard_map(
        sharded_grads,
        mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P("data")),
        check_vma=False,
    )(dict(scale=w, bias=b), x, cot)

    def full_loss(params, xs):
        y = _reference_bn(xs, params["scale"], params["bias"], 1e-5, 1)
        return jnp.sum(y * cot)

    ref = jax.grad(full_loss, argnums=(0, 1))(dict(scale=w, bias=b), x)
    np.testing.assert_allclose(np.asarray(grads[0]["scale"]), np.asarray(ref[0]["scale"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(grads[0]["bias"]), np.asarray(ref[0]["bias"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(grads[1]), np.asarray(ref[1]), atol=2e-4)


def test_group_size_subsets_axis(mesh):
    """group_size=4 -> two independent groups of 4 shards
    (create_syncbn_process_group equivalent)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32))
    bn = SyncBatchNorm(axis_name="data", group_size=4, track_running_stats=False)
    v = bn.init(jax.random.PRNGKey(0), x[:1])

    y = jax.shard_map(
        lambda xs: bn.apply(v, xs),
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P("data"),
        check_vma=False,
    )(x)

    w = jnp.ones((3,)); b = jnp.zeros((3,))
    for half in (slice(0, 4), slice(4, 8)):
        expected = _reference_bn(x[half], w, b, 1e-5, 1)
        np.testing.assert_allclose(np.asarray(y[half]), np.asarray(expected), atol=1e-5)


def test_eval_mode_uses_running_stats():
    x = jnp.ones((4, 3)) * 2.0
    bn = SyncBatchNorm()
    v = bn.init(jax.random.PRNGKey(0), x)
    stats = {"mean": jnp.full((3,), 1.0), "var": jnp.full((3,), 4.0),
             "num_batches_tracked": jnp.ones((), jnp.int32)}
    y = bn.apply({"params": v["params"], "batch_stats": stats}, x,
                 use_running_average=True)
    np.testing.assert_allclose(np.asarray(y), (2.0 - 1.0) / np.sqrt(4.0 + 1e-5), atol=1e-6)


def test_half_input_fp32_stats_and_fuse_relu():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32)).astype(jnp.bfloat16)
    bn = SyncBatchNorm(fuse_relu=True, track_running_stats=False)
    v = bn.init(jax.random.PRNGKey(0), x)
    y = bn.apply(v, x)
    assert y.dtype == jnp.bfloat16
    assert (np.asarray(y, np.float32) >= 0).all()


def test_momentum_none_cumulative_average():
    bn = SyncBatchNorm(momentum=None)
    x1 = jnp.ones((4, 2)) * 1.0
    x2 = jnp.ones((4, 2)) * 3.0
    v = bn.init(jax.random.PRNGKey(0), x1)
    _, v1 = bn.apply(v, x1, mutable=["batch_stats"])
    v = {"params": v["params"], **v1}
    _, v2 = bn.apply(v, x2, mutable=["batch_stats"])
    # cumulative mean of batch means [1, 3] -> 2
    np.testing.assert_allclose(np.asarray(v2["batch_stats"]["mean"]), 2.0, atol=1e-6)


def test_convert_syncbn_model():
    class Net(nn.Module):
        bn: nn.Module

        def __call__(self, x):
            return self.bn(x)

    net = Net(bn=SyncBatchNorm())
    conv = convert_syncbn_model(net, axis_name="data", group_size=2)
    assert conv.bn.axis_name == "data"
    assert conv.bn.group_size == 2
