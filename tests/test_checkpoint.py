"""Checkpoint/resume tests (reference: test_checkpointing.py in
tests/L0/run_amp — scaler state round-trip, optimizer-state continuity — plus
the topology-independent-resume design goal of SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, checkpoint
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp

TINY = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=16, hidden_dropout=0.0, compute_dtype=jnp.float32, remat=False,
)


@pytest.fixture(params=["npz"] + (["orbax"] if checkpoint._ocp else []))
def backend(request):
    return request.param


def _train_state():
    model = GPTModel(GPTConfig(axis=None, **TINY))
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    return model, mp_opt, params, mp_opt.init(params)


def test_save_restore_roundtrip(tmp_path, backend):
    model, mp_opt, params, opt_state = _train_state()
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    @jax.jit
    def step(p, s):
        ls, gs = jax.value_and_grad(
            lambda q: mp_opt.scale_loss(model.loss(q, toks, tgt), s))(p)
        return mp_opt.apply_gradients(s, p, gs)

    params, opt_state, _ = step(params, opt_state)
    state = {"step": jnp.asarray(1), "params": params, "opt": opt_state}
    checkpoint.save_checkpoint(str(tmp_path), 1, state, backend=backend)
    assert checkpoint.latest_step(str(tmp_path)) == 1

    fresh = {"step": jnp.asarray(0), "params": jax.tree.map(jnp.zeros_like, params),
             "opt": mp_opt.init(params)}
    restored = checkpoint.restore_checkpoint(str(tmp_path), fresh, backend=backend)
    assert int(restored["step"]) == 1
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored["params"], jax.device_get(params))
    # scaler + master state continuity
    assert float(restored["opt"].scaler.loss_scale) == float(opt_state.scaler.loss_scale)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored["opt"].master, jax.device_get(opt_state.master))
    # dtypes preserved (bf16 model params, fp32 masters)
    assert restored["params"]["layers"]["qkv"]["kernel"].dtype == jnp.bfloat16


def test_latest_step_discovery(tmp_path):
    assert checkpoint.latest_step(str(tmp_path)) is None
    for s in (1, 5, 3):
        checkpoint.save_checkpoint(str(tmp_path), s, {"x": jnp.ones(2)}, backend="npz")
    assert checkpoint.latest_step(str(tmp_path)) == 5
    r = checkpoint.restore_checkpoint(str(tmp_path), {"x": jnp.zeros(2)})
    np.testing.assert_array_equal(np.asarray(r["x"]), [1, 1])


def test_topology_independent_resume(tmp_path):
    """Save from a serial run, restore onto a TP=4 mesh with shardings from
    the current mesh — the 'resume can change mesh shape' contract."""
    par = GPTModel(GPTConfig(axis="model", **TINY))
    serial_params = par.init(jax.random.PRNGKey(0))
    checkpoint.save_checkpoint(str(tmp_path), 0, serial_params, backend="npz")

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), par.specs(),
            is_leaf=lambda x: isinstance(x, P))
        restored = checkpoint.restore_checkpoint(
            str(tmp_path), jax.tree.map(jnp.zeros_like, serial_params),
            sharding_tree=shardings)
        kern = restored["layers"]["qkv"]["kernel"]
        assert kern.sharding.spec == par.specs()["layers"]["qkv"]["kernel"]
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgt = jnp.roll(toks, -1, axis=-1)
        specs = par.specs()
        loss = jax.jit(jax.shard_map(
            par.loss, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=P(), check_vma=False))(restored, toks, tgt)
        # matches the serial model's loss on the same params
        serial = GPTModel(GPTConfig(axis=None, **TINY))
        np.testing.assert_allclose(
            float(loss), float(serial.loss(serial_params, toks, tgt)), rtol=2e-5)
    finally:
        mesh_lib.destroy_model_parallel()


def test_missing_leaf_errors(tmp_path):
    checkpoint.save_checkpoint(str(tmp_path), 0, {"a": jnp.ones(2)}, backend="npz")
    with pytest.raises(KeyError):
        checkpoint.restore_checkpoint(
            str(tmp_path), {"a": jnp.zeros(2), "b": jnp.zeros(3)}, backend="npz")
