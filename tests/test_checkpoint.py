"""Checkpoint/resume tests (reference: test_checkpointing.py in
tests/L0/run_amp — scaler state round-trip, optimizer-state continuity — plus
the topology-independent-resume design goal of SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, checkpoint
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp

TINY = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=16, hidden_dropout=0.0, compute_dtype=jnp.float32, remat=False,
)


@pytest.fixture(params=["npz"] + (["orbax"] if checkpoint._ocp else []))
def backend(request):
    return request.param


def _train_state():
    model = GPTModel(GPTConfig(axis=None, **TINY))
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    return model, mp_opt, params, mp_opt.init(params)


def test_save_restore_roundtrip(tmp_path, backend):
    model, mp_opt, params, opt_state = _train_state()
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    @jax.jit
    def step(p, s):
        ls, gs = jax.value_and_grad(
            lambda q: mp_opt.scale_loss(model.loss(q, toks, tgt), s))(p)
        return mp_opt.apply_gradients(s, p, gs)

    params, opt_state, _ = step(params, opt_state)
    state = {"step": jnp.asarray(1), "params": params, "opt": opt_state}
    checkpoint.save_checkpoint(str(tmp_path), 1, state, backend=backend)
    assert checkpoint.latest_step(str(tmp_path)) == 1

    fresh = {"step": jnp.asarray(0), "params": jax.tree.map(jnp.zeros_like, params),
             "opt": mp_opt.init(params)}
    restored = checkpoint.restore_checkpoint(str(tmp_path), fresh, backend=backend)
    assert int(restored["step"]) == 1
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored["params"], jax.device_get(params))
    # scaler + master state continuity
    assert float(restored["opt"].scaler.loss_scale) == float(opt_state.scaler.loss_scale)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored["opt"].master, jax.device_get(opt_state.master))
    # dtypes preserved (bf16 model params, fp32 masters)
    assert restored["params"]["layers"]["qkv"]["kernel"].dtype == jnp.bfloat16


def test_latest_step_discovery(tmp_path):
    assert checkpoint.latest_step(str(tmp_path)) is None
    for s in (1, 5, 3):
        checkpoint.save_checkpoint(str(tmp_path), s, {"x": jnp.ones(2)}, backend="npz")
    assert checkpoint.latest_step(str(tmp_path)) == 5
    r = checkpoint.restore_checkpoint(str(tmp_path), {"x": jnp.zeros(2)})
    np.testing.assert_array_equal(np.asarray(r["x"]), [1, 1])


def test_topology_independent_resume(tmp_path):
    """Save from a serial run, restore onto a TP=4 mesh with shardings from
    the current mesh — the 'resume can change mesh shape' contract."""
    par = GPTModel(GPTConfig(axis="model", **TINY))
    serial_params = par.init(jax.random.PRNGKey(0))
    checkpoint.save_checkpoint(str(tmp_path), 0, serial_params, backend="npz")

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), par.specs(),
            is_leaf=lambda x: isinstance(x, P))
        restored = checkpoint.restore_checkpoint(
            str(tmp_path), jax.tree.map(jnp.zeros_like, serial_params),
            sharding_tree=shardings)
        kern = restored["layers"]["qkv"]["kernel"]
        assert kern.sharding.spec == par.specs()["layers"]["qkv"]["kernel"]
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgt = jnp.roll(toks, -1, axis=-1)
        specs = par.specs()
        loss = jax.jit(jax.shard_map(
            par.loss, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=P(), check_vma=False))(restored, toks, tgt)
        # matches the serial model's loss on the same params
        serial = GPTModel(GPTConfig(axis=None, **TINY))
        np.testing.assert_allclose(
            float(loss), float(serial.loss(serial_params, toks, tgt)), rtol=2e-5)
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.skipif(checkpoint._ocp is None, reason="orbax unavailable")
def test_sharded_mpoptstate_mesh_reshape_resume(tmp_path):
    """The multi-host-safe resume contract (SURVEY.md §5): a full MPOptState
    laid out sharded on a pp=2 x tp=2 mesh is orbax-saved *without a host
    gather* and restored directly into the shardings of a different mesh
    (tp=4) — values, scaler state, and a loss computation all survive the
    reshape."""
    from apex_tpu.amp.frontend import MPOptState
    from apex_tpu.optimizers.fused_adam import FusedAdamState
    from apex_tpu.transformer.pipeline_parallel.schedules import pipeline_specs

    model, mp_opt, params, opt_state = _train_state()
    par = GPTModel(GPTConfig(axis="model", **TINY))

    def shardings_for(mesh, pipeline_sharded):
        pspecs = dict(par.specs())
        if pipeline_sharded:
            pspecs["layers"] = pipeline_specs(pspecs["layers"])
        param_sh = jax.tree.map(
            lambda s: NamedSharding(mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P))
        repl = NamedSharding(mesh, P())
        return {
            "step": repl,
            "params": param_sh,
            "opt": MPOptState(
                inner=FusedAdamState(repl, param_sh, param_sh),
                master=param_sh,
                scaler=jax.tree.map(lambda _: repl, opt_state.scaler),
            ),
        }

    state = {"step": jnp.asarray(3), "params": params, "opt": opt_state}

    mesh_a = mesh_lib.make_virtual_mesh(
        8, tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    try:
        sharded = jax.tree.map(jax.device_put, state, shardings_for(mesh_a, True))
        # genuinely sharded across pipe x model before saving
        assert len(sharded["params"]["layers"]["qkv"]["kernel"].sharding
                   .device_set) >= 4
        checkpoint.save_checkpoint(str(tmp_path), 3, sharded, backend="orbax")
    finally:
        mesh_lib.destroy_model_parallel()

    mesh_b = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        target = jax.tree.map(jnp.zeros_like, state)
        sh_b = shardings_for(mesh_b, False)
        restored = checkpoint.restore_checkpoint(
            str(tmp_path), target, 3, sharding_tree=sh_b, backend="orbax")
        kern = restored["params"]["layers"]["qkv"]["kernel"]
        assert kern.sharding == sh_b["params"]["layers"]["qkv"]["kernel"]
        assert kern.dtype == jnp.bfloat16
        assert int(restored["step"]) == 3
        assert float(restored["opt"].scaler.loss_scale) == float(
            opt_state.scaler.loss_scale)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            restored["opt"].master, jax.device_get(opt_state.master))
        # the restored sharded params compute the same loss as the originals
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgt = jnp.roll(toks, -1, axis=-1)
        specs = par.specs()
        loss = jax.jit(jax.shard_map(
            lambda p, t, g: par.loss(
                jax.tree.map(lambda x: x.astype(jnp.float32), p), t, g),
            mesh=mesh_b, in_specs=(specs, P(), P()),
            out_specs=P(), check_vma=False))(restored["params"], toks, tgt)
        ref = model.loss(
            jax.tree.map(lambda x: x.astype(jnp.float32), jax.device_get(params)),
            toks, tgt)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.skipif(checkpoint._ocp is None, reason="orbax unavailable")
def test_sharded_save_restores_as_host_numpy_without_shardings(tmp_path):
    """A checkpoint saved from sharded arrays must still open with no
    sharding_tree (inspection host / different device set): leaves come
    back as host numpy, ignoring the recorded shardings."""
    mesh = mesh_lib.make_virtual_mesh(8, tensor_model_parallel_size=8)
    try:
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("model", None)))
        checkpoint.save_checkpoint(str(tmp_path), 0, {"x": x}, backend="orbax")
    finally:
        mesh_lib.destroy_model_parallel()
    r = checkpoint.restore_checkpoint(
        str(tmp_path), {"x": jnp.zeros((8, 8))}, 0, backend="orbax")
    np.testing.assert_array_equal(
        np.asarray(r["x"]), np.arange(64, dtype=np.float32).reshape(8, 8))


def test_missing_leaf_errors(tmp_path):
    checkpoint.save_checkpoint(str(tmp_path), 0, {"a": jnp.ones(2)}, backend="npz")
    with pytest.raises(KeyError):
        checkpoint.restore_checkpoint(
            str(tmp_path), {"a": jnp.zeros(2), "b": jnp.zeros(3)}, backend="npz")
