"""BERT model tests (BASELINE.md config 3: BERT + FusedLAMB + fused LN).

Reference patterns: run_bert_minimal_test.py (BERT runs, loss sane, trains)
and serial-vs-TP-sharded equivalence (run_layers_test.py style).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import BertConfig, BertModel
from apex_tpu.optimizers import FusedLAMB
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    hidden_dropout=0.0,
    compute_dtype=jnp.float32,
    remat=False,
)


def _batch(key, batch=4, seq=16, vocab=64):
    ks = jax.random.split(key, 4)
    toks = jax.random.randint(ks[0], (batch, seq), 0, vocab)
    attn_mask = jnp.ones((batch, seq), jnp.int32).at[:, -3:].set(0)  # padding
    loss_mask = (jax.random.uniform(ks[1], (batch, seq)) < 0.15).astype(jnp.int32)
    labels = jax.random.randint(ks[2], (batch, seq), 0, vocab)
    nsp = jax.random.randint(ks[3], (batch,), 0, 2)
    return toks, attn_mask, loss_mask, labels, nsp


def test_bert_forward_shapes_and_loss():
    model = BertModel(BertConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))
    logits, binary = model.apply(params, toks, attn)
    assert logits.shape == (4, 16, 64)
    assert binary.shape == (4, 2)
    loss = model.loss(params, toks, attn, lmask, labels, nsp)
    # ~ln(64)=4.16 MLM + ~ln(2)=0.69 NSP at init
    assert 3.0 < float(loss) < 7.0


def test_bert_unroll_matches_scan():
    """unroll_layers must preserve the bidirectional path with a real
    padding mask (attn_bias rides the unrolled body's closure) — loss and
    grads match the lax.scan drive."""
    scan_m = BertModel(BertConfig(axis=None, **TINY))
    unroll_m = BertModel(BertConfig(axis=None, unroll_layers=True, **TINY))
    params = scan_m.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))

    def loss(m):
        return lambda p: m.loss(p, toks, attn, lmask, labels, nsp)

    l_s, g_s = jax.value_and_grad(loss(scan_m))(params)
    l_u, g_u = jax.value_and_grad(loss(unroll_m))(params)
    np.testing.assert_allclose(float(l_s), float(l_u), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_u)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bert_padding_mask_matters():
    """Attention must ignore padded keys: changing a masked-out token's
    content must not change unmasked positions' logits."""
    model = BertModel(BertConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, attn, *_ = _batch(jax.random.PRNGKey(1))
    logits1, _ = model.apply(params, toks, attn)
    toks2 = toks.at[:, -1].set((toks[:, -1] + 1) % 64)
    logits2, _ = model.apply(params, toks2, attn)
    # positions other than the changed (padded) one are identical
    np.testing.assert_allclose(
        np.asarray(logits1[:, :-3]), np.asarray(logits2[:, :-3]),
        rtol=1e-5, atol=1e-5)


def test_bert_tp_matches_serial():
    serial = BertModel(BertConfig(axis=None, **TINY))
    par = BertModel(BertConfig(axis="model", **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        specs = par.specs()
        sharded = tp.shard_params(params, specs, mesh)

        def loss_fn(p, toks, attn, lmask, labels, nsp):
            return par.loss(p, toks, attn, lmask, labels, nsp)

        fn = jax.jit(jax.shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(specs, P(), P(), P(), P(), P()),
            out_specs=(P(), specs), check_vma=False,
        ))
        v_p, g_p = fn(sharded, toks, attn, lmask, labels, nsp)
        v_s, g_s = jax.value_and_grad(serial.loss)(
            params, toks, attn, lmask, labels, nsp)
        np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
        flat_s, _ = jax.tree_util.tree_flatten(g_s)
        flat_p, _ = jax.tree_util.tree_flatten(jax.device_get(g_p))
        for a, b in zip(flat_s, flat_p):
            np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_bert_sequence_parallel_matches_serial():
    """ISSUE 4 equivalence gate, BERT side: the padding mask, tokentype
    embeddings (rank-sliced under SP), post-LN blocks, MLM masked mean, the
    [CLS]/NSP head past the sequence gather, and the vocab-parallel CE must
    all agree with serial — values and gradients (serial == plain TP is
    pinned by test_bert_tp_matches_serial, closing the 3-way gate)."""
    serial = BertModel(BertConfig(axis=None, **TINY))
    seqp = BertModel(BertConfig(axis="model", sequence_parallel=True, **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))
    tokentype = jax.random.randint(jax.random.PRNGKey(9), toks.shape, 0, 2)

    mesh = mesh_lib.make_virtual_mesh(4, tensor_model_parallel_size=4)
    try:
        specs = seqp.specs()
        sharded = tp.shard_params(params, specs, mesh)

        def loss_of(model):
            return lambda p: model.loss(p, toks, attn, lmask, labels, nsp,
                                        tokentype_ids=tokentype)

        v_s, g_s = jax.value_and_grad(loss_of(serial))(params)
        fn = jax.jit(jax.shard_map(
            jax.value_and_grad(loss_of(seqp)), mesh=mesh,
            in_specs=(specs,), out_specs=(P(), specs), check_vma=False))
        v_p, g_p = fn(sharded)
        np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
        flat_s, _ = jax.tree_util.tree_flatten(g_s)
        flat_p, _ = jax.tree_util.tree_flatten(jax.device_get(g_p))
        for a, b in zip(flat_s, flat_p):
            np.testing.assert_allclose(a, np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
    finally:
        mesh_lib.destroy_model_parallel()


def test_bert_fused_lamb_o2_trains():
    """The config-3 slice: bf16 O2 masters + FusedLAMB; loss must drop."""
    cfg = dict(TINY)
    cfg["compute_dtype"] = jnp.bfloat16
    model = BertModel(BertConfig(axis=None, **cfg))
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(FusedLAMB(lr=2e-2), policy)
    params = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    opt_state = mp_opt.init(params)
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))

    @jax.jit
    def step(p, s):
        def scaled(p):
            return mp_opt.scale_loss(
                model.loss(p, toks, attn, lmask, labels, nsp), s)
        ls, gs = jax.value_and_grad(scaled)(p)
        np_, ns, metrics = mp_opt.apply_gradients(s, p, gs)
        return np_, ns, ls / s.scaler.loss_scale, metrics

    first = None
    for _ in range(40):
        params, opt_state, loss, metrics = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert jnp.isfinite(loss)
    assert float(loss) < first * 0.9
    assert params["lm_dense"]["kernel"].dtype == jnp.bfloat16


def test_bert_stage_decomposition_matches_apply():
    from apex_tpu.models.bert import extended_attention_mask

    model = BertModel(BertConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1))
    full_lm, full_bin = model.apply(params, toks, attn, masked_lm_labels=labels)
    bias = extended_attention_mask(attn)
    h = model.embed(params, toks)
    sl0 = jax.tree.map(lambda x: x[:1], params["layers"])
    sl1 = jax.tree.map(lambda x: x[1:], params["layers"])
    h = model.run_layers(sl0, h, bias)
    h = model.run_layers(sl1, h, bias)
    staged_lm, staged_bin = model.head(params, h, labels)
    np.testing.assert_allclose(np.asarray(full_lm), np.asarray(staged_lm),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(full_bin), np.asarray(staged_bin),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_bert_context_parallel_matches_serial(sp_impl):
    """Sequence-parallel BERT (bidirectional ring/Ulysses via the shared
    TransformerBase._attend): loss parity serial vs cp=2, maskless/headless
    variant (the padded + NSP variant is the test below)."""
    cfg = dict(TINY, axis=None, add_binary_head=False)
    serial = BertModel(BertConfig(**cfg))
    par = BertModel(BertConfig(
        context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=sp_impl, **cfg))
    params = serial.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)
    lmask = jnp.ones((2, 16), jnp.int32)

    ref_loss, ref_grads = jax.value_and_grad(serial.loss)(
        params, toks, None, lmask, labels)

    mesh = mesh_lib.make_virtual_mesh(2, context_parallel_size=2)
    try:
        def sp_step(p, toks, lmask, labels):
            loss, g = jax.value_and_grad(par.loss)(p, toks, None, lmask, labels)
            return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                    jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

        seq_spec = P(None, mesh_lib.AXIS_CONTEXT)
        loss, grads = jax.jit(jax.shard_map(
            sp_step, mesh=mesh,
            in_specs=(P(), seq_spec, seq_spec, seq_spec),
            out_specs=(P(), P()),
            check_vma=False))(params, toks, lmask, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            jax.device_get(grads), jax.device_get(ref_grads))
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_bert_context_parallel_padded_nsp_matches_serial(sp_impl):
    """The REAL pretraining shape under context parallelism (VERDICT r3
    ask #4): a genuine padding attention_mask (→ segment ids riding the
    K/V ring), a non-uniform loss_mask (→ the global-weight-normalized
    local loss), and add_binary_head=True (→ the psum-replicated global
    [CLS] pooler). Loss AND grads must match the serial model, which uses
    the reference's additive -10000 bias construction."""
    cfg = dict(TINY, axis=None, add_binary_head=True)
    serial = BertModel(BertConfig(**cfg))
    par = BertModel(BertConfig(
        context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=sp_impl, **cfg))
    params = serial.init(jax.random.PRNGKey(0))
    toks, attn, lmask, labels, nsp = _batch(jax.random.PRNGKey(1), batch=2)
    # make the loss mask genuinely non-uniform across the two shards and
    # zero on padded positions (the masked-LM contract)
    lmask = (lmask.at[:, :3].set(1) * attn).astype(jnp.int32)
    assert int(lmask[:, :8].sum()) != int(lmask[:, 8:].sum())

    ref_loss, ref_grads = jax.value_and_grad(serial.loss)(
        params, toks, attn, lmask, labels, nsp)

    mesh = mesh_lib.make_virtual_mesh(2, context_parallel_size=2)
    try:
        def sp_step(p, toks, attn, lmask, labels, nsp):
            loss, g = jax.value_and_grad(par.loss)(
                p, toks, attn, lmask, labels, nsp)
            return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                    jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

        seq_spec = P(None, mesh_lib.AXIS_CONTEXT)
        loss, grads = jax.jit(jax.shard_map(
            sp_step, mesh=mesh,
            in_specs=(P(), seq_spec, seq_spec, seq_spec, seq_spec, P()),
            out_specs=(P(), P()),
            check_vma=False))(params, toks, attn, lmask, labels, nsp)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            jax.device_get(grads), jax.device_get(ref_grads))
    finally:
        mesh_lib.destroy_model_parallel()
