"""Keep benchmarks/gpt_scaling.py importable and runnable (the reference's
gpt_scaling_test.py is itself a test; here one tiny config guards the
harness against rot)."""

import importlib.util
import os

import pytest

from apex_tpu.parallel import mesh as mesh_lib


def _load_harness():
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "gpt_scaling.py")
    spec = importlib.util.spec_from_file_location("gpt_scaling", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_run_grid_writes_artifacts(tmp_path):
    """The reference-grid sweep (gpt_scaling_test.py:49-70 parity): one JSON
    artifact per config plus the combined table, via one call."""
    import json

    harness = _load_harness()
    rows = harness.run_grid(
        hidden=32, layers_list=[2], heads=4, vocab=64, seq=16,
        micro_batch=1, n_micro=2, steps=1, output_dir=str(tmp_path),
        grid=[(2, 1, 1), (1, 1, 2)])
    assert len(rows) == 2
    assert (tmp_path / "scaling_table.json").exists()
    per_config = sorted(p.name for p in tmp_path.glob("scaling_dp*_l2.json"))
    assert per_config == ["scaling_dp1_tp1_pp2_l2.json", "scaling_dp2_tp1_pp1_l2.json"]
    table = json.loads((tmp_path / "scaling_table.json").read_text())
    # reading-guide notes travel WITH the artifact (VERDICT r4 weak #5:
    # CPU-mesh tokens/s must not be read as scaling efficiency)
    assert "NOT a scaling-efficiency" in table["notes"]["reading_guide"]
    for row in table["rows"]:
        assert "skipped" in row or row["tokens_per_sec"] > 0
        assert row["config"]["layers"] == 2


def test_run_config_smoke():
    harness = _load_harness()
    res = harness.run_config(
        2, 1, 2, hidden=32, layers=2, heads=4, vocab=64, seq=16,
        micro_batch=1, n_micro=2, steps=1)
    if res is None:
        pytest.skip("fewer than 4 devices on this platform")
    assert res["config"] == {"dp": 2, "tp": 1, "pp": 2, "layers": 2}
    assert res["avg_iteration_time_s"] > 0
    assert res["tokens_per_sec"] > 0
    import numpy as np
    assert np.isfinite(res["loss"])
    assert not mesh_lib.model_parallel_is_initialized()  # harness cleans up


@pytest.mark.slow  # a second full pipelined-step compile; the SP model
# math itself is pinned in tier-1 by test_models/test_bert equivalence
def test_run_config_sequence_parallel_variant():
    """The sweep's sequence-parallel twin (ISSUE 4 satellite): the config
    label records the mode, the comm accounting sees the reduce-scatter
    traffic on the model axis, and the loss stays finite."""
    harness = _load_harness()
    res = harness.run_config(
        2, 2, 1, hidden=32, layers=2, heads=4, vocab=64, seq=16,
        micro_batch=1, n_micro=2, steps=1, sequence_parallel=True)
    if res is None:
        pytest.skip("fewer than 4 devices on this platform")
    assert res["config"]["sequence_parallel"] is True
    assert res["config"]["tp"] == 2
    import numpy as np
    assert np.isfinite(res["loss"])
    # the decomposed collectives ride the same per-axis byte tally the
    # plain psums did (monitor/comms.py; traced call sites)
    model_bytes = res["comm_bytes_by_axis"].get("model", {})
    assert model_bytes.get("bytes", 0) > 0
    assert not mesh_lib.model_parallel_is_initialized()
