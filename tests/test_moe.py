"""MoE tests (new capability — no reference counterpart; serial-vs-sharded
equivalence follows the repo's standard contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.transformer.moe import MoEMLP


def _layer(E=4, top_k=1, cf=8.0, axis=None, d=8, f=16):
    return MoEMLP(hidden_size=d, ffn_hidden_size=f, num_experts=E,
                  top_k=top_k, capacity_factor=cf, expert_axis=axis)


def _expert_ffn(params, e, x):
    h = x @ np.asarray(params["fc1"]["kernel"][e])
    h = jax.nn.gelu(h + np.asarray(params["fc1"]["bias"][e]))
    return h @ np.asarray(params["fc2"]["kernel"][e]) + np.asarray(
        params["fc2"]["bias"][e])


def test_top1_matches_per_token_expert():
    """With top_k=1 and ample capacity, each token's output is its argmax
    expert's FFN scaled by the UNNORMALIZED router prob p_i (Switch
    Transformer combine — scaling by p_i is what carries task-loss gradient
    into the router, since one_hot(argmax) is non-differentiable)."""
    layer = _layer(top_k=1)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (10, 8))
    out, _ = layer.apply(params, x)
    logits = np.asarray(x) @ np.asarray(params["router"]["kernel"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    choice = logits.argmax(-1)
    for i in range(10):
        e = int(choice[i])
        ref = probs[i, e] * _expert_ffn(params, e, np.asarray(x[i]))
        np.testing.assert_allclose(np.asarray(out[i]), ref, atol=1e-5)


def test_top1_router_gets_task_loss_gradient():
    """Switch top-1 routing must train the router through the model loss,
    not only through the aux losses."""
    layer = _layer(top_k=1)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (10, 8))

    def task_loss(p):
        out, _ = layer.apply(p, x)
        return jnp.sum(out ** 2)

    g = jax.grad(task_loss)(params)["router"]["kernel"]
    assert float(jnp.max(jnp.abs(g))) > 0.0


def test_top2_convex_combination():
    """top_k=2 output = gate-weighted mix of the two chosen experts, with
    renormalized gates summing to 1."""
    layer = _layer(top_k=2)
    params = layer.init(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (6, 8))
    out, _ = layer.apply(params, x)
    logits = np.asarray(x) @ np.asarray(params["router"]["kernel"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    top2 = np.argsort(-probs, axis=-1)[:, :2]
    for i in range(6):
        e1, e2 = top2[i]
        g = probs[i, [e1, e2]] / probs[i, [e1, e2]].sum()
        ref = g[0] * _expert_ffn(params, e1, np.asarray(x[i])) + \
              g[1] * _expert_ffn(params, e2, np.asarray(x[i]))
        np.testing.assert_allclose(np.asarray(out[i]), ref, atol=1e-5)


def test_capacity_drops_overflow_tokens():
    """Tokens beyond an expert's capacity contribute zero output (Switch
    drop behavior)."""
    layer = MoEMLP(hidden_size=8, ffn_hidden_size=16, num_experts=2,
                   top_k=1, capacity_factor=0.5)
    params = layer.init(jax.random.PRNGKey(0))
    # force all tokens to expert 0
    params["router"]["kernel"] = jnp.zeros((8, 2)).at[:, 0].set(
        jnp.ones(8))
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(4), (1, 8)), (8, 1))
    out, _ = layer.apply(params, x)
    # capacity = ceil(1*8*0.5/2) = 2: first 2 tokens served, rest dropped
    assert not np.allclose(np.asarray(out[0]), 0)
    np.testing.assert_allclose(np.asarray(out[2:]), 0, atol=1e-7)


def test_dropped_expert_share_is_lost_not_redistributed():
    """GShard combine: when a token's top-1 expert is over capacity but its
    top-2 expert still has room, the survivor keeps weight g2/(g1+g2) —
    the dropped share is NOT renormalized onto it."""
    layer = MoEMLP(hidden_size=8, ffn_hidden_size=16, num_experts=4,
                   top_k=2, capacity_factor=1.0)
    params = layer.init(jax.random.PRNGKey(0))
    # router reads features directly: e0 strong for everyone; e1/e2 are the
    # second choices of token types a/b respectively
    kernel = np.zeros((8, 4), np.float32)
    kernel[0, 0], kernel[1, 1], kernel[2, 2] = 4.0, 2.0, 2.0
    params["router"]["kernel"] = jnp.asarray(kernel)
    tok_a = np.zeros(8, np.float32); tok_a[0] = tok_a[1] = 1.0  # (e0, e1)
    tok_b = np.zeros(8, np.float32); tok_b[0] = tok_b[2] = 1.0  # (e0, e2)
    tok_a[3:] = 0.3; tok_b[3:] = -0.3  # nonzero payload features
    x = jnp.asarray(np.stack([tok_a] * 5 + [tok_b] * 3))

    out, _ = layer.apply(params, x)
    # capacity = ceil(2*8*1.0/4) = 4: e0 serves tokens 0-3 and drops 4-7;
    # e2 (3 b-tokens) is under capacity, so tokens 5-7 keep ONLY e2
    probs_b = np.asarray(jax.nn.softmax(jnp.asarray(tok_b @ kernel)))
    g0, g2 = probs_b[0], probs_b[2]
    w = g2 / (g0 + g2)
    partial = w * _expert_ffn(params, 2, tok_b)
    inflated = 1.0 * _expert_ffn(params, 2, tok_b)  # the renormalized bug
    for i in (5, 6, 7):
        np.testing.assert_allclose(np.asarray(out[i]), partial, atol=1e-5)
        assert not np.allclose(np.asarray(out[i]), inflated, atol=1e-3)
    # token 0 keeps both experts at full gate weights
    probs_a = np.asarray(jax.nn.softmax(jnp.asarray(tok_a @ kernel)))
    ga0, ga1 = probs_a[0], probs_a[1]
    full = (ga0 * _expert_ffn(params, 0, tok_a)
            + ga1 * _expert_ffn(params, 1, tok_a)) / (ga0 + ga1)
    np.testing.assert_allclose(np.asarray(out[0]), full, atol=1e-5)


def test_aux_losses():
    layer = _layer(E=4, top_k=1)
    params = layer.init(jax.random.PRNGKey(5))
    # uniform router -> perfectly balanced -> load-balancing loss == 1
    params["router"]["kernel"] = jnp.zeros((8, 4))
    x = jax.random.normal(jax.random.PRNGKey(6), (32, 8))
    _, aux = layer.apply(params, x)
    assert float(aux["load_balancing_loss"]) == pytest.approx(1.0, rel=1e-5)
    assert float(aux["router_z_loss"]) == pytest.approx(
        np.log(4) ** 2, rel=1e-5)
    # a skewed router scores strictly worse
    params["router"]["kernel"] = jnp.zeros((8, 4)).at[:, 0].set(5.0)
    _, aux2 = layer.apply(params, x)
    assert float(aux2["load_balancing_loss"]) > \
        float(aux["load_balancing_loss"]) + 0.05


@pytest.fixture
def mesh4():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    return Mesh(np.array(devs[:4]), ("expert",))


def test_expert_parallel_matches_serial(mesh4):
    """Tokens sharded over the expert axis + experts sharded: the
    all_to_all path computes the same function as the serial layer (ample
    capacity so no shard-local drop differences)."""
    layer = _layer(E=8, top_k=2, cf=16.0, axis="expert")
    params = layer.init(jax.random.PRNGKey(7))
    x = jax.random.normal(jax.random.PRNGKey(8), (16, 8))
    ref, ref_aux = layer.apply(params, x)

    specs = layer.specs()
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh4, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    f = jax.jit(jax.shard_map(
        layer.apply_expert_parallel, mesh=mesh4,
        in_specs=(specs, P("expert")), out_specs=(P("expert"), P()),
        check_vma=False))
    out, aux = f(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(float(aux["load_balancing_loss"]),
                               float(ref_aux["load_balancing_loss"]),
                               rtol=1e-5)


def test_expert_parallel_gradients_match_serial(mesh4):
    layer = _layer(E=4, top_k=1, cf=16.0, axis="expert")
    params = layer.init(jax.random.PRNGKey(9))
    x = jax.random.normal(jax.random.PRNGKey(10), (8, 8))

    def serial_loss(p):
        out, aux = layer.apply(p, x)
        return jnp.mean(out ** 2) + 0.01 * aux["load_balancing_loss"]

    ref = jax.grad(serial_loss)(params)

    specs = layer.specs()

    def ep_loss(p, xl):
        # the documented convention: local-mean loss per shard (aux
        # included), spec-aware gradient reduction afterwards
        out, aux = layer.apply_expert_parallel(p, xl)
        return jnp.mean(out ** 2) + 0.01 * aux["load_balancing_loss"]

    def grads(p, xl):
        from apex_tpu.parallel.distributed import allreduce_gradients_by_spec

        g = jax.grad(ep_loss)(p, xl)
        # replicated router pmeans; expert-sharded fc grads skip the psum
        # but keep the 1/ep averaging factor
        return allreduce_gradients_by_spec(
            g, specs, data_axes=("expert",), replicated_axes=())

    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh4, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    f = jax.jit(jax.shard_map(
        grads, mesh=mesh4, in_specs=(specs, P("expert")), out_specs=specs,
        check_vma=False))
    got = f(sharded, x)
    # atol covers einsum reduction-order noise on near-zero elements; real
    # routing errors produce O(grad-magnitude) differences, not 1e-4
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4),
        got, ref)


def test_validation_errors():
    with pytest.raises(ValueError, match="top_k"):
        MoEMLP(8, 16, num_experts=2, top_k=3)
    layer = _layer(E=6, axis="expert")
    params = layer.init(jax.random.PRNGKey(0))
    devs = jax.devices()
    if len(devs) >= 4:
        mesh = Mesh(np.array(devs[:4]), ("expert",))
        with pytest.raises(ValueError, match="divide"):
            jax.shard_map(
                layer.apply_expert_parallel, mesh=mesh,
                in_specs=(P(), P("expert")), out_specs=(P("expert"), P()),
                check_vma=False)(params, jnp.ones((8, 8)))


def test_moe_ep_x_tp_matches_serial():
    """EP x TP: experts over 'expert', each expert's FFN column/row-split
    over 'model' (VERDICT r2 next #6). Values AND gradients vs serial."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devs[:4]).reshape(2, 2), ("expert", "model"))
    serial = MoEMLP(hidden_size=8, ffn_hidden_size=16, num_experts=4,
                    top_k=2, capacity_factor=16.0)
    par = MoEMLP(hidden_size=8, ffn_hidden_size=16, num_experts=4,
                 top_k=2, capacity_factor=16.0,
                 expert_axis="expert", tp_axis="model")
    params = serial.init(jax.random.PRNGKey(11))
    x = jax.random.normal(jax.random.PRNGKey(12), (8, 8))
    ref, ref_aux = serial.apply(params, x)

    def serial_loss(p):
        out, aux = serial.apply(p, x)
        return jnp.mean(out ** 2) + 0.01 * aux["load_balancing_loss"]

    ref_g = jax.grad(serial_loss)(params)

    specs = par.specs()
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    # tokens shard over the expert axis, replicate over model (standard TP)
    xspec = P("expert")

    def fwd(p, xl):
        return par.apply_expert_parallel(p, xl)

    out, aux = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(specs, xspec),
        out_specs=(xspec, P()), check_vma=False))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(float(aux["load_balancing_loss"]),
                               float(ref_aux["load_balancing_loss"]),
                               rtol=1e-5)

    def grads(p, xl):
        from apex_tpu.parallel.distributed import allreduce_gradients_by_spec

        def loss(p):
            out, aux = par.apply_expert_parallel(p, xl)
            return jnp.mean(out ** 2) + 0.01 * aux["load_balancing_loss"]

        g = jax.grad(loss)(p)
        # expert dim skips the expert-axis psum (sharded), ffn dims skip
        # the model-axis psum; replicated router pmeans over both
        return allreduce_gradients_by_spec(
            g, specs, data_axes=("expert", "model"), replicated_axes=())

    got = jax.jit(jax.shard_map(
        grads, mesh=mesh, in_specs=(specs, xspec), out_specs=specs,
        check_vma=False))(sharded, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4),
        got, ref_g)


def test_capacity_divergence_under_congestion_is_bounded(mesh4):
    """Under congestion the parallel path caps per shard while serial caps
    globally (moe.py module docstring) — pin the documented divergence to
    a bound: per-shard caps sum to >= the global cap and within E extra
    slots per shard (ceil rounding), so the parallel path drops at most
    (kept_serial - sum_local_caps) fewer/more tokens; measured drop
    fractions must sit within that arithmetic bound."""
    import math

    E, ep, N, cf, k = 4, 4, 64, 0.5, 1
    layer = _layer(E=E, top_k=k, cf=cf, axis="expert")
    params = layer.init(jax.random.PRNGKey(13))
    x = jax.random.normal(jax.random.PRNGKey(14), (N, 8))

    C_global = layer._capacity(N)
    C_local = layer._capacity(N // ep)
    assert C_global == max(1, math.ceil(k * N * cf / E))
    assert C_local == max(1, math.ceil(k * (N // ep) * cf / E))
    # ceil rounding: the sharded layer can serve at most ep*C_local slots
    # per expert vs the serial C_global — never fewer slots in total
    assert C_global <= ep * C_local <= C_global + ep

    out_s, _ = layer.apply(params, x)
    specs = layer.specs()
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh4, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    out_p, _ = jax.jit(jax.shard_map(
        layer.apply_expert_parallel, mesh=mesh4,
        in_specs=(specs, P("expert")), out_specs=(P("expert"), P()),
        check_vma=False))(sharded, x)

    # top-1: a dropped token's output is exactly zero
    kept_s = int(jnp.sum(jnp.any(out_s != 0, axis=-1)))
    kept_p = int(jnp.sum(jnp.any(out_p != 0, axis=-1)))
    # serial keeps at most E*C_global tokens; parallel at most E*ep*C_local.
    assert kept_s <= E * C_global
    assert kept_p <= E * ep * C_local
    # divergence bound: both paths drop SOME tokens here (congestion is
    # real), and the kept counts differ by at most the slot-arithmetic gap
    # plus load imbalance across shards (each shard caps hot experts
    # locally, so the parallel path can keep at most ep*C_local and as few
    # as the most-imbalanced local distribution allows — still >= the
    # per-shard floor sum(min(load_shard_e, C_local)))
    assert kept_s < N and kept_p < N
    assert abs(kept_s - kept_p) <= E * ep


def test_dropped_fraction_metric():
    """aux["dropped_fraction"] (VERDICT r3 ask #6): zero at ample capacity,
    strictly positive and bounded under a congestion-inducing capacity
    factor, and not folded into any loss key."""
    x = jax.random.normal(jax.random.PRNGKey(2), (64, 8))
    ample = _layer(E=4, top_k=2, cf=8.0)
    params = ample.init(jax.random.PRNGKey(0))
    _, aux = ample.apply(params, x)
    assert float(aux["dropped_fraction"]) == 0.0

    tight = _layer(E=4, top_k=2, cf=0.5)
    _, aux = tight.apply(params, x)
    frac = float(aux["dropped_fraction"])
    # cf=0.5 serves at most half the balanced share: the fraction must be
    # large but can never exceed 1
    assert 0.25 < frac < 1.0, frac


def test_dropped_fraction_expert_parallel_matches_serial():
    """The EP path reports a sane global dropped fraction (pmean of
    shard-constant-denominator fractions)."""
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("data",))
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 8))
    serial = _layer(E=4, top_k=2, cf=1.0)
    params = serial.init(jax.random.PRNGKey(0))
    _, aux_s = serial.apply(params, x)

    par = _layer(E=4, top_k=2, cf=1.0, axis="data")

    def fn(p, xs):
        _, aux = par.apply_expert_parallel(p, xs)
        return aux["dropped_fraction"]

    specs = par.specs()
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    frac_p = float(jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(specs, P("data")),
        out_specs=P(), check_vma=False))(sharded, x))
    # EP caps capacity per shard (by design, static buckets), so the
    # fractions agree only in aggregate kind, not bitwise with serial:
    # assert both congest and stay bounded
    assert 0.0 < frac_p < 1.0
    assert 0.0 < float(aux_s["dropped_fraction"]) < 1.0
