"""Tests for apex_tpu.monitor.ledger + calibrate (ISSUE 16) — append
durability (truncated trailing line, mid-file corruption salvage,
concurrent appends from two processes), config-fingerprint stability,
the N-run regression gate (self-history passes, a seeded throughput drop
fails with report compare's machine shape), the predicted-vs-measured
calibration joins, and the armed-calibration-file precedence over the
``APEX_TPU_PEAK_*`` env overrides. All host-side and CPU-safe."""

import json
import os
import subprocess
import sys

from apex_tpu.monitor import calibrate, ledger
from apex_tpu.monitor.journal import MetricsJournal

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_record(rate=1000.0, wall=0.1, steps=8, **extra):
    measured = {"step_records": steps,
                "tokens_per_sec": {"p50": rate},
                "wall_s": {"p50": wall},
                "loss": {"last": 2.0}}
    measured.update(extra.pop("measured", {}))
    rec = {"kind": "run", "run": "t", "config": {"tp": 2, "pp": 1},
           "measured": measured, "predicted": {}}
    rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# append durability
# ---------------------------------------------------------------------------


def test_truncated_trailing_line_still_parses(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, {"run": "a"})
    ledger.append(path, {"run": "b"})
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "run", "run": "torn')  # kill mid-write
    rows = ledger.read(path)
    assert [r["run"] for r in rows] == ["a", "b"]
    assert rows.truncated and rows.bad_lines == 1


def test_corrupt_mid_file_record_salvages_the_rest(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, {"run": "a"})
    with open(path, "a") as f:
        f.write("NOT JSON AT ALL\n")
    ledger.append(path, {"run": "b"})
    rows = ledger.read(path)
    assert [r["run"] for r in rows] == ["a", "b"]
    assert rows.bad_lines == 1 and not rows.truncated


def test_append_sanitizes_nonfinite_to_strict_json(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, {"run": "a",
                         "measured": {"loss": {"last": float("nan")}}})
    rows = ledger.read(path)
    assert rows[0]["measured"]["loss"]["last"] is None
    assert any("loss" in k for k in rows[0]["nonfinite_keys"])


def test_concurrent_appends_interleave_whole_lines(tmp_path):
    # two writer processes hammer the same file; O_APPEND single-write
    # appends must interleave whole lines — every record parses
    path = str(tmp_path / "ledger.jsonl")
    prog = ("import sys; from apex_tpu.monitor import ledger\n"
            "for i in range(20):\n"
            "    ledger.append(sys.argv[1], {'run': sys.argv[2],"
            " 'pad': 'x' * 512})\n")
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
    procs = [subprocess.Popen([sys.executable, "-c", prog, path, name],
                              env=env) for name in ("w1", "w2")]
    for pr in procs:
        assert pr.wait(timeout=120) == 0
    rows = ledger.read(path)
    assert len(rows) == 40 and rows.bad_lines == 0 and not rows.truncated
    assert sorted({r["run"] for r in rows}) == ["w1", "w2"]


# ---------------------------------------------------------------------------
# fingerprint stability
# ---------------------------------------------------------------------------


def test_fingerprint_stable_under_key_order_and_none_omission():
    a = ledger.config_fingerprint({"tp": 2, "pp": 1, "schedule": None})
    b = ledger.config_fingerprint({"pp": 1, "tp": 2})
    assert a == b and len(a) == 12


def test_fingerprint_changes_on_any_knob_flip():
    base = {"dp": 4, "tp": 2, "pp": 1, "zero_level": 1,
            "reduce_dtype": None}
    fps = {ledger.config_fingerprint(base)}
    for knob, val in (("tp", 4), ("pp", 2), ("zero_level", 3),
                      ("reduce_dtype", "int8"), ("vpp", 2)):
        fps.add(ledger.config_fingerprint(dict(base, **{knob: val})))
    assert len(fps) == 6  # every flip is a new fingerprint


# ---------------------------------------------------------------------------
# append_run: the harness hook
# ---------------------------------------------------------------------------


def test_append_run_carries_both_blocks_and_modeled_step(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    jpath = str(tmp_path / "run.jsonl")
    with MetricsJournal(jpath, meta={"run": "t", "tp": 2}) as j:
        for step in range(4):
            j.log({"kind": "step", "step": step, "wall_s": 0.1,
                   "loss": 2.0 - 0.1 * step, "tokens": 1024,
                   "tokens_per_sec": 1000.0, "overflows": 0,
                   "bubble_fraction_expected": 0.25})
    rec = ledger.append_run(
        path, run="t", config={"run": "t", "tp": 2}, journal=jpath,
        predicted={"flops_per_step": 1e9, "comm_bytes_per_step": 1e6,
                   "hbm_peak_bytes": 1 << 20})
    assert rec["kind"] == "run" and rec["v"] == 1
    assert rec["fingerprint"] == ledger.config_fingerprint(
        {"run": "t", "tp": 2})
    assert rec["measured"]["step_records"] == 4
    assert rec["measured"]["tokens_per_sec"]["p50"] == 1000.0
    # the journal's armed floor stamp was salvaged into the predicted
    # block, and the modeled step seconds carry spec provenance
    assert rec["predicted"]["bubble_floor"] == 0.25
    assert rec["predicted"]["modeled_step_s"] > 0
    assert "peak_flops_source" in rec["predicted"]["spec"]
    assert rec["env"].get("python")
    # round-trips through the crash-tolerant reader
    assert ledger.read(path)[0]["fingerprint"] == rec["fingerprint"]


# ---------------------------------------------------------------------------
# journal meta enrichment (satellite: kind="meta" header provenance)
# ---------------------------------------------------------------------------


def test_journal_meta_header_enriched_with_fingerprint_and_env(tmp_path):
    jpath = str(tmp_path / "run.jsonl")
    meta = {"run": "t", "tp": 2, "pp": 1}
    with MetricsJournal(jpath, meta=dict(meta)):
        pass
    rows = MetricsJournal.read(jpath)
    assert rows[0]["kind"] == "meta"
    assert rows[0]["fingerprint"] == ledger.config_fingerprint(meta)
    assert rows[0]["env"].get("python")
    # a bare journal (no meta) stays headerless — disarmed programs are
    # byte-identical (test_monitor pins the record counts)
    bare = str(tmp_path / "bare.jsonl")
    with MetricsJournal(bare) as j:
        j.log({"kind": "step", "step": 0})
    assert [r["kind"] for r in MetricsJournal.read(bare)] == ["step"]


# ---------------------------------------------------------------------------
# trend + regress (the N-run gate)
# ---------------------------------------------------------------------------


def test_regress_first_run_and_self_history_pass(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, _run_record())
    res = ledger.regress(ledger.read(path))
    assert res["ok"] and res["checks"] == []  # no history: every check skips
    for _ in range(3):
        ledger.append(path, _run_record())
    res = ledger.regress(ledger.read(path))
    assert res["ok"] and not res["regressed"]
    assert any(c["check"] == "tokens_per_sec_p50" for c in res["checks"])


def test_regress_fails_seeded_throughput_drop_with_compare_shape(tmp_path):
    from apex_tpu.monitor import report

    path = str(tmp_path / "ledger.jsonl")
    for _ in range(3):
        ledger.append(path, _run_record(rate=1000.0))
    ledger.append(path, _run_record(rate=700.0))  # 30% drop
    res = ledger.regress(ledger.read(path), threshold=0.05)
    assert not res["ok"] and res["regressed"] == ["tokens_per_sec_p50"]
    # machine-shape parity with report compare --format json: same top
    # keys, same per-check row keys (satellite 2's contract)
    cmp = report.compare([{"kind": "step", "step": 0, "wall_s": 0.1,
                           "tokens": 8, "tokens_per_sec": 100.0}] * 2,
                         [{"kind": "step", "step": 0, "wall_s": 0.1,
                           "tokens": 8, "tokens_per_sec": 100.0}] * 2)
    assert set(res) >= set(cmp), (set(cmp) - set(res))
    assert {tuple(sorted(c)) for c in res["checks"]} == {
        tuple(sorted(c)) for c in cmp["checks"]}
    json.dumps(res)  # strict machine shape


def test_regress_gates_structure_median_and_fingerprint(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    # one noisy predecessor can't poison the median baseline
    for rate in (1000.0, 10.0, 1000.0):
        ledger.append(path, _run_record(rate=rate))
    ledger.append(path, _run_record(rate=990.0))
    assert ledger.regress(ledger.read(path))["ok"]
    # a run that journaled nothing fails the structural gate
    ledger.append(path, _run_record(measured={"step_records": 0,
                                              "tokens_per_sec": {},
                                              "wall_s": {}}, steps=0))
    res = ledger.regress(ledger.read(path))
    assert not res["ok"] and "step_records" in res["regressed"]
    # fingerprint filtering: a different config's history is invisible
    other = dict(_run_record(rate=5000.0), config={"tp": 8})
    other["fingerprint"] = ledger.config_fingerprint({"tp": 8})
    ledger.append(path, other)
    res = ledger.regress(ledger.read(path),
                         fingerprint=other["fingerprint"])
    assert res["ok"] and res["a"]["runs"] == 0


def test_trend_groups_by_fingerprint(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    for tp in (1, 1, 2):
        rec = dict(_run_record(), config={"tp": tp},
                   fingerprint=ledger.config_fingerprint({"tp": tp}))
        ledger.append(path, rec)
    tr = ledger.trend(ledger.read(path))
    assert len(tr) == 2
    counts = sorted(len(v["rows"]) for v in tr.values())
    assert counts == [1, 2]


# ---------------------------------------------------------------------------
# calibrate: joins, fit, file precedence
# ---------------------------------------------------------------------------


def test_calibrate_join_ratios():
    rec = _run_record(
        wall=0.2,
        measured={"hbm": {"peak_bytes": 4 << 20},
                  "timeline": {"bubble_fraction": {"p50": 0.30}},
                  "comm_bytes_by_axis": {"data": {"bytes": 2e6}}},
        predicted={"hbm_peak_bytes": 2 << 20, "bubble_floor": 0.25,
                   "comm_bytes_per_step": 1e6, "modeled_step_s": 0.1})
    j = calibrate.join(rec)
    assert j["hbm_ratio"] == 2.0
    assert j["bubble_ratio"] == 1.2
    assert j["comm_ratio"] == 2.0
    assert j["wall_ratio"] == 2.0
    # missing sides emit no ratio
    assert "hbm_ratio" not in calibrate.join(_run_record())


def test_calibrate_fit_and_file_round_trip(tmp_path, monkeypatch):
    recs = [_run_record(wall=0.1,
                        predicted={"flops_per_step": 2e11,
                                   "bytes_per_step": 1e10,
                                   "comm_bytes_per_step": 1e9})
            for _ in range(3)]
    fit = calibrate.fit(recs)
    assert fit["source"] == "calibrated"
    assert fit["peak_flops"] == 2e12  # 2e11 flops / 0.1 s
    assert fit["peak_hbm_bytes_per_sec"] == 1e11
    assert fit["n_records"]["peak_flops"] == 3
    path = str(tmp_path / "cal.json")
    calibrate.save(path, fit)
    loaded = calibrate.load(path)
    assert loaded["peak_flops"] == 2e12 and loaded["v"] == 1
    # corrupt/alien files degrade to None, never raise
    with open(path, "w") as f:
        f.write("{torn")
    assert calibrate.load(path) is None
    with open(path, "w") as f:
        json.dump({"unrelated": 1}, f)
    assert calibrate.load(path) is None


def test_calibrate_fit_dcn_peak(tmp_path, monkeypatch):
    """The DCN fit (ISSUE 19): predicted slow-tier bytes over the
    measured exposed DCN seconds p50 → peak_dcn_bytes_per_sec; an armed
    file feeds tracing.dcn_spec with source='calibrated', outranking the
    APEX_TPU_PEAK_DCN_GBPS env knob."""
    from apex_tpu.monitor import tracing

    recs = [_run_record(
        wall=0.1,
        measured={"timeline": {"tiers": {"dcn_s": {"p50": 0.01}}}},
        predicted={"dcn_bytes_per_step": 2.5e7}) for _ in range(3)]
    fit = calibrate.fit(recs)
    assert fit["peak_dcn_bytes_per_sec"] == 2.5e9  # 2.5e7 B / 0.01 s
    assert fit["n_records"]["peak_dcn_bytes_per_sec"] == 3
    path = str(tmp_path / "cal.json")
    calibrate.save(path, fit)
    monkeypatch.setenv("APEX_TPU_PEAK_DCN_GBPS", "9.9")  # outranked
    monkeypatch.setenv(calibrate.ENV_CALIBRATION, path)
    spec = tracing.dcn_spec("tpu v4")
    assert spec["dcn_bytes_per_sec"] == 2.5e9
    assert spec["source"] == "calibrated"
    monkeypatch.delenv(calibrate.ENV_CALIBRATION)
    spec = tracing.dcn_spec("tpu v4")
    assert spec["dcn_bytes_per_sec"] == 9.9e9
    assert spec["source"] == "env"


def test_calibration_file_outranks_peak_env(tmp_path, monkeypatch):
    from apex_tpu.monitor import mfu, tracing

    path = str(tmp_path / "cal.json")
    calibrate.save(path, {"source": "calibrated", "peak_flops": 2e12,
                          "peak_ici_bytes_per_sec": 5e10,
                          "peak_hbm_bytes_per_sec": 3e11})
    monkeypatch.setenv("APEX_TPU_PEAK_FLOPS", "9e99")  # the hand-typed lie
    monkeypatch.setenv(calibrate.ENV_CALIBRATION, path)
    spec = mfu.peak_spec("tpu v4")
    assert spec["peak_flops"] == 2e12
    assert "calibrated" in spec["source"]
    ici = tracing.ici_spec()
    assert ici["ici_bytes_per_sec"] == 5e10
    assert ici["source"] == "calibrated"
    # disarmed: env override wins again, nothing calibrated
    monkeypatch.delenv(calibrate.ENV_CALIBRATION)
    spec = mfu.peak_spec("tpu v4")
    assert spec["peak_flops"] == 9e99
    assert "calibrated" not in spec["source"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_list_trend_regress_calibrate(tmp_path, capsys):
    path = str(tmp_path / "ledger.jsonl")
    for rate in (1000.0, 1000.0, 700.0):
        ledger.append(path, dict(
            _run_record(rate=rate), fingerprint=ledger.config_fingerprint(
                {"tp": 2, "pp": 1}),
            predicted={"flops_per_step": 1e9}))
    assert ledger.main(["list", path, "--format", "json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 3 and rows[0]["tokens_per_sec_p50"] == 1000.0
    assert ledger.main(["trend", path, "--format", "json"]) == 0
    capsys.readouterr()
    # the seeded 30% drop exits non-zero with the machine shape on stdout
    assert ledger.main(["regress", path, "--format", "json"]) == 1
    res = json.loads(capsys.readouterr().out)
    assert res["regressed"] == ["tokens_per_sec_p50"]
    cal = str(tmp_path / "cal.json")
    assert ledger.main(["calibrate", path, "--output", cal,
                        "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fit"].get("peak_flops") and os.path.exists(cal)
    # a missing ledger file degrades to the empty verdict, rc 0
    assert ledger.main(["regress", str(tmp_path / "nope.jsonl")]) == 0
    capsys.readouterr()
