"""Sequence-parallel attention: ring + Ulysses vs full attention.

Pattern: serial-vs-sharded equivalence on a real-collective virtual CPU mesh
(SURVEY.md §4 — the TPU analog of the reference's serial-vs-parallel layer
tests, tests/L0/run_transformer/run_layers_test.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.transformer.ring import ring_attention, ulysses_attention

CP = 4
B, H, S, D = 2, 4, 128, 16  # 32 tokens per shard


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:CP]), ("context",))


def _qkv(key, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), dtype)
    k = jax.random.normal(kk, (B, H, S, D), dtype)
    v = jax.random.normal(kv, (B, H, S, D), dtype)
    return q, k, v


def _sharded(mesh, fn):
    spec = P(None, None, "context", None)
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, check_vma=False)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["pallas", "xla"])
def test_ring_forward_matches_full(mesh, causal, impl):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    fn = _sharded(mesh, lambda a, b_, c: ring_attention(
        a, b_, c, causal=causal, impl=impl, block_q=16, block_k=16))
    got = fn(q, k, v)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_full(mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    cot = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D))

    ring = _sharded(mesh, lambda a, b_, c: ring_attention(
        a, b_, c, causal=causal, impl="pallas", block_q=16, block_k=16))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * cot)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(3))
    fn = _sharded(mesh, lambda a, b_, c: ulysses_attention(a, b_, c, causal=causal))
    got = fn(q, k, v)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads(mesh):
    q, k, v = _qkv(jax.random.PRNGKey(4))
    cot = jax.random.normal(jax.random.PRNGKey(5), (B, H, S, D))
    fn = _sharded(mesh, lambda a, b_, c: ulysses_attention(a, b_, c, causal=True))
    got = jax.grad(lambda *xs: jnp.sum(fn(*xs) * cot), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda *xs: jnp.sum(mha_reference(*xs, causal=True) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


def test_ring_rejects_nothing_on_odd_shapes(mesh):
    # Shapes outside the Pallas envelope (seq not 8-aligned) fall back to the
    # XLA ring and still match (the fused_softmax.py:151-171 fallback pattern).
    b, h, s, d = 1, 2, 4 * 9, 8
    kq = jax.random.PRNGKey(6)
    q = jax.random.normal(kq, (b, h, s, d))
    spec = P(None, None, "context", None)
    fn = jax.jit(jax.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    got = fn(q, q, q)
    want = mha_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


# -- segment ids riding the ring (VERDICT r3 ask #4) -------------------------


def _seg_case(key, s_total):
    """q/k/v at (B, H, s_total, D) plus a padding-style segment array:
    batch row 0 pads the last quarter, row 1 the last half — so shards hold
    genuinely different id slices."""
    q, k, v = (jax.random.normal(kk, (B, H, s_total, D))
               for kk in jax.random.split(key, 3))
    seg = np.ones((B, s_total), np.int32)
    seg[0, -s_total // 4:] = 0
    seg[1, -s_total // 2:] = 0
    return q, k, v, jnp.asarray(seg)


def _sharded_seg(mesh, fn):
    spec = P(None, None, "context", None)
    sspec = P(None, "context")
    return jax.jit(
        jax.shard_map(fn, mesh=mesh,
                      in_specs=(spec, spec, spec, sspec),
                      out_specs=spec, check_vma=False))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["pallas", "xla"])
def test_ring_segment_ids_match_full(mesh, causal, impl):
    """Padding mask as segment ids whose kv shards rotate with K/V: the
    sharded ring equals full attention with the same global mask. S=512 on
    cp=4 gives 128-token shards, large enough that impl='pallas' really
    exercises the kernel's segment path (blk_k = 128)."""
    q, k, v, seg = _seg_case(jax.random.PRNGKey(7), 512)
    fn = _sharded_seg(mesh, lambda a, b_, c, s: ring_attention(
        a, b_, c, causal=causal, impl=impl, segment_ids=(s, s), pad_id=0,
        block_q=128, block_k=128))
    got = fn(q, k, v, seg)
    want = mha_reference(q, k, v, causal=causal, segment_ids=(seg, seg),
                         pad_id=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("impl", ["pallas", "xla"])
def test_ring_segment_grads_match_full(mesh, impl):
    q, k, v, seg = _seg_case(jax.random.PRNGKey(8), 512)
    cot = jax.random.normal(jax.random.PRNGKey(9), q.shape)
    fn = _sharded_seg(mesh, lambda a, b_, c, s: ring_attention(
        a, b_, c, causal=True, impl=impl, segment_ids=(s, s), pad_id=0,
        block_q=128, block_k=128))
    got = jax.grad(lambda *xs: jnp.sum(fn(*xs, seg) * cot),
                   argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda *xs: jnp.sum(mha_reference(
            *xs, causal=True, segment_ids=(seg, seg), pad_id=0) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


def test_ulysses_segment_ids_match_full(mesh):
    q, k, v, seg = _seg_case(jax.random.PRNGKey(10), 512)
    fn = _sharded_seg(mesh, lambda a, b_, c, s: ulysses_attention(
        a, b_, c, causal=False, segment_ids=(s, s), pad_id=0))
    got = fn(q, k, v, seg)
    want = mha_reference(q, k, v, segment_ids=(seg, seg), pad_id=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
