"""Collective round-trip tests on the virtual CPU mesh.

Models tests/L0/run_transformer/run_mappings_test.py (collective round
trips with known expected values) but with real XLA collectives in one
process (SURVEY.md §4 closing note).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc


@pytest.fixture
def mesh():
    m = parallel.initialize_model_parallel(tensor_model_parallel_size=4)
    yield m
    parallel.destroy_model_parallel()


def _smap(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


def test_psum_pmean(mesh):
    x = jnp.arange(8.0)

    def body(x):
        return cc.psum(x, "model"), cc.pmean(x, "model")

    s, m = _smap(mesh, body, P("model"), (P(), P()))(x)
    np.testing.assert_allclose(s, np.array([0 + 2 + 4 + 6, 1 + 3 + 5 + 7], np.float32))
    np.testing.assert_allclose(m, np.array([3.0, 4.0]))


def test_all_gather_reduce_scatter_roundtrip(mesh):
    x = jnp.arange(16.0).reshape(16, 1)

    def body(x):
        g = cc.all_gather(x, "model")          # every shard: full 16 rows
        return cc.reduce_scatter(g, "model")   # sum of 4 copies, re-scattered

    out = _smap(mesh, body, P("model", None), P("model", None))(x)
    np.testing.assert_allclose(out, 4.0 * np.arange(16.0).reshape(16, 1))


def test_ppermute_ring_shift(mesh):
    x = jnp.arange(4.0)

    def body(x):
        return cc.ppermute_shift(x, "model", shift=1)

    out = _smap(mesh, body, P("model"), P("model"))(x)
    # rank r's value lands on rank r+1 (mod 4)
    np.testing.assert_allclose(out, np.array([3.0, 0.0, 1.0, 2.0]))


def test_broadcast_from_src(mesh):
    x = jnp.arange(4.0)

    def body(x):
        return cc.broadcast(x, "model", src=2)

    out = _smap(mesh, body, P("model"), P("model"))(x)
    np.testing.assert_allclose(out, np.full(4, 2.0))


def test_axis_rank_size(mesh):
    def body():
        return cc.axis_rank("model")[None], jnp.full((1,), cc.axis_size("model"))

    r, s = _smap(mesh, body, (), (P("model"), P("model")))()
    np.testing.assert_array_equal(r, np.arange(4))
    np.testing.assert_array_equal(s, np.full(4, 4))


def test_all_to_all(mesh):
    # 4 shards each hold (4, 2); all_to_all swaps shard axis: afterwards each
    # holds rows j of every source — a transpose of the block layout.
    x = jnp.arange(32.0).reshape(16, 2)

    def body(x):
        return cc.all_to_all(x, "model", split_axis=0, concat_axis=1)

    out = _smap(mesh, body, P("model", None), P("model", None))(x)
    assert out.shape == (4, 8)
    # global row 0 of shard 0 is source-shard-0 row 0 ‖ shard-1 row 0 ‖ ...
    np.testing.assert_allclose(out[0], np.array([0, 1, 8, 9, 16, 17, 24, 25], np.float32))


def test_pmax_tree(mesh):
    tree = {"a": jnp.arange(4.0), "b": jnp.arange(4.0) * -1}

    def body(t):
        return cc.pmax(t, "model")

    out = _smap(mesh, body, P("model"), P())(tree)
    np.testing.assert_allclose(out["a"], [3.0])
    np.testing.assert_allclose(out["b"], [0.0])
