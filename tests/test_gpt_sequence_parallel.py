"""GPT with context (sequence) parallelism — the long-context capability the
reference lacks (SURVEY.md §2.3 row SP), integrated into the flagship model.

Contract: a GPT whose sequence dim is sharded over the ``context`` axis
(ring attention or Ulysses all-to-all inside the layer stack, position
embeddings offset per shard, per-token loss averaged over the axis) computes
the same loss and gradients as the serial model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.parallel import mesh as mesh_lib

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=32,
    hidden_dropout=0.0,
    compute_dtype=jnp.float32,
    remat=False,
)


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    if mesh_lib.model_parallel_is_initialized():
        mesh_lib.destroy_model_parallel()


@pytest.mark.parametrize("sp_impl,unroll", [
    ("ring", False), ("ulysses", False),
    # the unrolled layer drive must compose with both sequence-parallel
    # collectives (ppermute / all_to_all inside a Python loop body
    # instead of a scanned one)
    ("ring", True),
    ("ulysses", True),
])
def test_gpt_context_parallel_matches_serial(sp_impl, unroll):
    serial = GPTModel(GPTConfig(axis=None, **TINY))
    par = GPTModel(GPTConfig(
        axis=None, context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=sp_impl, unroll_layers=unroll, **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    mesh = mesh_lib.make_virtual_mesh(4, context_parallel_size=4)

    def sp_step(p, toks, tgt):
        # local per-token mean, then grads pmean'd over the context axis —
        # the same reduction DP does over 'data' (context is a gradient
        # reduction axis, mesh.get_gradient_reduction_axes)
        loss, g = jax.value_and_grad(par.loss)(p, toks, tgt)
        return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

    seq_spec = P(None, mesh_lib.AXIS_CONTEXT)  # shard dim 1 (sequence)
    fn = jax.jit(jax.shard_map(
        sp_step, mesh=mesh,
        in_specs=(P(), seq_spec, seq_spec), out_specs=(P(), P()),
        check_vma=False))
    v_p, g_p = fn(params, toks, tgt)
    v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(jax.device_get(g_p))):
        np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)


def test_gpt_context_parallel_bad_impl_rejected():
    par = GPTModel(GPTConfig(
        axis=None, context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl="nope", **TINY))
    mesh = mesh_lib.make_virtual_mesh(4, context_parallel_size=4)
    toks = jnp.zeros((2, 32), jnp.int32)
    with pytest.raises(ValueError, match="ring.*ulysses|ulysses.*ring"):
        jax.shard_map(
            lambda p, t: par.loss(p, t, t), mesh=mesh,
            in_specs=(P(), P(None, mesh_lib.AXIS_CONTEXT)), out_specs=P(),
            check_vma=False,
        )(par.init(jax.random.PRNGKey(0)), toks)


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_gpt_window_context_parallel_matches_serial(sp_impl):
    """Sliding-window attention (GPTConfig.attention_window) under context
    parallelism: the window mask is defined in global positions, so the
    sharded model must reproduce the serial windowed loss and grads —
    including across-shard windows (window 12 spans the 8-token shard
    boundary at cp=4)."""
    serial = GPTModel(GPTConfig(axis=None, attention_window=12, **TINY))
    par = GPTModel(GPTConfig(
        axis=None, context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=sp_impl, attention_window=12, **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    # the window must actually change the function (else this test would
    # pass with the mask dropped on the floor)
    dense = GPTModel(GPTConfig(axis=None, **TINY))
    assert abs(float(serial.loss(params, toks, tgt))
               - float(dense.loss(params, toks, tgt))) > 1e-6

    mesh = mesh_lib.make_virtual_mesh(4, context_parallel_size=4)

    def sp_step(p, toks, tgt):
        loss, g = jax.value_and_grad(par.loss)(p, toks, tgt)
        return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

    seq_spec = P(None, mesh_lib.AXIS_CONTEXT)
    fn = jax.jit(jax.shard_map(
        sp_step, mesh=mesh,
        in_specs=(P(), seq_spec, seq_spec), out_specs=(P(), P()),
        check_vma=False))
    v_p, g_p = fn(params, toks, tgt)
    v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(jax.device_get(g_p))):
        np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)


def test_rope_relative_shift_invariance():
    """apply_rope's defining property: scores depend only on relative
    distance — shifting every position by a constant leaves q·k
    unchanged. This is what makes shard-offset positions exact under CP."""
    from apex_tpu.models._transformer import apply_rope

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 8, 16))
    pos = jnp.arange(8)
    for shift in (1, 100, 10000):
        s0 = jnp.einsum("bhqd,bhkd->bhqk", apply_rope(q, pos),
                        apply_rope(k, pos))
        s1 = jnp.einsum("bhqd,bhkd->bhqk", apply_rope(q, pos + shift),
                        apply_rope(k, pos + shift))
        np.testing.assert_allclose(np.asarray(s0), np.asarray(s1),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_gpt_rope_context_parallel_matches_serial(sp_impl):
    """GPTConfig.position_embedding='rope' (no position table at all)
    under context parallelism: per-shard GLOBAL positions must reproduce
    the serial rotary model, values and grads."""
    serial = GPTModel(GPTConfig(axis=None, position_embedding="rope",
                                **TINY))
    par = GPTModel(GPTConfig(
        axis=None, context_axis=mesh_lib.AXIS_CONTEXT,
        sequence_parallel_impl=sp_impl, position_embedding="rope", **TINY))
    params = serial.init(jax.random.PRNGKey(0))
    assert "position" not in params  # rope has NO position parameters
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)

    # rope must actually position-encode. At 0.02-std init the scores are
    # ~0 and softmax is near-uniform, so ANY positional scheme barely
    # moves the outputs — sharpen attention (scale the qkv kernels) to
    # discriminate rope from none on the logits.
    sharp = dict(params)
    sharp["layers"] = dict(params["layers"])
    sharp["layers"]["qkv"] = jax.tree.map(lambda x: x * 20.0,
                                          params["layers"]["qkv"])
    none = GPTModel(GPTConfig(axis=None, position_embedding="none", **TINY))
    ldiff = float(jnp.max(jnp.abs(serial.apply(sharp, toks)
                                  - none.apply(sharp, toks))))
    assert ldiff > 1e-2, ldiff

    mesh = mesh_lib.make_virtual_mesh(4, context_parallel_size=4)

    def sp_step(p, toks, tgt):
        loss, g = jax.value_and_grad(par.loss)(p, toks, tgt)
        return (jax.lax.pmean(loss, mesh_lib.AXIS_CONTEXT),
                jax.lax.pmean(g, mesh_lib.AXIS_CONTEXT))

    seq_spec = P(None, mesh_lib.AXIS_CONTEXT)
    fn = jax.jit(jax.shard_map(
        sp_step, mesh=mesh,
        in_specs=(P(), seq_spec, seq_spec), out_specs=(P(), P()),
        check_vma=False))
    v_p, g_p = fn(params, toks, tgt)
    v_s, g_s = jax.value_and_grad(serial.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(v_s), float(v_p), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(jax.device_get(g_p))):
        np.testing.assert_allclose(a, np.asarray(b), rtol=2e-4, atol=2e-4)


def test_gpt_position_embedding_validation():
    with pytest.raises(ValueError, match="position_embedding"):
        GPTModel(GPTConfig(axis=None, position_embedding="alibi", **TINY))
