"""ASP class-workflow tests (reference: apex/contrib/sparsity/asp.py and
its test/toy_problem.py train-with-masks flow)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.contrib.sparsity import ASP, sequential_groups


@pytest.fixture(autouse=True)
def _reset_asp():
    ASP.reset()
    yield
    ASP.reset()


def _params(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "fc0": {"kernel": jax.random.normal(k1, (8, 16)), "bias": jnp.zeros(16)},
        "fc1": {"kernel": jax.random.normal(k2, (16, 16)), "bias": jnp.zeros(16)},
        "head": {"kernel": jax.random.normal(k3, (16, 4)), "bias": jnp.zeros(4)},
    }


def _sparsity(leaf):
    return float((np.asarray(leaf) == 0).mean())


def test_full_workflow_preserves_pattern_through_training():
    params = _params()
    ASP.init_model_for_pruning(params, "m4n2_1d")
    tx = ASP.init_optimizer_for_pruning(optax.adam(1e-2))
    assert not ASP.is_sparsity_enabled()
    params, masks = ASP.compute_sparse_masks(params)
    assert ASP.is_sparsity_enabled()
    assert _sparsity(params["fc0"]["kernel"]) == pytest.approx(0.5)

    x = jax.random.normal(jax.random.PRNGKey(9), (4, 8))
    y = jax.random.normal(jax.random.PRNGKey(10), (4, 4))

    def loss_fn(p):
        h = jax.nn.relu(x @ p["fc0"]["kernel"] + p["fc0"]["bias"])
        h = jax.nn.relu(h @ p["fc1"]["kernel"] + p["fc1"]["bias"])
        return jnp.mean((h @ p["head"]["kernel"] + p["head"]["bias"] - y) ** 2)

    state = tx.init(params)
    l0 = float(loss_fn(params))
    for _ in range(20):
        grads = jax.grad(loss_fn)(params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss_fn(params)) < l0
    # the 2:4 pattern survived training: pruned slots still zero
    for name in ("fc0", "fc1", "head"):
        m = np.asarray(masks[name]["kernel"])
        assert not np.any(np.asarray(params[name]["kernel"])[~m])


def test_name_filters():
    params = _params()
    ASP.init_model_for_pruning(params, "m4n2_1d",
                               disallowed_layer_names=["head"])
    ASP.init_optimizer_for_pruning(optax.sgd(1e-2))
    _, masks = ASP.compute_sparse_masks(params)
    assert masks["fc0"]["kernel"] is not None
    assert masks["head"]["kernel"] is None
    ASP.reset()
    ASP.init_model_for_pruning(params, allowed_layer_names=["fc1"])
    _, masks = ASP.compute_sparse_masks(params)
    assert masks["fc0"]["kernel"] is None
    assert masks["fc1"]["kernel"] is not None


def test_pattern_string_m8n4():
    params = {"w": {"kernel": jax.random.normal(jax.random.PRNGKey(0), (16, 8))}}
    ASP.init_model_for_pruning(params, "m8n4_1d")
    pruned, masks = ASP.compute_sparse_masks(params)
    assert _sparsity(pruned["w"]["kernel"]) == pytest.approx(0.5)
    # groups of 8 along the contraction dim each keep exactly 4
    m = np.asarray(masks["w"]["kernel"])
    assert (m.reshape(2, 8, 8).sum(axis=1) == 4).all()


def test_restore_pruned_weights_roundtrip():
    params = _params()
    ASP.init_model_for_pruning(params, allow_recompute_mask=True)
    pruned, _ = ASP.compute_sparse_masks(params)
    dense = ASP.restore_pruned_weights(pruned)
    assert not ASP.is_sparsity_enabled()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-7), dense, params)


def test_prune_trained_model_one_call_with_permutation():
    params = _params(seed=3)
    groups = sequential_groups(["fc0", "fc1", "head"])
    pruned, masks, tx = ASP.prune_trained_model(params, optax.adam(1e-3),
                                                permutation_groups=groups)
    assert ASP.is_sparsity_enabled()
    assert _sparsity(pruned["fc1"]["kernel"]) == pytest.approx(0.5)
    state = tx.init(pruned)
    grads = jax.tree.map(jnp.ones_like, pruned)
    updates, _ = tx.update(grads, state, pruned)
    # masked slots receive zero update
    m = np.asarray(masks["fc1"]["kernel"])
    assert not np.any(np.asarray(updates["fc1"]["kernel"])[~m])


def test_explicit_masks_kwarg_under_jit():
    """Masks passed explicitly are traced values: a step compiled once with
    all-ones masks (sparsity off) masks correctly when later called with
    real masks — no retrace, no baked-in constants."""
    params = _params()
    ASP.init_model_for_pruning(params)
    tx = ASP.init_optimizer_for_pruning(optax.sgd(1e-1))
    state = tx.init(params)

    traces = 0

    @jax.jit
    def step(p, s, masks):
        nonlocal traces
        traces += 1
        g = jax.tree.map(jnp.ones_like, p)
        u, s = tx.update(g, s, p, masks=masks)
        return optax.apply_updates(p, u), s

    pruned, masks = ASP.compute_sparse_masks(params)
    ones_masks = jax.tree.map(
        lambda m: None if m is None else jnp.ones_like(m),
        masks, is_leaf=lambda x: x is None)
    # trace with sparsity effectively off
    p1, _ = step(pruned, state, ones_masks)
    m = np.asarray(masks["fc0"]["kernel"])
    assert np.any(np.asarray(p1["fc0"]["kernel"])[~m])  # updates flowed
    # same compiled fn, real masks: pruned slots frozen
    p2, _ = step(pruned, state, masks)
    assert not np.any(np.asarray(p2["fc0"]["kernel"])[~m])
    assert traces == 1, "mask values must be traced, not trigger retrace"


def test_eligibility_follows_pattern_group_size():
    # (12, 8) kernel: divisible by 4 but not 8 -> m8n4 must skip it, not crash
    params = {"w": {"kernel": jax.random.normal(jax.random.PRNGKey(0), (12, 8))}}
    ASP.init_model_for_pruning(params, "m8n4_1d")
    pruned, masks = ASP.compute_sparse_masks(params)
    assert masks["w"]["kernel"] is None
    ASP.reset()
    # m2n1 prunes dims divisible by 2 that m4 would skip
    params = {"w": {"kernel": jax.random.normal(jax.random.PRNGKey(0), (6, 8))}}
    ASP.init_model_for_pruning(params, "m2n1_1d")
    pruned, masks = ASP.compute_sparse_masks(params)
    assert masks["w"]["kernel"] is not None
    assert _sparsity(pruned["w"]["kernel"]) == pytest.approx(0.5)


def test_degenerate_patterns_rejected():
    for bad in ("m4n6_1d", "m4n4_1d", "m4n0_1d"):
        ASP.reset()
        with pytest.raises(ValueError, match="0 < n < m"):
            ASP.init_model_for_pruning(_params(), bad)


def test_name_filters_match_path_components_exactly():
    params = {
        "fc1": {"kernel": jax.random.normal(jax.random.PRNGKey(0), (8, 8))},
        "fc10": {"kernel": jax.random.normal(jax.random.PRNGKey(1), (8, 8))},
    }
    ASP.init_model_for_pruning(params, disallowed_layer_names=["fc1"])
    _, masks = ASP.compute_sparse_masks(params)
    assert masks["fc1"]["kernel"] is None      # excluded
    assert masks["fc10"]["kernel"] is not None  # NOT a substring match


def test_double_restore_errors():
    params = _params()
    ASP.init_model_for_pruning(params, allow_recompute_mask=True)
    pruned, _ = ASP.compute_sparse_masks(params)
    ASP.restore_pruned_weights(pruned)
    with pytest.raises(RuntimeError):
        ASP.restore_pruned_weights(pruned)


def test_double_init_errors():
    ASP.init_model_for_pruning(_params())
    with pytest.raises(RuntimeError, match="already"):
        ASP.init_model_for_pruning(_params())
    assert ASP.already_init_asp_model()


def test_works_under_mixed_precision_optimizer():
    # compose before MixedPrecisionOptimizer: masters stay masked
    from apex_tpu import amp
    params = _params()
    ASP.init_model_for_pruning(params)
    tx = ASP.init_optimizer_for_pruning(optax.adam(1e-3))
    params, masks = ASP.compute_sparse_masks(params)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(tx, policy)
    params = amp.cast_params(params, policy)
    # re-mask after the cast (bf16 rounding keeps zeros zero, but be explicit)
    state = mp_opt.init(params)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8))

    def scaled(p):
        h = x.astype(p["fc0"]["kernel"].dtype) @ p["fc0"]["kernel"]
        return mp_opt.scale_loss(jnp.mean(h.astype(jnp.float32) ** 2), state)

    sloss, sgrads = jax.value_and_grad(scaled)(params)
    new_params, state, _ = mp_opt.apply_gradients(state, params, sgrads)
    m = np.asarray(masks["fc0"]["kernel"])
    assert not np.any(np.asarray(new_params["fc0"]["kernel"])[~m])
    assert not np.any(np.asarray(state.master["fc0"]["kernel"])[~m])
