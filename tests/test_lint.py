"""Tests for apex_tpu.lint — the project-invariant linter (engine 1: source
AST rules) and the jaxpr hazard analyzers (engine 2: lane padding,
collective-transpose, recompile hazards) — plus the tier-1 contract that the
repo itself lints clean with every suppression justified.

The REAL-step tripwire tests share module-scoped StepIR fixtures (ISSUE
13): each canonical step callable traces ONCE on the shared walker
(apex_tpu.lint.ir) and the same IR feeds every analyzer that reads it —
the dedupe that measurably cut this module's wall time (PERF_NOTES.md).
The IR walker and pass framework have their own suite in
tests/test_lint_ir.py."""

import json
import textwrap

import jax.numpy as jnp
import pytest
from jax import lax

from apex_tpu.lint import RULES, Suppressions, comm_scope_check, run_paths
from apex_tpu.lint import ir as lint_ir
from apex_tpu.lint import trace
from apex_tpu.lint.cli import main as lint_main


def _write(tmp_path, relpath, body):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return path


# ---------------------------------------------------------------------------
# module-scoped step IRs: each real step callable traces ONCE, every
# analyzer below reads the same shared walk
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def zero3_gpt_irs():
    """StepIRs of the REAL fully-sharded (ZeRO-3) GPT drives: the
    serialized unrolled chunk_meta step (zero3_prefetch=0), the
    double-buffered drive (=1), and the bulk whole-stack-gather
    regression — one ``value_and_grad`` trace each for the whole
    module."""
    import jax

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.distributed import (
        gather_chunked_tree,
        gather_stacked_leaf,
    )

    base = dict(vocab_size=64, hidden_size=16, num_layers=4,
                num_attention_heads=2, max_seq_len=8, hidden_dropout=0.0,
                axis=None, unroll_layers=True)
    params = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(GPTModel(GPTConfig(**base)).init,
                       jax.random.PRNGKey(0)))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), amp.get_policy("O2"),
        zero_axis="data", zero_level=3)
    meta = mp_opt.zero3_meta(params)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jnp.zeros((2, 8), jnp.int32)

    def loss_fn(prefetch):
        model = GPTModel(GPTConfig(zero3_prefetch=prefetch, **base))

        def fn(p):
            chunks = mp_opt.zero3_shard(p)
            rest = gather_chunked_tree(
                {k: v for k, v in chunks.items() if k != "layers"},
                rest_meta)
            return model.loss(dict(rest, layers=chunks["layers"]),
                              toks, toks, layer_chunk_meta=layer_meta)
        return fn

    def bulk_loss(p):
        chunks = mp_opt.zero3_shard(p)
        layers = jax.tree.map(
            lambda c, s: gather_stacked_leaf(c, s.shape, s.dtype, "data"),
            chunks["layers"], layer_meta.shapes,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
        rest = gather_chunked_tree(
            {k: v for k, v in chunks.items() if k != "layers"}, rest_meta)
        return GPTModel(GPTConfig(**base)).loss(
            dict(rest, layers=layers), toks, toks)

    def mk(fn):
        return lint_ir.trace_ir(jax.value_and_grad(fn), params,
                                axes={"data": 8})

    return {"serialized": mk(loss_fn(0)), "prefetched": mk(loss_fn(1)),
            "bulk": mk(bulk_loss), "num_layers": base["num_layers"]}


@pytest.fixture(scope="module")
def gpt_sp_forward_irs():
    """StepIRs of the plain-TP and sequence-parallel GPT forwards — the
    model-level SP regression gate's two traces, shared module-wide."""
    import jax

    from apex_tpu.models import GPTConfig, GPTModel

    tiny = dict(vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_seq_len=16, hidden_dropout=0.0,
                compute_dtype=jnp.float32, remat=False)
    toks = jnp.zeros((2, 16), jnp.int32)
    irs = {}
    for sp in (False, True):
        model = GPTModel(GPTConfig(axis="model", sequence_parallel=sp,
                                   **tiny))
        params = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype),
            jax.eval_shape(model.init, jax.random.PRNGKey(0)))
        irs[sp] = lint_ir.trace_ir(
            lambda p, t, m=model: m.apply(p, t, jnp.roll(t, -1, -1)),
            params, toks, axes={"model": 2})
    return irs


@pytest.fixture(scope="module")
def zero_amp_step_irs():
    """StepIRs of the real MixedPrecisionOptimizer steps the redundancy
    and quantized-wire tripwires pin: the ZeRO LAMB step, the replicated
    twin, the int8-wire step (+ its residual tree), and the fp32-wire
    ZeRO Adam step — four traces for the whole module."""
    import types

    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam, FusedLAMB
    from apex_tpu.parallel.distributed import allreduce_gradients

    policy = amp.get_policy("O2")
    params = {"w": jnp.ones((64, 64), jnp.bfloat16)}
    grads = {"w": jnp.ones((64, 64), jnp.float32)}

    def step(opt, reduce_first=False):
        def fn(p, g):
            st = opt.init(p)
            if reduce_first:
                g = allreduce_gradients(g, ("data",))
            return opt.apply_gradients(st, p, g)[0]
        return lint_ir.trace_ir(fn, params, grads, axes={"data": 8})

    lamb_zero = amp.MixedPrecisionOptimizer(
        FusedLAMB(lr=1e-2, norm_psum_axis="data"), policy,
        zero_axis="data", gather_dtype="bf16", log_grad_norm=True)
    replicated = amp.MixedPrecisionOptimizer(FusedLAMB(lr=1e-2), policy)
    q8 = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data", reduce_dtype="int8")
    fp32_adam = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data")
    residual = q8.zero_abstract_state(
        params, types.SimpleNamespace(shape={"data": 8})).residual
    return {"zero": step(lamb_zero),
            "replicated": step(replicated, reduce_first=True),
            "q8": step(q8), "fp32_wire": step(fp32_adam),
            "residual": residual}


# ---------------------------------------------------------------------------
# the tier-1 contract: the repo lints clean
# ---------------------------------------------------------------------------


def test_repo_lints_clean_with_justified_suppressions():
    """Every invariant the linter mechanizes must HOLD over the tree — an
    unsuppressed finding here is a real regression of a documented
    convention (CLAUDE.md), and a suppression without a justification is a
    waiver nobody can audit."""
    rep = run_paths()
    assert not rep.errors, "\n".join(f.format() for f in rep.errors)
    assert rep.files_scanned >= 100, rep.files_scanned
    assert set(rep.rules_run) == set(RULES)
    for f in rep.suppressed:
        assert f.justification, f"unjustified suppression: {f.format()}"


def test_cli_strict_exits_zero_on_repo(capsys):
    assert lint_main(["--strict"]) == 0
    assert lint_main(["--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(out)
    assert payload["errors"] == 0
    assert payload["files_scanned"] >= 100


def test_cli_list_rules_and_unknown_rule(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in RULES:
        assert name in out
    assert "lane-padding" in out  # the trace analyzers are advertised
    assert lint_main(["--rules", "not-a-rule"]) == 2


# ---------------------------------------------------------------------------
# acceptance fixture: three distinct named rules on seeded hazards
# ---------------------------------------------------------------------------


def test_seeded_hazards_flagged_by_three_named_rules(tmp_path):
    """The ISSUE acceptance: a bare pmean(loss) under grad, a missing
    comm: scope, and a (sq, 1) f32 operand are each flagged by a distinct
    named rule (grad-collective, comm-scope, lane-padding)."""
    bad = _write(tmp_path, "bad_step.py", '''
        """Deliberately-hazardous fixture."""
        import jax
        from jax import lax

        from apex_tpu.monitor.comms import collective_scope

        def unscoped_verb(tree, axis):
            return lax.psum(tree, axis)

        def loss_fn(params, batch):
            loss = lax.pmean((params * batch).sum(), "data")
            return loss

        step_grads = jax.grad(loss_fn)
    ''')
    rep = run_paths(paths=[str(bad)], root=str(tmp_path))
    by_rule = {}
    for f in rep.errors:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert "comm-scope" in by_rule, rep.findings
    assert any("unscoped_verb" in m for m in by_rule["comm-scope"])
    assert "grad-collective" in by_rule, rep.findings
    assert any("pmean" in m for m in by_rule["grad-collective"])

    # third distinct rule, engine 2: the (sq, 1) f32 operand
    pad = trace.lane_padding_report(
        lambda w: w * 2.0, jnp.ones((512, 1), jnp.float32), min_bytes=0)
    flagged = [f for f in pad["findings"] if f["shape"] == [512, 1]]
    assert flagged and flagged[0]["rule"] == "lane-padding"
    assert {"comm-scope", "grad-collective", flagged[0]["rule"]} == {
        "comm-scope", "grad-collective", "lane-padding"}


# ---------------------------------------------------------------------------
# engine 1 rules, one fixture each
# ---------------------------------------------------------------------------


def test_comm_scope_check_reports_violations_and_verbs(tmp_path):
    path = _write(tmp_path, "verbs.py", '''
        from jax import lax
        from apex_tpu.monitor.comms import collective_scope as _comm

        def good(tree, axis):
            with _comm("psum", axis, tree):
                return lax.psum(tree, axis)

        def bad(tree, axis):
            return lax.pmean(tree, axis)
    ''')
    violations, verbs = comm_scope_check(str(path))
    assert verbs == 2
    assert violations == [("bad", ["pmean"])]


def test_comm_scope_skips_files_outside_contract(tmp_path):
    # raw lax collectives WITHOUT the scope-helper import or marker are
    # other rules' business (model code psums activations legitimately)
    path = _write(tmp_path, "model.py", '''
        from jax import lax

        def stats(x, axis):
            return lax.pmean(x, axis)
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    assert not [f for f in rep.findings if f.rule == "comm-scope"]


def test_comm_scope_marker_opts_in(tmp_path):
    path = _write(tmp_path, "marked.py", '''
        from jax import lax

        LINT_COMM_SCOPE = True

        def verb(x, axis):
            return lax.psum(x, axis)
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    assert [f for f in rep.errors if f.rule == "comm-scope"]


def test_grad_collective_lambda_and_clean_variants(tmp_path):
    path = _write(tmp_path, "grads.py", '''
        import jax
        from jax import lax
        from apex_tpu.parallel import collectives

        g1 = jax.value_and_grad(lambda p: collectives.pmean(p.sum(), "data"))

        def clean_loss(p):
            return p.sum() * 2.0

        def train(p):
            loss, grads = jax.value_and_grad(clean_loss)(p)
            # reducing AFTER the grad call is the documented-correct shape
            return collectives.pmean(loss, "data"), grads
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    hits = [f for f in rep.errors if f.rule == "grad-collective"]
    assert len(hits) == 1 and "<lambda>" in hits[0].message


def test_pallas_interpret_rule(tmp_path):
    path = _write(tmp_path, "kern.py", '''
        from jax.experimental import pallas as pl

        def good(x):
            return pl.pallas_call(kernel, out_shape=x, interpret=True)(x)

        def bad(x):
            return pl.pallas_call(kernel, out_shape=x)(x)
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    hits = [f for f in rep.errors if f.rule == "pallas-interpret"]
    assert len(hits) == 1 and hits[0].line == 8


def test_module_citation_rule(tmp_path):
    flagged = _write(tmp_path, "apex_tpu/nocite.py", '"""Does things."""\n')
    cited = _write(tmp_path, "apex_tpu/cited.py",
                   '"""X (reference: apex/foo/bar.py:10-20)."""\n')
    waived = _write(tmp_path, "apex_tpu/waived.py",
                    '"""Y. No reference analog: invented here."""\n')
    outside = _write(tmp_path, "examples/nocite.py", '"""Free-form."""\n')
    rep = run_paths(paths=[str(p) for p in (flagged, cited, waived, outside)],
                    root=str(tmp_path))
    hits = [f for f in rep.errors if f.rule == "module-citation"]
    assert [f.path for f in hits] == ["apex_tpu/nocite.py"]


def test_bare_block_until_ready_rule(tmp_path):
    path = _write(tmp_path, "timing.py", '''
        import time
        import jax

        def timed_loop(step, params):
            t0 = time.perf_counter()
            params = step(params)
            jax.block_until_ready(params)
            return time.perf_counter() - t0

        def warmup_sync(params):
            # no clock in this scope: a bare sync is fine here
            jax.block_until_ready(params)
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    hits = [f for f in rep.errors if f.rule == "bare-block-until-ready"]
    assert len(hits) == 1 and hits[0].line == 8


def test_exception_retention_rule(tmp_path):
    path = _write(tmp_path, "oom.py", '''
        def retains(fn):
            errs = []
            try:
                fn()
            except Exception as e:
                errs.append(e)
            return errs

        def stores(self, fn):
            try:
                fn()
            except Exception as e:
                self.last = e

        def sanitizes(fn):
            try:
                fn()
            except Exception as e:
                return {"error": str(e)[:100]}
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    hits = [f for f in rep.errors if f.rule == "exception-retention"]
    assert sorted(f.line for f in hits) == [7, 14]  # append + attr store
    assert not any(f.line > 14 for f in hits)  # str(e) never flags


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_inline_and_comment_line_above(tmp_path):
    path = _write(tmp_path, "sup.py", '''
        from jax.experimental import pallas as pl

        def a(x):
            return pl.pallas_call(k)(x)  # lint: disable=pallas-interpret -- helper resolves it

        def b(x):
            # lint: disable=pallas-interpret -- wrapped by caller
            return pl.pallas_call(k)(x)

        def c(x):
            return pl.pallas_call(k)(x)
    ''')
    rep = run_paths(paths=[str(path)], root=str(tmp_path))
    hits = [f for f in rep.findings if f.rule == "pallas-interpret"]
    assert len(hits) == 3
    assert [f.suppressed for f in sorted(hits, key=lambda f: f.line)] == [
        True, True, False]
    assert all(f.justification for f in hits if f.suppressed)


def test_suppression_file_wide():
    sup = Suppressions(
        "# lint: disable-file=comm-scope -- generated file\nx = 1\n")
    assert sup.match("comm-scope", 99) == (True, "generated file")
    assert sup.match("grad-collective", 99) is None


def test_suppression_directive_inside_string_is_documentation():
    """A directive quoted in a docstring or string literal documents the
    grammar; it must never become a live file-wide waiver."""
    sup = Suppressions(
        '"""Grammar doc:\n'
        "    # lint: disable-file=comm-scope -- generated file\n"
        '"""\n'
        "s = '# lint: disable=grad-collective -- also quoted'\n"
        "x = 1\n")
    assert sup.match("comm-scope", 5) is None
    assert sup.match("grad-collective", 4) is None
    assert sup.file_wide == {}


def test_suppression_pending_does_not_leak_past_inline_directive():
    """A comment-only directive above a line that carries its own inline
    directive binds to THAT line (both apply) — it must not skip ahead and
    waive an unrelated later violation."""
    sup = Suppressions(
        "# lint: disable=rule-a -- above\n"
        "x = foo()  # lint: disable=rule-b -- inline\n"
        "y = bar()\n")
    assert sup.match("rule-a", 2) == (True, "above")
    assert sup.match("rule-b", 2) == (True, "inline")
    assert sup.match("rule-a", 3) is None


def test_nonexistent_path_fails_loudly(tmp_path):
    """A typo'd CI path must never lint 0 files and exit green."""
    with pytest.raises(ValueError, match="does not exist"):
        run_paths(paths=[str(tmp_path / "no_such_tree")])
    assert lint_main(["--strict", str(tmp_path / "no_such_tree")]) == 2


# ---------------------------------------------------------------------------
# engine 2: lane-padding auditor against the known numbers
# ---------------------------------------------------------------------------


def test_lane_padding_known_numbers():
    """The satellite contract: d=32 pads 4x to 128 lanes; a (sq, 1) f32
    window costs sq*128*4 resident bytes; a dense (b, h, nq, blk_q) lse
    table is pad-free (the flash_attention streamed-kernel design)."""

    def fn(q, w, lse):
        return (q * 2.0).sum() + w.sum() + lse.sum()

    q = jnp.ones((2, 4, 128, 32), jnp.float32)    # d=32 head
    w = jnp.ones((512, 1), jnp.float32)           # (sq, 1) f32 window
    lse = jnp.ones((2, 4, 8, 128), jnp.float32)   # dense (b, h, nq, blk_q)
    rep = trace.lane_padding_report(fn, q, w, lse, min_bytes=0)
    by_shape = {tuple(f["shape"]): f for f in rep["findings"]}

    head = by_shape[(2, 4, 128, 32)]
    assert head["waste_ratio"] == 4.0
    assert head["padded_bytes"] == 4 * head["bytes"]
    assert "pads to 128 lanes" in head["message"]

    window = by_shape[(512, 1)]
    assert window["padded_bytes"] == 512 * 128 * 4
    assert window["waste_ratio"] == 128.0
    assert "dense" in window["message"]  # the lse-table remediation hint

    assert (2, 4, 8, 128) not in by_shape  # dense tables are pad-free
    assert rep["audited"] >= 3
    assert rep["waste_bytes"] == (head["padded_bytes"] - head["bytes"]
                                  + window["padded_bytes"] - window["bytes"])


def test_tiling_constants_single_source_of_truth():
    """The auditor's byte math (monitor.hbm.lane_padded_bytes) and the
    calibrated flash-attention constants it is documented against must
    agree — if flash_attention ever recalibrates NUM_LANES/NUM_SUBLANES,
    this failure is the signal to update the hbm tiling rule too, instead
    of the two silently diverging."""
    from apex_tpu.monitor.hbm import lane_padded_bytes
    from apex_tpu.ops import flash_attention as fa

    assert fa.NUM_LANES == 128 and fa.NUM_SUBLANES == 8
    # one f32 tile row: lanes x sublanes x itemsize under both rule sets
    assert lane_padded_bytes((1, 1), 4) == fa.NUM_LANES * fa.NUM_SUBLANES * 4
    # the public resident-layout estimator counts the same lane padding
    # the auditor reports: d=32 occupies a full 128-lane tile in K+V
    sk, d, item = 2048, 32, 2
    d_eff = -(-d // fa.NUM_LANES) * fa.NUM_LANES
    assert fa.resident_vmem_bytes(2048, sk, d, 512, 512, item,
                                  False, False) >= 2 * sk * d_eff * item


def test_lane_padding_min_bytes_and_truncation():
    def fn(w):
        return w * 2.0

    w = jnp.ones((8, 1), jnp.float32)  # 4 KB padded: under the default floor
    assert not trace.lane_padding_report(fn, w)["findings"]
    full = trace.lane_padding_report(fn, w, min_bytes=0, max_findings=1)
    # input + output both flagged; truncation is reported, never silent
    assert len(full["findings"]) == 1 and full["findings_truncated"] == 1


def test_lane_padding_audits_pallas_boundaries():
    """Operands crossing a pallas_call boundary are audited even when the
    top-level signature is clean (the custom-call HBM-layout tax)."""
    from apex_tpu.ops.softmax import scaled_masked_softmax

    x = jnp.ones((2, 2, 8, 256), jnp.float32)  # minor dim 256: pad-free
    rep = trace.lane_padding_report(
        lambda a: scaled_masked_softmax(a, impl="pallas"), x)
    assert rep["audited"] >= 4  # signature + pallas operands/results
    assert not rep["findings"]


# ---------------------------------------------------------------------------
# engine 2: collective-transpose hazard detector
# ---------------------------------------------------------------------------


def test_transpose_hazard_flags_bare_pmean_under_grad():
    def bare(x):
        return lax.pmean(jnp.sum(x * x), "i")

    hz = trace.transpose_hazards(bare, jnp.ones((4,)), axes={"i": 8})
    assert hz["hazard"]
    assert hz["extra_in_backward"] == {"psum": 1}  # pmean lowers to psum+div
    assert hz["findings"][0]["rule"] == "grad-transpose"
    assert "over-counts" in hz["findings"][0]["message"]


def test_transpose_hazard_passes_identity_backward_psum():
    """The pipeline loss aggregation uses the identity-backward psum
    (reduce_from_tensor_model_parallel_region) — its custom_vjp leaves NO
    collective in the backward, so it must not be flagged."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region)

    def wrapped(x):
        return reduce_from_tensor_model_parallel_region(jnp.sum(x * x), "i")

    hz = trace.transpose_hazards(wrapped, jnp.ones((4,)), axes={"i": 8})
    assert not hz["hazard"], hz
    assert hz["forward"].get("psum", 0) >= 1  # the forward psum WAS seen
    assert hz["extra_in_backward"] == {}


def test_transpose_hazard_ignores_nonscalar_collectives():
    """psums of activations/grad tensors (e.g. the conjugate TP pair) are
    not loss-shaped; only scalar collectives count."""
    def loss(x):
        y = lax.psum(x * 2.0, "i")  # activation psum: non-scalar
        return jnp.sum(y * y)

    hz = trace.transpose_hazards(loss, jnp.ones((4,)), axes={"i": 8})
    assert hz["forward"] == {} and not hz["hazard"]


# ---------------------------------------------------------------------------
# engine 2: sequence-parallel decomposition tripwire
# ---------------------------------------------------------------------------


def test_sequence_parallel_hazard_flags_activation_psum():
    def regressed(x):
        y = lax.psum(x, "model")  # (b, s, h) all-reduce: the regression
        return y * 2.0

    hz = trace.sequence_parallel_hazards(
        regressed, jnp.ones((2, 8, 4)), axes={"model": 4})
    assert hz["hazard"] and hz["activation_psums"] == 1
    assert hz["findings"][0]["rule"] == "sp-regression"
    assert "psum_scatter/all_gather" in hz["findings"][0]["message"]


def test_sequence_parallel_hazard_passes_decomposed_and_scalar():
    """The decomposed conjugates (reduce_scatter/all_gather) and the
    scalar/rank-2 psums of the vocab-parallel CE are NOT hazards — and the
    census reports them under their buckets."""
    from apex_tpu.parallel.collectives import (
        SEQUENCE_PARALLEL_DECOMPOSED_PRIMS)
    from apex_tpu.transformer.tensor_parallel import mappings

    def decomposed(x):
        y = mappings.reduce_scatter_to_sequence_parallel_region(x, "model")
        y = mappings.gather_from_sequence_parallel_region(y, "model")
        loss2d = lax.psum(jnp.sum(y, -1), "model")  # (b, s): CE-shaped
        return loss2d

    hz = trace.sequence_parallel_hazards(
        decomposed, jnp.ones((2, 8, 4)), axes={"model": 4})
    assert not hz["hazard"], hz
    assert set(hz["census"]["activation"]) == set(
        SEQUENCE_PARALLEL_DECOMPOSED_PRIMS)
    assert hz["census"]["other"] == {"psum": 1}


def test_sequence_parallel_hazard_on_gpt_models(gpt_sp_forward_irs):
    """The model-level regression gate (ISSUE 4 evidence): a
    sequence-parallel GPT forward jaxpr carries ZERO activation psums on
    the TP axis (embedding + per-layer all decomposed), while the plain-TP
    twin shows the all-reduces the mode removes. (Both forwards come
    pre-traced from the module fixture — the analyzer reads the shared
    walk.)"""
    counts = {sp: trace.sequence_parallel_hazards(ir, tp_axis="model")
              for sp, ir in gpt_sp_forward_irs.items()}
    assert counts[True]["activation_psums"] == 0
    assert not counts[True]["hazard"]
    # plain TP: embedding psum + the per-layer pair (scanned body counts
    # call sites once — trace.sequence_parallel_hazards docstring)
    assert counts[False]["activation_psums"] == 3
    assert counts[False]["hazard"]
    # the decomposition is VISIBLE in the SP census, not merely absent
    assert counts[True]["census"]["activation"].get("reduce_scatter", 0) >= 3
    assert counts[True]["census"]["activation"].get("all_gather", 0) >= 3


# ---------------------------------------------------------------------------
# engine 2: ZeRO-redundancy tripwire
# ---------------------------------------------------------------------------


def test_zero_redundancy_flags_bulk_data_psum():
    def double_reduced(g):
        return lax.psum(g, "data") * 2.0  # full-size grad all-reduce

    hz = trace.zero_redundancy_hazards(
        double_reduced, jnp.ones((64, 128)), axes={"data": 8})
    assert hz["hazard"] and hz["bulk_psums"] == 1
    assert hz["findings"][0]["rule"] == "zero-redundancy"
    assert "psum_scatter" in hz["findings"][0]["message"]


def test_zero_redundancy_passes_decomposed_and_scalar():
    """The optimizer's scatter/gather conjugates pass; scalar collectives
    (loss pmean, found_inf pmax, LAMB norm psums) are exempt — reported
    under census['other'] — and the bulk census shows the decomposition
    (the gather is bulk by its RESULT: the per-rank operand is the small
    chunk, the output is the full param)."""
    from apex_tpu.optimizers.distributed import gather_leaf, scatter_chunk
    from apex_tpu.parallel.collectives import ZERO_DECOMPOSED_PRIMS

    def decomposed(g):
        chunk = scatter_chunk(g, 8, "data") / 8
        full = gather_leaf(chunk, g.shape, g.dtype, "data",
                           gather_dtype=jnp.bfloat16)
        loss = lax.pmean(jnp.sum(full), "data")
        bad = lax.pmax(jnp.float32(0.0), "data")
        norm = lax.psum(jnp.sum(chunk * chunk), "data")
        return loss + bad + norm

    hz = trace.zero_redundancy_hazards(
        decomposed, jnp.ones((64, 128)), axes={"data": 8})
    assert not hz["hazard"], hz
    assert set(hz["census"]["bulk"]) == set(ZERO_DECOMPOSED_PRIMS)
    assert hz["census"]["other"].get("pmax") == 1
    assert hz["census"]["other"].get("psum") >= 1  # the norm + loss pmean


def test_zero_redundancy_on_real_mixed_precision_step(zero_amp_step_irs):
    """The actual ZeRO amp step (MixedPrecisionOptimizer(zero_axis=...))
    traces clean; the replicated harness pattern (allreduce_gradients on
    the data axis) is exactly the flagged regression. (Both steps come
    pre-traced from the module fixture.)"""
    hz = trace.zero_redundancy_hazards(zero_amp_step_irs["zero"])
    assert not hz["hazard"], hz
    assert hz["census"]["bulk"].get("reduce_scatter") == 1

    hz = trace.zero_redundancy_hazards(zero_amp_step_irs["replicated"])
    assert hz["hazard"] and hz["bulk_psums"] >= 1


# ---------------------------------------------------------------------------
# engine 2: flat-DCN collective tripwire (ISSUE 19)
# ---------------------------------------------------------------------------


def test_flat_dcn_flags_tuple_axis_bulk_collective():
    def pod_flat(g):
        return lax.psum(g, ("dcn", "data")) * 2.0  # full payload over DCN

    hz = trace.flat_dcn_collective_hazards(
        pod_flat, jnp.ones((64, 128)), axes={"dcn": 2, "data": 4})
    assert hz["hazard"] and hz["flat_collectives"] == 1
    assert hz["findings"][0]["rule"] == "flat-dcn-collective"
    assert "hierarchy" in hz["findings"][0]["message"]
    assert hz["census"]["flat"] == {"psum": 1}


def test_flat_dcn_passes_staged_and_scalar():
    """The hierarchical decomposition passes — every hierarchy stage
    binds ONE axis, so the DCN hop lands in census['staged'] — and
    scalar collectives spanning both tiers (global loss pmean, found_inf
    pmax) are exempt under census['other']: 4 bytes cross the DCN
    either way."""
    from apex_tpu.parallel.hierarchy import hier_pmean, hier_psum

    def staged(g):
        full = hier_psum(g, "dcn", "data")
        mean = hier_pmean(g, "dcn", "data")
        loss = lax.pmean(jnp.sum(full), ("dcn", "data"))
        bad = lax.pmax(jnp.float32(0.0), ("dcn", "data"))
        return jnp.sum(mean) + loss + bad

    # the DCN hop carries 1/n_ici of the payload by construction, so the
    # bulk floor scales down with it at these tiny shapes (8192/4 elems)
    hz = trace.flat_dcn_collective_hazards(
        staged, jnp.ones((64, 128)), axes={"dcn": 2, "data": 4},
        min_bulk_elems=1024)
    assert not hz["hazard"], hz
    assert not hz["census"]["flat"]
    assert hz["census"]["staged"].get("psum", 0) >= 2  # the DCN hops
    assert hz["census"]["other"].get("pmax") == 1
    assert hz["census"]["other"].get("psum") == 1  # pmean lowers to psum


def test_flat_dcn_on_real_hierarchical_zero_step():
    """The actual two-tier optimizer step
    (MixedPrecisionOptimizer(zero_axis=..., dcn_axis=..., dcn_wire=...))
    traces clean — its scatter/gather stage per axis — while the SAME
    step under the flat tuple-axis group (zero_axis=("dcn", "data")) is
    exactly the flagged regression: every bulk chunk collective binds
    the DCN axis jointly with the island axis."""
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    gw = jnp.zeros((1, 64, 64), jnp.float32)

    def step_of(mp):
        def step(p, g0):
            st = mp.init(p)
            g = {"w": g0[0] * st.scaler.loss_scale}
            new_p, _st, m = mp.apply_gradients(st, p, g)
            return new_p, m["loss_scale"]

        return step

    # the staged chunks are 1/n_ici of the 4096-elem leaf: floor 1024
    axes = {"dcn": 2, "data": 4}
    flat_mp = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), amp.get_policy("O2"),
        zero_axis=("dcn", "data"))
    hz = trace.flat_dcn_collective_hazards(
        step_of(flat_mp), params, gw, axes=axes, min_bulk_elems=1024)
    assert hz["hazard"] and hz["flat_collectives"] >= 2, hz

    hier_mp = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), amp.get_policy("O2"), zero_axis="data",
        dcn_axis="dcn", dcn_wire="int8")
    hz = trace.flat_dcn_collective_hazards(
        step_of(hier_mp), params, gw, axes=axes, min_bulk_elems=1024)
    assert not hz["hazard"], hz
    assert hz["census"]["staged"], hz


# ---------------------------------------------------------------------------
# engine 2: ZeRO-3 bulk-gather tripwire
# ---------------------------------------------------------------------------


def test_zero3_gather_flags_whole_stack_gather():
    """A whole-stack (model-sized) param gather in a fully-sharded step is
    the O(model) rematerialization; the result-sized rule catches it even
    though the OPERAND is the small per-rank chunk stack."""
    from apex_tpu.optimizers.distributed import gather_stacked_leaf

    chunks = jnp.ones((8, 64), jnp.float32)  # (L, k) at n=8

    hz = trace.zero3_gather_hazards(
        lambda c: gather_stacked_leaf(c, (8, 64), jnp.float32, "data"),
        chunks, axes={"data": 8}, model_elems=8 * 512)
    assert hz["hazard"] and hz["bulk_gathers"] == 1, hz
    assert hz["findings"][0]["rule"] == "zero3-bulk-gather"
    assert hz["census"]["bulk_sites"][0]["result_elems"] == 8 * 512
    assert "per-layer" in hz["findings"][0]["message"]


def test_zero3_gather_passes_per_layer_gathers():
    from apex_tpu.optimizers.distributed import gather_leaf

    L, row = 8, (8, 64)
    chunks = jnp.ones((L, 64), jnp.float32)

    def per_layer(c):
        return jnp.stack([gather_leaf(c[i], row, jnp.float32, "data",
                                      gather_dtype=jnp.bfloat16)
                          for i in range(L)])

    hz = trace.zero3_gather_hazards(per_layer, chunks, axes={"data": 8},
                                    model_elems=L * 512)
    assert not hz["hazard"], hz
    assert hz["layer_gathers"] == L and hz["bulk_gathers"] == 0
    # threshold derivation: bulk_fraction (0.25 default) of the model
    assert hz["min_model_elems"] == L * 512 // 4


def test_zero3_gather_on_real_gpt_step(zero3_gpt_irs):
    """The real fully-sharded drive (zero3_shard + run_layers chunk_meta)
    traces clean through value_and_grad — every gather, forward AND the
    remat re-gathers in backward, is one layer's params — while
    materializing the stacked leaves whole before the loss is flagged.
    (All drives come pre-traced from the module fixture: one trace each,
    shared with the prefetch tripwire below.)"""
    # any single-layer row gather is <= ~1k elems; a stacked-leaf gather
    # is L x that — 4096 splits them at the fixture's (h=16, L=4) shape
    hz = trace.zero3_gather_hazards(zero3_gpt_irs["serialized"],
                                    min_model_elems=4096)
    assert not hz["hazard"], hz
    assert hz["layer_gathers"] >= zero3_gpt_irs["num_layers"]  # unrolled

    hz = trace.zero3_gather_hazards(zero3_gpt_irs["bulk"],
                                    min_model_elems=4096)
    assert hz["hazard"] and hz["bulk_gathers"] >= 1, hz


# ---------------------------------------------------------------------------
# engine 2: ZeRO-3 gather-prefetch tripwire
# ---------------------------------------------------------------------------


def test_unprefetched_gather_flags_remat_fused_gathers():
    """Per-layer gathers INSIDE rematerialized bodies (the serialized
    unrolled ZeRO-3 drive) are pinned to their layer's schedule — the
    hazard; free-standing gathers issued ahead of the compute (the
    double-buffered drive's structure) pass."""
    import jax

    from apex_tpu.optimizers.distributed import gather_leaf

    row = (16, 16)
    chunks = jnp.ones((4, 32), jnp.float32)  # 4 layers, k=32 at n=8
    h0 = jnp.ones((2, 16), jnp.float32)

    def serialized(c, h):
        for i in range(4):
            body = jax.checkpoint(
                lambda ci, hh: jnp.tanh(
                    hh @ gather_leaf(ci, row, jnp.float32, "data")))
            h = body(c[i], h)
        return jnp.sum(h * h)

    def prefetched(c, h):
        gathered = [gather_leaf(c[i], row, jnp.float32, "data")
                    for i in range(4)]
        for p in gathered:
            h = jnp.tanh(h @ p)
        return jnp.sum(h * h)

    bad = trace.unprefetched_gather_hazards(
        jax.grad(serialized, argnums=0), chunks, h0, axes={"data": 8})
    assert bad["hazard"] and bad["fused_gathers"] >= 2, bad
    assert bad["findings"][0]["rule"] == "unprefetched-gather"
    ok = trace.unprefetched_gather_hazards(
        jax.grad(prefetched, argnums=0), chunks, h0, axes={"data": 8})
    assert not ok["hazard"] and ok["free_gathers"] >= 4, ok


def test_unprefetched_gather_on_real_zero3_step(zero3_gpt_irs):
    """Both ways on the REAL drives: the serialized unrolled chunk_meta
    step (zero3_prefetch=0) flags; the double-buffered drive
    (zero3_prefetch=1, models/_transformer._prefetched_zero3_drive)
    traces clean with its gathers free — and still passes the bulk-gather
    tripwire (per-layer gathers only). The SAME StepIRs the bulk-gather
    test reads: one trace, N analyzers (the single-trace-walker
    contract)."""
    bad = trace.unprefetched_gather_hazards(zero3_gpt_irs["serialized"])
    assert bad["hazard"] and bad["fused_gathers"] >= 2, bad
    ok = trace.unprefetched_gather_hazards(zero3_gpt_irs["prefetched"])
    assert not ok["hazard"] and ok["free_gathers"] >= 4, ok
    # the prefetched drive must not regress the bulk-gather tripwire
    bulk = trace.zero3_gather_hazards(zero3_gpt_irs["prefetched"],
                                      min_model_elems=4096)
    assert not bulk["hazard"], bulk


# ---------------------------------------------------------------------------
# engine 2: quantized-collective tripwire
# ---------------------------------------------------------------------------


def test_quantized_comm_flags_fat_wire():
    """A step that requests a quantized grad reduce but still moves an
    fp32-sized bulk reduce payload on the zero axis is the fat-wire
    regression (the itemsize-keyed census catches the surviving
    psum_scatter AND an unencoded bulk all_to_all)."""
    from apex_tpu.optimizers.distributed import scatter_chunk

    big = jnp.ones((64, 128), jnp.float32)
    hz = trace.quantized_comm_hazards(
        lambda g: scatter_chunk(g, 8, "data") / 8, big, axes={"data": 8})
    assert hz["hazard"] and hz["fat_reduces"] == 1, hz
    assert hz["findings"][0]["rule"] == "quantized-comm-fat-wire"
    assert hz["census"] == {"4": {"reduce_scatter": 1}}

    # a bf16 wire is still fat (2 B/elem): only the 1-byte dtypes pass
    hz2 = trace.quantized_comm_hazards(
        lambda g: scatter_chunk(g.astype(jnp.bfloat16), 8, "data"),
        big, axes={"data": 8})
    assert hz2["hazard"] and hz2["census"] == {"2": {"reduce_scatter": 1}}


def test_quantized_comm_passes_encoded_pair_and_checks_residual():
    """The encoded all_to_all pair traces clean (the fp32 scale
    side-channel sits below the bulk floor); a quantized GRAD reduce whose
    state lacks the 'err' residual tree flags the error-feedback check."""
    from apex_tpu.parallel.quantize import quantized_reduce_scatter

    big = jnp.ones((64, 128), jnp.float32)

    def good(g):
        chunk, _ = quantized_reduce_scatter(g, 8, "data", "int8")
        return chunk / 8

    hz = trace.quantized_comm_hazards(good, big, axes={"data": 8},
                                      residual={"err": {"w": None}})
    assert not hz["hazard"], hz
    assert hz["quantized_reduces"] == 1 and hz["census"] == {
        "1": {"all_to_all": 1}}

    hz_nores = trace.quantized_comm_hazards(good, big, axes={"data": 8},
                                            residual=None)
    assert hz_nores["hazard"]
    assert hz_nores["findings"][0]["rule"] == "quantized-comm-no-residual"
    # default: residual unchecked (activation-only traffic has none)
    assert not trace.quantized_comm_hazards(
        good, big, axes={"data": 8})["hazard"]


def test_quantized_comm_on_real_mixed_precision_step(zero_amp_step_irs):
    """The actual reduce_dtype='int8' amp step traces clean with its
    residual state; the SAME step read at reduce_dtype=None is the
    flagged fat-wire pattern — the tripwire pair the selftest runs.
    (Pre-traced by the module fixture, shared with the redundancy
    test.)"""
    hz = trace.quantized_comm_hazards(
        zero_amp_step_irs["q8"], residual=zero_amp_step_irs["residual"])
    assert not hz["hazard"], hz
    assert hz["quantized_reduces"] >= 1

    hz = trace.quantized_comm_hazards(zero_amp_step_irs["fp32_wire"])
    assert hz["hazard"] and hz["fat_reduces"] >= 1


# ---------------------------------------------------------------------------
# engine 2: MoE dispatch tripwire (ISSUE 15)
# ---------------------------------------------------------------------------


def _moe_fixture(dispatch_dtype=None):
    """An expert-parallel MoE layer + (full, per-shard) param pair at a
    shape whose dispatch buckets clear the bulk floor (E=8, C=128, d=8:
    8192 elems/bucket)."""
    import jax

    from apex_tpu.transformer.moe import MoEMLP

    layer = MoEMLP(8, 16, num_experts=8, top_k=2, capacity_factor=2.0,
                   expert_axis="data", dispatch_dtype=dispatch_dtype)
    full = layer.init(jax.random.PRNGKey(0))
    local = {"router": full["router"],
             "fc1": jax.tree.map(lambda v: v[:1], full["fc1"]),
             "fc2": jax.tree.map(lambda v: v[:1], full["fc2"])}
    return layer, full, local, jnp.ones((256, 8), jnp.float32)


def test_moe_dispatch_flags_replicated_experts():
    """An expert-parallel request whose trace has NO dispatch-shaped
    all_to_all on the expert axis silently runs every expert on every
    rank — the replicated-expert regression."""
    layer, full, _, x = _moe_fixture()
    hz = trace.moe_dispatch_hazards(layer.apply, full, x, axes={"data": 8})
    assert hz["hazard"] and hz["dispatch_all_to_alls"] == 0, hz
    assert hz["findings"][0]["rule"] == "moe-dispatch-missing"


def test_moe_dispatch_passes_expert_parallel_and_checks_wire():
    """The real all_to_all dispatch passes; the SAME exact-wire dispatch
    under a quantized-wire request flags fat-wire; the encoded exchange
    (dispatch_dtype='int8') passes the wire check with its fp32 scale
    side-channel below the bulk floor."""
    layer, _, local, x = _moe_fixture()
    hz = trace.moe_dispatch_hazards(
        layer.apply_expert_parallel, local, x, axes={"data": 8})
    assert not hz["hazard"] and hz["dispatch_all_to_alls"] == 2, hz
    assert hz["census"]["dispatch"] == {"4": {"all_to_all": 2}}

    fat = trace.moe_dispatch_hazards(
        layer.apply_expert_parallel, local, x, axes={"data": 8},
        wire_dtype="int8")
    assert fat["hazard"] and fat["fat_dispatches"] == 2, fat
    assert fat["findings"][0]["rule"] == "moe-dispatch-fat-wire"

    qlayer, _, qlocal, _ = _moe_fixture(dispatch_dtype="int8")
    ok = trace.moe_dispatch_hazards(
        qlayer.apply_expert_parallel, qlocal, x, axes={"data": 8},
        wire_dtype="int8")
    assert not ok["hazard"], ok
    assert ok["census"]["dispatch"] == {"1": {"all_to_all": 2}}


def test_moe_dispatch_ignores_zero_grad_chunk_all_to_alls():
    """The quantized ZeRO grad reduce's rank-2 chunk-row all_to_alls on
    the SAME mesh axis land in census['chunk'], never the dispatch table
    — a zero+moe hybrid step audits each wire independently."""
    from apex_tpu.parallel.quantize import quantized_reduce_scatter

    def grad_reduce(g):
        chunk, _ = quantized_reduce_scatter(g, 8, "data", "int8")
        return chunk / 8

    hz = trace.moe_dispatch_hazards(
        grad_reduce, jnp.ones((64, 128), jnp.float32), axes={"data": 8},
        wire_dtype="int8")
    assert not hz["census"]["dispatch"], hz
    assert hz["census"]["chunk"] == {"1": {"all_to_all": 1}}
    # missing-dispatch still fires (there IS no dispatch) — callers hand
    # the tripwire the MoE step, not a bare grad reduce
    assert hz["findings"][0]["rule"] == "moe-dispatch-missing"


# ---------------------------------------------------------------------------
# engine 2: recompile-hazard scanner
# ---------------------------------------------------------------------------


def test_untimed_schedule_hazard_flags_spanless_drive():
    """A pipeline ring drive traced under an armed tracer with no pipe
    spans is the census-only regression (the step-anatomy tripwire); a
    span-emitting drive and a drive-free fn pass. The REAL compiled-vs-
    traced-drive pairing is pinned in tests/test_tracing.py."""
    import jax

    from apex_tpu.transformer.pipeline_parallel import schedules

    run_stage = lambda lp, h: h * (1.0 + jnp.sum(lp))  # noqa: E731
    layers_l = jnp.ones((4, 2, 2))
    h_mb = jnp.ones((4, 3, 5))
    ring = jax.vmap(
        lambda ll, hm: schedules._pipeline_ring(run_stage, ll, hm, "i"),
        axis_name="i")

    bad = trace.untimed_schedule_hazards(
        lambda: jax.make_jaxpr(ring)(layers_l, h_mb))
    assert bad["hazard"] and bad["drives"] == 1 and bad["pipe_spans"] == 0
    assert bad["findings"][0]["rule"] == "untimed-schedule"

    def timed():
        from apex_tpu.monitor import tracing

        jax.make_jaxpr(ring)(layers_l, h_mb)
        tracing.get_tracer().record("fwd", dur_s=0.01, cat="pipe", rank=0)

    ok = trace.untimed_schedule_hazards(timed)
    assert not ok["hazard"] and ok["pipe_spans"] == 1

    none = trace.untimed_schedule_hazards(lambda: jnp.ones(()) * 2)
    assert not none["hazard"] and none["drives"] == 0


def test_recompile_hazards_name_offending_leaves():
    haz = trace.recompile_hazards(
        {"opt": {"loss_scale": 2.0 ** 16}, "x": jnp.ones((2,), jnp.float32)},
        weak=jnp.asarray(1.0))
    kinds = {h["kind"]: h for h in haz}
    assert set(kinds) == {"python-scalar", "weak-type"}
    assert "loss_scale" in kinds["python-scalar"]["where"]
    assert kinds["weak-type"]["where"].startswith("kwargs")
    assert all(h["rule"] == "recompile-hazard" for h in haz)


def test_recompile_hazards_clean_signature():
    assert trace.recompile_hazards(
        jnp.ones((2, 2), jnp.bfloat16),
        {"step": jnp.asarray(0, jnp.int32)}) == []


def test_step_report_composite():
    rep = trace.step_report(
        lambda w, s: (w * s).sum(),
        jnp.ones((512, 1), jnp.float32), 2.0, min_bytes=0)
    assert rep["lane_padding"]["flagged"] >= 1
    assert rep["lane_padding"]["worst"][0]["shape"] == [512, 1]
    assert [h["kind"] for h in rep["recompile_hazards"]] == ["python-scalar"]


# ---------------------------------------------------------------------------
# decode-recompile tripwire (serving; the real engine stream is pinned in
# tests/test_serve.py)
# ---------------------------------------------------------------------------


def test_decode_recompile_flags_growing_kv_and_scalar_leaks():
    """A decode argument stream whose per-request KV grows with the
    sequence — or that ships python-int positions — is one recompile per
    generated token (the latency cliff the paged cache exists to
    prevent)."""
    grow = trace.decode_recompile_hazards(
        lambda t: (jnp.ones((1, 2, t + 4, 8), jnp.float32),
                   jnp.zeros((2,), jnp.int32)), ticks=3)
    assert grow["hazard"]
    rules = {f["rule"] for f in grow["findings"]}
    assert "decode-shape-churn" in rules
    assert any("recompile" in f["message"] for f in grow["findings"])

    leak = trace.decode_recompile_hazards(
        lambda t: (jnp.ones((4,), jnp.float32), {"tick": t}), ticks=2)
    assert leak["hazard"]
    assert any(f.get("kind") == "python-scalar" for f in leak["findings"])

    struct = trace.decode_recompile_hazards(
        lambda t: tuple(jnp.zeros((2,), jnp.int32) for _ in range(t + 1)),
        ticks=2)
    assert struct["hazard"]
    assert struct["findings"][0]["rule"] == "decode-structure-churn"


def test_decode_recompile_passes_shape_stable_stream():
    """The engine contract: identical shapes/dtypes every tick — fixed
    slot arrays, the paged pool, committed int32 positions, a traced
    tick scalar."""
    def args(t):
        return (jnp.zeros((2, 8, 4, 4), jnp.float32),   # page pool
                jnp.zeros((4, 6), jnp.int32),            # block tables
                jnp.zeros((4,), jnp.int32),              # lengths
                jnp.asarray(t, jnp.int32))               # traced tick

    ok = trace.decode_recompile_hazards(args, ticks=4)
    assert not ok["hazard"] and ok["ticks"] == 4 and ok["leaves"] == 4


def test_decode_recompile_audits_extra_streams_both_ways():
    """ISSUE 12: the extended tripwire audits the chunked-prefill and
    speculative-verify argument streams by the same rules — clean static
    streams pass (with per-stream leaf counts), a chunk width that grows
    with the prompt or a python-int draft length is flagged WITH its
    stream name (one recompile per request otherwise)."""
    decode = lambda t: (jnp.zeros((2, 8, 4, 4), jnp.float32),  # noqa: E731
                        jnp.asarray(t, jnp.int32))
    chunk_ok = lambda t: (jnp.zeros((1, 16), jnp.int32),       # noqa: E731
                          jnp.asarray(t * 16, jnp.int32),
                          jnp.asarray(16, jnp.int32))
    verify_ok = lambda t: (jnp.zeros((4, 3), jnp.int32),       # noqa: E731
                           jnp.zeros((4,), jnp.int32))
    ok = trace.decode_recompile_hazards(
        decode, ticks=3,
        extra_streams={"chunk": chunk_ok, "verify": verify_ok})
    assert not ok["hazard"], ok["findings"][:3]
    assert ok["stream_leaves"] == {"decode": 2, "chunk": 3, "verify": 2}

    # a chunk buffer that grows with the prompt = a fresh signature per
    # request; a python-int draft length = weak-typed cache churn
    bad = trace.decode_recompile_hazards(
        decode, ticks=2,
        extra_streams={
            "chunk": lambda t: (jnp.zeros((1, 16 * (t + 1)), jnp.int32),),
            "verify": lambda t: (jnp.zeros((4, 3), jnp.int32), 3)})
    assert bad["hazard"]
    tagged = {(f["stream"], f["rule"]) for f in bad["findings"]}
    assert ("chunk", "decode-shape-churn") in tagged, tagged
    assert ("verify", "recompile-hazard") in tagged, tagged
    assert all(f["stream"] != "decode" for f in bad["findings"])
