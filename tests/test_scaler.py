"""Dynamic loss scaler state machine (reference: tests/L0/run_amp, scaler.py)."""

import jax.numpy as jnp
import numpy as np

from apex_tpu.amp import LossScaler


def test_static_scale_never_changes():
    s = LossScaler.create(128.0)
    assert float(s.loss_scale) == 128.0
    s2 = s.update(jnp.asarray(True))
    assert float(s2.loss_scale) == 128.0


def test_dynamic_halves_on_overflow():
    s = LossScaler.create("dynamic")
    assert float(s.loss_scale) == 2.0 ** 16
    s2 = s.update(jnp.asarray(True))
    assert float(s2.loss_scale) == 2.0 ** 15
    assert int(s2.unskipped) == 0


def test_dynamic_doubles_after_window():
    s = LossScaler.create("dynamic", init_scale=4.0, scale_window=3)
    for _ in range(3):
        s = s.update(jnp.asarray(False))
    assert float(s.loss_scale) == 8.0
    assert int(s.unskipped) == 0


def test_min_max_caps():
    s = LossScaler.create("dynamic", init_scale=2.0, min_loss_scale=1.0)
    for _ in range(5):
        s = s.update(jnp.asarray(True))
    assert float(s.loss_scale) == 1.0

    s = LossScaler.create("dynamic", init_scale=2.0 ** 24, scale_window=1)
    s = s.update(jnp.asarray(False))
    assert float(s.loss_scale) == 2.0 ** 24


def test_unscale_detects_inf():
    s = LossScaler.create(2.0)
    grads = {"w": jnp.array([2.0, 4.0]), "b": jnp.array([jnp.inf])}
    unscaled, found = s.unscale(grads)
    assert bool(found)
    np.testing.assert_allclose(np.asarray(unscaled["w"]), [1.0, 2.0])


def test_scale_loss():
    s = LossScaler.create(8.0)
    assert float(s.scale(jnp.asarray(2.0, jnp.bfloat16))) == 16.0


def test_state_dict_roundtrip():
    s = LossScaler.create("dynamic")
    s = s.update(jnp.asarray(True))
    payload = s.state_dict()
    s2 = LossScaler.create("dynamic").load_state_dict(payload)
    assert float(s2.loss_scale) == float(s.loss_scale)
