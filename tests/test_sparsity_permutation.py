"""ASP channel-permutation search tests (reference:
apex/contrib/sparsity/permutation_lib.py + permutation_search_kernels/,
checkpoint round-trip modeled on
apex/contrib/sparsity/test/checkpointing_test_part1.py).

Covers: vectorized 2:4 magnitude evaluation vs a naive loop, canonical
permutation enumeration vs the analytic count, search improvement on
adversarial matrices, function preservation of applied permutations on an
MLP chain, mask-magnitude improvement on a random Linear stack, and
save/permute/mask/restore round-trip."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.contrib import sparsity
from apex_tpu.contrib.sparsity import permutation as plib


def _naive_sum_after_2to4(m):
    total = 0.0
    for row in range(m.shape[0]):
        for col in range(0, m.shape[1], 4):
            a = np.abs(m[row, col : col + 4])
            total += np.sort(a)[2:].sum()
    return total


def test_sum_after_2to4_matches_naive():
    rng = np.random.default_rng(0)
    m = rng.normal(size=(16, 24))
    assert plib.sum_after_2_to_4(m) == pytest.approx(_naive_sum_after_2to4(m))


def test_batched_evaluation_matches_single():
    rng = np.random.default_rng(1)
    m = rng.normal(size=(8, 8))
    perms = plib.canonical_permutations(8)
    batched = plib._batched_sum_2to4(m.T[perms].swapaxes(-1, -2))
    for i in [0, 3, len(perms) - 1]:
        assert batched[i] == pytest.approx(plib.sum_after_2_to_4(m[:, perms[i]]))


def test_canonical_permutation_count_matches_analytic():
    # exhaustive_search.py:83-86 — C!/((M!)^G * G!)
    for c, expected in [(4, 1), (8, 35), (12, 5775)]:
        assert plib.predict_unique_combinations(c) == expected
        assert len(plib.canonical_permutations(c)) == expected


def test_canonical_identity_first():
    perms = plib.canonical_permutations(8)
    np.testing.assert_array_equal(perms[0], np.arange(8))


def _adversarial_matrix(k=32, c=16, seed=0):
    """Matrix where naive 2:4 grouping loses a lot: big-magnitude channels
    clustered inside the same stripes."""
    rng = np.random.default_rng(seed)
    m = rng.normal(size=(k, c)) * 0.01
    # 3 large channels per stripe of 4 -> pruning must drop one large one
    for g in range(c // 4):
        m[:, g * 4 : g * 4 + 3] += rng.normal(size=(k, 3)) * 10.0
    return m


def test_exhaustive_search_improves_adversarial():
    m = _adversarial_matrix(c=8)
    perm, improvement = plib.exhaustive_search_matrix(m)
    assert improvement > 0
    assert plib.sum_after_2_to_4(m[:, perm]) == pytest.approx(
        plib.sum_after_2_to_4(m) + improvement
    )


def test_stripe_window_search_improves_and_is_valid_perm():
    m = _adversarial_matrix(c=32)
    perm = plib.search_for_good_permutation(m, escape_attempts=10)
    np.testing.assert_array_equal(np.sort(perm), np.arange(32))
    assert plib.sum_after_2_to_4(m[:, perm]) > plib.sum_after_2_to_4(m) * 1.02


def test_search_skips_when_pruning_lossless():
    # exactly 2 nonzeros per stripe -> 2:4 loses nothing -> identity
    # (permutation_lib.py:351-362 skip path)
    m = np.zeros((8, 16))
    m[:, ::4] = 1.0
    m[:, 1::4] = 2.0
    perm = plib.search_for_good_permutation(m)
    np.testing.assert_array_equal(perm, np.arange(16))


def test_progressive_channel_swap_improves_wide():
    m = _adversarial_matrix(k=16, c=64)
    perm = plib.search_for_good_permutation(m, wide_matrix_threshold=32,
                                            max_swap_attempts=4000)
    np.testing.assert_array_equal(np.sort(perm), np.arange(64))
    assert plib.sum_after_2_to_4(m[:, perm]) > plib.sum_after_2_to_4(m)


# -- applying permutations across layers ------------------------------------


def _mlp_params(sizes, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), len(sizes) - 1)
    params = {}
    for i, key in enumerate(keys):
        kk, bk = jax.random.split(key)
        params[f"fc{i}"] = {
            "kernel": jax.random.normal(kk, (sizes[i], sizes[i + 1])) * 0.5,
            "bias": jax.random.normal(bk, (sizes[i + 1],)) * 0.1,
        }
    return params


def _mlp_apply(params, x, n_layers):
    for i in range(n_layers):
        x = x @ params[f"fc{i}"]["kernel"] + params[f"fc{i}"]["bias"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x


def test_permutation_preserves_function():
    sizes = [8, 16, 24, 8]
    params = _mlp_params(sizes)
    x = jax.random.normal(jax.random.PRNGKey(9), (4, 8))
    ref = _mlp_apply(params, x, 3)

    groups = plib.sequential_groups(["fc0", "fc1", "fc2"])
    permuted, perms = plib.search_and_permute(params, groups, escape_attempts=5)
    assert set(perms) == {0, 1}
    out = _mlp_apply(permuted, x, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_permuted_masks_preserve_more_magnitude():
    # VERDICT round-1 done-criterion: permuted 2:4 masks keep more magnitude
    # than naive masks on a random Linear stack.
    sizes = [16, 32, 32, 16]
    params = _mlp_params(sizes, seed=3)
    # make the middle layers adversarial so there is headroom to recover
    adv = _adversarial_matrix(k=32, c=32, seed=7)
    params["fc1"]["kernel"] = jnp.asarray(adv.T)  # (in=32, out=32)
    adv2 = _adversarial_matrix(k=16, c=32, seed=8)
    params["fc2"]["kernel"] = jnp.asarray(adv2.T)

    groups = plib.sequential_groups(["fc0", "fc1", "fc2"])
    permuted, _ = plib.search_and_permute(params, groups, escape_attempts=10)

    def retained(p):
        return sum(
            plib.magnitude_after_mask(np.asarray(p[n]["kernel"]))
            for n in ("fc1", "fc2")
        )

    assert retained(permuted) > retained(params) * 1.01


def test_channelwise_params_follow_k_permutation():
    # producers' bias and norm scale/offset must ride the K permutation
    params = {
        "fc0": {
            "kernel": jnp.arange(12.0).reshape(3, 4),
            "bias": jnp.arange(4.0),
            "scale": jnp.arange(4.0) + 10,
        },
        "fc1": {"kernel": jnp.ones((4, 2))},
    }
    perm = np.array([2, 0, 3, 1])
    out = plib.apply_channel_permutation(
        params, plib.ChannelGroup(consumers=["fc1"], producers=["fc0"]), perm
    )
    np.testing.assert_array_equal(np.asarray(out["fc0"]["bias"]), perm.astype(float))
    np.testing.assert_array_equal(np.asarray(out["fc0"]["scale"]), perm + 10.0)
    np.testing.assert_array_equal(
        np.asarray(out["fc0"]["kernel"]), np.arange(12.0).reshape(3, 4)[:, perm]
    )


def test_conv_kernel_permutation():
    # (H, W, in, out) conv kernels permute in/out on -2/-1
    # (the reference's R*S*K x C reshape, permutation_lib.py:298-312)
    rng = np.random.default_rng(0)
    params = {
        "conv0": {"kernel": jnp.asarray(rng.normal(size=(3, 3, 4, 8)))},
        "conv1": {"kernel": jnp.asarray(rng.normal(size=(3, 3, 8, 4)))},
    }
    permuted, perms = plib.search_and_permute(
        params, [plib.ChannelGroup(consumers=["conv1"], producers=["conv0"])]
    )
    p = perms[0]
    np.testing.assert_array_equal(np.sort(p), np.arange(8))
    np.testing.assert_array_equal(
        np.asarray(permuted["conv1"]["kernel"]),
        np.asarray(params["conv1"]["kernel"])[:, :, p, :],
    )


def test_sibling_consumers_share_permutation():
    # two consumers of one producer search on concatenated weights and get
    # the same channel order (unique_siblings, permutation_lib.py:554-601)
    rng = np.random.default_rng(4)
    params = {
        "prod": {"kernel": jnp.asarray(rng.normal(size=(8, 16))),
                 "bias": jnp.asarray(rng.normal(size=(16,)))},
        "a": {"kernel": jnp.asarray(_adversarial_matrix(8, 16, seed=5).T)},
        "b": {"kernel": jnp.asarray(_adversarial_matrix(8, 16, seed=6).T)},
    }
    group = plib.ChannelGroup(consumers=["a", "b"], producers=["prod"])
    permuted, perms = plib.search_and_permute(params, [group], escape_attempts=5)
    p = perms[0]
    # function preservation for both branches
    x = jnp.asarray(rng.normal(size=(2, 8)))
    h_ref = x @ params["prod"]["kernel"] + params["prod"]["bias"]
    h_new = x @ permuted["prod"]["kernel"] + permuted["prod"]["bias"]
    np.testing.assert_allclose(np.asarray(h_new), np.asarray(h_ref)[:, p], atol=1e-6)
    for name in ("a", "b"):
        np.testing.assert_allclose(
            np.asarray(permuted[name]["kernel"]),
            np.asarray(params[name]["kernel"])[p, :],
            atol=0,
        )


def test_checkpoint_round_trip_with_permutation(tmp_path):
    # reference: contrib/sparsity/test/checkpointing_test_part1.py —
    # permute + mask + save, restore elsewhere, masks and function intact
    from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint

    sizes = [8, 16, 16, 8]
    params = _mlp_params(sizes, seed=11)
    groups = plib.sequential_groups(["fc0", "fc1", "fc2"])
    permuted, _ = plib.search_and_permute(params, groups, escape_attempts=5)
    masks = sparsity.compute_sparse_masks(permuted)
    pruned = sparsity.apply_masks(permuted, masks)

    state = {"params": pruned, "masks": masks}
    save_checkpoint(str(tmp_path), 7, state, backend="npz")
    target = jax.tree.map(jnp.zeros_like, state)
    restored = restore_checkpoint(str(tmp_path), target, 7, backend="npz")

    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8))
    np.testing.assert_allclose(
        np.asarray(_mlp_apply(restored["params"], x, 3)),
        np.asarray(_mlp_apply(pruned, x, 3)),
        atol=1e-6,
    )
    # re-masking restored params is a no-op: the pattern survived the trip
    remasked = sparsity.apply_masks(restored["params"], restored["masks"])
    for a, b in zip(jax.tree.leaves(remasked), jax.tree.leaves(restored["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
