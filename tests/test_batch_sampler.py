"""Batch sampler tests (reference: tests/L0/run_transformer/test_batch_sampler.py)."""

import numpy as np
import pytest

from apex_tpu.transformer.data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
    get_kth_microbatch,
)


def test_pretraining_sampler_disjoint_cover():
    """All DP ranks together cover each global batch exactly once, in order."""
    total, mbs, dp = 32, 2, 4
    per_rank = [
        list(MegatronPretrainingSampler(total, 0, mbs, r, dp)) for r in range(dp)
    ]
    n_batches = total // (mbs * dp)
    assert all(len(b) == n_batches for b in per_rank)
    for step in range(n_batches):
        merged = np.concatenate([per_rank[r][step] for r in range(dp)])
        np.testing.assert_array_equal(
            merged, np.arange(step * mbs * dp, (step + 1) * mbs * dp))


def test_pretraining_sampler_resume_and_drop_last():
    s = MegatronPretrainingSampler(10, consumed_samples=4, micro_batch_size=2,
                                   data_parallel_rank=0, data_parallel_size=2,
                                   drop_last=False)
    batches = list(s)
    np.testing.assert_array_equal(batches[0], [4, 5])
    # tail of 2 (<4) still yielded when drop_last=False
    np.testing.assert_array_equal(batches[-1], [8, 9])


def test_pretraining_sampler_validation():
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(0, 0, 2, 0, 1)
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(8, 8, 2, 0, 1)
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(8, 0, 2, 3, 2)


def test_random_sampler_epoch_determinism_and_disjoint():
    total, mbs, dp = 64, 4, 2
    a0 = list(MegatronPretrainingRandomSampler(total, 0, mbs, 0, dp))
    b0 = list(MegatronPretrainingRandomSampler(total, 0, mbs, 0, dp))
    for x, y in zip(a0, b0):
        np.testing.assert_array_equal(x, y)  # same epoch -> same permutation
    r1 = list(MegatronPretrainingRandomSampler(total, 0, mbs, 1, dp))
    seen0 = set(np.concatenate(a0).tolist())
    seen1 = set(np.concatenate(r1).tolist())
    assert not seen0 & seen1  # rank buckets disjoint
    assert len(seen0) == total // dp


def test_random_sampler_resume_skips_consumed():
    total, mbs, dp = 64, 4, 2
    full = list(MegatronPretrainingRandomSampler(total, 0, mbs, 0, dp))
    resumed = list(MegatronPretrainingRandomSampler(total, 16, mbs, 0, dp))
    for x, y in zip(full[2:], resumed):  # 16 consumed = 2 steps of mbs*dp
        np.testing.assert_array_equal(x, y)


def test_get_kth_microbatch():
    batch = {"x": np.arange(12).reshape(6, 2), "y": np.arange(6)}
    mb = get_kth_microbatch(batch, 1, 3)
    np.testing.assert_array_equal(mb["y"], [2, 3])
    np.testing.assert_array_equal(mb["x"], [[4, 5], [6, 7]])
