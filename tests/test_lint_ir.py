"""Tests for apex_tpu.lint.ir (the shared single-trace jaxpr walker + pass
framework) and the four whole-program passes (engine 3, ISSUE 13):
collective-consistency, static-hbm, dtype-drift, comm-bytes — each tested
both ways (a minimal step that fires the finding + the clean/fixed twin
that passes), plus the step-audit gate and the static-HBM-vs-measured
cross-check."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.lint import ir as lint_ir
from apex_tpu.lint.passes import (
    collective_consistency_pass,
    comm_bytes_pass,
    dtype_drift_pass,
    static_hbm_pass,
)
from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()


def _mesh(n=4, name="i"):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), (name,))


def _ring(n=4):
    return [(a, (a + 1) % n) for a in range(n)]


# ---------------------------------------------------------------------------
# the walker: one trace, one walk, threaded context
# ---------------------------------------------------------------------------


def test_step_ir_threads_context_and_duck_types():
    """The walk carries shard_map axis sizes, remat containment, and
    cond-branch indices; a StepIR quacks like a ClosedJaxpr so every
    legacy analyzer accepts it unchanged."""
    mesh = _mesh()

    def body(x):
        y = lax.psum(x, "i")
        inner = jax.checkpoint(lambda h: jnp.tanh(h) * 2.0)
        y = inner(y)
        return lax.cond(jnp.sum(y) > 0,
                        lambda z: z * 2.0, lambda z: z + 1.0, y)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("i"),
                       out_specs=P("i"), check_vma=False)
    ir = lint_ir.trace_ir(fn, jnp.ones((8, 4)))
    assert hasattr(ir, "jaxpr") and hasattr(ir, "invars")  # duck-typing

    psums = [n for n in ir.nodes if n.eqn.primitive.name == "psum"]
    assert psums and psums[0].axis_sizes == {"i": 4}
    assert psums[0].in_shard_map and not psums[0].in_remat

    remat_nodes = [n for n in ir.nodes if n.in_remat]
    assert remat_nodes, "checkpoint body equations must be marked in_remat"
    branch_nodes = {n.branch for n in ir.nodes if n.branch is not None}
    assert branch_nodes == {0, 1}, branch_nodes

    # the legacy iteration order still sees every equation
    assert len(list(lint_ir.ensure_ir(ir).iter_eqns())) == len(ir.nodes)


def test_ensure_ir_shares_one_walk():
    """Handing the same pre-traced jaxpr to N analyzers reuses one cached
    walk (the dedupe tests/test_lint.py's fixtures lean on)."""
    jx = jax.make_jaxpr(lambda x: jnp.tanh(x) * 2.0)(jnp.ones((4,)))
    a, b = lint_ir.ensure_ir(jx), lint_ir.ensure_ir(jx)
    assert a is b
    assert a.nodes is b.nodes


def test_run_passes_aggregates_and_rejects_unknown():
    res = lint_ir.run_passes(lambda x: x * 2.0, jnp.ones((4,)))
    assert set(res["passes"]) == set(lint_ir.PASS_REGISTRY)
    assert res["ok"] and res["errors"] == 0
    with pytest.raises(ValueError, match="unknown lint pass"):
        lint_ir.run_passes(lambda x: x, jnp.ones((2,)),
                           passes=["no-such-pass"])


def test_apply_suppressions_honors_source_grammar(tmp_path):
    """A jaxpr-level finding with provenance is waived by the standard
    '# lint: disable=<rule> -- why' comment at its source line; a finding
    with no provenance stays unsuppressed (waivers must be auditable)."""
    mod = tmp_path / "widening.py"
    mod.write_text("x = 1\n"
                   "y = upcast(x)  # lint: disable=dtype-drift -- fp32 "
                   "softmax numerics\n")
    findings = [
        {"rule": "dtype-drift", "path": str(mod), "line": 2, "message": "m"},
        {"rule": "dtype-drift", "path": str(mod), "line": 1, "message": "m"},
        {"rule": "dtype-drift", "message": "no provenance"},
    ]
    lint_ir.apply_suppressions(findings, root=str(tmp_path))
    assert findings[0].get("suppressed") is True
    assert "softmax" in findings[0]["justification"]
    assert not findings[1].get("suppressed")
    assert not findings[2].get("suppressed")
    assert findings[0]["path"] == "widening.py"  # repo-relative rewrite


# ---------------------------------------------------------------------------
# collective-consistency: both ways
# ---------------------------------------------------------------------------


def test_collective_consistency_flags_divergent_cond_branches():
    mesh = _mesh()

    def body(x):
        y = lax.psum(x, "i")
        return lax.cond(jnp.sum(y) > 0,
                        lambda z: lax.ppermute(z, "i", _ring()),
                        lambda z: z,  # no collective: the deadlock shape
                        y)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("i"),
                       out_specs=P("i"), check_vma=False)
    res = collective_consistency_pass(lint_ir.trace_ir(fn, jnp.ones((8, 4))))
    kinds = [f["kind"] for f in res["findings"]]
    assert kinds == ["branch-divergence"], res
    assert "deadlock" in res["findings"][0]["message"]


def test_collective_consistency_passes_agreeing_branches_and_ring():
    mesh = _mesh()

    def body(x):
        ring = lambda z: lax.ppermute(z, "i", _ring())  # noqa: E731
        return lax.cond(jnp.sum(x) > 0,
                        lambda z: ring(z) * 2.0,
                        lambda z: ring(z) + 1.0, x)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("i"),
                       out_specs=P("i"), check_vma=False)
    res = collective_consistency_pass(lint_ir.trace_ir(fn, jnp.ones((8, 4))))
    assert not res["findings"], res
    assert res["conds_checked"] == 1 and res["ppermutes_checked"] == 2


def test_collective_consistency_flags_malformed_ppermute():
    mesh = _mesh()

    # two ranks send to slot 1; rank 3 out of nowhere receives nothing
    def body(x):
        return lax.ppermute(x, "i", [(0, 1), (2, 1)])

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("i"),
                       out_specs=P("i"), check_vma=False)
    res = collective_consistency_pass(lint_ir.trace_ir(fn, jnp.ones((8, 4))))
    assert [f["kind"] for f in res["findings"]] == ["malformed-ppermute"]
    assert "destination" in res["findings"][0]["message"]

    ok = jax.shard_map(lambda x: lax.ppermute(x, "i", _ring()), mesh=mesh,
                       in_specs=P("i"), out_specs=P("i"), check_vma=False)
    assert not collective_consistency_pass(
        lint_ir.trace_ir(ok, jnp.ones((8, 4))))["findings"]


# ---------------------------------------------------------------------------
# static-hbm: both ways + the acceptance synthetics
# ---------------------------------------------------------------------------


def test_static_hbm_peak_tracks_live_ranges():
    """Hand-computable program: peak = inputs + both intermediates live at
    the residual add; the estimate must sit between the resident floor
    and the sum of every value ever created (frees DO happen)."""
    w = jnp.ones((256, 256), jnp.float32)   # 256 KiB
    x = jnp.ones((256, 256), jnp.float32)

    def f(w, x):
        h1 = jnp.tanh(x @ w)      # 256 KiB
        h2 = jnp.tanh(h1 @ w)     # 256 KiB, h1 still live for the add
        return h1 + h2

    res = static_hbm_pass(lint_ir.trace_ir(f, w, x))
    kib = 256 * 256 * 4
    assert res["resident_in_bytes"] == 2 * kib
    # the worst point holds exactly 3 arrays (w + h1 + t2 at the second
    # matmul: x and each tanh input die at their last use); never the sum
    # of everything ever created (5+)
    assert 3 * kib <= res["peak_bytes"] <= 4 * kib, res["peak_bytes"]
    assert res["peak_padded_bytes"] >= res["peak_bytes"]


def test_static_hbm_flags_bhs1_operand_at_boundary():
    """The acceptance synthetic: a (b, h, s, 1) f32 operand crossing a
    custom-call boundary occupies 128x its nbytes under T(8,128); the
    dense (b, h, s, 128) twin is pad-free."""
    def bad(x):
        y = jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y.sum()

    x = jnp.ones((2, 4, 512, 1), jnp.float32)
    res = static_hbm_pass(lint_ir.trace_ir(bad, x), min_bytes=0)
    hits = [f for f in res["findings"] if f["shape"] == [2, 4, 512, 1]
            and "pure_callback" in f["where"]]
    assert hits and hits[0]["waste_ratio"] == 128.0, res["findings"]
    assert hits[0]["rule"] == "static-hbm"
    assert "dense" in hits[0]["message"]  # the lse-table remediation hint

    dense = jnp.ones((2, 4, 512, 128), jnp.float32)
    res2 = static_hbm_pass(lint_ir.trace_ir(bad, dense), min_bytes=0)
    assert not res2["findings"], res2["findings"]


def test_static_hbm_estimate_within_2x_of_measured():
    """The cross-check the acceptance pins at 110M (the slow test below):
    the pass's estimated peak bytes vs monitor.hbm's MEASURED live bytes
    after one materialized O2 train step, within 2x — here on a small GPT
    so it rides tier-1."""
    from apex_tpu.lint.audit import hbm_crosscheck

    res = hbm_crosscheck(
        materialize=True,
        config=dict(vocab_size=512, hidden_size=128, num_layers=2,
                    num_attention_heads=4, max_seq_len=64))
    assert res["ok"], res
    assert 0.5 <= res["ratio"] <= 2.0, res


@pytest.mark.slow
def test_static_hbm_estimate_within_2x_of_measured_110m():
    """The pinned 110M-class dense config (bench.py's (768, 12) profile
    shape): estimated peak within 2x of the measured figure."""
    from apex_tpu.lint.audit import hbm_crosscheck

    res = hbm_crosscheck(materialize=True)
    assert res["ok"], res


# ---------------------------------------------------------------------------
# dtype-drift: both ways
# ---------------------------------------------------------------------------

_BIG = (64, 1024)  # 64 Ki elements: over the default model-sized floor


def test_dtype_drift_flags_silent_fp32_round_trip():
    def drift(x):
        wide = x.astype(jnp.float32) * jnp.float32(2.0)
        return wide.astype(jnp.bfloat16).sum()

    res = dtype_drift_pass(
        lint_ir.trace_ir(drift, jnp.ones(_BIG, jnp.bfloat16)))
    assert len(res["findings"]) == 1, res
    f = res["findings"][0]
    assert f["rule"] == "dtype-drift" and f["dtype"] == "float32"
    assert f["bytes"] == 64 * 1024 * 4
    assert "path" in f and "line" in f  # provenance for suppression
    assert res["upcasts"] >= 1


def test_dtype_drift_passes_narrow_weak_promotion_and_anchored_fp32():
    """`2.0 * x` stays bf16 (weak promotion resolves down) — clean; an
    fp32 excursion that touches GENUINE fp32 state (a master/moment/LN
    weight) is intentional mixed precision — clean."""
    x = jnp.ones(_BIG, jnp.bfloat16)

    # (.sum()'s f32 accumulator IS a large upcast — booked in the stats —
    # but it reduces to a scalar and never round-trips large: clean)
    res = dtype_drift_pass(lint_ir.trace_ir(lambda x: (x * 2.0).sum(), x))
    assert not res["findings"], res

    master = jnp.ones(_BIG, jnp.float32)

    def anchored(x, m):
        return (x.astype(jnp.float32) + m).astype(jnp.bfloat16).sum()

    res2 = dtype_drift_pass(lint_ir.trace_ir(anchored, x, master))
    assert not res2["findings"], res2


def test_dtype_drift_respects_min_elems_floor():
    small = jnp.ones((8, 8), jnp.bfloat16)  # 64 elems: numerics, not drift

    def drift(x):
        return (x.astype(jnp.float32) * jnp.float32(2.0)) \
            .astype(jnp.bfloat16).sum()

    assert not dtype_drift_pass(lint_ir.trace_ir(drift, small))["findings"]


def test_dtype_drift_clean_on_real_zero_amp_step():
    """The real O2 ZeRO step's fp32 work all touches genuine fp32 state
    (masters, moments) — no drift finding."""
    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), amp.get_policy("O2"), zero_axis="data")
    params = {"w": jnp.ones((256, 1024), jnp.bfloat16)}
    grads = {"w": jnp.ones((256, 1024), jnp.float32)}

    def step(p, g):
        st = opt.init(p)
        return opt.apply_gradients(st, p, g)[0]

    res = dtype_drift_pass(
        lint_ir.trace_ir(step, params, grads, axes={"data": 8}))
    assert not res["findings"], res["findings"]


# ---------------------------------------------------------------------------
# comm-bytes: both ways
# ---------------------------------------------------------------------------


def test_comm_bytes_flags_unbooked_collective_traffic():
    """A bare lax.psum moves bulk wire bytes the comm: accounting never
    books — the finding; the scoped verb (parallel/collectives.psum)
    reconciles clean. Both read the account attached by the SAME single
    trace (trace_ir(comm=True))."""
    from apex_tpu.parallel import collectives

    big = jnp.ones((4, 64, 128), jnp.float32)

    bare = lint_ir.trace_ir(
        jax.vmap(lambda x: lax.psum(x, "i"), axis_name="i"), big, comm=True)
    res = comm_bytes_pass(bare)
    assert len(res["findings"]) == 1, res
    assert res["findings"][0]["dtype"] == "float32"
    assert "comm:" in res["findings"][0]["message"]
    assert res["booked_total_bytes"] == 0

    scoped = lint_ir.trace_ir(
        jax.vmap(lambda x: collectives.psum(x, "i"), axis_name="i"),
        big, comm=True)
    res2 = comm_bytes_pass(scoped)
    assert not res2["findings"], res2
    assert res2["booked_total_bytes"] > 0
    assert "psum[float32]" in res2["static_by_verb_dtype"]


def test_comm_bytes_without_account_reports_table_only():
    res = comm_bytes_pass(lint_ir.trace_ir(
        jax.vmap(lambda x: lax.psum(x, "i"), axis_name="i"),
        jnp.ones((4, 64, 128), jnp.float32)))
    assert not res["findings"]  # nothing to reconcile against
    assert res["booked_by_verb_dtype"] is None
    assert res["static_total_bytes"] > 0


def test_comm_bytes_scalar_traffic_stays_under_floor():
    """Tiny unbooked collectives (the found_inf pmax class) never flag:
    the floor keeps the reconciliation about BULK wire traffic."""
    res = comm_bytes_pass(lint_ir.trace_ir(
        jax.vmap(lambda x: lax.pmax(jnp.sum(x), "i"), axis_name="i"),
        jnp.ones((4, 16), jnp.float32), comm=True))
    assert not res["findings"], res


# ---------------------------------------------------------------------------
# the audit gate (the full program set runs in monitor.selftest + the
# CLI; here the cheap subset proves the wiring end to end in tier-1)
# ---------------------------------------------------------------------------


def test_audit_subset_runs_clean():
    from apex_tpu.lint import audit as lint_audit

    verdict = lint_audit.run_audit(
        programs=("zero3_prefetch", "serve_decode"))
    assert verdict["all_ok"], verdict
    z3 = verdict["programs"]["zero3_prefetch"]
    assert set(z3["passes"]) == set(lint_ir.PASS_REGISTRY)
    assert not z3["tripwires"]["zero3-bulk-gather"]["hazard"]
    assert not z3["tripwires"]["unprefetched-gather"]["hazard"]
    sd = verdict["programs"]["serve_decode"]
    assert not sd["tripwires"]["decode-recompile"]["hazard"]


def test_audit_rejects_unknown_program_names():
    """A typo'd CI subset must never audit 0 programs and exit green."""
    from apex_tpu.lint import audit as lint_audit

    with pytest.raises(ValueError, match="unknown audit program"):
        lint_audit.run_audit(programs=("zero3-prefetch",))


def test_audit_step_program_reports_injected_hazard():
    """The gate actually gates: a step with a divergent-cond collective
    audits NOT ok, with the finding attributed to its pass."""
    from apex_tpu.lint import audit as lint_audit

    mesh = _mesh()

    def body(x):
        return lax.cond(jnp.sum(x) > 0,
                        lambda z: lax.psum(z, "i"), lambda z: z, x)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("i"),
                       out_specs=P("i"), check_vma=False)
    verdict = lint_audit.audit_step_program(fn, jnp.ones((8, 4)),
                                            label="injected")
    assert not verdict["ok"]
    assert verdict["passes"]["collective-consistency"]["findings"]


# ---------------------------------------------------------------------------
# plan-feasibility: planner claim vs traced step (ISSUE 18)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def _tiny_plan_spec():
    from apex_tpu import plan as plan_mod

    return plan_mod.ModelSpec("lintir-tiny", 128, 64, 4, 4, 32)


def test_plan_feasibility_clean_on_planner_zero3_steps(_tiny_plan_spec):
    """Both ZeRO-3 drives the planner can emit (scan + remat, unrolled +
    prefetch) trace to per-layer gathers — the pass stays silent and the
    census shows the gather anatomy it checked."""
    from apex_tpu import plan as plan_mod

    for cand in (plan_mod.Candidate(dp=4, zero_level=3),
                 plan_mod.Candidate(dp=4, zero_level=3, zero3_prefetch=1,
                                    unroll=True)):
        step = plan_mod.feasibility_step(_tiny_plan_spec, cand)
        sir = lint_ir.trace_ir(step["fn"], *step["args"],
                               axes=step["axes"])
        res = lint_ir.run_passes(
            sir, passes=["plan-feasibility"],
            options={"plan-feasibility": {
                "plan": step["plan"],
                "model_elems": step["model_elems"]}})
        r = res["passes"]["plan-feasibility"]
        assert res["ok"], r
        assert r["audited"] and not r["findings"]
        z3 = r["census"]["zero3_gather"]
        assert not z3["hazard"] and z3["layer_gathers"] > 0


def test_plan_feasibility_flags_bulk_gather_claimed_as_zero3(
        _tiny_plan_spec):
    """A step that gathers the whole layer stack up front (the
    O(model)-rematerialization class) contradicts a ZeRO-3 score: the
    pass adopts the zero3-bulk-gather finding under its own rule with
    the plan claim attached. Without a plan option the pass is inert."""
    import jax.numpy as jnp

    from apex_tpu import amp, plan as plan_mod
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.distributed import (
        gather_chunked_tree,
        gather_stacked_leaf,
    )
    from apex_tpu.plan.search import abstract_params, model_config_kwargs

    spec = _tiny_plan_spec
    kw = model_config_kwargs(spec)
    kw.update(remat=True)
    model = GPTModel(GPTConfig(**kw))
    abstract = abstract_params(spec)
    mp3 = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-4), amp.get_policy("O2"), zero_axis="data",
        zero_level=3)
    meta = mp3.zero3_meta(abstract)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jax.ShapeDtypeStruct((1, spec.seq), jnp.int32)

    def bulk_loss(p, toks, tgts):
        chunks = mp3.zero3_shard(p)
        rest = gather_chunked_tree(
            {k: v for k, v in chunks.items() if k != "layers"}, rest_meta)
        layers = jax.tree.map(
            lambda c, s: gather_stacked_leaf(c, s.shape, s.dtype,
                                             meta.axis),
            chunks["layers"], layer_meta.shapes,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
        return model.loss(dict(rest, layers=layers), toks, tgts)

    step = plan_mod.feasibility_step(
        spec, plan_mod.Candidate(dp=4, zero_level=3))
    sir = lint_ir.trace_ir(jax.value_and_grad(bulk_loss), abstract, toks,
                           toks, axes={"data": 4})
    res = lint_ir.run_passes(
        sir, passes=["plan-feasibility"],
        options={"plan-feasibility": {"plan": step["plan"],
                                      "model_elems": step["model_elems"]}})
    r = res["passes"]["plan-feasibility"]
    assert not res["ok"] and r["findings"]
    f = r["findings"][0]
    assert f["rule"] == "plan-feasibility"
    assert "plan scored as" in f["message"]
    assert f["plan_claim"].startswith("ZeRO-3")
    # inert without the plan option: not every audited program is planned
    inert = lint_ir.run_passes(sir, passes=["plan-feasibility"])
    assert inert["ok"]
    assert inert["passes"]["plan-feasibility"] == {
        "findings": [], "audited": False, "census": {}}


def test_plan_feasibility_moe_dispatch_both_ways():
    """The expert-parallel claim: the planner's EP step carries its
    dispatch all_to_alls (silent); a serial-expert step scored as EP
    fires the adopted moe-dispatch finding."""
    import jax.numpy as jnp

    from apex_tpu import plan as plan_mod
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.plan.search import model_config_kwargs

    spec = plan_mod.ModelSpec("lintir-tinymoe", 128, 64, 4, 4, 32,
                              moe_experts=4)
    cand = plan_mod.Candidate(dp=4, moe_expert_axis="data",
                              moe_dispatch_dtype="int8")
    step = plan_mod.feasibility_step(spec, cand)
    sir = lint_ir.trace_ir(step["fn"], *step["args"], axes=step["axes"])
    opts = {"plan-feasibility": {"plan": step["plan"],
                                 "model_elems": step["model_elems"]}}
    r = lint_ir.run_passes(sir, passes=["plan-feasibility"],
                           options=opts)["passes"]["plan-feasibility"]
    assert r["audited"] and not r["findings"], r

    kw = model_config_kwargs(spec)
    kw.update(remat=True)
    serial = GPTModel(GPTConfig(**kw))
    full = jax.eval_shape(serial.init, jax.random.PRNGKey(0))
    toks = jax.ShapeDtypeStruct((1, spec.seq), jnp.int32)
    sir_s = lint_ir.trace_ir(
        jax.value_and_grad(lambda p, a, b: serial.loss(p, a, b)),
        full, toks, toks, axes={"data": 4})
    rs = lint_ir.run_passes(sir_s, passes=["plan-feasibility"],
                            options=opts)["passes"]["plan-feasibility"]
    assert rs["findings"]
    assert "all_to_all" in rs["findings"][0]["message"]
    assert rs["findings"][0]["plan_claim"].startswith("expert-parallel")


def test_audit_plan_program_runs_clean():
    """The registered `plan` audit program: search a tiny spec, trace the
    winner's feasibility step, and the plan-feasibility pass must audit
    it (not skip) and find nothing."""
    from apex_tpu.lint import audit as lint_audit

    verdict = lint_audit.run_audit(programs=("plan",))
    assert verdict["all_ok"], verdict
    prog = verdict["programs"]["plan"]
    pf = prog["passes"]["plan-feasibility"]
    assert pf["audited"] and not pf["findings"]
    assert pf["plan"]["zero_level"] == 3
