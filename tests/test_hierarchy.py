"""Two-tier hierarchical collectives (parallel/hierarchy.py).

The equivalence contract: every hier_* collective computes the SAME
function — values AND gradients — as its flat counterpart over the tuple
axis ``(dcn, ici)`` on a simulated two-host mesh (2 islands x 4 devices).
Integer-valued fp32 payloads make the sums association-free, so "same"
is bit-exact, not a tolerance. The per-tier accounting claims (DCN hop =
1/n_ici of the payload; int8 wire = exactly 1/4 the fp32 DCN bytes) are
pinned off CommAccount.by_tier()/by_verb_dtype().
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.monitor import comms
from apex_tpu.optimizers.distributed import gather_leaf, scatter_chunk
from apex_tpu.parallel import hierarchy

N_DCN = 2
N_ICI = 4
AXES = ("dcn", "data")


@pytest.fixture
def mesh():
    devs = np.array(jax.devices()[:N_DCN * N_ICI]).reshape(N_DCN, N_ICI)
    return Mesh(devs, AXES)


def _int_valued(key, shape):
    """Integer-valued fp32: float sums are exact regardless of
    association, so hierarchical == flat is bit-exact."""
    return jax.random.randint(key, shape, -8, 9).astype(jnp.float32)


def _smap(mesh, fn, in_specs, out_specs):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


# ---------------------------------------------------------------------------
# psum / pmean
# ---------------------------------------------------------------------------


def test_hier_psum_matches_flat_values_and_grads(mesh):
    x = _int_valued(jax.random.PRNGKey(0), (N_DCN * N_ICI, 6, 5))
    w = _int_valued(jax.random.PRNGKey(1), (6, 5))
    shard = P(AXES)

    def flat(w, x):
        with comms.collective_scope("psum", AXES, x):
            y = lax.psum(w * x, AXES)
        return jnp.sum(y * x)

    def hier(w, x):
        y = hierarchy.hier_psum(w * x, "dcn", "data")
        return jnp.sum(y * x)

    for fn in (flat, hier):
        fn.__name__ = fn.__name__  # keep names for failure messages
    run_flat = _smap(mesh, flat, (P(), shard), P())
    run_hier = _smap(mesh, hier, (P(), shard), P())
    # the per-rank losses differ (x is sharded): compare per-rank outputs
    # by keeping the loss local — grads are the real target here
    lf, gf = jax.value_and_grad(lambda w: jnp.sum(run_flat(w, x)))(w)
    lh, gh = jax.value_and_grad(lambda w: jnp.sum(run_hier(w, x)))(w)
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lh))
    np.testing.assert_array_equal(np.asarray(gf), np.asarray(gh))

    # pmean: same decomposition, averaged
    def mean_hier(x):
        return hierarchy.hier_pmean(x, "dcn", "data")

    def mean_flat(x):
        with comms.collective_scope("pmean", AXES, x):
            return lax.pmean(x, AXES)

    out_h = _smap(mesh, mean_hier, (shard,), shard)(x)
    out_f = _smap(mesh, mean_flat, (shard,), shard)(x)
    np.testing.assert_array_equal(np.asarray(out_h), np.asarray(out_f))


# ---------------------------------------------------------------------------
# reduce-scatter / all-gather (the ZeRO chunk pair)
# ---------------------------------------------------------------------------


def test_hier_scatter_chunk_matches_flat(mesh):
    n = N_DCN * N_ICI
    # 103 elements: exercises the zero-padding path too
    x = _int_valued(jax.random.PRNGKey(2), (n, 103))
    shard = P(AXES)

    def flat(x):
        return scatter_chunk(x, n, AXES)

    def hier(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data")
        return chunk

    universal = P(AXES)
    out_f = _smap(mesh, flat, (shard,), universal)(x)
    out_h = _smap(mesh, hier, (shard,), universal)(x)
    np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_h))


def test_hier_gather_chunk_bitmatches_flat(mesh):
    n = N_DCN * N_ICI
    shape = (13, 8)  # 104 elements -> chunk 13, no padding loss
    full = _int_valued(jax.random.PRNGKey(3), shape) / 4.0
    universal = P(AXES)

    def slice_chunks(x):
        from apex_tpu.optimizers.distributed import local_chunk

        idx = lax.axis_index("dcn") * N_ICI + lax.axis_index("data")
        return local_chunk(x, n, idx)

    chunks = _smap(mesh, slice_chunks, (P(),), universal)(full)

    for gd in (None, jnp.bfloat16):
        def flat(c):
            return gather_leaf(c, shape, jnp.float32, AXES, gather_dtype=gd)

        def hier(c):
            return hierarchy.hier_gather_chunk(
                c, shape, jnp.float32, "dcn", "data", gather_dtype=gd)

        out_f = _smap(mesh, flat, (universal,), P())(chunks)
        out_h = _smap(mesh, hier, (universal,), P())(chunks)
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_h))
    # exact wire round-trips the original
    np.testing.assert_array_equal(
        np.asarray(_smap(mesh, lambda c: hierarchy.hier_gather_chunk(
            c, shape, jnp.float32, "dcn", "data"),
            (universal,), P())(chunks)),
        np.asarray(full))


# ---------------------------------------------------------------------------
# all-to-all (the two-hop MoE dispatch)
# ---------------------------------------------------------------------------


def test_hier_all_to_all_matches_flat_values_and_grads(mesh):
    n = N_DCN * N_ICI
    # local (per-rank) payload (1, 3n, 5): split dim 1 into n blocks of 3,
    # concatenate received blocks on dim 2 — the general reshard shape
    x = _int_valued(jax.random.PRNGKey(4), (n, n * 3, 5))
    c = _int_valued(jax.random.PRNGKey(5), (n, 3, 5 * n))
    shard = P(AXES)

    def flat(x, c):
        with comms.collective_scope("all_to_all", AXES, x):
            y = lax.all_to_all(x, AXES, split_axis=1, concat_axis=2,
                               tiled=True)
        return jnp.sum(y * c)

    def hier(x, c):
        y = hierarchy.hier_all_to_all(x, "dcn", "data",
                                      split_axis=1, concat_axis=2)
        return jnp.sum(y * c)

    run_flat = _smap(mesh, flat, (shard, shard), P())
    run_hier = _smap(mesh, hier, (shard, shard), P())
    lf, gf = jax.value_and_grad(lambda x: jnp.sum(run_flat(x, c)))(x)
    lh, gh = jax.value_and_grad(lambda x: jnp.sum(run_hier(x, c)))(x)
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lh))
    np.testing.assert_array_equal(np.asarray(gf), np.asarray(gh))


# ---------------------------------------------------------------------------
# per-tier accounting: DCN hop carries 1/n_ici; int8 wire is exactly 1/4
# ---------------------------------------------------------------------------


def _census(fn, *args):
    with comms.comm_accounting() as acct:
        jax.make_jaxpr(
            lambda *a: jax.shard_map(
                fn,
                mesh=Mesh(np.array(jax.devices()[:N_DCN * N_ICI]).reshape(
                    N_DCN, N_ICI), AXES),
                in_specs=tuple(P(AXES) for _ in args), out_specs=P(AXES),
                check_vma=False)(*a))(*args)
    return acct


def test_dcn_tier_booking_and_int8_quarter_bytes():
    n = N_DCN * N_ICI
    x = jnp.zeros((n, 128), jnp.float32)

    def exact(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data")
        return chunk

    def quant(x):
        chunk, _ = hierarchy.hier_scatter_chunk(x, "dcn", "data",
                                                wire_dtype="int8")
        return chunk

    a_exact = _census(exact, x)
    a_quant = _census(quant, x)

    local = x.size // n  # bookings are per-rank payloads (local shapes)
    tiers = a_exact.by_tier()
    # per-rank payload: ici stage ships the full padded local leaf, the
    # dcn stage exactly 1/n_ici of it
    assert tiers["ici"]["bytes"] == local * 4
    assert tiers["dcn"]["bytes"] == local * 4 // N_ICI

    # int8 wire: the bulk DCN payload is exactly 1/4 the fp32 bytes; the
    # fp32 scale side-channel is booked separately (by_verb_dtype rows)
    dcn_rows = a_quant.by_verb_dtype(axis="dcn")
    assert dcn_rows["all_to_all[int8]"]["bytes"] == local // N_ICI
    assert dcn_rows["all_to_all[int8]"]["bytes"] * 4 == \
        a_exact.by_tier()["dcn"]["bytes"]
    # the side-channel is n_dcn fp32 scales — negligible next to the bulk
    assert dcn_rows["all_to_all[float32]"]["bytes"] == N_DCN * 4
    # the ici stage is identical (and full-precision) in both programs
    assert a_quant.by_tier()["ici"]["bytes"] == local * 4


def test_moe_two_hop_dispatch_matches_single_hop(mesh):
    """MoEMLP(dcn_axis=...): the two-hop hierarchical dispatch computes
    the same function — output AND gradients, bit-exact — as the flat
    single-hop all_to_all over the tuple expert group (only the exchange
    differs between the paths, and hier_all_to_all is bit-exact)."""
    from apex_tpu.transformer.moe import MoEMLP

    n = N_DCN * N_ICI
    kw = dict(hidden_size=16, ffn_hidden_size=32, num_experts=8,
              top_k=2, capacity_factor=2.0)
    flat = MoEMLP(expert_axis=AXES, **kw)
    hier = MoEMLP(expert_axis="data", dcn_axis="dcn", **kw)
    # identical param placement: both shard the expert dim over the full
    # (dcn, data) group (specs() spells it as the tuple entry)
    params = flat.init(jax.random.PRNGKey(13))
    pspecs = flat.specs()
    assert pspecs == hier.specs()
    h = jax.random.normal(jax.random.PRNGKey(14), (n, 4, 16))
    c = jax.random.normal(jax.random.PRNGKey(15), (n, 4, 16))
    shard = P(AXES)

    def run(moe):
        def fwd(params, h, c):
            out, aux = moe.apply_expert_parallel(params, h)
            return jnp.sum(out * c) + aux["load_balancing_loss"]

        step = _smap(mesh, fwd, (pspecs, shard, shard), P())
        return jax.value_and_grad(
            lambda p, h: jnp.sum(step(p, h, c)), argnums=(0, 1))(params, h)

    (lf, (gpf, ghf)) = run(flat)
    (lh, (gph, ghh)) = run(hier)
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lh))
    np.testing.assert_array_equal(np.asarray(ghf), np.asarray(ghh))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), gpf, gph)


def test_zero_step_dcn_axis_bitmatches_flat_group(mesh):
    """MixedPrecisionOptimizer(dcn_axis=..., dcn_wire=None): the whole
    sharded step — chunk init, scatter, Adam update, gather — bit-matches
    the flat optimizer over the tuple axis (integer-valued grads make the
    scatter sums exact, so identical chunks drive identical updates)."""
    from apex_tpu import amp as amp_mod
    from apex_tpu.optimizers import FusedAdam

    params = {"w": _int_valued(jax.random.PRNGKey(7), (7, 5)) / 4.0,
              "b": _int_valued(jax.random.PRNGKey(8), (13,)) / 8.0}
    n = N_DCN * N_ICI
    grads = {"w": _int_valued(jax.random.PRNGKey(9), (n, 7, 5)),
             "b": _int_valued(jax.random.PRNGKey(10), (n, 13))}
    policy = amp_mod.get_policy("O2")

    def run(mp_opt):
        def step(p, gw, gb):
            st = mp_opt.init(p)
            # scaled grads: each rank's own slice (leading dim sharded)
            g = {"w": gw[0] * st.scaler.loss_scale,
                 "b": gb[0] * st.scaler.loss_scale}
            new_p, new_st, metrics = mp_opt.apply_gradients(st, p, g)
            return new_p, new_st.master, metrics["loss_scale"]

        fn = _smap(mesh, step, (P(), P(AXES), P(AXES)),
                   (P(), P(AXES), P()))
        return fn(params, grads["w"], grads["b"])

    flat_p, flat_m, flat_s = run(amp_mod.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis=AXES))
    hier_p, hier_m, hier_s = run(amp_mod.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data", dcn_axis="dcn",
        dcn_wire=None))
    for k in params:
        np.testing.assert_array_equal(np.asarray(flat_p[k]),
                                      np.asarray(hier_p[k]))
        np.testing.assert_array_equal(np.asarray(flat_m[k]),
                                      np.asarray(hier_m[k]))
    np.testing.assert_array_equal(np.asarray(flat_s), np.asarray(hier_s))


def _offload_fixtures():
    n = N_DCN * N_ICI
    params = {"b": _int_valued(jax.random.PRNGKey(20), (13,)) / 8.0,
              "v": _int_valued(jax.random.PRNGKey(21), (11, 3)) / 4.0,
              "w": _int_valued(jax.random.PRNGKey(22), (7, 5)) / 4.0}
    g1 = {k: _int_valued(jax.random.PRNGKey(30 + i), (n,) + v.shape)
          for i, (k, v) in enumerate(params.items())}
    g2 = {k: _int_valued(jax.random.PRNGKey(40 + i), (n,) + v.shape)
          for i, (k, v) in enumerate(params.items())}
    return params, g1, g2


def _offload_two_step_pair(mesh, mk, params, g1, g2):
    """(resident, offloaded) two-step drives of the SAME optimizer
    config: resident runs whole-tree in one shard_map; the offload driver
    streams host buckets. Returns ((params, masters, loss_scale), ...)
    with masters keyed by param name on both sides."""
    from apex_tpu.optimizers.offload import HostOffloadedZero

    mp_r = mk()

    def body(p, ga, gb):
        st = mp_r.init(p)
        s = st.scaler.loss_scale
        p1, st1, _ = mp_r.apply_gradients(
            st, p, jax.tree.map(lambda g: g[0] * s, ga))
        s1 = st1.scaler.loss_scale
        p2, st2, m = mp_r.apply_gradients(
            st1, p1, jax.tree.map(lambda g: g[0] * s1, gb))
        return p2, st2.master, m["loss_scale"]

    gspec = {k: P(AXES) for k in params}
    res_p, res_m, res_s = _smap(
        mesh, body, (P(), gspec, gspec),
        ({k: P() for k in params}, {k: P(AXES) for k in params}, P()))(
            params, g1, g2)

    off = HostOffloadedZero(mk(), mesh, None, num_buckets=2)
    state = off.init(params)
    assert len(state.host) == 2  # masters/moments/residual are off-device
    s = float(state.scaler.loss_scale)
    p1, state, _ = off.apply_gradients(
        state, params, jax.tree.map(lambda g: g * s, g1))
    s = float(state.scaler.loss_scale)
    p2, state, m = off.apply_gradients(
        state, p1, jax.tree.map(lambda g: g * s, g2))
    keys = sorted(params)
    off_m = {}
    for b, idxs in enumerate(off._buckets):
        for i in idxs:
            off_m[keys[i]] = state.host[b]["master"][str(i)]
    return (res_p, res_m, res_s), (p2, off_m, m["loss_scale"])


def test_offloaded_step_bitmatches_resident(mesh):
    """HostOffloadedZero: two bucketed host-offloaded steps — masters and
    momentum round-tripping through host RAM with H2D prefetch — produce
    bit-identical params, masters, and loss scale vs the resident in-HBM
    optimizer. Dyadic hyperparameters (lr/momentum powers of two) +
    integer grads keep every intermediate exactly representable, so the
    equality survives cross-program FMA contraction (the resident and
    bucketed programs are DIFFERENT XLA programs)."""
    from apex_tpu import amp as amp_mod
    from apex_tpu.optimizers import FusedSGD

    params, g1, g2 = _offload_fixtures()
    policy = amp_mod.get_policy("O2")

    def mk():
        return amp_mod.MixedPrecisionOptimizer(
            FusedSGD(lr=0.03125, momentum=0.5), policy,
            zero_axis="data", dcn_axis="dcn", dcn_wire=None)

    (res_p, res_m, res_s), (off_p, off_m, off_s) = _offload_two_step_pair(
        mesh, mk, params, g1, g2)
    for k in params:
        np.testing.assert_array_equal(np.asarray(res_p[k]),
                                      np.asarray(off_p[k]))
        np.testing.assert_array_equal(np.asarray(res_m[k]),
                                      np.asarray(off_m[k]))
    np.testing.assert_array_equal(np.asarray(res_s), np.asarray(off_s))


def test_offloaded_adam_int8_wire_tracks_resident(mesh):
    """The full production config — Adam moments + the default int8 DCN
    wire with its EF residual offloaded per bucket — tracks the resident
    step to float rounding (Adam's non-dyadic betas admit 1-ulp
    cross-program FMA differences; anything beyond rounding would mean
    the residual or moments were mis-bucketed). Also pins the prefetch
    span evidence: bucket b+1's H2D dispatches before bucket b's apply
    lands."""
    from apex_tpu import amp as amp_mod
    from apex_tpu.monitor import tracing
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.offload import HostOffloadedZero

    params, g1, g2 = _offload_fixtures()
    policy = amp_mod.get_policy("O2")

    def mk():
        # dcn_wire defaults to int8: the residual is live, offloaded state
        return amp_mod.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-2), policy, zero_axis="data", dcn_axis="dcn")

    (res_p, _, res_s), (off_p, _, off_s) = _offload_two_step_pair(
        mesh, mk, params, g1, g2)
    for k in params:
        np.testing.assert_allclose(np.asarray(res_p[k]),
                                   np.asarray(off_p[k]),
                                   rtol=0, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(res_s), np.asarray(off_s))

    # timeline evidence: bucket b+1's H2D span is dispatched before
    # bucket b's apply span lands (the prefetch issue-ahead discipline)
    off = HostOffloadedZero(mk(), mesh, None, num_buckets=2)
    state = off.init(params)
    s = float(state.scaler.loss_scale)
    with tracing.scoped(tracing.Tracer(None)) as tr:
        off.apply_gradients(state, params,
                            jax.tree.map(lambda g: g * s, g1))
    spans = [r for r in tr.records if r.get("kind") == "span"]
    h2d = [r for r in spans if r["name"] == "offload.h2d"]
    app = [r for r in spans if r["name"] == "offload.apply"]
    assert [r["bucket"] for r in h2d] == [0, 1]
    assert [r["bucket"] for r in app] == [0, 1]
    assert h2d[1]["ts"] <= app[0]["ts"] + app[0]["dur_s"]


def test_zero_step_dcn_wire_default_and_residual_layout(mesh):
    """The quantized DCN hop defaults ON (EQuARX): dcn_wire='int8' is the
    constructor default, the residual covers n_dcn chunks per leaf (1/n_ici
    the flat quantized residual), and the stepped params TRACK the exact
    path within the per-block quantization error."""
    from apex_tpu import amp as amp_mod
    from apex_tpu.optimizers import FusedAdam

    policy = amp_mod.get_policy("O2")
    mp_q = amp_mod.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data", dcn_axis="dcn")
    assert mp_q.dcn_wire == "int8"
    # reduce_dtype is the FLAT quantized wire; the tiers are disjoint
    with pytest.raises(ValueError, match="reduce_dtype does not compose"):
        amp_mod.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-2), policy, zero_axis="data", dcn_axis="dcn",
            reduce_dtype="int8")
    with pytest.raises(ValueError, match="dcn_axis only applies"):
        amp_mod.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-2), policy, dcn_axis="dcn")

    params = {"w": _int_valued(jax.random.PRNGKey(11), (6, 8)) / 4.0}
    n = N_DCN * N_ICI
    grads = _int_valued(jax.random.PRNGKey(12), (n, 6, 8))

    def step(mp_opt):
        def body(p, g):
            st = mp_opt.init(p)
            gs = {"w": g[0] * st.scaler.loss_scale}
            new_p, new_st, _ = mp_opt.apply_gradients(st, p, gs)
            err = (new_st.residual["err"]["w"]
                   if new_st.residual is not None else jnp.zeros((0,)))
            return new_p["w"], err

        return _smap(mesh, body, (P(), P(AXES)), (P(), P(AXES)))(
            params, grads)

    q_p, q_err = step(mp_q)
    e_p, _ = step(amp_mod.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data", dcn_axis="dcn",
        dcn_wire=None))
    # residual layout: n_dcn * chunk elements per rank (48/8 = 6 -> 12;
    # the sharded out-spec concatenates the 8 ranks' leaves)
    chunk = params["w"].size // n
    assert q_err.shape == (n * N_DCN * chunk,)
    err = np.max(np.abs(np.asarray(q_p) - np.asarray(e_p)))
    assert err < 1e-2  # int8 hop tracks the exact step, does not match it
    x = jax.random.normal(jax.random.PRNGKey(6),
                          (N_DCN * N_ICI, 64)) * 3.0
    shard = P(AXES)
    out_e = _smap(mesh, lambda x: hierarchy.hier_psum(x, "dcn", "data"),
                  (shard,), shard)(x)
    out_q = _smap(mesh, lambda x: hierarchy.hier_psum(
        x, "dcn", "data", wire_dtype="int8"), (shard,), shard)(x)
    # quantization is lossy by design: the int8 wire must TRACK the exact
    # sum (per-block scale bounds the error), not bit-match it
    err = np.max(np.abs(np.asarray(out_q) - np.asarray(out_e)))
    scale = np.max(np.abs(np.asarray(out_e))) + 1e-9
    assert err / scale < 0.05
