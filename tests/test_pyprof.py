"""pyprof-equivalent tests (the reference tests pyprof via example scripts,
apex/pyprof/examples; here the cost model itself is assertable)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import pyprof


def test_cost_analysis_matmul_flops():
    a = jnp.zeros((64, 128), jnp.float32)
    b = jnp.zeros((128, 32), jnp.float32)
    costs = pyprof.cost_analysis(lambda x, y: x @ y, a, b)
    # 2*m*n*k FLOPs — the blas.py GEMM formula (pyprof/prof/blas.py)
    assert costs["flops"] == pytest.approx(2 * 64 * 128 * 32, rel=0.01)


def test_primitive_counts_sees_structure():
    def fn(x):
        return jax.nn.relu(x @ x.T) + jnp.tanh(x).sum()

    counts = pyprof.primitive_counts(fn, jnp.zeros((8, 8)))
    assert counts.get("dot_general", 0) == 1
    assert counts.get("tanh", 0) == 1


def test_primitive_counts_recurses_into_scan():
    def fn(x):
        def body(c, _):
            return jnp.tanh(c @ c), None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    counts = pyprof.primitive_counts(fn, jnp.zeros((4, 4)))
    assert counts.get("scan", 0) == 1
    assert counts.get("dot_general", 0) >= 1  # found inside the scan body


def _hlo_with_metadata(fn, *args):
    """Lowered text carrying scope metadata across jax vintages: newer
    jax exposes it via ``as_text(debug_info=True)``; older Lowered.as_text
    takes no such kwarg and strips location info, but the compiled
    executable's HLO keeps op_name metadata (where the trace-join reads
    it anyway)."""
    lowered = jax.jit(fn).lower(*args)
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:
        return lowered.compile().as_text()


def test_annotate_and_scope_in_hlo():
    @pyprof.annotate("my_hot_block")
    def fn(x):
        return x * 2 + 1

    assert "my_hot_block" in _hlo_with_metadata(fn, jnp.zeros((4,)))

    def gn(x):
        with pyprof.scope("outer_region"):
            return x + 1

    assert "outer_region" in _hlo_with_metadata(gn, jnp.zeros((4,)))


def test_profile_fn_reports_throughput():
    a = jnp.zeros((256, 256), jnp.float32)
    rep = pyprof.profile_fn(lambda x: x @ x, a, steps=3)
    assert rep["seconds_per_call"] > 0
    assert rep["flops"] == pytest.approx(2 * 256**3, rel=0.01)
    assert rep["achieved_flops_per_sec"] > 0


def test_profile_fn_counts_pallas_flops():
    """The XLA cost model sees zero FLOPs inside Pallas custom-calls;
    profile_fn must merge the jaxpr-level count so flash-kernel programs
    are not under-reported (VERDICT r4 weak #3). The jaxpr count must also
    multiply the kernel body by its grid trip count."""
    from apex_tpu.ops.flash_attention import flash_attention

    b, h, s, d = 1, 2, 512, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), jnp.bfloat16)

    def fwd(q):
        return flash_attention(q, q, q, causal=True, impl="pallas")

    ideal = 4 * b * h * s * s * d  # QK^T + PV GEMMs
    rep = pyprof.profile_fn(fwd, q, steps=2)
    assert rep["flops_jaxpr"] >= ideal  # grid-multiplied, not one trip
    assert rep["flops_jaxpr"] < 3 * ideal  # and not wildly over
    assert rep["flops"] == max(rep["flops_xla_cost_model"],
                               rep["flops_jaxpr"])
    if rep["flops_xla_cost_model"] < 0.5 * rep["flops_jaxpr"]:
        assert rep["flops_undercounted"]


def test_trace_writes_profile(tmp_path):
    with pyprof.trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    import os
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace files written"


def test_per_scope_costs_gemm_attribution():
    """Scoped GEMMs land on their scope with the blas.py 2*m*n*k formula."""
    a = jnp.zeros((64, 128), jnp.float32)
    w1 = jnp.zeros((128, 256), jnp.float32)
    w2 = jnp.zeros((256, 32), jnp.float32)

    def fn(a, w1, w2):
        with pyprof.scope("first"):
            h = a @ w1
        with pyprof.scope("second"):
            return h @ w2

    costs = pyprof.per_scope_costs(fn, a, w1, w2)
    assert costs["first"]["flops"] == 2 * 64 * 128 * 256
    assert costs["second"]["flops"] == 2 * 64 * 256 * 32
    assert costs["<total>"]["flops"] == (
        costs["first"]["flops"] + costs["second"]["flops"])


def test_per_scope_costs_scan_multiplies_by_length():
    def fn(x):
        def body(c, _):
            with pyprof.scope("inner"):
                return jnp.tanh(c @ c), None
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    costs = pyprof.per_scope_costs(fn, jnp.zeros((8, 8), jnp.float32))
    inner = [v for k, v in costs.items() if "inner" in k]
    assert sum(r["flops"] for r in inner) >= 5 * 2 * 8 * 8 * 8


def test_report_gpt_attention_mlp_dominate(capsys):
    """The per-scope table must attribute a GPT train step's FLOPs to the
    model's scoped blocks, with attention+mlp+head covering the bulk of a
    layer's cost — the 'which layer eats my step time' answer the
    reference's prof stage gives (pyprof/prof/output.py)."""
    from apex_tpu.models import GPTConfig, GPTModel

    cfg = GPTConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_attention_heads=4,
        max_seq_len=32, hidden_dropout=0.0, axis=None,
        compute_dtype=jnp.float32, remat=False)
    m = GPTModel(cfg)
    p = m.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
    tgt = jnp.roll(toks, -1, -1)

    def train(p):
        return jax.value_and_grad(m.loss)(p, toks, tgt)

    costs = pyprof.report(train, p, depth=2)
    out = capsys.readouterr().out
    assert "mlp" in out and "attention" in out  # printed table

    total = costs["<total>"]["flops"]
    assert total > 0

    def share(*names):
        return sum(
            r["flops"] for k, r in costs.items()
            if k != "<total>" and any(n in k for n in names)) / total

    # fwd + bwd (jvp/transpose-prefixed scopes) of the model's blocks
    assert share("attention", "mlp", "head") > 0.8
    assert share("attention") > 0.1
    assert share("mlp") > 0.2


# -- measured per-scope seconds (VERDICT r3 ask #5) --------------------------


def test_hlo_scope_map_parses_compiled_metadata():
    """The HLO-metadata join key behind measured_scope_seconds: every
    instruction's op_name carries the named_scope stack on any backend."""
    from apex_tpu.pyprof.prof import _hlo_scope_map

    @jax.jit
    def f(x):
        with jax.named_scope("attention"):
            y = x @ x.T
        with jax.named_scope("mlp"):
            z = jax.nn.gelu(y @ y)
        return z.sum()

    x = jnp.ones((128, 128))
    mapping = _hlo_scope_map(f.lower(x).compile().as_text())
    scopes = set(mapping.values())
    assert any("attention" in s for s in scopes), scopes
    assert any("mlp" in s for s in scopes), scopes


def test_accumulate_events_drops_control_flow_envelopes():
    """The TPU device trace carries BOTH a while/conditional envelope
    event and each body instruction; counting both double-bills the loop
    body (observed ~2x on the scanned GPT layer stack). The accumulator
    must keep the body rows and drop the envelope."""
    from apex_tpu.pyprof.prof import _accumulate_events

    scope_of = {
        "while.1": "jvp()",
        "fusion.1": "jvp()/attention",
        "fusion.2": "jvp()/mlp",
        "conditional.3": "jvp()",
        "call.4": "jvp()",
    }
    ps = int(1e12)  # 1 second
    events = [
        {"name": "while.1", "args": {"device_duration_ps": 2 * ps}},
        {"name": "fusion.1", "args": {"device_duration_ps": ps}},
        {"name": "fusion.2", "args": {"device_duration_ps": ps}},
        {"name": "conditional.3", "args": {"device_duration_ps": ps}},
        {"name": "call.4", "args": {"device_duration_ps": ps}},
        {"name": "unknown.9", "args": {"device_duration_ps": ps}},  # unjoined
        {"name": "fusion.1", "args": {}},  # no duration
    ]
    scopes, kinds = _accumulate_events(events, scope_of, steps=1, depth=2)
    assert scopes["<total_device>"] == pytest.approx(2.0)  # body only
    assert scopes["jvp()/attention"] == pytest.approx(1.0)
    assert "while" not in kinds and "conditional" not in kinds
    assert kinds["fusion"] == pytest.approx(2.0)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="device traces only exist on TPU")
def test_measured_scope_seconds_on_tpu():
    """On-chip: measured per-scope device time for a GPT step; the model's
    scoped blocks must account for most of the step and sum to ~total."""
    from apex_tpu.models import GPTConfig, GPTModel

    cfg = GPTConfig(
        vocab_size=256, hidden_size=128, num_layers=2,
        num_attention_heads=4, max_seq_len=128, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.float32, remat=False)
    m = GPTModel(cfg)
    p = m.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 256)
    secs = pyprof.measured_scope_seconds(
        lambda p: jax.value_and_grad(m.loss)(p, toks, jnp.roll(toks, -1, -1)),
        p, steps=3, depth=2)
    total = secs.pop("<total_device>")
    assert total > 0
    assert abs(sum(secs.values()) - total) < 1e-9
    blocks = sum(v for k, v in secs.items()
                 if any(n in k for n in ("attention", "mlp", "head",
                                         "embed")))
    # named blocks carry the matmuls; LN/residual layer-body ops land on
    # the bare jvp()/transpose(jvp()) rows, so the scoped share is well
    # under 1 on tiny models
    assert blocks / total > 0.3, secs
