"""pyprof-equivalent tests (the reference tests pyprof via example scripts,
apex/pyprof/examples; here the cost model itself is assertable)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import pyprof


def test_cost_analysis_matmul_flops():
    a = jnp.zeros((64, 128), jnp.float32)
    b = jnp.zeros((128, 32), jnp.float32)
    costs = pyprof.cost_analysis(lambda x, y: x @ y, a, b)
    # 2*m*n*k FLOPs — the blas.py GEMM formula (pyprof/prof/blas.py)
    assert costs["flops"] == pytest.approx(2 * 64 * 128 * 32, rel=0.01)


def test_primitive_counts_sees_structure():
    def fn(x):
        return jax.nn.relu(x @ x.T) + jnp.tanh(x).sum()

    counts = pyprof.primitive_counts(fn, jnp.zeros((8, 8)))
    assert counts.get("dot_general", 0) == 1
    assert counts.get("tanh", 0) == 1


def test_primitive_counts_recurses_into_scan():
    def fn(x):
        def body(c, _):
            return jnp.tanh(c @ c), None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    counts = pyprof.primitive_counts(fn, jnp.zeros((4, 4)))
    assert counts.get("scan", 0) == 1
    assert counts.get("dot_general", 0) >= 1  # found inside the scan body


def test_annotate_and_scope_in_hlo():
    @pyprof.annotate("my_hot_block")
    def fn(x):
        return x * 2 + 1

    hlo = jax.jit(fn).lower(jnp.zeros((4,))).as_text(debug_info=True)
    assert "my_hot_block" in hlo

    def gn(x):
        with pyprof.scope("outer_region"):
            return x + 1

    hlo2 = jax.jit(gn).lower(jnp.zeros((4,))).as_text(debug_info=True)
    assert "outer_region" in hlo2


def test_profile_fn_reports_throughput():
    a = jnp.zeros((256, 256), jnp.float32)
    rep = pyprof.profile_fn(lambda x: x @ x, a, steps=3)
    assert rep["seconds_per_call"] > 0
    assert rep["flops"] == pytest.approx(2 * 256**3, rel=0.01)
    assert rep["achieved_flops_per_sec"] > 0


def test_trace_writes_profile(tmp_path):
    with pyprof.trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    import os
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace files written"
