"""Step-anatomy tracing tests (apex_tpu/monitor/tracing.py +
schedules.traced_pipeline_timeline + the traced ZeRO step build).

Pins the tentpole claims: spans are strict JSON and crash-tolerant like
the journal; the analytic bubble floors and the anatomy fraction
invariant hold; the traced tick drive computes the SAME loss/grads as
the serial model while measuring a bubble fraction within tolerance of
the analytic floor; Chrome export is structurally loadable; and a
tracer that is DISARMED leaves the ZeRO step program byte-identical.
"""

import io
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.monitor import tracing
from apex_tpu.monitor.journal import MetricsJournal


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_span_nesting_depth_step_and_barrier():
    tr = tracing.Tracer(None, meta={"run": "t"})
    tr.step = 7
    with tr.span("step") as outer:
        with tr.span("inner", cat="compute", phase="fwd") as sp:
            sp.barrier(jnp.ones((4,)))
            sp.annotate(extra=1)
        outer.barrier(jnp.zeros(()))
    spans = [r for r in tr.records if r["kind"] == "span"]
    assert [s["name"] for s in spans] == ["inner", "step"]
    inner, outer_rec = spans
    assert inner["depth"] == 1 and outer_rec["depth"] == 0
    assert inner["step"] == 7 and outer_rec["step"] == 7
    assert inner["extra"] == 1 and inner["cat"] == "compute"
    assert 0 <= inner["dur_s"] <= outer_rec["dur_s"]


def test_span_records_error_flag_and_propagates():
    tr = tracing.Tracer(None)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    assert tr.records[-1]["name"] == "boom"
    assert tr.records[-1]["error"] is True


def test_nonfinite_span_values_serialize_strict_json():
    buf = io.StringIO()
    tr = tracing.Tracer(buf)
    tr.record("w", dur_s=float("nan"), cat="host", metric=float("inf"))
    line = buf.getvalue().strip()
    rec = json.loads(line)  # strict parser: bare NaN/Infinity would raise
    assert rec["dur_s"] is None and rec["metric"] is None
    assert sorted(rec["nonfinite_keys"]) == ["dur_s", "metric"]


def test_trace_read_tolerates_corrupt_and_truncated_lines(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with tracing.Tracer(path) as tr:
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
    with open(path, "a") as f:
        f.write("not json at all\n")
        f.write('{"kind": "span", "name": "torn')
    rows = tracing.Tracer.read(path)
    assert len(rows) == 2
    assert rows.bad_lines == 2 and rows.truncated  # journal semantics
    # and the chrome export of the torn file still works off the prefix
    trace = tracing.chrome_trace(rows)
    assert len([e for e in trace["traceEvents"] if e["ph"] == "X"]) == 2


def test_scoped_arming_restores_previous_state():
    assert tracing.get_tracer() is None
    tr = tracing.Tracer(None)
    with tracing.scoped(tr):
        assert tracing.get_tracer() is tr
        with tracing.maybe_span(tracing.get_tracer(), "x") as sp:
            sp.barrier(1.0)
    assert tracing.get_tracer() is None
    assert tr.records and tr.records[-1]["name"] == "x"
    # maybe_span with no tracer is a no-op null span
    with tracing.maybe_span(None, "y") as sp:
        sp.barrier(1.0)
        sp.annotate(z=1)


# ---------------------------------------------------------------------------
# analytic floors + anatomy math
# ---------------------------------------------------------------------------


def test_expected_bubble_fraction_known_points():
    """Hand-computed floors for all four planners: gpipe/1f1b share
    (S-1)/(M+S-1), interleaved divides the live slots by vpp, and the
    zero-bubble W/B split lands at (S-1)/(3M+S-1) — 3M live slots per
    rank, only the S-1 fill ticks idle."""
    ebf = tracing.expected_bubble_fraction
    assert math.isclose(ebf("gpipe", 8, 4), 3 / 11)
    assert math.isclose(ebf("1f1b", 8, 4), 3 / 11)
    assert math.isclose(ebf("interleaved", 8, 4, 2), 3 / 19)
    assert math.isclose(ebf("interleaved", 4, 4, 1), 3 / 7)
    # zero-bubble hand points: S=4,M=8 -> 3/27; S=2,M=4 -> 1/13; S=3,M=3
    # -> 2/11 — each strictly below the 1f1b floor at the same (S, M)
    assert math.isclose(ebf("zero-bubble", 8, 4), 3 / 27)
    assert math.isclose(ebf("zero-bubble", 4, 2), 1 / 13)
    assert math.isclose(ebf("zero-bubble", 3, 3), 2 / 11)
    for M, S in ((8, 4), (4, 2), (3, 3)):
        assert ebf("zero-bubble", M, S) < ebf("1f1b", M, S)
    assert ebf("1f1b", 8, 1) == 0.0  # no pipeline, no bubble
    assert ebf("zero-bubble", 8, 1) == 0.0
    with pytest.raises(ValueError):
        ebf("mystery", 8, 4)
    with pytest.raises(ValueError):
        ebf("1f1b", 0, 4)


def test_schedule_plans_meet_closed_form_floors():
    """Schedule-as-data pinning: the greedy planners' COUNTED idle
    fractions equal the closed-form floors at every tested (S, M), and
    the interleaved plan mirrors the ring algebra's tick count."""
    from apex_tpu.transformer.pipeline_parallel import plan_schedule
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_tick_count,
    )

    for sched in ("gpipe", "1f1b", "zero-bubble"):
        for S in (2, 3, 4):
            for M in (S, 4, 8):
                if M < S:
                    continue
                plan = plan_schedule(sched, M, S)
                floor = tracing.expected_bubble_fraction(sched, M, S)
                assert math.isclose(plan.bubble_fraction(), floor), (
                    sched, S, M, plan.bubble_fraction(), floor)
                want_ticks = (3 * M + S - 1 if sched == "zero-bubble"
                              else 2 * (M + S - 1))
                assert plan.ticks == want_ticks, (sched, S, M, plan.ticks)
    for vpp in (1, 2):
        plan = plan_schedule("interleaved", 4, 4, vpp)
        assert plan.ticks == 2 * pipeline_tick_count(4, 4, vpp)
        assert math.isclose(
            plan.bubble_fraction(),
            tracing.expected_bubble_fraction("interleaved", 4, 4, vpp))


def test_schedule_plan_dependencies_and_counts():
    """Replay each plan against the pipeline dependency graph: every
    (rank, microbatch) does each of its slot kinds exactly once, forwards
    arrive only after the upstream rank's forward, input-grads only after
    the downstream rank's, weight-grads only after the rank's own
    input-grad — the W/B factoring's soundness condition."""
    from apex_tpu.transformer.pipeline_parallel import plan_schedule

    for sched in ("gpipe", "1f1b", "zero-bubble"):
        S, M = 3, 4
        plan = plan_schedule(sched, M, S)
        done = {}  # (kind, s, m) -> tick
        for t in range(plan.ticks):
            for s in range(S):
                sl = plan.ranks[s][t]
                if sl.kind == "idle":
                    continue
                key = (sl.kind, s, sl.microbatch)
                assert key not in done, key
                done[key] = t
                m = sl.microbatch
                if sl.kind == "fwd" and s > 0:
                    assert done[("fwd", s - 1, m)] < t, (sched, key)
                if sl.kind in ("bwd", "bwd_input"):
                    assert done[("fwd", s, m)] < t, (sched, key)
                    if s < S - 1:
                        assert done[(sl.kind, s + 1, m)] < t, (sched, key)
                if sl.kind == "bwd_weight":
                    assert done[("bwd_input", s, m)] < t, (sched, key)
        kinds = (("fwd", "bwd_input", "bwd_weight")
                 if sched == "zero-bubble" else ("fwd", "bwd"))
        for k in kinds:
            for s in range(S):
                for m in range(M):
                    assert (k, s, m) in done, (sched, k, s, m)


def test_step_anatomy_fractions_sum_to_one():
    for wall, comp, comm in ((0.1, 0.06, 0.06), (0.1, 0.1, 0.0),
                             (0.1, 0.02, 0.01), (0.2, 0.3, 0.05),
                             (0.05, 0.0, 0.0)):
        an = tracing.step_anatomy(wall_s=wall, compute_s=comp, comm_s=comm)
        assert abs(an["compute_frac"] + an["comm_frac"]
                   + an["stall_frac"] - 1.0) < 1e-6, an
    # hand point: 60+60ms in a 100ms wall → 20ms overlapped = 1/3 of min
    an = tracing.step_anatomy(wall_s=0.1, compute_s=0.06, comm_s=0.06)
    assert abs(an["overlap_fraction"] - 1 / 3) < 1e-3
    # nothing to overlap → no overlap_fraction field
    assert "overlap_fraction" not in tracing.step_anatomy(
        wall_s=0.1, compute_s=0.05, comm_s=0.0)


def test_step_anatomy_modeled_sources_and_ici_override(monkeypatch):
    spec = {"platform": "x", "peak_flops": 1e12,
            "peak_hbm_bytes_per_sec": 1e11, "source": "test"}
    ici = {"platform": "x", "ici_bytes_per_sec": 1e9, "source": "test"}
    an = tracing.step_anatomy(wall_s=0.1, flops=5e10, comm_bytes=2e7,
                              spec=spec, ici=ici)
    assert abs(an["compute_s"] - 0.05) < 1e-9
    assert abs(an["comm_s"] - 0.02) < 1e-9
    assert an["compute_source"].startswith("cost_model")
    assert an["comm_source"].startswith("wire_model")
    monkeypatch.setenv(tracing.ENV_PEAK_ICI_GBPS, "123")
    got = tracing.ici_spec("tpu v4")
    assert got["ici_bytes_per_sec"] == 123e9 and got["source"] == "env"
    monkeypatch.delenv(tracing.ENV_PEAK_ICI_GBPS)
    got = tracing.ici_spec("tpu v4")
    assert got["ici_bytes_per_sec"] == tracing.ICI_SPECS["v4"]
    assert got["source"] == "table:v4"


def test_pipeline_anatomy_synthetic_timeline_and_chrome_export():
    # 2 ranks, 3 units, 4 ticks per direction, uniform 10ms slots:
    # one idle slot per rank per direction → bubble = 1/4 == 1F1B floor
    tr = tracing.Tracer(None)
    for phase in ("fwd", "bwd"):
        for t in range(4):
            for s in range(2):
                live = 0 <= t - s < 3
                kw = {"microbatch": t - s} if live else {}
                tr.record(phase if live else "bubble", dur_s=0.01,
                          cat="pipe", rank=s, tick=t, phase=phase, **kw)
            tr.record("send", dur_s=0.002, cat="pipe-comm", rank=0,
                      tick=t, phase=phase)
    pa = tracing.pipeline_anatomy(tr.records)
    assert math.isclose(pa["bubble_fraction"]["mean"], 0.25)
    assert math.isclose(
        pa["bubble_fraction"]["mean"],
        tracing.expected_bubble_fraction("1f1b", 3, 2))
    assert pa["ranks"]["0"]["fwd_s"] == pytest.approx(0.03)
    assert pa["ranks"]["0"]["send_s"] == pytest.approx(0.016)  # 4x2 phases
    # per-microbatch slot rollup: every unit saw one fwd and one bwd
    # slot on each of the 2 ranks
    assert pa["microbatches"]["0"]["fwd_s"] == pytest.approx(0.02)
    assert pa["microbatches"]["0"]["bwd_s"] == pytest.approx(0.02)

    trace = json.loads(json.dumps(tracing.chrome_trace(tr.records)))
    ev = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(ev) == 24 and {e["pid"] for e in meta} == {0, 1}
    for e in ev:
        assert {"name", "cat", "pid", "tid", "ts", "dur", "args"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    # pipe slots ride the compute track, send/recv the comm track
    assert {e["tid"] for e in ev if e["cat"] == "pipe"} == {0}
    assert {e["tid"] for e in ev if e["cat"] == "pipe-comm"} == {1}

    summary = tracing.timeline_summary(tr.records)
    assert summary["pipeline"]["bubble_fraction"]["mean"] == 0.25
    assert summary["by_cat"]["pipe"]["count"] == 16


# ---------------------------------------------------------------------------
# journal integration (set_step_comm / set_bubble_fraction)
# ---------------------------------------------------------------------------


def test_journal_anatomy_and_bubble_fields(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with MetricsJournal(path) as j:
        j.set_step_costs(flops_per_token=1e6, bytes_per_token=10.0,
                         platform="tpu v4")
        j.set_step_comm(1e6, platform="tpu v4")
        j.set_bubble_fraction(0.27, 0.25)
        j.step_start()
        j.step_end(step=0, loss=jnp.asarray(2.0), tokens=4096)
    rec = [r for r in MetricsJournal.read(path) if r["kind"] == "step"][-1]
    # fractions round to 4dp in the record; the invariant holds to that
    assert abs(rec["compute_frac"] + rec["comm_frac"]
               + rec["stall_frac"] - 1.0) < 2e-3
    assert rec["bubble_fraction"] == 0.27
    assert rec["bubble_fraction_expected"] == 0.25
    # and the report rolls them into the timeline section
    from apex_tpu.monitor import report

    analysis = report.analyze(MetricsJournal.read(path))
    tl = analysis["timeline"]
    assert tl["bubble_fraction"]["last"] == 0.27
    assert tl["bubble_fraction_expected"] == 0.25
    assert "compute_frac_mean" in tl


# ---------------------------------------------------------------------------
# the traced pipeline tick drive (measured bubble vs analytic floor)
# ---------------------------------------------------------------------------

TINY = dict(
    vocab_size=64, hidden_size=32, num_layers=4, num_attention_heads=4,
    max_seq_len=16, hidden_dropout=0.0, compute_dtype=jnp.float32,
    remat=False)


def _drive_setup(S, vpp):
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer import tensor_parallel as tp_mod
    from apex_tpu.transformer.pipeline_parallel import pipeline_specs
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        interleave_stack,
    )

    mesh = mesh_lib.make_virtual_mesh(S, pipeline_model_parallel_size=S)
    model = GPTModel(GPTConfig(axis=None, **TINY))
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)
    layer_specs = pipeline_specs(model.specs()["layers"])
    layers = params["layers"]
    if vpp > 1:
        layers = interleave_stack(layers, S, vpp)
    layers_sh = tp_mod.shard_params(layers, layer_specs, mesh)
    rest = {k: v for k, v in params.items() if k != "layers"}
    return mesh, model, params, rest, layers_sh, layer_specs, toks, tgt


def test_traced_drive_matches_serial_and_measures_bubble():
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.pipeline_parallel import (
        traced_pipeline_timeline,
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        deinterleave_stack,
    )

    S, vpp, M = 2, 2, 4
    mesh, model, params, rest, layers_sh, layer_specs, toks, tgt = (
        _drive_setup(S, vpp))
    try:
        tr = tracing.Tracer(None)
        loss, grads, anatomy = traced_pipeline_timeline(
            mesh, embed=model.embed,
            run_layers=lambda lp, h: model.run_layers(lp, h),
            head_loss=lambda p, h, t: model.head(p, h, t),
            rest_params=rest, layers=layers_sh, layer_specs=layer_specs,
            batch=toks, targets=tgt, num_microbatches=M,
            virtual_pipeline_size=vpp, tracer=tr, step=0)

        # equivalence: the timeline is the anatomy of the REAL function
        sl, sg = jax.value_and_grad(
            lambda p: model.loss(p, toks, tgt))(params)
        assert abs(float(loss) - float(sl)) < 1e-5
        gl = deinterleave_stack(grads["layers"], S, vpp)
        for a, b in zip(jax.tree.leaves(gl), jax.tree.leaves(sg["layers"])):
            np.testing.assert_allclose(a, b, atol=1e-5)
        for k in rest:
            for a, b in zip(jax.tree.leaves(grads[k]),
                            jax.tree.leaves(sg[k])):
                np.testing.assert_allclose(a, b, atol=1e-5)

        # measured bubble within tolerance of the analytic floor (all
        # ranks execute every tick in SPMD, so slot durations are near
        # uniform; contended-CI tolerance of half the floor + 0.04 abs)
        expected = anatomy["expected_bubble_fraction"]
        measured = anatomy["bubble_fraction"]["mean"]
        assert math.isclose(
            expected,
            tracing.expected_bubble_fraction("interleaved", M, S, vpp),
            rel_tol=1e-3)
        assert abs(measured - expected) <= max(0.04, 0.5 * expected), anatomy

        # every slot kind landed as spans; analyzer agrees with anatomy
        names = {r["name"] for r in tr.records if r.get("cat") == "pipe"}
        assert {"fwd", "bwd", "bubble"} <= names
        comm_names = {r["name"] for r in tr.records
                      if r.get("cat") == "pipe-comm"}
        assert comm_names == {"send", "recv"}
        pa = tracing.pipeline_anatomy(tr.records)
        assert pa["bubble_fraction"]["mean"] == pytest.approx(
            measured, abs=1e-6)
    finally:
        mesh_lib.destroy_model_parallel()


def test_traced_schedule_timeline_zero_bubble_beats_1f1b():
    """The plan executor's measured drive: loss AND grads equal the
    serial model for BOTH the 1f1b and zero-bubble plans, and the
    zero-bubble W/B split's measured bubble lands strictly below 1f1b's
    at the same (S, M), near its own floor."""
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.pipeline_parallel import (
        plan_schedule,
        traced_schedule_timeline,
    )

    S, M = 2, 4
    mesh, model, params, rest, layers_sh, layer_specs, toks, tgt = (
        _drive_setup(S, 1))
    try:
        sl, sg = jax.value_and_grad(
            lambda p: model.loss(p, toks, tgt))(params)
        measured = {}
        for sched in ("1f1b", "zero-bubble"):
            tr = tracing.Tracer(None)
            plan = plan_schedule(sched, M, S)
            loss, grads, anatomy = traced_schedule_timeline(
                plan, mesh, embed=model.embed,
                run_layers=lambda lp, h: model.run_layers(lp, h),
                head_loss=lambda p, h, t: model.head(p, h, t),
                rest_params=rest, layers=layers_sh,
                layer_specs=layer_specs, batch=toks, targets=tgt,
                tracer=tr, step=0)
            assert abs(float(loss) - float(sl)) < 1e-5, sched
            for a, b in zip(jax.tree.leaves(grads["layers"]),
                            jax.tree.leaves(sg["layers"])):
                np.testing.assert_allclose(a, b, atol=1e-5)
            for k in rest:
                for a, b in zip(jax.tree.leaves(grads[k]),
                                jax.tree.leaves(sg[k])):
                    np.testing.assert_allclose(
                        np.asarray(a, np.float32), b, atol=1e-5)
            floor = anatomy["expected_bubble_fraction"]
            mean = anatomy["bubble_fraction"]["mean"]
            # the plan's counted floor must match the closed form, and
            # the measurement must approach it (contended-CI tolerance)
            assert math.isclose(
                anatomy["plan_bubble_fraction"],
                tracing.expected_bubble_fraction(sched, M, S),
                abs_tol=1e-4)
            assert abs(mean - floor) <= max(0.06, 0.5 * floor), anatomy
            # W/B spans land as bwd slots with the wb attr
            if sched == "zero-bubble":
                wb = {r.get("wb") for r in tr.records
                      if r.get("cat") == "pipe" and r.get("wb")}
                assert wb == {"B", "W"}, wb
            measured[sched] = mean
        assert measured["zero-bubble"] < measured["1f1b"], measured
    finally:
        mesh_lib.destroy_model_parallel()


def test_untimed_schedule_tripwire_on_real_drives():
    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.pipeline_parallel import (
        pipelined_loss_fn,
        traced_pipeline_timeline,
    )

    S, vpp, M = 2, 1, 4
    mesh, model, params, rest, layers_sh, layer_specs, toks, tgt = (
        _drive_setup(S, vpp))
    try:
        # the compiled ring under an armed tracer emits no spans: hazard
        pipe_loss = pipelined_loss_fn(
            embed=model.embed,
            run_layers=lambda lp, h: model.run_layers(lp, h),
            head_loss=lambda p, h, t: model.head(p, h, t),
            num_microbatches=M)
        compiled_drive = jax.shard_map(
            pipe_loss, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), rest), layer_specs,
                      P(), P()),
            out_specs=P(), check_vma=False)
        bad = lint_trace.untimed_schedule_hazards(
            lambda: jax.make_jaxpr(compiled_drive)(
                rest, layers_sh, toks, tgt))
        assert bad["hazard"] and bad["drives"] == 1
        assert bad["findings"][0]["rule"] == "untimed-schedule"

        # the traced tick drive passes: spans flow to the scoped tracer
        ok = lint_trace.untimed_schedule_hazards(
            lambda: traced_pipeline_timeline(
                mesh, embed=model.embed,
                run_layers=lambda lp, h: model.run_layers(lp, h),
                head_loss=lambda p, h, t: model.head(p, h, t),
                rest_params=rest, layers=layers_sh,
                layer_specs=layer_specs, batch=toks, targets=tgt,
                num_microbatches=M))
        assert not ok["hazard"] and ok["pipe_spans"] > 0
    finally:
        mesh_lib.destroy_model_parallel()


# ---------------------------------------------------------------------------
# traced ZeRO step: phase spans + disarmed byte-identity
# ---------------------------------------------------------------------------


def _zero_setup(traced, tracer=None):
    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.amp import build_zero_train_step
    from apex_tpu.transformer.pipeline_parallel import (
        prepare_pipelined_model,
    )

    mesh = mesh_lib.make_virtual_mesh(8)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_seq_len=16,
                    hidden_dropout=0.0, compute_dtype=jnp.bfloat16,
                    remat=False)
    model = GPTModel(cfg)
    policy = amp.get_policy("O2")
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), policy, zero_axis=mesh_lib.AXIS_DATA,
        zero_level=2)
    full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    specs, params, pipe_loss = prepare_pipelined_model(
        model, full, mesh, num_microbatches=2)
    rest_specs = {k: v for k, v in specs.items() if k != "layers"}
    opt_state, state_specs = mp_opt.zero_init(params, mesh, specs)
    step = build_zero_train_step(
        mp_opt, mesh, specs, state_specs, pipe_loss,
        rest_specs=rest_specs,
        grad_axes=mesh_lib.get_gradient_reduction_axes(),
        data_spec=P(mesh_lib.AXIS_DATA), zero_axis=mesh_lib.AXIS_DATA,
        traced=traced, tracer=tracer)
    toks = jax.random.randint(jax.random.PRNGKey(1), (16, 16), 0, 64)
    shard = lambda a: jax.device_put(  # noqa: E731
        a, NamedSharding(mesh, P(mesh_lib.AXIS_DATA)))
    return step, params, opt_state, shard(toks), shard(
        jnp.roll(toks, -1, axis=-1))


def test_traced_zero_step_matches_untraced_and_emits_phase_spans():
    from apex_tpu.parallel import mesh as mesh_lib

    try:
        step_u, p, s, toks, tgts = _zero_setup(False)
        p_u, s_u, loss_u, _ = step_u(p, s, toks, tgts)
        mesh_lib.destroy_model_parallel()
        tr = tracing.Tracer(None)
        step_t, p, s, toks, tgts = _zero_setup(True, tr)
        p_t, s_t, loss_t, _ = step_t(p, s, toks, tgts)
        assert float(loss_u) == float(loss_t)
        for a, b in zip(jax.tree.leaves(p_u), jax.tree.leaves(p_t)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        names = [r["name"] for r in tr.records if r["kind"] == "span"]
        assert names == ["zero.grads", "zero.apply"]
        grads_span = tr.records[0]
        apply_span = tr.records[1]
        assert grads_span["cat"] == "compute"
        assert apply_span["cat"] == "comm"
        # the phase spans carry the comm-accounting join: the level-2
        # apply phase moves the psum_scatter + gather payloads
        assert apply_span["comm_bytes"] > 0
    finally:
        mesh_lib.destroy_model_parallel()


def test_disarmed_tracer_leaves_zero_step_program_byte_identical():
    """Arming the GLOBAL tracer must not change a traced=False build —
    the acceptance criterion that --trace stays an opt-in and disarmed
    harness programs are byte-identical."""
    from apex_tpu.parallel import mesh as mesh_lib

    try:
        step_a, p, s, toks, tgts = _zero_setup(False)
        text_a = step_a.lower(p, s, toks, tgts).as_text()
        mesh_lib.destroy_model_parallel()
        with tracing.scoped(tracing.Tracer(None)):
            step_b, p, s, toks, tgts = _zero_setup(False)
            text_b = step_b.lower(p, s, toks, tgts).as_text()
        assert text_a == text_b
    finally:
        mesh_lib.destroy_model_parallel()
