"""contrib.multihead_attn tests (reference: apex/contrib/test/multihead_attn/
— fused vs torch fallback equivalence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
    mha_naive_reference,
)


def test_self_attn_matches_naive():
    mha = SelfMultiheadAttn(embed_dim=32, num_heads=4)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    out = mha.apply(params, x)
    ref = mha_naive_reference(params, x, num_heads=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_self_attn_bias_and_grads():
    mha = SelfMultiheadAttn(embed_dim=32, num_heads=4, bias=True)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))
    loss, grads = jax.value_and_grad(
        lambda p: jnp.sum(jnp.square(mha.apply(p, x))))(params)
    assert jnp.isfinite(loss)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads))
    assert grads["in_bias"].shape == (96,)


def test_self_attn_key_padding_mask():
    """Masked keys must not influence the output at unmasked queries."""
    mha = SelfMultiheadAttn(embed_dim=16, num_heads=2)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
    pad = jnp.zeros((1, 8), bool).at[:, -2:].set(True)
    out1 = mha.apply(params, x, key_padding_mask=pad)
    x2 = x.at[:, -1].set(x[:, -1] + 3.0)
    out2 = mha.apply(params, x2, key_padding_mask=pad)
    np.testing.assert_allclose(np.asarray(out1[:, :6]), np.asarray(out2[:, :6]),
                               rtol=1e-5, atol=1e-5)


def test_norm_add_residual_path():
    mha = SelfMultiheadAttn(embed_dim=16, num_heads=2, include_norm_add=True)
    params = mha.init(jax.random.PRNGKey(0))
    assert "ln_scale" in params
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    out = mha.apply(params, x)
    # zeroing the attention out-proj leaves exactly the residual
    z = dict(params, out_weight=jnp.zeros_like(params["out_weight"]))
    np.testing.assert_allclose(np.asarray(mha.apply(z, x)), np.asarray(x),
                               rtol=1e-6, atol=1e-6)
    assert out.shape == x.shape


def test_encdec_attn_shapes_and_memory_dependence():
    mha = EncdecMultiheadAttn(embed_dim=16, num_heads=2, bias=True)
    params = mha.init(jax.random.PRNGKey(0))
    q = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 16))
    mem = jax.random.normal(jax.random.PRNGKey(2), (2, 10, 16))
    out = mha.apply(params, q, mem)
    assert out.shape == (2, 6, 16)
    out2 = mha.apply(params, q, mem + 1.0)
    assert float(jnp.abs(out - out2).max()) > 1e-4


def test_attn_dropout_determinism():
    mha = SelfMultiheadAttn(embed_dim=16, num_heads=2, dropout=0.5)
    params = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    k = jax.random.PRNGKey(3)
    o1 = mha.apply(params, x, dropout_key=k)
    o2 = mha.apply(params, x, dropout_key=k)
    o3 = mha.apply(params, x, dropout_key=jax.random.PRNGKey(4))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert float(jnp.abs(o1 - o3).max()) > 1e-5
    # eval (no key): deterministic, no dropout
    oe = mha.apply(params, x)
    ref = mha_naive_reference(params, x, num_heads=2)
    np.testing.assert_allclose(np.asarray(oe), np.asarray(ref), rtol=2e-5, atol=2e-5)
