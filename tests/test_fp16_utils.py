"""Legacy fp16_utils API tests (reference: tests/L0/run_fp16util/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    DynamicLossScaler,
    LossScaler,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
)
from apex_tpu.optimizers import FusedAdam


def _params():
    k = jax.random.PRNGKey(0)
    return {
        "dense": {"kernel": jax.random.normal(k, (8, 8), jnp.bfloat16),
                  "bias": jnp.zeros((8,), jnp.bfloat16)},
        "bn": {"scale": jnp.ones((8,), jnp.float32)},
        "step": jnp.zeros((), jnp.int32),  # non-float leaf survives untouched
    }


def test_convert_network_keeps_norms_fp32():
    p = convert_network(
        {"dense": {"kernel": jnp.zeros((2, 2), jnp.float32)},
         "bn": {"scale": jnp.ones((2,), jnp.float32)}},
        dtype=jnp.bfloat16)
    assert p["dense"]["kernel"].dtype == jnp.bfloat16
    assert p["bn"]["scale"].dtype == jnp.float32


def test_prep_and_copy_helpers_roundtrip():
    model = _params()
    model2, master = prep_param_lists(model)
    assert master["dense"]["kernel"].dtype == jnp.float32
    assert master["step"].dtype == jnp.int32
    g = jax.tree.map(
        lambda a: jnp.ones_like(a) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        model)
    g32 = model_grads_to_master_grads(g)
    assert g32["dense"]["bias"].dtype == jnp.float32
    back = master_params_to_model_params(master, model)
    assert back["dense"]["kernel"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(back["dense"]["kernel"], np.float32),
        np.asarray(model["dense"]["kernel"], np.float32))


def test_legacy_scalers():
    s = LossScaler(128.0)
    assert float(s.loss_scale) == 128.0
    assert not s.dynamic
    d = DynamicLossScaler(init_scale=2.0 ** 8, scale_window=1)
    assert d.dynamic
    # overflow halves, a clean window doubles
    d2 = d.update(jnp.asarray(True))
    assert float(d2.loss_scale) == 2.0 ** 7
    d3 = d2.update(jnp.asarray(False))
    assert float(d3.loss_scale) == 2.0 ** 8


def test_fp16_optimizer_step_and_overflow_skip():
    model = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
    state = opt.init(model)
    assert state.master["w"].dtype == jnp.float32

    def loss_fn(p):
        return jnp.sum(jnp.square(p["w"].astype(jnp.float32)))

    @jax.jit
    def train(p, s):
        g = jax.grad(lambda q: opt.scale_loss(loss_fn(q), s))(p)
        return opt.step(s, p, g, max_norm=10.0)

    p1, s1, info = train(model, state)
    assert not bool(info["overflow"])
    assert float(jnp.abs(p1["w"].astype(jnp.float32) - 1.0).max()) > 0
    assert p1["w"].dtype == jnp.bfloat16

    # inf grads -> skip step, halve scale
    bad = {"w": jnp.full((4,), jnp.inf, jnp.bfloat16)}
    p2, s2, info2 = jax.jit(opt.step)(s1, p1, bad)
    assert bool(info2["overflow"])
    np.testing.assert_array_equal(np.asarray(p2["w"], np.float32),
                                  np.asarray(p1["w"], np.float32))
    assert float(s2.scaler.loss_scale) == float(s1.scaler.loss_scale) / 2


def test_fp16_optimizer_static_scale_never_skips():
    """Legacy static LossScaler has no overflow machinery: the step proceeds
    and non-finites surface in the params (loss_scaler.py:10-45)."""
    model = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = FP16_Optimizer(FusedAdam(lr=0.1), static_loss_scale=128.0)
    state = opt.init(model)
    bad = {"w": jnp.full((4,), jnp.inf, jnp.bfloat16)}
    p, s, info = jax.jit(opt.step)(state, model, bad)
    assert bool(info["overflow"])  # reported...
    assert float(s.scaler.loss_scale) == 128.0  # ...but scale untouched
    assert not np.all(np.asarray(p["w"], np.float32) == 1.0)  # step happened


def test_fp16_optimizer_clip_master_grads():
    opt = FP16_Optimizer(FusedAdam(lr=0.1))
    g = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 2.0)}
    clipped, norm = opt.clip_master_grads(g, max_norm=1.0)
    np.testing.assert_allclose(float(norm), np.sqrt(48 + 16), rtol=1e-6)
    total = jnp.sqrt(sum(jnp.sum(jnp.square(v)) for v in jax.tree.leaves(clipped)))
    np.testing.assert_allclose(float(total), 1.0, rtol=1e-4)


def test_fp16_optimizer_state_dict_roundtrip():
    model = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
    state = opt.init(model)
    g = {"w": jnp.full((4,), 0.5, jnp.bfloat16) * state.scaler.loss_scale}
    _, state1, _ = jax.jit(opt.step)(state, model, g)
    payload = jax.device_get(opt.state_dict(state1))
    fresh = opt.init(model)
    restored = opt.load_state_dict(fresh, payload)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.master, state1.master)
    assert float(restored.scaler.loss_scale) == float(state1.scaler.loss_scale)
