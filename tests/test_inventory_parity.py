"""Inventory-parity tests: amp function registries, FastLayerNorm, FMHA
varlen, Reducer, transformer.testing harness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, precision
from apex_tpu.amp.functions import (
    float_function,
    half_function,
    promote_function,
    set_active_policy,
)
from apex_tpu.contrib.fmha import fmha, fmha_reference
from apex_tpu.contrib.layer_norm import FastLayerNorm
from apex_tpu.ops.layer_norm import layer_norm_reference
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import Reducer
from apex_tpu.transformer.testing import (
    get_args,
    initialize_distributed,
    parse_args,
    set_args,
    set_random_seed,
)


# -- amp function registries -------------------------------------------------

@pytest.fixture(autouse=True)
def _reset_policy():
    yield
    set_active_policy(None)


def test_half_float_promote_functions():
    set_active_policy(precision.get_policy("O1"))

    @half_function
    def matmul_like(a, b):
        assert a.dtype == jnp.bfloat16 and b.dtype == jnp.bfloat16
        return a @ b

    @float_function
    def loss_like(x):
        assert x.dtype == jnp.float32
        return jnp.mean(x)

    @promote_function
    def add_like(a, b):
        assert a.dtype == b.dtype == jnp.float32
        return a + b

    a = jnp.ones((4, 4), jnp.float32)
    b = jnp.ones((4, 4), jnp.bfloat16)
    assert matmul_like(a, a).dtype == jnp.bfloat16
    assert loss_like(b).dtype == jnp.float32
    assert add_like(a, b).dtype == jnp.float32


def test_functions_noop_without_policy():
    @half_function
    def f(a):
        return a

    x = jnp.ones((2,), jnp.float32)
    assert f(x).dtype == jnp.float32  # no active policy: untouched


# -- FastLayerNorm -----------------------------------------------------------

def test_fast_layer_norm_matches_reference():
    ln = FastLayerNorm(256)
    params = ln.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256))
    np.testing.assert_allclose(
        np.asarray(ln.apply(params, x)),
        np.asarray(layer_norm_reference(x, params["weight"], params["bias"])),
        rtol=2e-5, atol=2e-5)


def test_fast_layer_norm_envelope_validation():
    with pytest.raises(ValueError):
        FastLayerNorm(250)  # not a multiple of 8
    with pytest.raises(ValueError):
        FastLayerNorm(65544)


# -- FMHA varlen -------------------------------------------------------------

def test_fmha_varlen_matches_reference():
    h, d = 2, 16
    lengths = [5, 9, 3]
    cu = jnp.asarray(np.cumsum([0] + lengths))
    total = int(cu[-1])
    qkv = jax.random.normal(jax.random.PRNGKey(0), (total, 3, h, d))
    out = fmha(qkv, cu, max_seqlen=16)
    ref = fmha_reference(qkv, cu)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_fmha_causal():
    h, d = 1, 8
    cu = jnp.asarray([0, 6])
    qkv = jax.random.normal(jax.random.PRNGKey(0), (6, 3, h, d))
    out = fmha(qkv, cu, max_seqlen=8, causal=True)
    ref = fmha_reference(qkv, cu, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


# -- Reducer -----------------------------------------------------------------

def test_reducer_manual_averaging():
    mesh = mesh_lib.make_virtual_mesh(4)
    try:
        red = Reducer(mesh_lib.AXIS_DATA)

        def fn(x):
            return red.reduce(x)

        x = jnp.arange(8.0)  # shards [0,1] [2,3] [4,5] [6,7]
        out = jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=P(mesh_lib.AXIS_DATA), out_specs=P(mesh_lib.AXIS_DATA),
            check_vma=False))(x)
        # each shard becomes the mean over shards: [(0+2+4+6)/4, (1+3+5+7)/4]*4
        np.testing.assert_allclose(np.asarray(out), [3, 4] * 4)
    finally:
        mesh_lib.destroy_model_parallel()


# -- transformer.testing harness --------------------------------------------

def test_arguments_and_global_vars():
    args = parse_args(["--hidden-size", "512", "--bf16",
                       "--tensor-model-parallel-size", "2"])
    assert args.hidden_size == 512 and args.bf16
    set_args(args)
    assert get_args().tensor_model_parallel_size == 2
    with pytest.raises(ValueError):
        parse_args(["--fp16", "--bf16"])


def test_commons_initialize_distributed():
    mesh = initialize_distributed(tensor_model_parallel_size=2)
    try:
        assert mesh_lib.get_tensor_model_parallel_world_size() == 2
        key = set_random_seed(1234)
        assert key.shape == (2,)
    finally:
        mesh_lib.destroy_model_parallel()


def test_disable_casts_context():
    from apex_tpu.amp.functions import disable_casts

    set_active_policy(precision.get_policy("O1"))

    @half_function
    def f(a):
        return a

    x = jnp.ones((2,), jnp.float32)
    assert f(x).dtype == jnp.bfloat16
    with disable_casts():
        assert f(x).dtype == jnp.float32  # casts suspended
    assert f(x).dtype == jnp.bfloat16  # restored


def test_groupbn_nhwc_surface():
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC, batch_norm_add_relu

    bn = BatchNorm2d_NHWC(8, fuse_relu=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    variables = bn.init(jax.random.PRNGKey(1), x)
    y, _ = bn.apply(variables, x, mutable=["batch_stats"])
    assert y.shape == x.shape
    assert float(jnp.min(y)) >= 0.0  # fused relu
    # add+relu epilogue on a plain BN output
    bn2 = BatchNorm2d_NHWC(8)
    v2 = bn2.init(jax.random.PRNGKey(1), x)
    out, _ = bn2.apply(v2, x, mutable=["batch_stats"])
    z = batch_norm_add_relu(out, -out)
    np.testing.assert_allclose(np.asarray(z), 0.0, atol=1e-6)
