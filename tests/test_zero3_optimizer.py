"""ZeRO-3 fully-sharded params: replicated == ZeRO-1/2 == ZeRO-3, end to end.

Extends tests/test_zero_optimizer.py to ``zero_level=3``: the bf16 working
params persist as 1/dp chunk trees (``zero3_init``) and each layer's weight
tree is all-gathered just-in-time inside the layer loop
(models/_transformer.run_layers ``chunk_meta``), re-gathered in the backward
by per-layer remat, with grads arriving as per-layer reduce-scattered chunks
(the gather transposes). The three modes must agree on the loss trajectory
AND the final params — including through an overflow-skipped step, which
must leave every rank's chunk shards bit-identical to their pre-step
buffers — on the scan and unroll layer drives, and on the
tp x pp x dp pipelined hybrid (slow-marked).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.distributed import gather_chunked_tree
from apex_tpu.parallel import collectives
from apex_tpu.parallel.distributed import allreduce_gradients

N = 8
POISON_STEP = 1  # the forced-overflow (skipped) step of the 3-step sandwich


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("data",))


def _gpt(unroll):
    cfg = GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=2,
        num_attention_heads=4, max_seq_len=16, hidden_dropout=0.0,
        axis=None, compute_dtype=jnp.bfloat16, remat=False,
        unroll_layers=unroll)
    return GPTModel(cfg)


def _batch(mesh):
    toks = jax.random.randint(jax.random.PRNGKey(1), (N * 2, 16), 0, 128)
    tgts = jnp.roll(toks, -1, axis=-1)
    put = lambda a: jax.device_put(a, NamedSharding(mesh, P("data")))  # noqa: E731
    return put(toks), put(tgts)


@pytest.mark.parametrize("unroll", [False, True], ids=["scan", "unroll"])
def test_zero3_gpt_matches_replicated_and_zero2(mesh, unroll):
    """3-step sandwich (normal, overflow-skipped, normal) on identical
    batches: replicated, ZeRO-1/2 and ZeRO-3 must produce the same losses
    and loss-scale trajectory, equivalent final params, and the skipped
    step must leave the ZeRO-3 chunk shards bit-identical per rank.

    The overflow is injected by ADDING an inf scalar to every grad leaf
    inside the compiled step (finite + inf = inf, no NaNs), so the same
    jit drives normal and skipped steps deterministically on every path.
    """
    model = _gpt(unroll)
    policy = amp.get_policy("O2")
    full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    pspecs = jax.tree.map(lambda _: P(), full)
    data_spec = P("data")
    toks, tgts = _batch(mesh)
    poisons = [jnp.float32(jnp.inf) if t == POISON_STEP else jnp.float32(0)
               for t in range(3)]

    def run(mode):
        # lr 1e-3 bounds the bf16-noise drift between the paths' differing
        # reduction orders (test_zero_optimizer.py's measured rationale)
        mp_opt = amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-3), policy,
            zero_axis=None if mode == "repl" else "data",
            zero_level=3 if mode == "zero3" else 2,
            gather_dtype="bf16" if mode == "zero2" else None)

        if mode == "zero3":
            z3 = mp_opt.zero3_init(full, mesh, pspecs)
            layer_meta = z3.meta.subtree("layers")
            rest_meta = z3.meta.select(
                [k for k in z3.meta.shapes if k != "layers"])

            def zstep(p, s, tk, tg, poison):
                rest_c = {k: v for k, v in p.items() if k != "layers"}

                def scaled(rest_c, layer_c):
                    rest = gather_chunked_tree(rest_c, rest_meta)
                    return model.loss(
                        dict(rest, layers=layer_c), tk, tg,
                        layer_chunk_meta=layer_meta) * s.scaler.loss_scale

                loss, (rg, lg) = jax.value_and_grad(scaled, argnums=(0, 1))(
                    rest_c, p["layers"])
                g = jax.tree.map(lambda x: x + poison, dict(rg, layers=lg))
                new_p, new_s, m = mp_opt.apply_gradients(s, p, g)
                return new_p, new_s, collectives.pmean(loss, "data"), m

            step = jax.jit(jax.shard_map(
                zstep, mesh=mesh,
                in_specs=(z3.param_specs, z3.state_specs, data_spec,
                          data_spec, P()),
                out_specs=(z3.param_specs, z3.state_specs, P(), P()),
                check_vma=False))
            p, s = z3.params, z3.opt_state
        elif mode == "zero2":
            opt_state, sspecs = mp_opt.zero_init(full, mesh, pspecs)

            def zstep(p, s, tk, tg, poison):
                def scaled(p):
                    return model.loss(p, tk, tg) * s.scaler.loss_scale

                loss, g = jax.value_and_grad(scaled)(p)
                g = jax.tree.map(lambda x: x + poison, g)
                new_p, new_s, m = mp_opt.apply_gradients(s, p, g)
                return new_p, new_s, collectives.pmean(loss, "data"), m

            step = jax.jit(jax.shard_map(
                zstep, mesh=mesh,
                in_specs=(pspecs, sspecs, data_spec, data_spec, P()),
                out_specs=(pspecs, sspecs, P(), P()), check_vma=False))
            p, s = full, opt_state
        else:
            opt_state = mp_opt.init(full)

            def grads_fn(p, tk, tg, scale, poison):
                def scaled(p):
                    return model.loss(p, tk, tg) * scale

                loss, g = jax.value_and_grad(scaled)(p)
                g = allreduce_gradients(g, ("data",))
                g = jax.tree.map(lambda x: x + poison, g)
                return collectives.pmean(loss, "data"), g

            shard_fn = jax.shard_map(
                grads_fn, mesh=mesh,
                in_specs=(pspecs, data_spec, data_spec, P(), P()),
                out_specs=(P(), pspecs), check_vma=False)

            @jax.jit
            def step(p, s, tk, tg, poison):
                loss, g = shard_fn(p, tk, tg, s.scaler.loss_scale, poison)
                new_p, new_s, m = mp_opt.apply_gradients(s, p, g)
                return new_p, new_s, loss, m

            p, s = full, opt_state

        losses, scales, founds = [], [], []
        pre_poison = None
        for t in range(3):
            if t == POISON_STEP and mode == "zero3":
                pre_poison = jax.tree.map(np.asarray, p)
            p, s, loss, m = step(p, s, toks, tgts, poisons[t])
            losses.append(float(loss) / float(s.scaler.loss_scale)
                          if t != POISON_STEP
                          else float(loss))  # scale halved after the skip
            scales.append(float(m["loss_scale"]))
            founds.append(bool(m["found_inf"]))
            if t == POISON_STEP and mode == "zero3":
                # the skip leaves every rank's chunk shards bit-identical
                for a, b in zip(jax.tree.leaves(pre_poison),
                                jax.tree.leaves(jax.tree.map(np.asarray, p))):
                    np.testing.assert_array_equal(a, b)
        if mode == "zero3":
            p = mp_opt.zero3_materialize(z3, mesh, pspecs, param_chunks=p)
        return p, losses, scales, founds

    results = {mode: run(mode) for mode in ("repl", "zero2", "zero3")}
    p_ref, l_ref, sc_ref, f_ref = results["repl"]
    assert f_ref == [False, True, False]
    assert sc_ref[POISON_STEP] == sc_ref[0] / 2  # the skip halved the scale
    for mode in ("zero2", "zero3"):
        p_m, l_m, sc_m, f_m = results[mode]
        assert f_m == f_ref and sc_m == sc_ref, mode
        # the poisoned step's raw loss is scaled by the pre-skip scale on
        # every path; compare it at that scale
        np.testing.assert_allclose(l_m, l_ref, rtol=2e-3, err_msg=mode)
        key = lambda kv: str(kv[0])  # noqa: E731
        for (ka, a), (_, b) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(p_ref), key=key),
                sorted(jax.tree_util.tree_leaves_with_path(p_m), key=key)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-2, atol=2e-2, err_msg=f"{mode}:{ka}")


def test_zero3_init_shapes_specs_and_materialize_roundtrip(mesh):
    """zero3_init: stacked layer leaves chunk PER ROW ((L, k), each rank
    holding its (L, k/N) shard), non-layer leaves 1-D; state specs follow
    by rank; and zero3_materialize restores the exact bf16 params (the
    chunk layout is pure slicing — no arithmetic, so bit-exact)."""
    model = _gpt(unroll=False)
    policy = amp.get_policy("O2")
    full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    pspecs = jax.tree.map(lambda _: P(), full)
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), policy, zero_axis="data", zero_level=3)
    z3 = mp_opt.zero3_init(full, mesh, pspecs)

    from apex_tpu.optimizers.distributed import chunk_size

    L = model.cfg.num_layers
    qkv = z3.params["layers"]["qkv"]["kernel"]
    row = full["layers"]["qkv"]["kernel"][0].size
    assert qkv.shape == (L, chunk_size(row, N) * N)
    assert {s.data.shape for s in qkv.addressable_shards} \
        == {(L, qkv.shape[1] // N)}
    # masters mirror the chunk layout in fp32
    assert z3.opt_state.master["layers"]["qkv"]["kernel"].shape == qkv.shape
    assert z3.opt_state.master["layers"]["qkv"]["kernel"].dtype \
        == jnp.float32
    # non-layer leaves are 1-D chunks over every mesh axis
    wte = z3.params["embedding"]["embedding"]
    assert wte.ndim == 1
    assert {s.data.shape for s in wte.addressable_shards} \
        == {(wte.shape[0] // N,)}
    # exact round-trip
    back = mp_opt.zero3_materialize(z3, mesh, pspecs)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero3_wiring_validation():
    """Level/axis validation fails loudly: zero_level=3 without an axis,
    zero_init at level 3 (must use zero3_init), zero3_init below level 3,
    and out-of-range levels."""
    policy = amp.get_policy("O2")
    with pytest.raises(ValueError, match="zero_level=3 requires zero_axis"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy,
                                    zero_level=3)
    with pytest.raises(ValueError, match="zero_level must be"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy,
                                    zero_axis="data", zero_level=4)
    z3 = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy,
                                     zero_axis="data", zero_level=3)
    with pytest.raises(ValueError, match="zero3_init"):
        z3.zero_init({"w": jnp.ones((8,), jnp.bfloat16)}, None, None)
    z2 = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-3), policy,
                                     zero_axis="data")
    with pytest.raises(ValueError, match="requires zero_level=3"):
        z2.zero3_init({"w": jnp.ones((8,), jnp.bfloat16)}, None, None)


def test_zero3_step_passes_gather_tripwire(mesh):
    """The real ZeRO-3 GPT step traces clean under
    lint.trace.zero3_gather_hazards — per-layer gathers only, no
    model-sized bulk param gather — while the level-2 wiring (bulk
    post-update gather) is exactly what the tripwire exists to catch in
    a step claiming fully-sharded params."""
    from apex_tpu.lint.trace import zero3_gather_hazards

    model = _gpt(unroll=True)
    policy = amp.get_policy("O2")
    full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    total = sum(x.size for x in jax.tree.leaves(full))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-3), policy, zero_axis="data", zero_level=3)
    meta = mp_opt.zero3_meta(full)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jnp.zeros((2, 16), jnp.int32)

    def z3_step(p):
        chunks = mp_opt.zero3_shard(p)
        rest_c = {k: v for k, v in chunks.items() if k != "layers"}

        def scaled(rest_c, layer_c):
            rest = gather_chunked_tree(rest_c, rest_meta)
            return model.loss(dict(rest, layers=layer_c), toks, toks,
                              layer_chunk_meta=layer_meta)

        _, (rg, lg) = jax.value_and_grad(scaled, argnums=(0, 1))(
            rest_c, chunks["layers"])
        st = mp_opt.init(p)
        return mp_opt.apply_gradients(st, chunks, dict(rg, layers=lg))[0]

    # the embedding (vocab x hidden) dominates this tiny model, so the
    # model-sized threshold must sit above it: only a whole-stack layer
    # gather (or a full-model gather) counts as bulk here
    rep = zero3_gather_hazards(
        z3_step, full, axes={"data": N},
        min_model_elems=full["embedding"]["embedding"].size + 1)
    assert not rep["hazard"], rep
    assert rep["layer_gathers"] >= model.cfg.num_layers


@pytest.mark.slow
def test_zero3_hybrid_tp_pp_dp():
    """ZeRO-3 through build_zero_train_step on the tp=2 x sp x pp=2 x dp=2
    hybrid: loss parity with replicated and ZeRO-2 on the same mesh and
    batches. Heavyweight (three pipelined compiles): slow-marked;
    dryrun_multichip(8) smokes the same composition in the gate."""
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.parallel.distributed import allreduce_gradients_by_spec
    from apex_tpu.transformer.amp import build_zero_train_step
    from apex_tpu.transformer.pipeline_parallel import (
        prepare_pipelined_model,
    )

    hybrid = mesh_lib.make_virtual_mesh(
        8, tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    try:
        cfg = GPTConfig(
            vocab_size=128, hidden_size=64, num_layers=4,
            num_attention_heads=4, max_seq_len=32, hidden_dropout=0.0,
            axis=mesh_lib.AXIS_MODEL, sequence_parallel=True,
            compute_dtype=jnp.bfloat16, remat=True)
        model = GPTModel(cfg)
        policy = amp.get_policy("O2")
        full = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
        specs, params, pipe_loss = prepare_pipelined_model(
            model, full, hybrid, num_microbatches=2)
        rest_specs = {k: v for k, v in specs.items() if k != "layers"}
        layer_specs = specs["layers"]
        grad_axes = mesh_lib.get_gradient_reduction_axes()
        data_spec = P(mesh_lib.AXIS_DATA)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
        tgts = jnp.roll(toks, -1, axis=-1)
        put = lambda a: jax.device_put(  # noqa: E731
            a, NamedSharding(hybrid, data_spec))
        toks, tgts = put(toks), put(tgts)

        def losses_for(level):
            mp_opt = amp.MixedPrecisionOptimizer(
                FusedAdam(lr=1e-2), policy,
                zero_axis=mesh_lib.AXIS_DATA if level else None,
                zero_level=level or 2,
                gather_dtype="bf16" if level else None)
            if level == 3:
                z3 = mp_opt.zero3_init(params, hybrid, specs)
                step = build_zero_train_step(
                    mp_opt, hybrid, None, None, None,
                    rest_specs=rest_specs, layer_specs=layer_specs,
                    grad_axes=grad_axes, data_spec=data_spec,
                    zero_axis=mesh_lib.AXIS_DATA,
                    zero3=z3, model=model, num_microbatches=2)
                p, s = z3.params, z3.opt_state
            elif level == 2:
                opt_state, sspecs = mp_opt.zero_init(params, hybrid, specs)
                step = build_zero_train_step(
                    mp_opt, hybrid, specs, sspecs, pipe_loss,
                    rest_specs=rest_specs, layer_specs=layer_specs,
                    grad_axes=grad_axes, data_spec=data_spec,
                    zero_axis=mesh_lib.AXIS_DATA)
                p, s = params, opt_state
            else:
                opt_state = mp_opt.init(params)

                def sstep(p, tk, tg, scale):
                    rest = {k: v for k, v in p.items() if k != "layers"}

                    def scaled_loss(rest, layers):
                        return pipe_loss(rest, layers, tk, tg) * scale

                    loss, (rg, lg) = jax.value_and_grad(
                        scaled_loss, argnums=(0, 1))(rest, p["layers"])
                    rg = allreduce_gradients_by_spec(rg, rest_specs)
                    lg = allreduce_gradients_by_spec(lg, layer_specs)
                    return (collectives.pmean(loss, grad_axes),
                            dict(rg, layers=lg))

                shard_fn = jax.shard_map(
                    sstep, mesh=hybrid,
                    in_specs=(specs, data_spec, data_spec, P()),
                    out_specs=(P(), specs), check_vma=False)

                @jax.jit
                def step(p, s, tk, tg):
                    loss, g = shard_fn(p, tk, tg, s.scaler.loss_scale)
                    new_p, new_s, m = mp_opt.apply_gradients(s, p, g)
                    return new_p, new_s, loss, m

                p, s = params, opt_state

            out = []
            for _ in range(2):
                p, s, loss, _ = step(p, s, toks, tgts)
                # build_zero_train_step returns the UNSCALED loss
                out.append(float(loss) / (float(s.scaler.loss_scale)
                                          if level == 0 else 1.0))
            return out

        l_repl = losses_for(0)
        np.testing.assert_allclose(losses_for(2), l_repl, rtol=2e-3)
        np.testing.assert_allclose(losses_for(3), l_repl, rtol=2e-3)
    finally:
        mesh_lib.destroy_model_parallel()


@pytest.mark.parametrize("prefetch", [1, 2])
def test_zero3_prefetch_matches_serialized_drive(prefetch):
    """The double-buffered gather drive (zero3_prefetch > 0,
    models/_transformer._prefetched_zero3_drive) computes the SAME loss
    and grads as the serialized in-body-gather drive: the custom VJP's
    backward re-gathers through jax.vjp of the same gather (so chunk
    grads still arrive reduce-scattered) and rematerializes each layer —
    only the issue ORDER of the collectives changes. Exercised under a
    vmapped data axis (dp=8) so every gather/scatter runs for real."""
    DPV = 8
    base = dict(vocab_size=128, hidden_size=32, num_layers=4,
                num_attention_heads=4, max_seq_len=16, hidden_dropout=0.0,
                axis=None, compute_dtype=jnp.float32, unroll_layers=True)
    policy = amp.get_policy("O0")
    params = amp.cast_params(
        GPTModel(GPTConfig(**base)).init(jax.random.PRNGKey(0)), policy)
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-4), policy, zero_axis="data", zero_level=3)
    meta = mp_opt.zero3_meta(params)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)

    def loss_fn(pf):
        model = GPTModel(GPTConfig(zero3_prefetch=pf, **base))

        def fn(p):
            chunks = mp_opt.zero3_shard(p)
            rest = gather_chunked_tree(
                {k: v for k, v in chunks.items() if k != "layers"},
                rest_meta)
            return model.loss(dict(rest, layers=chunks["layers"]),
                              toks, toks, layer_chunk_meta=layer_meta)
        return fn

    pbatch = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (DPV,) + x.shape), params)
    l0, g0 = jax.jit(jax.vmap(jax.value_and_grad(loss_fn(0)),
                              axis_name="data"))(pbatch)
    l1, g1 = jax.jit(jax.vmap(jax.value_and_grad(loss_fn(prefetch)),
                              axis_name="data"))(pbatch)
    np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-4, atol=1e-5)


def test_zero3_prefetch_validation():
    """The prefetch drive's guardrails fail loudly: scan drive, aux
    layers, and dropout/bias are named, not silently ignored."""
    base = dict(vocab_size=64, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=8, hidden_dropout=0.0,
                axis=None, compute_dtype=jnp.float32)
    model = GPTModel(GPTConfig(unroll_layers=False, zero3_prefetch=1,
                               **base))
    mp_opt = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-4), amp.get_policy("O0"),
        zero_axis="data", zero_level=3)
    params = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(model.init, jax.random.PRNGKey(0)))
    meta = mp_opt.zero3_meta(params)
    layer_meta = meta.subtree("layers")
    chunks = jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype),
        jax.eval_shape(
            lambda p: jax.vmap(mp_opt.zero3_shard,
                               axis_name="data")(
                jax.tree.map(lambda x: x[None], p)), params))
    h = jnp.zeros((1, 2, 8, 16), jnp.float32)
    with pytest.raises(ValueError, match="unroll_layers"):
        jax.eval_shape(
            lambda c, hh: jax.vmap(
                lambda ci, hi: model.run_layers(
                    ci, hi, chunk_meta=layer_meta),
                axis_name="data")(c, hh),
            chunks["layers"], h)
