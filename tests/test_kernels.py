"""Pallas kernel equivalence tests (interpret mode on CPU).

Models the reference's kernel test strategy (SURVEY.md §4):
tests/L0/run_fused_layer_norm/ (fused vs F.layer_norm, mixed dtypes),
tests/L0/run_transformer/test_fused_softmax.py (fused vs torch softmax),
and the xentropy contrib tests — here fused-Pallas vs pure-XLA reference,
forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import layer_norm as ln
from apex_tpu.ops import softmax as sm
from apex_tpu.ops import xentropy as xe


def _assert_close(a, b, tol=2e-5):
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32), atol=tol, rtol=tol
    )


@pytest.mark.parametrize("rows,hidden", [(4, 64), (37, 256), (128, 130)])
@pytest.mark.parametrize("affine", [True, False])
def test_layer_norm_fwd_bwd(rows, hidden, affine):
    x = jax.random.normal(jax.random.key(0), (rows, hidden), jnp.float32)
    w = (jax.random.normal(jax.random.key(1), (hidden,)) + 1.0) if affine else None
    b = jax.random.normal(jax.random.key(2), (hidden,)) if affine else None

    def f_p(x, w, b):
        return jnp.sum(jnp.sin(ln.layer_norm(x, w, b, impl="pallas")))

    def f_r(x, w, b):
        return jnp.sum(jnp.sin(ln.layer_norm_reference(x, w, b)))

    _assert_close(
        ln.layer_norm(x, w, b, impl="pallas"), ln.layer_norm_reference(x, w, b)
    )
    if affine:
        gp = jax.grad(f_p, argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(f_r, argnums=(0, 1, 2))(x, w, b)
    else:
        gp = jax.grad(f_p, argnums=(0,))(x, w, b)
        gr = jax.grad(f_r, argnums=(0,))(x, w, b)
    for p, r in zip(gp, gr):
        _assert_close(p, r)


def test_rms_norm_fwd_bwd():
    x = jax.random.normal(jax.random.key(0), (33, 192), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (192,)) + 1.0
    _assert_close(ln.rms_norm(x, w, impl="pallas"), ln.rms_norm_reference(x, w))
    gp = jax.grad(lambda x, w: jnp.sum(jnp.sin(ln.rms_norm(x, w, impl="pallas"))), (0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(jnp.sin(ln.rms_norm_reference(x, w))), (0, 1))(x, w)
    for p, r in zip(gp, gr):
        _assert_close(p, r)


def test_layer_norm_mixed_dtype():
    """bf16 input, fp32 affine — the MixedFused contract
    (fused_layer_norm.py:398-436): output bf16, stats fp32."""
    x = jax.random.normal(jax.random.key(0), (16, 128), jnp.bfloat16)
    w = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)
    y = ln.layer_norm(x, w, b, impl="pallas")
    assert y.dtype == jnp.bfloat16
    _assert_close(y, ln.layer_norm_reference(x, w, b), tol=2e-2)


def test_layer_norm_module():
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    m = FusedLayerNorm(normalized_shape=64, impl="pallas")
    x = jax.random.normal(jax.random.key(0), (4, 7, 64))
    params = m.init(jax.random.key(1), x)
    y = m.apply(params, x)
    assert y.shape == x.shape
    assert params["params"]["scale"].dtype == jnp.float32
    _assert_close(y, ln.layer_norm_reference(x.reshape(-1, 64)).reshape(x.shape))

    r = FusedRMSNorm(normalized_shape=(64,), impl="pallas")
    pr = r.init(jax.random.key(1), x)
    assert "bias" not in pr["params"]
    _assert_close(r.apply(pr, x), ln.rms_norm_reference(x.reshape(-1, 64)).reshape(x.shape))


def test_layer_norm_multidim_normalized_shape():
    from apex_tpu.normalization import fused_layer_norm_affine

    x = jax.random.normal(jax.random.key(0), (5, 3, 4, 8))
    w = jnp.full((4, 8), 1.5)
    b = jnp.full((4, 8), 0.25)
    y = fused_layer_norm_affine(x, w, b, (4, 8), impl="pallas")
    ref = ln.layer_norm_reference(x.reshape(5, 3, 32), w.reshape(-1), b.reshape(-1))
    _assert_close(y, ref.reshape(x.shape))


@pytest.mark.parametrize("scale", [1.0, 0.125])
def test_scaled_masked_softmax(scale):
    x = jax.random.normal(jax.random.key(0), (2, 4, 17, 33), jnp.float32)
    mask = jax.random.bernoulli(jax.random.key(1), 0.25, (2, 1, 17, 33))
    _assert_close(
        sm.scaled_masked_softmax(x, mask, scale, impl="pallas"),
        sm.scaled_masked_softmax_reference(x, mask, scale),
    )
    gp = jax.grad(lambda a: jnp.sum(jnp.sin(sm.scaled_masked_softmax(a, mask, scale, impl="pallas"))))(x)
    gr = jax.grad(lambda a: jnp.sum(jnp.sin(sm.scaled_masked_softmax_reference(a, mask, scale))))(x)
    _assert_close(gp, gr)


def test_causal_softmax():
    x = jax.random.normal(jax.random.key(0), (2, 2, 24, 24), jnp.float32)
    yp = sm.scaled_upper_triang_masked_softmax(x, 0.5, impl="pallas")
    yr = sm.scaled_masked_softmax_reference(x, None, 0.5, causal=True)
    _assert_close(yp, yr)
    # strictly causal: probability above the diagonal ~ 0
    assert float(yp[0, 0, 0, 1]) < 1e-4
    gp = jax.grad(lambda a: jnp.sum(jnp.cos(sm.scaled_upper_triang_masked_softmax(a, 0.5, impl="pallas"))))(x)
    gr = jax.grad(lambda a: jnp.sum(jnp.cos(sm.scaled_masked_softmax_reference(a, None, 0.5, causal=True))))(x)
    _assert_close(gp, gr)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_softmax_cross_entropy(smoothing):
    logits = jax.random.normal(jax.random.key(0), (37, 101), jnp.float32) * 3
    labels = jax.random.randint(jax.random.key(1), (37,), 0, 101)
    labels = labels.at[5].set(-100)  # ignored row
    lp = xe.softmax_cross_entropy(logits, labels, smoothing, impl="pallas")
    lr = xe.softmax_cross_entropy_reference(logits, labels, smoothing)
    _assert_close(lp, lr)
    assert float(lp[5]) == 0.0
    gp = jax.grad(lambda a: jnp.sum(xe.softmax_cross_entropy(a, labels, smoothing, impl="pallas")))(logits)
    gr = jax.grad(lambda a: jnp.sum(xe.softmax_cross_entropy_reference(a, labels, smoothing)))(logits)
    _assert_close(gp, gr)
    # ignored row contributes no gradient
    assert float(jnp.max(jnp.abs(gp[5]))) == 0.0


def test_xentropy_batched_shape():
    logits = jax.random.normal(jax.random.key(0), (4, 9, 64))
    labels = jax.random.randint(jax.random.key(1), (4, 9), 0, 64)
    out = xe.softmax_cross_entropy(logits, labels, impl="pallas")
    assert out.shape == (4, 9)
    _assert_close(out, xe.softmax_cross_entropy_reference(logits, labels))


def test_per_head_mask():
    """Regression: a full (b, np, sq, sk) mask must be honored per head."""
    x = jax.random.normal(jax.random.key(0), (2, 3, 16, 32), jnp.float32)
    mask = jax.random.bernoulli(jax.random.key(1), 0.3, (2, 3, 16, 32))
    _assert_close(
        sm.scaled_masked_softmax(x, mask, 1.0, impl="pallas"),
        sm.scaled_masked_softmax_reference(x, mask, 1.0),
    )
    with pytest.raises(ValueError):
        sm.scaled_masked_softmax(x, mask[:, :2], 1.0, impl="pallas")
