"""Auto-parallelism planner (ISSUE 18): blind-reproduction picks this
repo earned empirically (ZeRO-3 at 2.7B, zero-bubble at S=4/M=4, int8
wire only under a narrowed ICI), the residency pin against
monitor.hbm.param_state_report, the search-table contract, the CLI, and
the ONE shared zero3_prefetch-needs-unroll rejection text."""

import json

import jax
import pytest

from apex_tpu import plan as plan_mod
from apex_tpu.utils.compat import ensure_jax_compat

ensure_jax_compat()

TINY = plan_mod.ModelSpec("plan-tiny", 128, 64, 4, 4, 32)


@pytest.fixture(autouse=True)
def _clean_peak_env(monkeypatch):
    """The picks are blind: no shell-leaked peak overrides or armed
    calibration file may skew the modeled clocks."""
    for k in ("APEX_TPU_PEAK_FLOPS", "APEX_TPU_PEAK_HBM_GBPS",
              "APEX_TPU_PEAK_ICI_GBPS", "APEX_TPU_PEAK_DCN_GBPS",
              "APEX_TPU_CALIBRATION"):
        monkeypatch.delenv(k, raising=False)


# ---------------------------------------------------------------------------
# the three blind picks
# ---------------------------------------------------------------------------


def test_blind_pick_zero3_for_27b_under_16gib():
    """Given only shape + mesh + budget, the search lands on the
    placement-rung verdict: ZeRO-3 places a 2.7B-class model on 8 ranks
    under 16 GiB; replicated and ZeRO-1/2 carry static-hbm provenance."""
    r = plan_mod.search("gpt-2.7b", mesh=8, hbm_gb=16.0)
    w = r["winner"]["candidate"]
    assert w["zero_level"] == 3
    assert r["winner"]["predicted"]["hbm_bytes"] < 16 * 1024**3
    rej_levels = {x["candidate"]["zero_level"]
                  for x in r["rejected"]
                  if x.get("rejected_by") == "static-hbm"
                  and x["candidate"].get("dp") == 8}
    assert {0, 2} <= rej_levels
    # a rejection is auditable, not a verdict: it still carries the
    # predicted anatomy that sank it
    over = next(x for x in r["rejected"]
                if x.get("rejected_by") == "static-hbm")
    assert over["predicted"]["hbm_bytes"] > 16 * 1024**3
    assert "exceeds budget" in over["reason"]


def test_blind_pick_zerobubble_at_pinned_pp():
    """Pinned at pp=4 with 4 microbatches, the zero-bubble schedule wins
    on modeled step seconds through its lower analytic floor
    ((S-1)/(3M+S-1) vs 1F1B's (S-1)/(M+S-1))."""
    from apex_tpu.monitor import tracing

    r = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                        num_microbatches=4, constraints={"pp": 4})
    assert r["winner"]["candidate"]["schedule"] == "zerobubble"
    best = {}
    for rec in r["ranked"]:
        best.setdefault(rec["candidate"]["schedule"],
                        rec["predicted"]["step_seconds"])
    assert best["zerobubble"] < best["interleaved"] < best["1f1b"]
    assert r["winner"]["predicted"]["bubble_floor"] == pytest.approx(
        tracing.expected_bubble_fraction("zerobubble", 4, 4))


def test_blind_pick_int8_wire_only_where_ici_binds(monkeypatch):
    """The EQuARX deployment rule as feasibility: on the default wire
    model the int8 candidate is rejected wire-not-binding; narrow the
    modeled ICI and the SAME search flips to the quantized wire."""
    r = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                        constraints={"dp": 8, "zero_level": 2})
    assert r["winner"]["candidate"]["reduce_dtype"] is None
    wnb = [x for x in r["rejected"]
           if x.get("rejected_by") == "wire-not-binding"]
    assert wnb and "int8" == wnb[0]["candidate"]["reduce_dtype"]

    monkeypatch.setenv("APEX_TPU_PEAK_ICI_GBPS", "0.001")
    narrowed = plan_mod.search("gpt-345m", mesh=8, hbm_gb=16.0,
                               constraints={"dp": 8, "zero_level": 2})
    assert narrowed["winner"]["candidate"]["reduce_dtype"] == "int8"


def test_blind_pick_int8_dcn_wire_on_pod_rung(monkeypatch):
    """The 13B analytic rung priced per tier (ISSUE 19): on the two-tier
    8x8 pod layout at v4 datasheet clocks the inter-island hop binds, so
    the winner blind-picks dcn_wire=int8 and the un-quantized shapes
    carry the named dcn-bound provenance (with predicted per-tier bytes
    — not the generic wire-not-binding); the flat mesh=64 search of the
    SAME model stays fp32. A widened APEX_TPU_PEAK_DCN_GBPS flips the
    pod verdict — the EQuARX rule, per tier."""
    pod = plan_mod.search("gpt-13b", mesh=64, hbm_gb=16.0, islands=8,
                          num_microbatches=2, platform="v4")
    w = pod["winner"]
    assert w["candidate"]["dcn_wire"] == "int8"
    assert pod["dcn_spec"]["source"].startswith("table")
    assert w["predicted"]["comm_bytes_by_tier"]["dcn"] > 0
    bound = [x for x in pod["rejected"]
             if x.get("rejected_by") == "dcn-bound"]
    assert bound and all(x["candidate"]["dcn_wire"] is None
                         for x in bound)
    # the rejection is auditable: predicted per-tier bytes ride both the
    # record and the reason text (the calibrate-join seam)
    assert "dcn" in bound[0]["predicted"]["comm_bytes_by_tier"]
    assert "dcn=" in bound[0]["reason"]

    flat = plan_mod.search("gpt-13b", mesh=64, hbm_gb=16.0,
                           num_microbatches=2, platform="v4")
    fc = flat["winner"]["candidate"]
    assert fc["dcn_wire"] is None and fc["islands"] == 1
    assert fc["reduce_dtype"] is None
    assert "dcn" not in flat["winner"]["predicted"]["comm_bytes_by_tier"]

    # widen the modeled DCN and the SAME pod search keeps the exact wire
    monkeypatch.setenv("APEX_TPU_PEAK_DCN_GBPS", "1000")
    wide = plan_mod.search("gpt-13b", mesh=64, hbm_gb=16.0, islands=8,
                           num_microbatches=2, platform="v4")
    assert wide["winner"]["candidate"]["dcn_wire"] is None
    assert not any(x.get("rejected_by") == "dcn-bound"
                   for x in wide["rejected"])


# ---------------------------------------------------------------------------
# the cost model's anchors
# ---------------------------------------------------------------------------


def test_residency_columns_equal_param_state_report():
    """One cost model, no drift: the planner's ZeRO-3 param/opt columns
    at tp=pp=1 are byte-identical to monitor.hbm.param_state_report's
    (the 345M @ dp=8 710 -> 89 MB pin rides the same arithmetic)."""
    from apex_tpu.monitor.hbm import param_state_report

    spec = plan_mod.MODEL_PRESETS["gpt-345m"]
    report = param_state_report(plan_mod.abstract_params(spec), 8)
    rec = plan_mod.score_candidate(
        spec, plan_mod.Candidate(dp=8, zero_level=3, gather_dtype="bf16"))
    res = rec["predicted"]["hbm"]["residency"]
    z3 = report["per_rank"]["zero3"]
    assert res["param_bytes"] == z3["param_bytes"]
    assert res["opt_bytes"] == z3["opt_bytes"]
    # the pin itself: bf16 working params 710 -> 89 MB at dp=8
    repl = report["per_rank"]["replicated"]["param_bytes"]
    assert repl / 2**20 == pytest.approx(710, rel=0.05)
    assert z3["param_bytes"] / 2**20 == pytest.approx(89, rel=0.05)


def test_search_table_contract_and_winner_roundtrip():
    """Every ranked record carries the full predicted anatomy; the
    winner's candidate round-trips through Candidate(**...); an
    impossible budget rejects everything with named provenance."""
    r = plan_mod.search(TINY, mesh=8, hbm_gb=16.0)
    assert r["n_enumerated"] > len(r["ranked"]) > 0
    for rec in r["ranked"][:5] + [r["winner"]]:
        p = rec["predicted"]
        assert p["hbm_bytes"] > 0 and p["step_seconds"] > 0
        assert "ici" in p["comm_bytes_by_tier"]
        assert 0.0 <= p["bubble_floor"] < 1.0
    cand = plan_mod.Candidate(**r["winner"]["candidate"])
    assert cand.dp * cand.tp * cand.pp == 8

    broke = plan_mod.search(TINY, mesh=8, hbm_bytes=1 << 10)
    assert broke["winner"] is None
    assert broke["rejected"]
    assert all(x["rejected_by"] for x in broke["rejected"])


def test_search_constraints_filter_not_reject():
    """Pinning a knob narrows the space without inventing rejections."""
    r = plan_mod.search(TINY, mesh=8, hbm_gb=16.0,
                        constraints={"zero_level": 3, "pp": 1})
    assert all(rec["candidate"]["zero_level"] == 3
               and rec["candidate"]["pp"] == 1 for rec in r["ranked"])
    assert not any(x["rejected_by"].startswith("constraint:zero_level")
                   for x in r["rejected"])


def test_cli_json_and_bad_model(capsys):
    from apex_tpu.plan.__main__ import main

    rc = main(["--model", "128,64,4,4,32", "--mesh", "8",
               "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["winner"] is not None
    assert out["ranked"][0] == out["winner"]
    assert main(["--model", "gpt-9000t"]) == 2


# ---------------------------------------------------------------------------
# the shared rejection text (tentpole satellite: one message, two sites)
# ---------------------------------------------------------------------------


def test_zero3_prefetch_needs_unroll_message_shared():
    """run_layers (trace time) and build_zero_train_step (build time)
    reject a prefetch-without-unroll config with the SAME constant — the
    harness/audit asymmetry was a config that built fine and only died
    deep inside the first trace."""
    import types

    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.models._transformer import ZERO3_PREFETCH_NEEDS_UNROLL
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.plan.search import model_config_kwargs
    from apex_tpu.transformer.amp import build_zero_train_step

    kw = model_config_kwargs(TINY)
    kw.update(remat=True, zero3_prefetch=1)  # unroll_layers NOT set
    model = GPTModel(GPTConfig(**kw))
    abstract = plan_mod.abstract_params(TINY)
    mp3 = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-4), amp.get_policy("O2"), zero_axis="data",
        zero_level=3)
    meta = mp3.zero3_meta(abstract)
    layer_meta = meta.subtree("layers")
    rest_meta = meta.select([k for k in meta.shapes if k != "layers"])
    toks = jax.ShapeDtypeStruct((1, TINY.seq), jnp.int32)

    def zero3_loss(p, t):
        from apex_tpu.optimizers.distributed import gather_chunked_tree

        chunks = mp3.zero3_shard(p)
        rest = gather_chunked_tree(
            {k: v for k, v in chunks.items() if k != "layers"}, rest_meta)
        return model.loss(dict(rest, layers=chunks["layers"]), t, t,
                          layer_chunk_meta=layer_meta)

    from apex_tpu.lint import ir as lint_ir

    with pytest.raises(ValueError) as trace_err:
        lint_ir.trace_ir(zero3_loss, abstract, toks, axes={"data": 4})
    assert str(trace_err.value) == ZERO3_PREFETCH_NEEDS_UNROLL

    with pytest.raises(ValueError) as build_err:
        build_zero_train_step(
            mp3, mesh=None, specs=None, state_specs=None, pipe_loss=None,
            rest_specs=None, grad_axes=("data",),
            data_spec=None, zero3=types.SimpleNamespace(),
            model=model, num_microbatches=1)
    assert str(build_err.value) == ZERO3_PREFETCH_NEEDS_UNROLL
