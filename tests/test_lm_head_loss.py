"""Chunked LM-head cross-entropy tests (reference memory-saving lineage:
apex/contrib/xentropy — equivalence against the materialized computation is
the test contract, test_label_smoothing.py style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.lm_head_loss import (
    lm_head_cross_entropy,
    lm_head_cross_entropy_reference,
)


def _data(key, N=12, H=16, V=64, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    h = jax.random.normal(k1, (N, H), dtype)
    wte = jax.random.normal(k2, (V, H), dtype) * 0.5
    t = jax.random.randint(k3, (N,), 0, V)
    return h, wte, t


@pytest.mark.parametrize("num_chunks", [1, 4, 8])
def test_loss_matches_materialized(num_chunks):
    h, wte, t = _data(jax.random.PRNGKey(0))
    out = lm_head_cross_entropy(h, wte, t, num_chunks)
    ref = lm_head_cross_entropy_reference(h, wte, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_gradients_match_materialized():
    h, wte, t = _data(jax.random.PRNGKey(1))
    w = jax.random.normal(jax.random.PRNGKey(2), (12,))  # per-token weights

    def fused(h, wte):
        return jnp.sum(lm_head_cross_entropy(h, wte, t, 4) * w)

    def ref(h, wte):
        return jnp.sum(lm_head_cross_entropy_reference(h, wte, t) * w)

    (dh_f, dw_f) = jax.grad(fused, argnums=(0, 1))(h, wte)
    (dh_r, dw_r) = jax.grad(ref, argnums=(0, 1))(h, wte)
    np.testing.assert_allclose(np.asarray(dh_f), np.asarray(dh_r),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_r),
                               rtol=1e-4, atol=1e-5)


def test_batched_shape_and_bf16():
    h = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 16), jnp.bfloat16)
    wte = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.bfloat16)
    t = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, 32)
    out = jax.jit(lambda h, w: lm_head_cross_entropy(h, w, t, 4))(h, wte)
    assert out.shape == (2, 6)
    ref = lm_head_cross_entropy_reference(h, wte, t)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2)


def test_under_jit_and_value_and_grad():
    h, wte, t = _data(jax.random.PRNGKey(3))

    @jax.jit
    def loss_fn(h, wte):
        return jnp.mean(lm_head_cross_entropy(h, wte, t, 8))

    v, g = jax.value_and_grad(loss_fn, argnums=(0, 1))(h, wte)
    assert jnp.isfinite(v)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in g)


def test_vocab_chunk_divisibility_validated():
    h, wte, t = _data(jax.random.PRNGKey(0), V=60)
    with pytest.raises(ValueError):
        lm_head_cross_entropy(h, wte, t, 8)


def test_gpt_with_chunked_head_matches_plain():
    from apex_tpu.models import GPTConfig, GPTModel

    base = dict(vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_seq_len=16, hidden_dropout=0.0,
                axis=None, compute_dtype=jnp.float32, remat=False)
    plain = GPTModel(GPTConfig(**base))
    fused = GPTModel(GPTConfig(lm_head_chunks=4, **base))
    params = plain.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    tgt = jnp.roll(toks, -1, axis=-1)
    v_p, g_p = jax.value_and_grad(plain.loss)(params, toks, tgt)
    v_f, g_f = jax.value_and_grad(fused.loss)(params, toks, tgt)
    np.testing.assert_allclose(float(v_p), float(v_f), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_p), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
