"""Tests for apex_tpu.monitor.mfu (peak specs, roofline join, cost
extraction) and apex_tpu.monitor.report (journal analysis + the compare
regression gate, including the CLI surface)."""

import json
import math

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.monitor import MetricsJournal, mfu_metrics, peak_spec
from apex_tpu.monitor import mfu as mfu_lib
from apex_tpu.monitor import report


# ---------------------------------------------------------------------------
# mfu: peak specs
# ---------------------------------------------------------------------------


def test_peak_spec_table_rows(monkeypatch):
    monkeypatch.delenv(mfu_lib.ENV_PEAK_FLOPS, raising=False)
    monkeypatch.delenv(mfu_lib.ENV_PEAK_HBM_GBPS, raising=False)
    v4 = peak_spec("TPU v4")
    assert v4["peak_flops"] == 275e12
    assert v4["peak_hbm_bytes_per_sec"] == 1228e9
    assert v4["source"] == "table:v4"
    # device_kind variants land on the right row
    assert peak_spec("tpu TPU v5 lite")["peak_flops"] == 197e12
    assert peak_spec("cpu")["source"] == "table:cpu"
    assert peak_spec("weird-accelerator")["source"] == "fallback"


def test_peak_spec_env_overrides(monkeypatch):
    """The tunnel-calibration knobs: a measured sustained ceiling beats
    the datasheet, and the record says so via source='env'."""
    monkeypatch.setenv(mfu_lib.ENV_PEAK_FLOPS, "78e12")
    monkeypatch.setenv(mfu_lib.ENV_PEAK_HBM_GBPS, "900")
    spec = peak_spec("tpu v4")
    assert spec["peak_flops"] == 78e12
    assert spec["peak_hbm_bytes_per_sec"] == 900e9
    assert spec["source"] == "env"
    # malformed overrides fall back to the table row
    monkeypatch.setenv(mfu_lib.ENV_PEAK_FLOPS, "not-a-number")
    monkeypatch.delenv(mfu_lib.ENV_PEAK_HBM_GBPS, raising=False)
    spec = peak_spec("tpu v4")
    assert spec["peak_flops"] == 275e12 and spec["source"] == "table:v4"
    # one-sided override: per-knob provenance, never a blanket 'env'
    monkeypatch.setenv(mfu_lib.ENV_PEAK_FLOPS, "78e12")
    spec = peak_spec("tpu v4")
    assert spec["peak_flops"] == 78e12
    assert spec["peak_hbm_bytes_per_sec"] == 1228e9  # datasheet kept
    assert spec["source"] == "flops:env|hbm:table:v4"
    # a malformed HBM knob must not discard the valid FLOPS one
    monkeypatch.setenv(mfu_lib.ENV_PEAK_HBM_GBPS, "fast")
    spec = peak_spec("tpu v4")
    assert spec["peak_flops"] == 78e12
    assert spec["source"] == "flops:env|hbm:table:v4"


# ---------------------------------------------------------------------------
# mfu: roofline join
# ---------------------------------------------------------------------------

_SPEC = {"platform": "test", "peak_flops": 100e12,
         "peak_hbm_bytes_per_sec": 1e12, "source": "test"}


def test_mfu_metrics_compute_bound():
    # 10 TFLOP + 0.1 GB in 0.2 s: mfu 0.5, bw_util 0.0005 -> compute-bound
    m = mfu_metrics(flops=10e12, bytes_accessed=1e8, wall_s=0.2, spec=_SPEC)
    assert m["mfu"] == pytest.approx(0.5, abs=1e-4)
    assert m["hbm_bw_util"] == pytest.approx(5e-4, abs=1e-4)
    assert m["bound"] == "compute"
    assert m["achieved_tflops"] == pytest.approx(50.0, abs=0.01)
    assert m["ridge_intensity"] == pytest.approx(100.0, abs=0.01)
    assert m["peak_source"] == "test"


def test_mfu_metrics_memory_bound_and_balanced():
    # 1 GFLOP + 100 GB: memory time 0.1 s >> compute time 1e-5 s
    m = mfu_metrics(flops=1e9, bytes_accessed=100e9, wall_s=0.5, spec=_SPEC)
    assert m["bound"] == "memory"
    # on the ridge (intensity == peak_flops/peak_bw = 100): balanced
    m = mfu_metrics(flops=100e12, bytes_accessed=1e12, wall_s=1.0, spec=_SPEC)
    assert m["bound"] == "balanced"


def test_mfu_metrics_degenerate_inputs():
    assert "mfu" not in mfu_metrics(flops=1e12, bytes_accessed=1e9,
                                    wall_s=0.0, spec=_SPEC)
    m = mfu_metrics(flops=0.0, bytes_accessed=0.0, wall_s=1.0, spec=_SPEC)
    assert m["mfu"] == 0.0 and "bound" not in m


def test_traced_step_costs_matmul():
    costs = mfu_lib.traced_step_costs(
        lambda a, b: a @ b, jnp.ones((16, 32)), jnp.ones((32, 8)))
    assert costs["flops"] == 2 * 16 * 8 * 32
    # algorithmic bytes: operands + result, f32
    assert costs["bytes"] == (16 * 32 + 32 * 8 + 16 * 8) * 4
    assert costs["method"] == "jaxpr"


def test_compiled_step_costs_with_jaxpr_floor():
    compiled = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((16, 32)), jnp.ones((32, 8))).compile()
    costs = mfu_lib.compiled_step_costs(compiled)
    assert costs["flops"] > 0 and costs["bytes"] > 0
    # the jaxpr floor wins when the cost model undercounts (Pallas case)
    floored = mfu_lib.compiled_step_costs(compiled, jaxpr_flops=1e18)
    assert floored["flops"] == 1e18
    assert floored["method"] == "cost_model+jaxpr"


def test_pyprof_program_costs_join():
    from apex_tpu.pyprof import program_costs

    costs = program_costs(lambda a, b: a @ b,
                          jnp.ones((16, 32)), jnp.ones((32, 8)))
    assert costs["flops"] >= 2 * 16 * 8 * 32
    assert costs["flops_jaxpr"] == 2 * 16 * 8 * 32
    assert "bytes_accessed" in costs and "flops_undercounted" in costs


def test_journal_step_costs_arm_mfu_fields(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsJournal(path) as j:
        j.set_step_costs(flops_per_token=1e9, bytes_per_token=1e6,
                         platform="tpu v4")
        j.step_end(step=0, loss=jnp.asarray(1.0), tokens=1000, wall_s=0.1)
        j.step_end(step=1, loss=jnp.asarray(1.0))  # no tokens: no mfu
    rows = [r for r in MetricsJournal.read(path) if r["kind"] == "step"]
    # 1e12 flops / 0.1 s = 10 TF/s over the 275 TF/s v4 peak
    assert rows[0]["mfu"] == pytest.approx(1e13 / 275e12, abs=1e-4)
    assert rows[0]["hbm_bw_util"] == pytest.approx(1e10 / 1228e9, abs=1e-4)
    assert rows[0]["bound"] == "compute"
    assert "mfu" not in rows[1]


# ---------------------------------------------------------------------------
# report: analysis
# ---------------------------------------------------------------------------


def _step(step, ts, rate=1000.0, loss=2.0, rank=0, **extra):
    rec = {"v": 1, "kind": "step", "step": step, "ts": ts, "wall_s": 0.1,
           "tokens": 100, "tokens_per_sec": rate, "loss": loss,
           "rank": rank, "overflows": 0}
    rec.update(extra)
    return rec


def test_analyze_percentiles_and_stalls():
    # steady 1 s cadence with one 30 s hole after step 4
    recs = [_step(i, 100.0 + i + (29.0 if i > 4 else 0.0),
                  rate=900.0 + 20 * i) for i in range(10)]
    a = report.analyze(recs)
    assert a["step_records"] == 10
    assert a["tokens_per_sec"]["p50"] == pytest.approx(990.0, abs=1.0)
    assert a["tokens_per_sec"]["min"] == 900.0
    assert a["stalls"]["count"] == 1
    assert a["stalls"]["gaps"][0]["after_step"] == 4
    assert a["stalls"]["gaps"][0]["gap_s"] == pytest.approx(30.0, abs=0.1)


def test_analyze_loss_spikes_and_nonfinite():
    recs = [_step(i, 100.0 + i, loss=1.0) for i in range(8)]
    recs.append(_step(8, 108.0, loss=50.0))                    # spike
    nan_rec = _step(9, 109.0)
    nan_rec["loss"] = None
    nan_rec["nonfinite_keys"] = ["loss"]                       # sanitized NaN
    recs.append(nan_rec)
    a = report.analyze(recs)
    assert a["loss"]["spike_count"] == 1
    assert a["loss"]["spikes"][0]["step"] == 8
    assert a["loss"]["nonfinite_count"] == 1
    assert a["loss"]["nonfinite_steps"] == [9]


def test_analyze_hbm_trend_and_ranks_and_comm():
    recs = []
    for i in range(6):
        recs.append(_step(i, 100.0 + i, rate=1000.0, rank=0,
                          hbm={"live_bytes": 1000 + 100 * i}))
        recs.append(_step(i, 100.2 + i, rate=500.0, rank=1))
    recs.append({"kind": "meta", "ts": 99.0,
                 "comm_bytes_by_axis": {"data": {"bytes": 4096, "calls": 2},
                                        "model": {"bytes": 512, "calls": 1}}})
    a = report.analyze(recs)
    assert a["hbm"]["growth_bytes"] == 500
    assert a["hbm"]["trend_bytes_per_sample"] == pytest.approx(100.0, abs=1.0)
    assert a["ranks"]["straggler_rank"] == 1
    assert a["ranks"]["skew"] == pytest.approx(2.0, abs=0.01)
    assert a["comm_bytes_by_axis"]["data"] == {"bytes": 4096, "calls": 2}


def test_analyze_mfu_forensics_recompile_rollups():
    recs = [_step(i, 100.0 + i, mfu=0.3 + 0.01 * i, hbm_bw_util=0.5,
                  bound="compute", peak_source="env") for i in range(5)]
    recs.append({"kind": "forensics", "ts": 105.0, "trigger": "overflow",
                 "nonfinite_groups": ["layers"]})
    recs.append({"kind": "recompile", "ts": 106.0, "fn": "train_step",
                 "signature": "f32[8]", "compile_s": 1.5})
    recs.append({"kind": "recompile", "ts": 107.0, "fn": "train_step",
                 "signature": "f32[16]", "compile_s": 2.5})
    a = report.analyze(recs)
    assert a["mfu"]["p50"] == pytest.approx(0.32, abs=1e-6)
    assert a["mfu"]["bound"] == {"compute": 5}
    assert a["mfu"]["peak_source"] == "env"
    assert a["forensics"] == {"count": 1, "by_trigger": {"overflow": 1},
                              "nonfinite_groups": ["layers"]}
    assert a["recompiles"]["train_step"] == {"compiles": 2, "compile_s": 4.0,
                                             "signatures": 2}


def test_analyze_empty_and_render_smoke(capsys):
    a = report.analyze([])
    assert a["step_records"] == 0 and a["overflows"] == 0
    report.render(a)
    report.render(report.analyze(
        [_step(0, 100.0, hbm={"live_bytes": 10}, mfu=0.5, bound="compute")]))
    out = capsys.readouterr().out
    assert "records:" in out and "throughput" in out


# ---------------------------------------------------------------------------
# report: compare gate
# ---------------------------------------------------------------------------


def test_compare_ok_and_regressed():
    a = [_step(i, 100.0 + i, rate=1000.0) for i in range(8)]
    same = report.compare(a, list(a))
    assert same["ok"] and not same["regressed"]
    b = [_step(i, 100.0 + i, rate=800.0) for i in range(8)]  # -20%
    res = report.compare(a, b, threshold=0.05)
    assert not res["ok"] and "tokens_per_sec_p50" in res["regressed"]
    # within threshold: ok
    c = [_step(i, 100.0 + i, rate=970.0) for i in range(8)]  # -3%
    assert report.compare(a, c, threshold=0.05)["ok"]


def test_analyze_timeline_section():
    recs = [_step(i, 100.0 + i, bubble_fraction=0.27,
                  bubble_fraction_expected=0.25, overlap_fraction=0.4,
                  compute_frac=0.7, comm_frac=0.2, stall_frac=0.1)
            for i in range(4)]
    tl = report.analyze(recs)["timeline"]
    assert tl["bubble_fraction"] == {"last": 0.27, "p50": 0.27}
    assert tl["bubble_fraction_expected"] == 0.25
    assert tl["overlap_fraction"]["p50"] == 0.4
    assert tl["compute_frac_mean"] == 0.7
    assert "timeline" not in report.analyze(
        [_step(i, 100.0 + i) for i in range(4)])


def test_compare_overlap_threshold_gate():
    """The comm/compute overlap fraction gates like throughput (higher is
    better, so must_not_drop) — the machine gate for the ZeRO-3
    double-buffered gather work."""
    a = [_step(i, 100.0 + i, overlap_fraction=0.60) for i in range(6)]
    worse = [_step(i, 100.0 + i, overlap_fraction=0.40) for i in range(6)]
    res = report.compare(a, worse, overlap_threshold=0.10)
    assert "overlap_fraction_p50" in res["regressed"]
    # within tolerance: ok
    near = [_step(i, 100.0 + i, overlap_fraction=0.57) for i in range(6)]
    assert report.compare(a, near, overlap_threshold=0.10)["ok"]
    # defaults to --threshold when unset
    res2 = report.compare(a, worse, threshold=0.05)
    assert "overlap_fraction_p50" in res2["regressed"]
    # a HIGHER overlap (the prefetch-improvement direction) never
    # regresses, and absent stamps skip the check
    better = [_step(i, 100.0 + i, overlap_fraction=0.90) for i in range(6)]
    assert report.compare(a, better)["ok"]
    plain = [_step(i, 100.0 + i) for i in range(6)]
    res3 = report.compare(plain, plain, overlap_threshold=0.10)
    assert "overlap_fraction_p50" not in [c["check"] for c in res3["checks"]]
    # CLI surface
    import os
    import tempfile

    d = tempfile.mkdtemp(prefix="apex_tpu_overlap_gate_")
    try:
        pa, pb = os.path.join(d, "a.jsonl"), os.path.join(d, "b.jsonl")
        for path, rows in ((pa, a), (pb, worse)):
            with open(path, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
        import contextlib
        import io

        with contextlib.redirect_stdout(io.StringIO()):
            assert report.main(
                ["compare", pa, pb, "--overlap-threshold", "0.10"]) == 1
            assert report.main(
                ["compare", pa, pa, "--overlap-threshold", "0.10"]) == 0
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)


def test_compare_bubble_threshold_gate():
    a = [_step(i, 100.0 + i, bubble_fraction=0.20) for i in range(6)]
    worse = [_step(i, 100.0 + i, bubble_fraction=0.30) for i in range(6)]
    res = report.compare(a, worse, bubble_threshold=0.10)
    assert "bubble_fraction_p50" in res["regressed"]
    # within tolerance (threshold 0.10 + the 0.01 abs slack): ok
    near = [_step(i, 100.0 + i, bubble_fraction=0.225) for i in range(6)]
    assert report.compare(a, near, bubble_threshold=0.10)["ok"]
    # bubble_threshold defaults to --threshold when unset
    res2 = report.compare(a, worse, threshold=0.05)
    assert "bubble_fraction_p50" in res2["regressed"]
    # a LOWER bubble (the schedule-improvement direction) never regresses
    better = [_step(i, 100.0 + i, bubble_fraction=0.05) for i in range(6)]
    assert report.compare(a, better)["ok"]
    # CLI surface
    import os
    import tempfile

    d = tempfile.mkdtemp()
    try:
        pa, pb = os.path.join(d, "a.jsonl"), os.path.join(d, "b.jsonl")
        for path, rows in ((pa, a), (pb, worse)):
            with open(path, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
        assert report.main(
            ["compare", pa, pb, "--bubble-threshold", "0.1", "--json"]) == 1
        assert report.main(
            ["compare", pa, pb, "--bubble-threshold", "0.6", "--json"]) == 0
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)


def test_shared_tolerance_predicates():
    """The one predicate pair every fractional gate shares (satellite:
    no copy-pasted tolerance handling per metric)."""
    drop = report.must_not_drop(0.05)
    assert drop(100.0, 94.9) and not drop(100.0, 95.1)
    grow = report.must_not_grow(0.05)
    assert grow(100.0, 105.1) and not grow(100.0, 104.9)
    slack = report.must_not_grow(0.10, slack=0.01)
    assert not slack(0.0, 0.009) and slack(0.0, 0.011)


def test_compare_overflow_and_hbm_and_nonfinite_regressions():
    a = [_step(i, 100.0 + i, hbm={"live_bytes": 1000}) for i in range(6)]
    b = [dict(_step(i, 100.0 + i, hbm={"live_bytes": 1000 + 50_000_000 * i}),
              overflows=3) for i in range(6)]
    res = report.compare(a, b)
    assert "overflow_rate" in res["regressed"]
    assert "hbm_growth_bytes" in res["regressed"]
    n = [_step(i, 100.0 + i) for i in range(6)]
    n[3] = dict(n[3], loss=None, nonfinite_keys=["loss"])
    assert "nonfinite_losses" in report.compare(a, n)["regressed"]


def test_compare_overflow_rate_tolerates_warmup_and_length():
    """A longer healthy run with the same per-step overflow rate (or a
    couple of warmup overflows) must not regress; a rate explosion must."""
    a = [dict(_step(i, 100.0 + i), overflows=min(i, 2)) for i in range(100)]
    b = [dict(_step(i, 100.0 + i), overflows=min(i, 3)) for i in range(200)]
    assert report.compare(a, b)["ok"]  # 2/100 vs 3/200: rate went DOWN
    bad = [dict(_step(i, 100.0 + i), overflows=i) for i in range(100)]
    assert "overflow_rate" in report.compare(a, bad)["regressed"]


def test_compare_mfu_skipped_on_peak_source_mismatch():
    """An env-calibrated baseline vs a datasheet candidate must not fake
    an MFU regression — the check is skipped and labelled."""
    a = [_step(i, 100.0 + i, mfu=0.8, peak_source="env") for i in range(6)]
    b = [_step(i, 100.0 + i, mfu=0.2, peak_source="table:v4")
         for i in range(6)]
    res = report.compare(a, b)
    row = next(c for c in res["checks"] if c["check"] == "mfu_p50")
    assert row.get("skipped") == "peak_source mismatch"
    assert not row["regressed"] and res["ok"]
    # same provenance: the 4x drop IS a regression
    b2 = [_step(i, 100.0 + i, mfu=0.2, peak_source="env") for i in range(6)]
    assert "mfu_p50" in report.compare(a, b2)["regressed"]


def test_compare_fails_candidate_with_no_step_records():
    """A candidate that crashed before journaling any step must FAIL the
    gate, not skip every signal check and pass green."""
    a = [_step(i, 100.0 + i) for i in range(5)]
    res = report.compare(a, [{"kind": "meta", "ts": 99.0}])
    assert not res["ok"] and "step_records" in res["regressed"]
    # two empty journals compare as equals (nothing to regress FROM)
    assert report.compare([], [])["ok"]


def test_compare_missing_signals_are_skipped():
    """Journals without mfu/hbm rows: those checks silently skip rather
    than crash or false-positive."""
    a = [_step(i, 100.0 + i) for i in range(4)]
    res = report.compare(a, list(a))
    names = {c["check"] for c in res["checks"]}
    assert "mfu_p50" not in names and "hbm_growth_bytes" not in names
    assert res["ok"]


# ---------------------------------------------------------------------------
# report: CLI (the operator surface)
# ---------------------------------------------------------------------------


def _write_journal(path, rate, steps=6):
    with MetricsJournal(str(path)) as j:
        for i in range(steps):
            j.step_end(step=i, loss=jnp.asarray(2.0 - 0.1 * i),
                       tokens=1024, wall_s=1024.0 / rate)


def test_cli_report_and_json(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    _write_journal(path, rate=2000.0)
    assert report.main([str(path)]) == 0
    assert "throughput tok/s" in capsys.readouterr().out
    assert report.main([str(path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["step_records"] == 6
    assert payload["tokens_per_sec"]["p50"] == pytest.approx(2000.0, rel=1e-3)


def test_cli_compare_exit_codes(tmp_path, capsys):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_journal(a, rate=2000.0)
    _write_journal(b, rate=1000.0)
    assert report.main(["compare", str(a), str(a)]) == 0
    assert report.main(["compare", str(a), str(b)]) == 1
    capsys.readouterr()
    assert report.main(["compare", str(a), str(b), "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert "tokens_per_sec_p50" in payload["regressed"]
    # a generous threshold accepts the 2x drop
    assert report.main(["compare", str(a), str(b),
                        "--threshold", "0.9"]) == 0


def test_cli_tolerates_truncated_journal(tmp_path, capsys):
    """A watchdog-killed run's torn final line must not kill the report
    (the whole point of a crash-time journal)."""
    path = tmp_path / "torn.jsonl"
    _write_journal(path, rate=2000.0)
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "step", "step": 6, "tokens_per')
    assert report.main([str(path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["truncated"] is True
    assert payload["step_records"] == 6


def test_report_loss_ignores_scaled_nan_free_floats(tmp_path):
    """math.isfinite guard sanity: plain inf in a record round-trips as
    null via the journal, and analyze counts it non-finite."""
    path = tmp_path / "inf.jsonl"
    with MetricsJournal(str(path)) as j:
        j.step_end(step=0, loss=jnp.asarray(float("inf")), tokens=10,
                   wall_s=0.1)
    rows = MetricsJournal.read(path)
    steps = [r for r in rows if r["kind"] == "step"]
    assert steps[0]["loss"] is None
    assert "loss" in steps[0]["nonfinite_keys"]
    a = report.analyze(rows)
    assert a["loss"]["nonfinite_count"] == 1


def test_report_rolls_up_opt_state_bytes(tmp_path):
    """Journals armed with set_opt_state_bytes (the ZeRO bytes/rank ÷ dp
    claim) roll up into analyze() and the rendered view."""
    import io

    path = tmp_path / "zero.jsonl"
    with MetricsJournal(str(path)) as j:
        j.set_opt_state_bytes(512 << 20)
        for step in range(3):
            j.step_end(step=step, loss=jnp.float32(2.0), tokens=1024,
                       wall_s=0.1)
    a = report.analyze(MetricsJournal.read(path))
    assert a["opt_state_bytes"] == {"last": 512 << 20, "peak": 512 << 20}
    buf = io.StringIO()
    report.render(a, file=buf)
    assert "opt state: 536.9 MB/rank" in buf.getvalue()


def test_percentile_helper():
    assert report._percentile([1.0], 0.5) == 1.0
    assert report._percentile([1.0, 2.0, 3.0, 4.0], 0.5) == 2.5
    assert report._percentile([1.0, 2.0, 3.0], 0.0) == 1.0
    assert report._percentile([1.0, 2.0, 3.0], 1.0) == 3.0
    assert math.isclose(report._percentile([0.0, 10.0], 0.9), 9.0)
