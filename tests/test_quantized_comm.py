"""Quantized collectives with error feedback (parallel/quantize.py +
MixedPrecisionOptimizer(reduce_dtype=...) + GPTConfig.activation_comm_dtype).

Pattern from test_zero_optimizer.py (the reference's test_dist_adam.py
ethos: sharded vs replicated given the same gradients), extended along the
wire-dtype axis: the int8/e5m2-wire ZeRO step must TRACK the fp32-wire
step (quantization is lossy by design — the equivalence is a tolerance,
the convergence gate is `monitor.report compare --loss-threshold`), the
overflow skip must stay bit-identical per rank INCLUDING the new
error-feedback residual, and `reduce_dtype=None` must leave the state
structure and step math exactly as before the knob existed.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.distributed import chunk_size, scatter_chunk
from apex_tpu.parallel import quantize

N = 8
STEPS = 4
OVERFLOW_STEP = 2


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("data",))


# ---------------------------------------------------------------------------
# the encode/decode primitives
# ---------------------------------------------------------------------------


def test_canon_wire_dtype():
    assert quantize.canon_wire_dtype(None) is None
    assert quantize.canon_wire_dtype("int8") == "int8"
    assert quantize.canon_wire_dtype(jnp.int8) == "int8"
    assert quantize.canon_wire_dtype("E5M2") == "e5m2"
    assert quantize.canon_wire_dtype("fp8") == "e5m2"
    assert quantize.canon_wire_dtype(jnp.float8_e5m2) == "e5m2"
    with pytest.raises(ValueError):
        quantize.canon_wire_dtype("int4")
    with pytest.raises(ValueError):
        quantize.canon_wire_dtype(jnp.bfloat16)


def test_encode_decode_error_bounded_by_scale():
    """Deterministic int8 round-trip error <= scale/2 per element; all-zero
    rows survive (scale 1.0, exact zeros back)."""
    rows = jnp.concatenate([
        jax.random.normal(jax.random.PRNGKey(0), (3, 64)) * 10.0,
        jnp.zeros((1, 64)),
    ])
    scales = quantize.block_scales(rows, "int8")
    dec = quantize.decode(quantize.encode(rows, scales, "int8"), scales)
    err = jnp.abs(dec - rows)
    assert float(jnp.max(err - 0.5 * scales[:, None])) <= 1e-6
    np.testing.assert_array_equal(np.asarray(dec[-1]), np.zeros((64,)))
    # e5m2: relative error bounded by its 2-mantissa-bit ulp (2^-3)
    dece = quantize.decode(
        quantize.encode(rows, quantize.block_scales(rows, "e5m2"), "e5m2"),
        quantize.block_scales(rows, "e5m2"))
    rel = jnp.abs(dece - rows) / (jnp.abs(rows) + 1e-9)
    assert float(jnp.median(rel[:3])) <= 2.0 ** -3


def test_stochastic_rounding_is_zero_mean_and_int8_only():
    rows = jnp.full((1, 256), 0.3)  # sits between two int8 codes
    scales = quantize.block_scales(rows, "int8")
    decs = []
    for i in range(64):
        q = quantize.encode(rows, scales, "int8", key=jax.random.PRNGKey(i))
        decs.append(float(jnp.mean(quantize.decode(q, scales))))
    # deterministic rounding is constant-biased; the dithered mean
    # approaches the true value
    assert abs(np.mean(decs) - 0.3) < abs(decs[0] - 0.3) + 1e-3 or \
        abs(np.mean(decs) - 0.3) < 0.002
    with pytest.raises(ValueError):
        quantize.encode(rows, scales, "e5m2", key=jax.random.PRNGKey(0))


@pytest.mark.parametrize("wire", ["int8", "e5m2"])
def test_quantized_reduce_scatter_matches_exact(wire):
    """SUM semantics and chunk layout identical to scatter_chunk; only the
    wire payload is lossy (per-chunk-scale-bounded)."""
    g = jax.random.normal(jax.random.PRNGKey(1), (N, 533))  # padded leaf

    def qrs(x):
        c, _ = quantize.quantized_reduce_scatter(x, N, "data", wire)
        return c

    out = jax.vmap(qrs, axis_name="data")(g)
    ref = jax.vmap(lambda x: scatter_chunk(x, N, "data"),
                   axis_name="data")(g)
    assert out.shape == ref.shape
    rel = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert rel < (0.02 if wire == "int8" else 0.1), (wire, rel)


def test_error_feedback_telescopes_not_accumulates():
    """Reducing the SAME grads T times: without the residual the rounding
    bias accumulates ~linearly; with it the cumulative error stays bounded
    (the EF/1-bit-Adam construction)."""
    T = 16
    g = jax.random.normal(jax.random.PRNGKey(2), (N, 257))
    ref = jax.vmap(lambda x: scatter_chunk(x, N, "data"),
                   axis_name="data")(g)
    pad = chunk_size(257, N) * N

    def run(with_ef):
        res = jnp.zeros((N, pad))
        cum = jnp.zeros_like(ref)
        errs = []
        for t in range(1, T + 1):
            def one(x, r):
                c, nr = quantize.quantized_reduce_scatter(
                    x, N, "data", "int8", residual=(r if with_ef else None))
                return c, (nr if nr is not None else r)

            c, res = jax.vmap(one, axis_name="data")(g, res)
            cum = cum + c
            errs.append(float(jnp.max(jnp.abs(cum - t * ref))))
        return errs

    ef, no_ef = run(True), run(False)
    assert ef[-1] <= 2.0 * max(ef[:4]), ef       # bounded
    assert no_ef[-1] > 3.0 * ef[-1], (no_ef[-1], ef[-1])  # divergent


def test_quantized_gather_chunk_identical_across_ranks():
    """The int8 param gather: every rank decodes the SAME view (ranks
    cannot diverge), close to the exact gather."""
    chunks = jax.random.normal(jax.random.PRNGKey(3), (N, 64))

    def qg(c):
        return quantize.quantized_gather_chunk(c, "data", "int8")

    out = jax.vmap(qg, axis_name="data")(chunks)
    flat = chunks.reshape(-1)
    for r in range(1, N):
        np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(out[r]))
    rel = float(jnp.max(jnp.abs(out[0] - flat)) / jnp.max(jnp.abs(flat)))
    assert rel < 0.01, rel


# ---------------------------------------------------------------------------
# MixedPrecisionOptimizer(reduce_dtype=...) — the ZeRO wire
# ---------------------------------------------------------------------------


def _params(policy):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    full = {
        "w": jax.random.normal(k1, (13, 7)),  # 91 elems: padded chunks
        "b": jax.random.normal(k2, (7,)),
        "s": jax.random.normal(k3, ()),
    }
    return amp.cast_params(full, policy)


def _per_replica_grads(params):
    grads = []
    for t in range(STEPS):
        per = [jax.tree.map(
            lambda p, r=r, t=t: jax.random.normal(
                jax.random.PRNGKey(1000 + 17 * t + r), p.shape), params)
            for r in range(N)]
        if t == OVERFLOW_STEP:
            per[3] = jax.tree.map(lambda g: jnp.full_like(g, jnp.inf), per[3])
        grads.append(per)
    return grads


def _run_zero(mesh, params, grads, reduce_dtype, stochastic=False):
    """STEPS of the sharded amp step; returns (params, states, scales)."""
    policy = amp.get_policy("O2")
    z = amp.MixedPrecisionOptimizer(
        FusedAdam(lr=1e-2), policy, zero_axis="data",
        reduce_dtype=reduce_dtype, stochastic_rounding=stochastic)
    pspecs = jax.tree.map(lambda _: P(), params)
    zstate, sspecs = z.zero_init(params, mesh, pspecs)
    gspec = jax.tree.map(lambda _: P("data"), params)

    def zstep(p, st, g):
        g = jax.tree.map(lambda x: x[0], g)
        scaled = jax.tree.map(lambda gg: gg * st.scaler.loss_scale, g)
        return z.apply_gradients(st, p, scaled)

    fn = jax.jit(jax.shard_map(
        zstep, mesh=mesh, in_specs=(pspecs, sspecs, gspec),
        out_specs=(pspecs, sspecs, P()), check_vma=False))

    def stack(per):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *per)

    p, states, scales = params, [zstate], []
    for t in range(STEPS):
        p, zstate, m = fn(p, zstate, stack(grads[t]))
        states.append(zstate)
        scales.append(float(m["loss_scale"]))
    return p, states, scales


def test_int8_wire_tracks_fp32_wire_through_overflow_skip(mesh):
    """The quantized-wire ZeRO trajectory: same loss-scale decisions as
    the fp32 wire (the skip is driven by found_inf, computed BEFORE the
    wire), params within quantization tolerance, and the error-feedback
    residual carried as per-rank sharded state that an overflow-skipped
    step leaves bit-identical."""
    policy = amp.get_policy("O2")
    params = _params(policy)
    grads = _per_replica_grads(params)

    p_ref, _, scales_ref = _run_zero(mesh, params, grads, None)
    p_q, states_q, scales_q = _run_zero(mesh, params, grads, "int8")

    # identical skip trajectory: the overflow decision sees the raw grads
    assert scales_q == scales_ref
    assert scales_ref[OVERFLOW_STEP] == scales_ref[0] / 2

    # state structure: residual rides the sharded state, fp32-wire has None
    assert states_q[-1].residual is not None
    assert set(states_q[-1].residual) == {"err"}
    for name in params:
        err = states_q[-1].residual["err"][name]
        n_elems = int(np.prod(params[name].shape)) if params[name].shape else 1
        assert err.shape == (chunk_size(n_elems, N) * N * N,)  # N per-rank

    # the overflow-skipped step left masters AND residual unchanged
    before, after = states_q[OVERFLOW_STEP], states_q[OVERFLOW_STEP + 1]
    for a, b in zip(jax.tree.leaves(before.master),
                    jax.tree.leaves(after.master)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(before.residual["err"]),
                    jax.tree.leaves(after.residual["err"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a NON-skipped step did advance the residual (error feedback is live)
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(states_q[0].residual["err"]),
                        jax.tree.leaves(states_q[1].residual["err"])))
    assert moved

    # params track the fp32 wire within quantization tolerance
    for name in params:
        np.testing.assert_allclose(
            np.asarray(p_q[name], np.float32),
            np.asarray(p_ref[name], np.float32),
            rtol=5e-2, atol=5e-2, err_msg=name)


def test_stochastic_rounding_wire_runs_and_advances_key(mesh):
    policy = amp.get_policy("O2")
    params = _params(policy)
    grads = _per_replica_grads(params)
    p_q, states, scales = _run_zero(mesh, params, grads, "int8",
                                    stochastic=True)
    assert "key" in states[-1].residual
    # the dither stream advances every step, through the skip too
    k1 = np.asarray(states[1].residual["key"])
    k2 = np.asarray(states[2].residual["key"])
    assert not np.array_equal(k1, k2)
    for name in params:
        assert np.all(np.isfinite(np.asarray(p_q[name], np.float32)))


def test_reduce_dtype_none_keeps_legacy_state_shape(mesh):
    """reduce_dtype=None must be indistinguishable from the pre-knob ZeRO
    state: residual None end-to-end (so every existing journal/checkpoint
    consumer sees the exact tree it saw before)."""
    policy = amp.get_policy("O2")
    params = _params(policy)
    z = amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data")
    pspecs = jax.tree.map(lambda _: P(), params)
    zstate, _ = z.zero_init(params, mesh, pspecs)
    assert zstate.residual is None
    abstract = z.zero_abstract_state(params, mesh, pspecs)
    assert abstract.residual is None


def test_int8_param_gather_end_to_end(mesh):
    """gather_dtype='int8' on the ZeRO-1/2 post-update gather: params come
    back rank-identical (every rank decodes the same quantized view) and
    within quantization tolerance of the bf16-gather run."""
    policy = amp.get_policy("O2")
    params = _params(policy)
    grads = _per_replica_grads(params)

    def run(gather_dtype):
        z = amp.MixedPrecisionOptimizer(
            FusedAdam(lr=1e-2), policy, zero_axis="data",
            gather_dtype=gather_dtype)
        pspecs = jax.tree.map(lambda _: P(), params)
        zstate, sspecs = z.zero_init(params, mesh, pspecs)
        gspec = jax.tree.map(lambda _: P("data"), params)

        def zstep(p, st, g):
            g = jax.tree.map(lambda x: x[0], g)
            scaled = jax.tree.map(lambda gg: gg * st.scaler.loss_scale, g)
            new_p, new_st, m = z.apply_gradients(st, p, scaled)
            return new_p, new_st, jax.tree.map(lambda x: x[None], new_p)

        fn = jax.jit(jax.shard_map(
            zstep, mesh=mesh, in_specs=(pspecs, sspecs, gspec),
            out_specs=(pspecs, sspecs, gspec), check_vma=False))
        stacked_g = jax.tree.map(lambda *xs: jnp.stack(xs), *grads[0])
        p, st, stacked = fn(params, zstate, stacked_g)
        return p, stacked

    p_q, stacked = run("int8")
    p_ref, _ = run("bf16")
    for name, leaf in stacked.items():
        arr = np.asarray(leaf, np.float32)
        for r in range(1, N):
            np.testing.assert_array_equal(arr[0], arr[r], err_msg=name)
    for name in params:
        a = np.asarray(p_q[name], np.float32)
        b = np.asarray(p_ref[name], np.float32)
        assert np.max(np.abs(a - b)) <= 0.02 * (np.max(np.abs(b)) + 1e-6), \
            name


def test_reduce_dtype_validation():
    policy = amp.get_policy("O2")
    with pytest.raises(ValueError, match="zero_axis"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    reduce_dtype="int8")
    with pytest.raises(ValueError, match="zero_level=3"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data", zero_level=3,
                                    reduce_dtype="int8")
    with pytest.raises(ValueError, match="stochastic_rounding"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data", reduce_dtype="e5m2",
                                    stochastic_rounding=True)
    with pytest.raises(ValueError):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data", reduce_dtype="int4")
    # int8 GATHER at zero3: the JIT gathers are differentiated — the
    # encode's round() would zero the grads through the AD transpose
    with pytest.raises(ValueError, match="zero_level=3"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data", zero_level=3,
                                    gather_dtype="int8")
    # bulk stacked gathers have no quantized path — loud error, not a cast
    from apex_tpu.optimizers.distributed import gather_stacked_leaf

    with pytest.raises(ValueError, match="per-LEAF"):
        gather_stacked_leaf(jnp.ones((2, 4)), (8,), jnp.float32, "data",
                            gather_dtype=jnp.int8)
    # the only integer wire is int8: a wider int must not silently route
    # through the 8-bit encode
    with pytest.raises(ValueError, match="int8"):
        amp.MixedPrecisionOptimizer(FusedAdam(lr=1e-2), policy,
                                    zero_axis="data", gather_dtype="int16")


def test_activation_comm_dtype_serial_twin_ignores_knob():
    """Serial-vs-sharded one-code-path convention: the axis=None twin of a
    quantized-SP config builds and runs with the knob ignored (same as
    sequence_parallel itself)."""
    from apex_tpu.models import GPTConfig, GPTModel

    m = GPTModel(GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2,
        num_attention_heads=4, max_seq_len=32, hidden_dropout=0.0,
        axis=None, sequence_parallel=True, activation_comm_dtype="int8",
        remat=False))
    assert m._acd is None
    toks = jnp.zeros((2, 32), jnp.int32)
    assert np.isfinite(float(m.loss(m.init(jax.random.PRNGKey(0)),
                                    toks, toks)))


# ---------------------------------------------------------------------------
# sequence-parallel activation wire (GPTConfig.activation_comm_dtype)
# ---------------------------------------------------------------------------


def test_activation_comm_dtype_requires_sequence_parallel():
    from apex_tpu.models import GPTConfig, GPTModel

    with pytest.raises(ValueError, match="activation_comm_dtype"):
        GPTModel(GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                           num_attention_heads=4, max_seq_len=32,
                           axis="model", activation_comm_dtype="int8"))
    with pytest.raises(ValueError, match="comm_dtype"):
        from apex_tpu.transformer import tensor_parallel as tp

        tp.RowParallelLinear(8, 8, axis="model", comm_dtype="int8")


def test_sp_quantized_activations_track_exact(mesh):
    """The sequence-parallel GPT forward+backward with int8 activation
    wire: loss and grads within quantization tolerance of the exact-wire
    run on the same tp=2 x dp=4 mesh (values AND gradients — the repo's
    serial-vs-sharded convention, relaxed to the lossy-wire tolerance)."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.parallel.distributed import allreduce_gradients_by_spec
    from apex_tpu.transformer import tensor_parallel as tp_mod

    tp_size = 2
    hybrid = mesh_lib.make_virtual_mesh(
        N, tensor_model_parallel_size=tp_size)
    try:
        losses, grads = {}, {}
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
        tgts = jnp.roll(toks, -1, axis=-1)
        for label, acd in (("exact", None), ("int8", "int8")):
            cfg = GPTConfig(
                vocab_size=128, hidden_size=64, num_layers=2,
                num_attention_heads=4, max_seq_len=32, hidden_dropout=0.0,
                axis=mesh_lib.AXIS_MODEL, sequence_parallel=True,
                activation_comm_dtype=acd, remat=False)
            model = GPTModel(cfg)
            params = model.init(jax.random.PRNGKey(0))
            specs = model.specs()
            placed = tp_mod.shard_params(params, specs, hybrid)

            def step(p, t, tg):
                loss, g = jax.value_and_grad(
                    lambda p: model.loss(p, t, tg))(p)
                g = allreduce_gradients_by_spec(g, specs)
                from apex_tpu.parallel import collectives

                return collectives.pmean(
                    loss, mesh_lib.get_gradient_reduction_axes()), g

            fn = jax.jit(jax.shard_map(
                step, mesh=hybrid,
                in_specs=(specs, P(mesh_lib.AXIS_DATA),
                          P(mesh_lib.AXIS_DATA)),
                out_specs=(P(), specs), check_vma=False))
            l, g = fn(placed, toks, tgts)
            losses[label], grads[label] = float(l), g
        assert abs(losses["int8"] - losses["exact"]) \
            < 0.05 * abs(losses["exact"]) + 1e-3, losses
        ge = jax.tree.leaves(grads["exact"])
        gq = jax.tree.leaves(grads["int8"])
        for a, b in zip(ge, gq):
            a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
            denom = np.max(np.abs(a)) + 1e-6
            assert np.max(np.abs(a - b)) / denom < 0.15, denom
    finally:
        mesh_lib.destroy_model_parallel()


# ---------------------------------------------------------------------------
# the convergence machine-check (report compare --loss-threshold)
# ---------------------------------------------------------------------------


def _paired_convergence(mesh, *, hidden, layers, seq, steps, loss_threshold):
    """Train the same tiny GPT twice over the data mesh — fp32-wire vs
    int8-wire ZeRO — journaling each, then gate with
    `monitor.report compare --loss-threshold` (the reusable machine check
    the 345M-class paired run uses on-chip)."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor import report
    from apex_tpu.monitor.journal import MetricsJournal

    policy = amp.get_policy("O2")
    cfg = GPTConfig(vocab_size=256, hidden_size=hidden, num_layers=layers,
                    num_attention_heads=4, max_seq_len=seq,
                    hidden_dropout=0.0, axis=None, remat=False)
    model = GPTModel(cfg)
    params0 = amp.cast_params(model.init(jax.random.PRNGKey(0)), policy)
    pspecs = jax.tree.map(lambda _: P(), params0)
    rng = np.random.default_rng(0)
    batches = [jnp.asarray(rng.integers(0, 256, (N * 2, seq)))
               for _ in range(steps)]

    paths = {}
    with tempfile.TemporaryDirectory() as d:
        for label, wire in (("fp32", None), ("int8", "int8")):
            z = amp.MixedPrecisionOptimizer(
                FusedAdam(lr=1e-3), policy, zero_axis="data",
                reduce_dtype=wire)
            state, sspecs = z.zero_init(params0, mesh, pspecs)

            def zstep(p, st, toks, tgts):
                def scaled(p):
                    return model.loss(p, toks, tgts) * st.scaler.loss_scale

                loss, g = jax.value_and_grad(scaled)(p)
                new_p, new_st, m = z.apply_gradients(st, p, g)
                from apex_tpu.parallel import collectives

                return (new_p, new_st,
                        collectives.pmean(loss, "data"), m)

            fn = jax.jit(jax.shard_map(
                zstep, mesh=mesh,
                in_specs=(pspecs, sspecs, P("data"), P("data")),
                out_specs=(pspecs, sspecs, P(), P()), check_vma=False))
            path = os.path.join(d, f"{label}.jsonl")
            p, st = params0, state
            with MetricsJournal(path, meta={"wire": label}) as j:
                for t, toks in enumerate(batches):
                    tgts = jnp.roll(toks, -1, axis=-1)
                    # the step scales the backward by the INPUT state's
                    # loss scale; unscale with that same value
                    scale_in = float(st.scaler.loss_scale)
                    j.step_start()
                    p, st, loss, m = fn(p, st, toks, tgts)
                    j.step_end(step=t, loss=float(loss) / scale_in,
                               tokens=N * 2 * seq, metrics=m)
            paths[label] = MetricsJournal.read(path)
        # threshold (throughput/MFU) wide open: on the CPU virtual mesh
        # the encode/decode pair costs real host time, which says nothing
        # about the wire — the convergence gate (loss_threshold) is the
        # check under test here
        res = report.compare(paths["fp32"], paths["int8"], threshold=0.9,
                             loss_threshold=loss_threshold)
    return res


def test_paired_wire_convergence_gate(mesh):
    """Fast twin of the paired convergence run: 6 steps of a tiny GPT at
    both wires; the int8 journal must pass `report compare` with the
    loss-threshold gate armed (and the gate must actually have run)."""
    res = _paired_convergence(mesh, hidden=64, layers=2, seq=32, steps=6,
                              loss_threshold=0.1)
    assert any(c["check"] == "loss_last" for c in res["checks"]), res
    assert res["ok"], res


@pytest.mark.slow
def test_paired_wire_convergence_gate_long(mesh):
    """The longer paired run (slow tier): more steps, tighter threshold —
    the closest this container gets to the 345M-class on-chip pairing
    (which uses the same report-compare gate via pretrain_gpt --journal
    --reduce-dtype int8)."""
    res = _paired_convergence(mesh, hidden=128, layers=4, seq=64, steps=20,
                              loss_threshold=0.05)
    assert res["ok"], res
