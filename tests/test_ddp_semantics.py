"""DDP semantics against analytic gradients
(reference: tests/distributed/DDP/ddp_race_condition_test.py:28-60 — a
``loss = sum(a * b * x)`` model whose gradients are known in closed form,
checked under aggressive bucketing/stream settings).

The race surface (buckets/streams) does not exist under jit, but the
*semantic* contract the test pins down — every rank's grad equals the
average of the closed-form per-rank grads, for every option combination —
is exactly what allreduce_gradients must guarantee.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.distributed import DistributedDataParallel, allreduce_gradients


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    if mesh_lib.model_parallel_is_initialized():
        mesh_lib.destroy_model_parallel()


def _setup():
    mesh = mesh_lib.make_virtual_mesh(4)
    # params replicated; per-rank inputs x differ => grads differ per rank
    params = {"a": jnp.arange(1.0, 4.0), "b": jnp.asarray([2.0, -1.0, 0.5])}
    x = jnp.arange(8.0).reshape(4, 2, 1) + 1.0  # (ranks*2, 1) sharded rows
    return mesh, params, x.reshape(8, 1)


def _analytic_avg_grads(params, x):
    # loss_r = sum_i sum_j a_i * b_i * x_rj  => da_i = b_i * sum(x_r), etc.
    sum_x_per_rank = np.asarray(x).reshape(4, 2).sum(axis=1)
    mean_sum_x = sum_x_per_rank.mean()
    return {
        "a": np.asarray(params["b"]) * mean_sum_x,
        "b": np.asarray(params["a"]) * mean_sum_x,
    }


@pytest.mark.parametrize("fp32,predivide", [(False, 1.0), (True, 1.0),
                                            (False, 2.0), (True, 4.0)])
def test_grads_match_closed_form(fp32, predivide):
    mesh, params, x = _setup()

    def loss_fn(p, x):
        return jnp.sum(p["a"] * p["b"] * jnp.sum(x))

    ddp = DistributedDataParallel(
        loss_fn, axes=mesh_lib.AXIS_DATA,
        allreduce_always_fp32=fp32, gradient_predivide_factor=predivide)

    fn = jax.jit(jax.shard_map(
        lambda p, x: ddp.value_and_grad(p, x)[1], mesh=mesh,
        in_specs=(P(), P(mesh_lib.AXIS_DATA)), out_specs=P(),
        check_vma=False))
    grads = fn(params, x)
    expect = _analytic_avg_grads(params, x)
    np.testing.assert_allclose(np.asarray(grads["a"]), expect["a"], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["b"]), expect["b"], rtol=1e-5)


def test_bf16_grads_reduce_in_fp32_when_asked():
    """allreduce_always_fp32 upcasts before the sum and restores the grad
    dtype after (distributed.py:52-58 dtype-split buckets). The fp32 path's
    mean must equal the exact average rounded once to bf16.

    (A numeric contrast against the non-upcast path is not asserted: XLA is
    free to — and on CPU does — accumulate bf16 psums in wider precision, so
    the two paths coincide there; the option's guarantee is that the math is
    fp32 *by contract* rather than by backend accident.)"""
    mesh = mesh_lib.make_virtual_mesh(4)
    g = jnp.asarray([256.0, 1.0, 1.0, 1.0], jnp.bfloat16)

    out32 = jax.jit(jax.shard_map(
        lambda g: allreduce_gradients(
            {"g": g}, mesh_lib.AXIS_DATA, allreduce_always_fp32=True)["g"],
        mesh=mesh,
        in_specs=P(mesh_lib.AXIS_DATA), out_specs=P(mesh_lib.AXIS_DATA),
        check_vma=False))(g)
    assert out32.dtype == jnp.bfloat16  # dtype restored after fp32 math
    np.testing.assert_allclose(
        np.asarray(out32, np.float32),
        np.full(4, np.float32(jnp.bfloat16(259.0 / 4))))
