"""Flash attention vs unfused reference — forward and gradients.

Reference test pattern: tests/L0/run_transformer/test_fused_softmax.py
(fused vs torch softmax equivalence) extended to full attention, covering
the surface of fmhalib/fast_multihead_attn (causal, additive mask,
cross-attention kv length, bf16).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import flash_attention, mha_reference

B, H, SQ, D = 2, 4, 128, 32


def _qkv(key, sq=SQ, sk=SQ, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, sq, D), dtype)
    k = jax.random.normal(kk, (B, H, sk, D), dtype)
    v = jax.random.normal(kv, (B, H, sk, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal, impl="pallas")
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1), sq=64, sk=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, impl="pallas",
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_additive_bias_mask():
    q, k, v = _qkv(jax.random.PRNGKey(2))
    # padding mask: last 32 keys masked for batch element 1 (b,1,1→sq,sk bias)
    bias = jnp.zeros((B, 1, SQ, SQ))
    bias = bias.at[1, :, :, -32:].set(-10000.0)
    out = flash_attention(q, k, v, bias, impl="pallas")
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, bias, impl="pallas")))(q)
    gr = jax.grad(lambda q: jnp.sum(mha_reference(q, k, v, bias)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4, atol=1e-4)


def test_bias_gradient_matches_reference():
    q, k, v = _qkv(jax.random.PRNGKey(8), sq=64, sk=64)
    bias = 0.1 * jax.random.normal(jax.random.PRNGKey(9), (B, H, 64, 64))

    gf = jax.grad(lambda b_: jnp.sum(
        flash_attention(q, k, v, b_, impl="pallas", block_q=16, block_k=16) ** 2))(bias)
    gr = jax.grad(lambda b_: jnp.sum(mha_reference(q, k, v, b_) ** 2))(bias)
    assert float(jnp.max(jnp.abs(gr))) > 1e-3  # reference grad is nonzero
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=1e-4, atol=1e-4)


def test_broadcast_bias_gradient():
    """ALiBi/T5-style bias broadcast over batch (1,h,sq,sk) and the key-padding
    shape (b,1,1,sk) must both work and receive summed gradients."""
    q, k, v = _qkv(jax.random.PRNGKey(10), sq=32, sk=32)
    for shape in [(1, H, 32, 32), (B, 1, 1, 32), (1, 1, 32, 32)]:
        bias = 0.1 * jax.random.normal(jax.random.PRNGKey(11), shape)
        out = flash_attention(q, k, v, bias, impl="pallas", block_q=8, block_k=8)
        ref = mha_reference(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=str(shape))
        gf = jax.grad(lambda b_: jnp.sum(
            flash_attention(q, k, v, b_, impl="pallas", block_q=8, block_k=8) ** 2))(bias)
        gr = jax.grad(lambda b_: jnp.sum(mha_reference(q, k, v, b_) ** 2))(bias)
        assert gf.shape == shape
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4, err_msg=str(shape))


def test_causal_bias_gradient():
    q, k, v = _qkv(jax.random.PRNGKey(12), sq=64, sk=64)
    bias = 0.1 * jax.random.normal(jax.random.PRNGKey(13), (1, H, 64, 64))
    gf = jax.grad(lambda b_: jnp.sum(
        flash_attention(q, k, v, b_, causal=True, impl="pallas",
                        block_q=16, block_k=16) ** 2))(bias)
    gr = jax.grad(lambda b_: jnp.sum(mha_reference(q, k, v, b_, causal=True) ** 2))(bias)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=1e-4, atol=1e-4)


def test_cross_attention_kv_longer():
    q, k, v = _qkv(jax.random.PRNGKey(3), sq=32, sk=128)
    out = flash_attention(q, k, v, impl="pallas", block_q=16, block_k=32)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_bf16_tolerance():
    q, k, v = _qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, impl="pallas")
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=3e-2, atol=3e-2
    )


def test_unaligned_falls_back_to_xla():
    q, k, v = _qkv(jax.random.PRNGKey(5), sq=30, sk=30)
    out = flash_attention(q, k, v, impl="auto")  # 30 % 8 != 0 → xla path
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_fused_scale_mask_softmax_module():
    from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax

    x = jax.random.normal(jax.random.PRNGKey(6), (2, 4, 16, 16), jnp.bfloat16)
    mask = jax.random.bernoulli(jax.random.PRNGKey(7), 0.3, (2, 1, 16, 16))
    sm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding, scale=0.5)
    y = sm(x, mask)
    assert y.dtype == jnp.float32  # softmax_in_fp32 default
    ref = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding, scale=0.5,
                                fused=False)(x, mask)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-2, atol=2e-2)

    causal = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal,
                                   softmax_in_fp32=False)
    yc = causal(x)
    assert yc.dtype == jnp.bfloat16
    # each row sums to 1 and is upper-triangular-masked
    s = np.asarray(yc, np.float32).sum(-1)
    np.testing.assert_allclose(s, np.ones_like(s), rtol=2e-2)
    assert np.asarray(yc, np.float32)[0, 0, 0, 1:].max() == 0.0

    # causal + padding mask composed in one fused pass
    both = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)(x, mask)
    ref_both = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal,
                                     fused=False)(x, mask)
    np.testing.assert_allclose(np.asarray(both), np.asarray(ref_both),
                               rtol=2e-2, atol=2e-2)

    # unaligned sk falls back to the unfused path instead of the kernel
    x_odd = jax.random.normal(jax.random.PRNGKey(8), (2, 2, 12, 30))
    y_odd = FusedScaleMaskSoftmax()(x_odd)
    ref_odd = FusedScaleMaskSoftmax(fused=False)(x_odd)
    np.testing.assert_allclose(np.asarray(y_odd), np.asarray(ref_odd), rtol=1e-5)


# ---------------------------------------------------------------------------
# Packed varlen (segment ids): the fmha cu_seqlens semantics computed
# natively by the kernel with block skipping (VERDICT r2 missing #2).
# ---------------------------------------------------------------------------


def _packed_case(key, lengths, h=4, d=32, dtype=jnp.float32):
    total = sum(lengths)
    qkv = jax.random.normal(key, (total, 3, h, d), dtype)
    cu = jnp.asarray(np.cumsum([0] + list(lengths)), jnp.int32)
    return qkv, cu


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lengths", [[128, 64, 192, 128], [512], [8, 8, 496]])
def test_fmha_packed_matches_reference(causal, lengths):
    from apex_tpu.contrib.fmha import fmha, fmha_reference

    qkv, cu = _packed_case(jax.random.PRNGKey(0), lengths)
    out = fmha(qkv, cu, max_seqlen=512, causal=causal)
    ref = fmha_reference(qkv, cu, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_fmha_trailing_padding_rows_are_zero():
    """Tokens past cu_seqlens[-1] are padding: output exactly 0."""
    from apex_tpu.contrib.fmha import fmha

    qkv = jax.random.normal(jax.random.PRNGKey(0), (256, 3, 4, 32))
    cu = jnp.asarray([0, 100, 180], jnp.int32)  # 76 trailing pad tokens
    out = fmha(qkv, cu, max_seqlen=512)
    np.testing.assert_array_equal(np.asarray(out[180:]), 0.0)


def test_fmha_gradients_match_padded_reference():
    """Grads through the packed kernel == per-sequence dense grads."""
    from apex_tpu.contrib.fmha import fmha

    lengths = [128, 256, 128]
    qkv, cu = _packed_case(jax.random.PRNGKey(1), lengths)
    w = jax.random.normal(jax.random.PRNGKey(2), (sum(lengths), 4, 32))

    def packed_loss(qkv):
        return jnp.sum(fmha(qkv, cu, max_seqlen=512, causal=True) * w)

    def dense_loss(qkv):
        total = 0.0
        for i in range(len(lengths)):
            s, e = int(cu[i]), int(cu[i + 1])
            q, k, v = (qkv[s:e, j].transpose(1, 0, 2)[None] for j in range(3))
            o = mha_reference(q, k, v, causal=True)
            total = total + jnp.sum(o[0].transpose(1, 0, 2) * w[s:e])
        return total

    g_packed = jax.grad(packed_loss)(qkv)
    g_dense = jax.grad(dense_loss)(qkv)
    np.testing.assert_allclose(np.asarray(g_packed), np.asarray(g_dense),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_pallas_matches_xla(causal):
    """Direct segment-ids surface: kernel (with block skip) vs XLA mask."""
    q, k, v = _qkv(jax.random.PRNGKey(3), sq=256, sk=256)
    seg = jnp.asarray(
        np.repeat([1, 2, 3, 9], [64, 96, 64, 32])[None].repeat(B, 0))
    out_p = flash_attention(q, k, v, segment_ids=(seg, seg), pad_id=9,
                            causal=causal, impl="pallas")
    out_x = flash_attention(q, k, v, segment_ids=(seg, seg), pad_id=9,
                            causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)


def test_segment_block_skip_equals_mask_only():
    """contiguous_segments=True (block skipping) computes the same function
    as mask-only evaluation — skipped blocks really were all-masked."""
    q, k, v = _qkv(jax.random.PRNGKey(4), sq=512, sk=512)
    seg = jnp.asarray(
        np.repeat([1, 2, 3], [128, 256, 128])[None].repeat(B, 0))
    out_skip = flash_attention(q, k, v, segment_ids=(seg, seg),
                               contiguous_segments=True, impl="pallas")
    out_mask = flash_attention(q, k, v, segment_ids=(seg, seg),
                               contiguous_segments=False, impl="pallas")
    np.testing.assert_allclose(np.asarray(out_skip), np.asarray(out_mask),
                               rtol=1e-6, atol=1e-6)


# -- streamed kernels (block-bounded VMEM, VERDICT r3 ask #3) ----------------


@pytest.mark.parametrize("causal", [False, True])
def test_streamed_matches_resident(causal):
    """stream='always' (K/V loop in the grid, scratch accumulators) computes
    the same function — values AND grads — as the resident layout."""
    q, k, v = _qkv(jax.random.PRNGKey(5), sq=256, sk=256)
    kw = dict(causal=causal, impl="pallas", block_q=64, block_k=64)
    out_s = flash_attention(q, k, v, stream="always", **kw)
    out_r = flash_attention(q, k, v, stream="never", **kw)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                               rtol=1e-6, atol=1e-6)

    def loss(mode):
        return lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, stream=mode, **kw) ** 2)

    gs = jax.grad(loss("always"), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss("never"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("contiguous", [False, True])
def test_streamed_segments_match_xla(contiguous):
    """Streamed segment path (ids + metadata arriving blockwise) vs the XLA
    mask, with padding and causal, fwd + grads."""
    q, k, v = _qkv(jax.random.PRNGKey(6), sq=256, sk=256)
    seg = jnp.asarray(
        np.repeat([1, 2, 3, 9], [64, 96, 64, 32])[None].repeat(B, 0))
    kw = dict(segment_ids=(seg, seg), pad_id=9, causal=True)
    out_s = flash_attention(q, k, v, stream="always", impl="pallas",
                            block_q=64, block_k=128,
                            contiguous_segments=contiguous, **kw)
    out_x = flash_attention(q, k, v, impl="xla", **kw)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)
    gs = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, stream="always", impl="pallas", block_q=64, block_k=128,
        contiguous_segments=contiguous, **kw) ** 2))(q)
    gx = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, impl="xla", **kw) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gx),
                               rtol=1e-4, atol=1e-4)


def test_streamed_ring_offsets_match_resident():
    """The ring-attention entry points (_flash_fwd/_flash_bwd with global
    position offsets) agree between streamed and resident layouts."""
    from apex_tpu.ops.flash_attention import _flash_bwd, _flash_fwd

    q, k, v = _qkv(jax.random.PRNGKey(7), sq=128, sk=128)
    offs = jnp.asarray([256, 128], jnp.int32)  # q shard after k shard
    kw = dict(scale=D ** -0.5, causal=True, blk_q=64, blk_k=64)
    o_s, lse_s = _flash_fwd(q, k, v, None, offs, stream=True, **kw)
    o_r, lse_r = _flash_fwd(q, k, v, None, offs, stream=False, **kw)
    np.testing.assert_allclose(np.asarray(o_s), np.asarray(o_r),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_r),
                               rtol=1e-6, atol=1e-6)
    do = jax.random.normal(jax.random.PRNGKey(8), q.shape, q.dtype)
    g_s = _flash_bwd(q, k, v, None, offs, o_s, lse_s, do, stream=True, **kw)
    g_r = _flash_bwd(q, k, v, None, offs, o_r, lse_r, do, stream=False, **kw)
    for a, b in zip(g_s[:3], g_r[:3]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_stream_auto_threshold():
    """'auto' stays resident at model shapes and switches to streamed when
    the resident residency estimate crosses the VMEM budget (the s≈8k
    segment configs that hit the 16 MB wall in r3)."""
    from apex_tpu.ops.flash_attention import (
        _RESIDENT_VMEM_BUDGET,
        _resident_vmem_bytes,
    )

    small = _resident_vmem_bytes(1024, 1024, 64, 1024, 1024, 2, False, False)
    assert small <= _RESIDENT_VMEM_BUDGET
    # packed fmha at realistic total token counts (ADVICE r3 medium):
    # 32k packed tokens with segment operands must stream
    packed = _resident_vmem_bytes(32768, 32768, 64, 1024, 1024, 2, False, True)
    assert packed > _RESIDENT_VMEM_BUDGET
    # long-context causal at 8k with segments (r3's VMEM-wall case)
    long_seg = _resident_vmem_bytes(8192, 8192, 64, 1024, 1024, 2, False, True)
    assert long_seg > _RESIDENT_VMEM_BUDGET
    # LANE PADDING must be counted: at d=32, s=8192 the resident dK/dV
    # pass allocates 17.3 MB on TPU (minor dims pad to 128 lanes; the
    # (sq, 1) lse/delta windows cost sq*128*4 each) though the unpadded
    # arithmetic says 1.6 MB — the un-streamable config that failed to
    # compile live in r4. Must stream.
    d32 = _resident_vmem_bytes(8192, 8192, 32, 1024, 1024, 2, False, False)
    assert d32 > _RESIDENT_VMEM_BUDGET
    # and the padding floor must not push model shapes (1k-2k, d=64) off
    # the measured-faster resident path
    assert _resident_vmem_bytes(
        2048, 2048, 64, 1024, 1024, 2, False, False) <= _RESIDENT_VMEM_BUDGET


def test_fully_masked_causal_segment_row_is_zero_both_impls():
    """ADVICE r3 low #2: a row whose same-segment keys all sit ABOVE the
    causal diagonal is fully masked only once the causal mask is applied;
    kernel and XLA fallback must agree it outputs exactly 0."""
    sq = sk = 128
    q, k, v = _qkv(jax.random.PRNGKey(9), sq=sq, sk=sk)
    # q position 0 belongs to segment 2, but all segment-2 keys live in the
    # upper half of the sequence (causally invisible from position 0)
    q_seg = jnp.asarray(np.r_[[2], np.ones(sq - 1, int)][None].repeat(B, 0))
    kv_seg = jnp.asarray(np.repeat([1, 2], [64, 64])[None].repeat(B, 0))
    for impl in ("pallas", "xla"):
        out = flash_attention(q, k, v, segment_ids=(q_seg, kv_seg),
                              causal=True, impl=impl,
                              contiguous_segments=False)
        np.testing.assert_array_equal(
            np.asarray(out[:, :, 0, :]), 0.0,
            err_msg=f"{impl}: causally-fully-masked row must be zero")


def test_segment_bounds_cover_exact_blocks():
    """The precomputed block ranges are tight: for blk=128 segments aligned
    to block boundaries, each q block's [start, end) spans exactly its own
    segment's k blocks."""
    from apex_tpu.ops.flash_attention import _seg_metadata

    seg = jnp.asarray(np.repeat([1, 2, 2, 3], 128)[None])  # (1, 512)
    bq, bk, _, _ = _seg_metadata(seg, seg, 128, 128)
    np.testing.assert_array_equal(np.asarray(bq[0, 0]), [0, 1, 1, 3])
    np.testing.assert_array_equal(np.asarray(bq[0, 1]), [1, 3, 3, 4])
    np.testing.assert_array_equal(np.asarray(bk[0, 0]), [0, 1, 1, 3])
    np.testing.assert_array_equal(np.asarray(bk[0, 1]), [1, 3, 3, 4])


# -- sliding-window (local) attention (beyond-reference capability) ----------


def _window_bias(sq, sk, window, causal):
    """Explicit additive mask implementing the window semantics, for
    checking mha_reference's window path independently."""
    q_pos = np.arange(sq)[:, None]
    k_pos = np.arange(sk)[None, :]
    bad = (q_pos - k_pos) >= window
    if causal:
        bad |= k_pos > q_pos
    else:
        bad |= (k_pos - q_pos) >= window
    return jnp.asarray(np.where(bad, -1e30, 0.0)[None, None])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("window", [16, 24, 100])
def test_window_reference_matches_explicit_mask(causal, window):
    """mha_reference's window path equals dense attention under the
    equivalent explicit mask (window 24 is not a block multiple; 100
    covers most of the 128-seq band)."""
    q, k, v = _qkv(jax.random.PRNGKey(20))
    got = mha_reference(q, k, v, causal=causal, window=window)
    want = mha_reference(q, k, v, _window_bias(SQ, SQ, window, causal),
                         causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("window", [16, 24, 100])
def test_window_pallas_matches_xla(causal, window):
    """Kernel window path (with block-range skipping at block_q/k=16, so
    the clip bounds are exercised hard) vs the XLA window path — values
    and all three input gradients."""
    q, k, v = _qkv(jax.random.PRNGKey(21), sq=64, sk=64)
    kw = dict(causal=causal, window=window)

    out_p = flash_attention(q, k, v, impl="pallas", block_q=16, block_k=16,
                            **kw)
    out_x = flash_attention(q, k, v, impl="xla", **kw)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)

    gp = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, impl="pallas", block_q=16, block_k=16, **kw) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, impl="xla", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_window_streamed_matches_resident(causal):
    """Streamed kernels (grid-level pl.when skip) compute the same window
    function as the resident layout — values and grads."""
    q, k, v = _qkv(jax.random.PRNGKey(22), sq=256, sk=256)
    kw = dict(causal=causal, window=48, impl="pallas", block_q=64,
              block_k=64)
    out_s = flash_attention(q, k, v, stream="always", **kw)
    out_r = flash_attention(q, k, v, stream="never", **kw)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                               rtol=1e-6, atol=1e-6)
    gs = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, stream="always", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, stream="never", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_window_composes_with_segments():
    """Window + packed segment ids: both masks apply (a query sees only
    same-segment keys inside its window), kernel vs XLA."""
    q, k, v = _qkv(jax.random.PRNGKey(23), sq=256, sk=256)
    seg = jnp.asarray(
        np.repeat([1, 2, 3, 9], [64, 96, 64, 32])[None].repeat(B, 0))
    kw = dict(segment_ids=(seg, seg), pad_id=9, causal=True, window=40)
    out_p = flash_attention(q, k, v, impl="pallas",
                            contiguous_segments=True, **kw)
    out_x = flash_attention(q, k, v, impl="xla", **kw)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)


def test_window_covering_everything_is_dense():
    """window >= seq is dense attention (and takes the no-window kernel)."""
    q, k, v = _qkv(jax.random.PRNGKey(24), sq=64, sk=64)
    out_w = flash_attention(q, k, v, causal=True, window=64, impl="pallas",
                            block_q=16, block_k=16)
    out_d = flash_attention(q, k, v, causal=True, impl="pallas",
                            block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out_w), np.asarray(out_d),
                               rtol=0, atol=0)


def test_window_validation():
    q, k, v = _qkv(jax.random.PRNGKey(25), sq=64, sk=64)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, window=0)


def test_window_ring_offsets_match_global():
    """Window masking uses GLOBAL positions: running the kernels shard-wise
    with ring offsets reproduces the corresponding block of full-sequence
    window attention (the context-parallel contract)."""
    from apex_tpu.ops.flash_attention import _flash_fwd

    sq = 128
    q, k, v = _qkv(jax.random.PRNGKey(26), sq=2 * sq, sk=2 * sq)
    want = mha_reference(q, k, v, causal=True, window=48)
    # shard 1's q block against shard 0's k block plus its own: two ring
    # steps of a cp=2 ring (q_off = sq; k_off = 0 then sq)
    kw = dict(scale=D ** -0.5, causal=True, blk_q=64, blk_k=64, window=48)
    q1 = q[:, :, sq:]
    o_parts = []
    lse_parts = []
    for k_off, ks in ((0, slice(0, sq)), (sq, slice(sq, 2 * sq))):
        offs = jnp.asarray([sq, k_off], jnp.int32)
        o_s, lse_s = _flash_fwd(q1, k[:, :, ks], v[:, :, ks], None, offs,
                                **kw)
        o_parts.append(o_s)
        lse_parts.append(lse_s)
    # online-softmax merge of the two ring steps (what ring.py does)
    m = jnp.maximum(lse_parts[0], lse_parts[1])
    w0 = jnp.exp(lse_parts[0] - m)
    w1 = jnp.exp(lse_parts[1] - m)
    merged = (o_parts[0] * w0 + o_parts[1] * w1) / (w0 + w1)
    np.testing.assert_allclose(np.asarray(merged),
                               np.asarray(want[:, :, sq:]),
                               rtol=2e-5, atol=2e-5)


def test_window_cross_shape_fully_masked_rows_zero_both_impls():
    """Cross-attention (sq != sk) with a window: queries whose whole band
    lies beyond the key sequence are fully masked and must output exactly
    0 on BOTH impls (the XLA path's zeroing is gated on `masked`, which
    must include the window case — r5 review finding)."""
    q, k, v = _qkv(jax.random.PRNGKey(27), sq=128, sk=32)
    for impl in ("pallas", "xla"):
        out = flash_attention(q, k, v, causal=True, window=16, impl=impl,
                              block_q=16, block_k=16)
        # rows p >= sk + window - 1 = 47 see no keys at all
        np.testing.assert_array_equal(
            np.asarray(out[:, :, 48:, :]), 0.0,
            err_msg=f"{impl}: window-fully-masked rows must be zero")
    out_p = flash_attention(q, k, v, causal=True, window=16, impl="pallas",
                            block_q=16, block_k=16)
    out_x = flash_attention(q, k, v, causal=True, window=16, impl="xla")
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_window_restricted_streamed_grid(causal):
    """The window-RESTRICTED streamed grid (inner extent < nk, trips
    remapped via _window_grid) — both causal and bidirectional branches
    must be live (sq=512, blk=64, window=16 -> width 3 of nk=8) and match
    the resident layout, values and grads."""
    from apex_tpu.ops.flash_attention import _window_grid

    assert _window_grid(64, 64, 8, causal, 16) is not None
    q, k, v = _qkv(jax.random.PRNGKey(28), sq=512, sk=512)
    kw = dict(causal=causal, window=16, impl="pallas", block_q=64,
              block_k=64)
    out_s = flash_attention(q, k, v, stream="always", **kw)
    out_r = flash_attention(q, k, v, stream="never", **kw)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                               rtol=1e-6, atol=1e-6)
    gs = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, stream="always", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, stream="never", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("contiguous", [False, True])
def test_window_restricted_grid_with_segments(contiguous):
    """Restricted windowed grid + segment ids: the remapped kmap/qmap
    BlockSpecs must fetch the RIGHT id blocks and metadata (sq=512,
    window=32, blk 64/128 -> restricted), kernel vs XLA, fwd + grads.

    Grads over argnums=(0, 1, 2): dq exercises the remapped dQ pass, but
    dk/dv come from the SEPARATE streamed dK/dV pass, whose qmap remap
    (which q trips each k block sees under the window restriction) the
    dq assertion cannot catch (ADVICE finding: a qmap-remap bug slipped
    through while only dq was value-asserted)."""
    from apex_tpu.ops.flash_attention import _window_grid

    assert _window_grid(64, 128, 4, True, 32) is not None
    q, k, v = _qkv(jax.random.PRNGKey(29), sq=512, sk=512)
    seg = jnp.asarray(
        np.repeat([1, 2, 3, 9], [128, 192, 128, 64])[None].repeat(B, 0))
    kw = dict(segment_ids=(seg, seg), pad_id=9, causal=True, window=32)
    out_s = flash_attention(q, k, v, stream="always", impl="pallas",
                            block_q=64, block_k=128,
                            contiguous_segments=contiguous, **kw)
    out_x = flash_attention(q, k, v, impl="xla", **kw)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)
    gs = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, stream="always", impl="pallas", block_q=64, block_k=128,
        contiguous_segments=contiguous, **kw) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, impl="xla", **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gs, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"{name} mismatch")


def test_stream_auto_crossover_at_4k():
    """'auto' streams at s >= 4096 even though the resident layout now
    COMPILES there (dense lse tables removed its VMEM wall): measured
    on-chip, resident dK/dV falls behind streamed past ~2k (27.4 vs
    17.7 ms at 4096 d=64) because it re-streams whole-sq q/do per k
    block. Asserted on the shared decision helper (jit-cache-proof)."""
    from apex_tpu.ops.flash_attention import _auto_stream

    wall, crossover = _auto_stream(4096, 4096, 64, 1024, 1024, 2,
                                   False, False)
    assert crossover and not wall  # streams on throughput, not memory
    wall, crossover = _auto_stream(2048, 2048, 64, 1024, 1024, 2,
                                   False, False)
    assert not crossover and not wall  # model shapes stay resident


def test_stream_auto_crossover_scales_with_row_bytes():
    """The crossover was MEASURED at d=64 bf16; the resident dK/dV DMA
    bill moves LANE-PADDED rows (minor dim pads to 128 lanes — the same
    rule _resident_vmem_bytes counts), so every d <= 128 bf16 shares the
    measured 4096 boundary, and the boundary halves only when the padded
    row actually doubles: fp32 itemsize, or d > 128 (ADVICE finding: the
    scaling must be documented against its d=64 measurement basis, not
    guessed from unpadded arithmetic)."""
    from apex_tpu.ops.flash_attention import _auto_stream

    # the whole d=32..128 bf16 family DMAs identical 256 B padded rows:
    # one measured boundary, 4096
    for d in (32, 64, 128):
        _, crossover = _auto_stream(2048, 2048, d, 1024, 1024, 2,
                                    False, False)
        assert not crossover, d
        _, crossover = _auto_stream(4096, 4096, d, 1024, 1024, 2,
                                    False, False)
        assert crossover, d
    # fp32 doubles the padded row -> boundary halves to 2048
    _, crossover = _auto_stream(2048, 2048, 64, 1024, 1024, 4,
                                False, False)
    assert crossover
    _, crossover = _auto_stream(1024, 1024, 64, 1024, 1024, 4,
                                False, False)
    assert not crossover
    # d=256 bf16: two padded lanes-groups per row -> 2048 as well
    _, crossover = _auto_stream(2048, 2048, 256, 1024, 1024, 2,
                                False, False)
    assert crossover


def test_bias_past_crossover_keeps_resident_kernel(monkeypatch):
    """Dense bias + the >= 4k crossover: the streamed path has no dbias
    pass, but the resident kernel COMPILES there (no VMEM wall) and
    beats dense XLA attention — auto must keep it rather than fall back
    to mha_reference (r5 review finding)."""
    from apex_tpu.ops.flash_attention import _auto_stream

    # blk_q=128 keeps the resident bias window small: crossover fires
    # but the wall does NOT — the branch under test
    wall, crossover = _auto_stream(4096, 4096, D, 128, 128, 2, True, False)
    assert crossover and not wall
    q, k, v = _qkv(jax.random.PRNGKey(31), sq=4096, sk=4096,
                   dtype=jnp.bfloat16)
    bias = jnp.zeros((B, 1, 4096, 4096))
    bias = bias.at[1, :, :, -64:].set(-10000.0)
    ref = mha_reference(q, k, v, bias, causal=True)
    # the oracle below compares against mha_reference, so an XLA-fallback
    # regression would pass trivially — assert the dispatch itself: the
    # fallback must NOT run inside this flash_attention call
    import apex_tpu.ops.flash_attention as fa

    def no_fallback(*a, **kw):
        raise AssertionError(
            "crossover-only bias case fell back to mha_reference")

    monkeypatch.setattr(fa, "mha_reference", no_fallback)
    out = fa.flash_attention(q, k, v, bias, causal=True, impl="pallas",
                             block_q=128, block_k=128)
    monkeypatch.undo()
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)
