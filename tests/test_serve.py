"""Serving engine (apex_tpu/serve): paged KV cache, flash-decode,
continuous batching.

The tier-1 equivalence gate (ISSUE 10): greedy decode through the paged KV
cache must match the argmax of a full-context forward pass at every
generated position — serial AND tp=2-sharded, with and without
``attention_window`` — plus host-side unit invariants for the block
allocator / scheduler / sampler, the flash-decode kernel against its dense
oracle, request-journal robustness under mid-request truncation, and the
decode-recompile tripwire on the engine's real tick argument stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.ops.flash_decode import flash_decode, paged_attention_reference
from apex_tpu.serve import (
    BlockAllocator,
    CacheOutOfBlocks,
    ContinuousBatcher,
    Engine,
    Request,
    ServeConfig,
)
from apex_tpu.serve.cache import NULL_BLOCK, blocks_for
from apex_tpu.serve.sampler import fold_tick, sample_tokens

BASE = dict(vocab_size=61, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_seq_len=64, hidden_dropout=0.0,
            compute_dtype=jnp.float32, remat=False)


def make_requests(vocab=61, spec=((5, 6), (11, 5), (3, 7))):
    rng = np.random.default_rng(7)
    return [Request(prompt=list(rng.integers(0, vocab, n)),
                    max_new_tokens=m, request_id=i)
            for i, (n, m) in enumerate(spec)]


def assert_greedy_matches_oracle(model, params, results):
    """Every generated token == argmax of ONE full-context forward over
    the finished sequence (the gate's phrasing: bit-match at every
    position)."""
    for req in results.values():
        seq = list(req.prompt) + req.tokens
        logits = model.apply(params, jnp.asarray([seq], jnp.int32))
        ref = np.asarray(jnp.argmax(logits[0], -1))
        for t in range(len(req.prompt), len(seq)):
            assert int(ref[t - 1]) == seq[t], (
                req.request_id, t, int(ref[t - 1]), seq[t])


# ---------------------------------------------------------------------------
# host-side units: allocator, scheduler, sampler
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_null_block_reserved_and_ids_unique(self):
        a = BlockAllocator(8)
        got = a.alloc_many(7)
        assert NULL_BLOCK not in got and len(set(got)) == 7
        assert a.available == 0

    def test_exhaustion_raises_and_free_restores(self):
        a = BlockAllocator(4)
        got = a.alloc_many(3)
        with pytest.raises(CacheOutOfBlocks):
            a.alloc()
        a.free(got[:2])
        assert a.available == 2
        again = a.alloc_many(2)
        assert set(again) == set(got[:2])  # freed pages reuse (no fragments)

    def test_double_free_and_bad_ids_raise(self):
        a = BlockAllocator(4)
        b = a.alloc()
        a.free([b])
        with pytest.raises(ValueError):
            a.free([b])
        with pytest.raises(ValueError):
            a.free([NULL_BLOCK])
        with pytest.raises(ValueError):
            a.free([99])

    def test_blocks_for(self):
        assert [blocks_for(n, 8) for n in (1, 8, 9, 16, 17)] == [1, 1, 2, 2, 3]


class TestContinuousBatcher:
    def test_fifo_admission_and_slot_reuse(self):
        b = ContinuousBatcher(2)
        reqs = make_requests(spec=((3, 2), (3, 2), (3, 2), (3, 2)))
        for r in reqs:
            b.submit(r)
        placed = b.admit()
        assert [(s, r.request_id) for s, r in placed] == [(0, 0), (1, 1)]
        assert b.queue_depth == 2 and b.occupancy == 1.0
        assert b.admit() == []  # full: nothing admitted
        done = b.retire(0)
        assert done.request_id == 0
        placed = b.admit()  # queue head takes the freed slot
        assert [(s, r.request_id) for s, r in placed] == [(0, 2)]
        b.retire(1)
        b.retire(0)
        assert [(s, r.request_id) for s, r in b.admit()] == [(0, 3)]
        with pytest.raises(ValueError):
            b.retire(1)  # empty slot

    def test_request_validation(self):
        with pytest.raises(ValueError):
            Request(prompt=[], max_new_tokens=1)
        with pytest.raises(ValueError):
            Request(prompt=[1], max_new_tokens=0)


class TestSampler:
    def test_greedy_is_argmax_and_needs_no_keys(self):
        logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.5]])
        assert sample_tokens(logits).tolist() == [1, 0]

    def test_top_k_restricts_support_and_keys_reproduce(self):
        logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 16)),
                             jnp.float32)
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        draw1 = sample_tokens(logits, keys, temperature=1.0, top_k=3)
        draw2 = sample_tokens(logits, keys, temperature=1.0, top_k=3)
        assert draw1.tolist() == draw2.tolist()  # deterministic per key
        top3 = np.argsort(np.asarray(logits), -1)[:, -3:]
        for i, t in enumerate(draw1.tolist()):
            assert t in top3[i]
        # fold_tick decorrelates ticks without changing shapes
        draw3 = sample_tokens(logits, fold_tick(keys, jnp.asarray(1)),
                              temperature=1.0, top_k=3)
        assert draw3.shape == draw1.shape


# ---------------------------------------------------------------------------
# flash-decode kernel vs oracles
# ---------------------------------------------------------------------------


class TestFlashDecode:
    def _pages(self, kh=2, d=16, n=10, blk=8):
        rng = np.random.default_rng(3)
        kp = jnp.asarray(rng.normal(size=(n, blk, kh, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(n, blk, kh, d)), jnp.float32)
        return kp, vp

    @pytest.mark.parametrize("window", [None, 5])
    def test_pallas_interpret_matches_xla_reference(self, window):
        kp, vp = self._pages()
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.normal(size=(3, 4, 16)), jnp.float32)  # GQA G=2
        tables = jnp.asarray(
            rng.permutation(np.arange(1, 13)).reshape(3, 4), jnp.int32)
        lengths = jnp.asarray([17, 0, 32], jnp.int32)  # incl. an idle slot
        ref = paged_attention_reference(q, kp, vp, tables, lengths,
                                        window=window)
        ker = flash_decode(q, kp, vp, tables, lengths, window=window,
                           impl="pallas")
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=1e-5)
        assert np.allclose(np.asarray(ref[1]), 0.0)  # idle slot: exact 0

    def test_reference_matches_dense_attention_last_row(self):
        """The decode primitive IS the last row of dense attention over
        the same keys (the gate's numerical core): gather the pages,
        broadcast kv heads GQA-style, compare against mha_reference."""
        kp, vp = self._pages()
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
        tables = jnp.asarray([[3, 1, 7, 2]], jnp.int32)
        L = 19
        out = paged_attention_reference(q, kp, vp, tables,
                                        jnp.asarray([L], jnp.int32))
        k = jnp.repeat(kp[tables[0]].reshape(-1, 2, 16)[:L], 2,
                       axis=1).transpose(1, 0, 2)[None]
        v = jnp.repeat(vp[tables[0]].reshape(-1, 2, 16)[:L], 2,
                       axis=1).transpose(1, 0, 2)[None]
        dense = mha_reference(q[:, :, None, :], k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense[:, :, 0]),
                                   atol=1e-5)

    def test_validation(self):
        kp, vp = self._pages()
        q = jnp.zeros((1, 3, 16), jnp.float32)  # 3 % 2 != 0
        with pytest.raises(ValueError):
            flash_decode(q, kp, vp, jnp.zeros((1, 2), jnp.int32),
                         jnp.zeros((1,), jnp.int32))


# ---------------------------------------------------------------------------
# the engine equivalence gate
# ---------------------------------------------------------------------------


class TestEngineEquivalence:
    @pytest.mark.parametrize("window", [None, 8])
    def test_greedy_decode_matches_full_forward(self, window):
        """The serving serial==sharded analog, serial half: greedy decode
        via the paged cache == full-context forward argmax at every
        position, with and without the sliding window."""
        cfg = GPTConfig(axis=None, attention_window=window, **BASE)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        eng = Engine(model, params,
                     ServeConfig(max_batch=2, max_seq=48, block_size=8))
        results = eng.run(make_requests())
        assert len(results) == 3
        assert_greedy_matches_oracle(model, params, results)
        assert eng.allocator.used == 0 and eng.batcher.idle

    @pytest.mark.parametrize("window", [None, 8])
    def test_tp2_matches_serial(self, window):
        """The sharded half: a TP=2 engine (kv heads + vocab sharded,
        mappings.py conjugates in embed/proj/head) must emit the same
        token streams as the serial build of the same weights — with and
        without the sliding window."""
        from apex_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_virtual_mesh(8, tensor_model_parallel_size=2)
        try:
            base = dict(BASE, vocab_size=64,  # vocab shards V/tp ways
                        attention_window=window)
            model_s = GPTModel(GPTConfig(axis=None, **base))
            model_tp = GPTModel(GPTConfig(axis=mesh_lib.AXIS_MODEL, **base))
            params = model_s.init(jax.random.PRNGKey(0))
            scfg = ServeConfig(max_batch=2, max_seq=48, block_size=8)
            res_s = Engine(model_s, params, scfg).run(
                make_requests(vocab=64))
            eng_tp = Engine(model_tp, params, scfg, mesh=mesh)
            res_tp = eng_tp.run(make_requests(vocab=64))
            for rid in res_s:
                assert res_s[rid].tokens == res_tp[rid].tokens, rid
            assert_greedy_matches_oracle(model_s, params, res_tp)
        finally:
            mesh_lib.destroy_model_parallel()

    def test_rope_positions_decode_exactly(self):
        """Rope decode rotates each slot's token at its OWN position
        (apply_rope_at); the equivalence gate catches any offset error."""
        cfg = GPTConfig(axis=None, position_embedding="rope", **BASE)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(1))
        eng = Engine(model, params,
                     ServeConfig(max_batch=2, max_seq=48, block_size=8))
        results = eng.run(make_requests(spec=((9, 4), (4, 5))))
        assert_greedy_matches_oracle(model, params, results)

    def test_pool_pressure_defers_admission_not_correctness(self):
        """A pool too small to co-host every request must QUEUE, not
        corrupt: with 2 usable pages and two 2-page requests, admission
        defers the second (reservation-based control — an un-prefilled
        seated slot would decode garbage) and both still decode exactly;
        a request the pool can NEVER hold is rejected at submit (it
        would spin the serve loop forever)."""
        model = GPTModel(GPTConfig(axis=None, **BASE))
        params = model.init(jax.random.PRNGKey(0))
        eng = Engine(model, params,
                     ServeConfig(max_batch=2, max_seq=48, block_size=8,
                                 num_blocks=3))  # 2 usable pages
        reqs = make_requests(spec=((5, 6), (4, 7)))  # 2 pages worst-case each
        results = eng.run(reqs)
        assert len(results) == 2
        assert_greedy_matches_oracle(model, params, results)
        assert eng.allocator.used == 0 and eng.batcher.idle
        with pytest.raises(ValueError, match="pages worst-case"):
            eng.submit(Request(prompt=list(range(17)), max_new_tokens=20))

    def test_unservable_configs_fail_loudly(self):
        cfg = GPTConfig(axis=None, context_axis="context", **BASE)
        with pytest.raises(ValueError, match="context"):
            Engine(GPTModel(cfg), {}, ServeConfig())

    def test_zero3_materialize_exports_serve_params(self):
        """The training-checkpoint import path: ZeRO-3's 1/dp chunk trees
        gather back (zero3_materialize) to exactly the params the engine
        was trained with — serving equivalence then follows from the
        engine being a pure function of params."""
        from apex_tpu import amp
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer import tensor_parallel as tp_mod

        mesh = mesh_lib.make_virtual_mesh(8)
        try:
            model = GPTModel(GPTConfig(axis=None, **BASE))
            mp_opt = amp.MixedPrecisionOptimizer(
                FusedAdam(lr=1e-3), amp.get_policy("O0"),
                zero_axis=mesh_lib.AXIS_DATA, zero_level=3)
            full = model.init(jax.random.PRNGKey(0))
            specs = jax.tree.map(lambda _: jax.sharding.PartitionSpec(),
                                 full)
            placed = tp_mod.shard_params(full, specs, mesh)
            z3 = mp_opt.zero3_init(placed, mesh, specs)
            out = Engine.params_from_zero3(mp_opt, z3, mesh, specs)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)), out, full)
        finally:
            mesh_lib.destroy_model_parallel()


# ---------------------------------------------------------------------------
# journaling, report rollup, tripwire
# ---------------------------------------------------------------------------


class TestServeObservability:
    @pytest.fixture(scope="class")
    def served(self, tmp_path_factory):
        from apex_tpu.monitor.journal import MetricsJournal

        path = str(tmp_path_factory.mktemp("serve") / "serve.jsonl")
        model = GPTModel(GPTConfig(axis=None, **BASE))
        params = model.init(jax.random.PRNGKey(0))
        eng = Engine(model, params,
                     ServeConfig(max_batch=2, max_seq=48, block_size=8))
        with MetricsJournal(path, meta={"run": "test_serve"}) as j:
            results = eng.run(make_requests(), journal=j)
        return path, eng, results

    def test_request_records_and_serving_section(self, served):
        from apex_tpu.monitor import report
        from apex_tpu.monitor.journal import MetricsJournal

        path, eng, results = served
        rows = MetricsJournal.read(path)
        reqs = [r for r in rows if r["kind"] == "request"]
        assert len(reqs) == len(results) == 3
        for r in reqs:
            assert isinstance(r["ttft_s"], float)
            assert r["new_tokens"] >= 1
            assert isinstance(r["itl_s"], list)
        steps = [r for r in rows if r["kind"] == "step"]
        assert steps and all("queue_depth" in r and "slot_occupancy" in r
                             for r in steps)
        sv = report.analyze(rows).get("serving")
        assert sv and sv["requests"] == 3
        assert set(sv["ttft_ms"]) >= {"p50", "p99"}
        assert set(sv["itl_ms"]) >= {"p50", "p99"}
        assert "tokens_per_sec_per_user" in sv

    def test_compare_gates_latency_regression(self, served):
        from apex_tpu.monitor import report
        from apex_tpu.monitor.journal import MetricsJournal

        path, _, _ = served
        rows = MetricsJournal.read(path)
        assert report.compare(rows, rows, threshold=0.1)["ok"]
        worse = []
        for r in rows:
            r2 = dict(r)
            if r2.get("kind") == "request":
                if isinstance(r2.get("ttft_s"), float):
                    r2["ttft_s"] = 3.0 * r2["ttft_s"]
                r2["itl_s"] = [3.0 * v for v in (r2.get("itl_s") or [])]
            worse.append(r2)
        res = report.compare(rows, worse, threshold=0.1)
        assert not res["ok"]
        assert {"ttft_ms_p50", "itl_ms_p50"} & set(res["regressed"])

    def test_compare_flags_candidate_that_served_nothing(self, served):
        """A candidate whose journal has NO request records (crashed
        before serving) must fail the serve_requests gate, not skip it
        (analyze omits the whole serving section in that case)."""
        from apex_tpu.monitor import report
        from apex_tpu.monitor.journal import MetricsJournal

        path, _, _ = served
        rows = MetricsJournal.read(path)
        stripped = [r for r in rows if r.get("kind") != "request"]
        res = report.compare(rows, stripped, threshold=0.1)
        assert "serve_requests" in res["regressed"]

    def test_truncated_request_journal_still_parses(self, served):
        """Crash-tolerant journal lines under mid-request truncation:
        a torn final request record must not break the rollup (journal
        read semantics)."""
        from apex_tpu.monitor import report
        from apex_tpu.monitor.journal import MetricsJournal

        path, _, _ = served
        torn = path + ".torn"
        with open(path) as f:
            content = f.read()
        with open(torn, "w") as f:
            f.write(content)
            f.write('{"kind": "request", "request_id": 9, "ttft_s": 0.0')
        rows = MetricsJournal.read(torn)
        assert rows.truncated and rows.bad_lines == 1
        sv = report.analyze(rows).get("serving")
        assert sv and sv["requests"] == 3  # the torn record never counted

    def test_decode_signature_shape_stable(self, served):
        """The decode-recompile tripwire on the REAL engine argument
        stream: every tick must ship the same tree of shapes/dtypes."""
        from apex_tpu.lint import trace as lint_trace

        _, eng, _ = served
        tw = lint_trace.decode_recompile_hazards(eng.decode_args, ticks=3)
        assert not tw["hazard"], tw["findings"][:3]
        assert tw["leaves"] > 0
